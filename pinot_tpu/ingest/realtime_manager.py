"""Realtime segment lifecycle: consume -> queryable -> seal -> immutable.

Reference parity: pinot-core
data/manager/realtime/RealtimeSegmentDataManager.java:122 — one consumer
thread per stream partition (:716,1450), consumeLoop fetching batches
(:439,765), end-criteria (rows/time) triggering segment completion: build
the immutable segment, swap it into the table data manager, persist the
stream offset as the replay checkpoint, open the next CONSUMING segment
(SURVEY.md §3.3). The controller-side completion FSM is collapsed into the
local commit callback until multi-instance coordination lands
(controller-lite owns it then).

Production hardening (the ingestion PR):

* **Zero-gap seal pipeline** (local-commit path): the seal-lock is held
  only for the SNAPSHOT — the mutable rotates immediately and the
  consumer keeps consuming into the next CONSUMING segment while
  `_build_immutable` runs on a per-partition build executor. The sealed
  mutable keeps serving queries until its immutable replacement has been
  built AND warmed (`TableDataManager.add_segment` runs the warmup
  replay + residency seeding BEFORE publishing), so a seal is never
  query-visible. Commits checkpoint strictly in seal order (a later
  segment's offset never persists past an earlier segment that has not
  committed — a crash between them must re-consume, not lose rows).
* **Backpressure**: a mutable-bytes budget (`pinot.server.ingest.
  memory.bytes`, covering the mutable AND sealed-pending-build bytes)
  shrinks fetch batches adaptively as it fills and pauses the consumer
  at the ceiling; a lag ceiling (`pinot.server.ingest.lag.pause.ms`)
  bounds how far a paused partition may fall behind by force-sealing
  into the build pipeline instead of pausing indefinitely. Pause state
  is surfaced per partition (`paused`, `pause()`/`resume()` ops hooks,
  `ingest_paused` gauge).
* **Chaos sites** (deterministic seeded failpoints, byte-identical
  decision-journal replay): `ingest.seal.build`, `ingest.seal.swap`,
  `ingest.checkpoint` (payload hook — a torn policy degrades to
  re-consume-not-corrupt), `ingest.upsert.apply`, plus the pre-existing
  `ingest.realtime.consume`. A `SimulatedCrash` raised into the consume
  loop VANISHES the consumer mid-batch — no checkpoint, no cleanup —
  exactly as if the process had been SIGKILLed; recovery is a new
  manager resuming from the committed offset + validDocIds snapshots.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from pinot_tpu.controller.completion import COMMIT_SUCCESS
from pinot_tpu.ingest.mutable_segment import MutableSegment
from pinot_tpu.ingest.stream import (
    LongMsgOffset, StreamConfig, get_stream_factory)
from pinot_tpu.ingest.transforms import TransformPipeline
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.server.data_manager import TableDataManager
from pinot_tpu.utils.failpoints import SimulatedCrash, fire

log = logging.getLogger(__name__)


class RealtimeSegmentDataManager:
    """One stream partition's consumer + segment rotation."""

    #: backoff before a failed seal build / torn checkpoint retries
    SEAL_RETRY_S = 0.25

    def __init__(self, table_config: TableConfig, schema: Schema,
                 stream_config: StreamConfig, partition_id: int,
                 table_data_manager: TableDataManager, segment_store_dir: str,
                 start_offset: Optional[LongMsgOffset] = None,
                 on_commit: Optional[Callable[[str, LongMsgOffset], None]] = None,
                 ingestion_delay_tracker=None,
                 completion_manager=None, instance_id: str = "server_0",
                 deep_store=None,
                 on_open: Optional[Callable[[str], None]] = None,
                 start_seq: int = 0, config=None, metrics=None,
                 recover_segments: Optional[List] = None):
        """completion_manager: a controller SegmentCompletionManager for
        multi-replica coordination (exactly one replica commits per
        segment, ref BlockingSegmentCompletionFSM); None = single-replica
        local commits, the prior behavior.
        deep_store: a segment.fs.SegmentDeepStore — committed segments
        upload there and the completion protocol advertises the STORE URI
        as the download path, so a replica (or restarted server) recovers
        the committed copy without a shared build directory (ref
        SplitSegmentCommitter uploading via PinotFS).
        config: a PinotConfiguration for the backpressure knobs.
        metrics: a MetricsRegistry for the ingestion meters/gauges.
        recover_segments: already-loaded committed segments of THIS
        partition (restart path) — their rows re-register into the
        upsert/dedup metadata (upsert via the persisted validDocIds
        snapshots, making restart O(valid) not O(total)) so a resumed
        consumer neither replays committed rows as duplicates nor loses
        the upsert battle history."""
        self.table_config = table_config
        self.schema = schema
        self.stream_config = stream_config
        self.partition_id = partition_id
        self.tdm = table_data_manager
        self.store_dir = segment_store_dir
        self.on_commit = on_commit
        self.completion = completion_manager
        self.instance_id = instance_id
        self.deep_store = deep_store
        #: fires with the new CONSUMING segment's name at each rotation —
        #: cluster roles register it so brokers route consuming rows
        self.on_open = on_open
        #: durable location of the most recent commit (deep-store URI when
        #: one is configured, else the local build dir); cluster roles
        #: persist it in SegmentState so restarted servers can recover
        self.last_commit_uri: Optional[str] = None
        #: row count of the most recently committed segment (cluster roles
        #: report it in SegmentState so merge bucketing sees real sizes)
        self.last_commit_docs: int = 0
        self._catchup_target: Optional[int] = None
        self._catchup_deadline = 0.0
        #: a DISCARD rewound current_offset: the in-flight fetched batch
        #: is stale and must be abandoned (or rows between the committed
        #: offset and the batch cursor would be skipped)
        self._restart_fetch = False
        self.pipeline = TransformPipeline(table_config, schema)
        self.delay_tracker = ingestion_delay_tracker

        from pinot_tpu.utils.config import PinotConfiguration
        cfg = config or PinotConfiguration()
        self.memory_budget_bytes = cfg.get_int(
            "pinot.server.ingest.memory.bytes")
        self.lag_pause_ms = cfg.get_float("pinot.server.ingest.lag.pause.ms")
        self.fetch_max_rows = max(
            1, cfg.get_int("pinot.server.ingest.fetch.max.rows"))
        self._metrics = metrics
        self._labels = {"table": table_config.name,
                        "partition": str(partition_id)}

        # upsert/dedup metadata (ref RealtimeTableDataManager wiring)
        self.upsert_manager = None
        self.dedup_manager = None
        if table_config.upsert is not None:
            from pinot_tpu.segment.upsert import PartitionUpsertMetadataManager
            cmp_col = (table_config.upsert.comparison_column
                       or table_config.retention.time_column)
            self.upsert_manager = PartitionUpsertMetadataManager(
                schema.primary_key_columns, cmp_col)
        elif table_config.dedup is not None:
            from pinot_tpu.segment.upsert import PartitionDedupMetadataManager
            self.dedup_manager = PartitionDedupMetadataManager(
                schema.primary_key_columns)
        # restart recovery: committed segments re-enter the metadata in
        # seq order so cross-segment last-wins replays deterministically
        for seg in recover_segments or []:
            try:
                if self.upsert_manager is not None:
                    self.upsert_manager.add_segment(seg)
                elif self.dedup_manager is not None:
                    self.dedup_manager.add_segment(seg)
            except Exception:  # noqa: BLE001 — recovery is best-effort;
                # a bad segment costs accuracy, never the consumer
                log.exception("upsert/dedup recovery failed for %s",
                              getattr(seg, "name", "?"))

        factory = get_stream_factory(stream_config)
        self.consumer = factory.create_partition_consumer(stream_config, partition_id)
        if start_offset is None:
            meta = factory.create_metadata_provider(stream_config)
            start_offset = meta.start_offset(partition_id,
                                             stream_config.offset_criteria)
        self.current_offset = start_offset
        self.error_count = 0
        self.rows_indexed = 0
        #: start_seq: sequence of the next CONSUMING segment — a restarted
        #: server resumes AFTER its committed segments (ref LLCSegmentName
        #: sequencing), never replaying seq 0
        self._seq = start_seq
        #: index/seal mutual exclusion: a commit snapshots + swaps the
        #: mutable segment; rows must not land in it concurrently or they
        #: are lost while the checkpoint advances past them. The lock is
        #: held for SNAPSHOTS only — never across an immutable build
        self._seal_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.mutable: Optional[MutableSegment] = None
        # -- zero-gap seal pipeline state --------------------------------
        self._build_pool: Optional[ThreadPoolExecutor] = None
        #: sealed mutables whose immutable build has not committed yet —
        #: they still serve queries AND still count against the memory
        #: budget (the real OOM risk under an overdriven producer)
        self._pending_sealed: List[MutableSegment] = []
        #: guards the retry queues below — separate from the seal lock so
        #: a retry can be enqueued while the seal lock is held (the sync
        #: FSM commit paths run under it)
        self._retry_lock = threading.Lock()
        #: (not-before, sealed, offset, seq) of failed builds to retry
        self._retry_seals: List[tuple] = []
        #: (not-before, seq, name, offset, uri, docs) of torn checkpoints
        #: to retry — a checkpoint retries WITHOUT rebuilding the segment
        self._retry_checkpoints: List[tuple] = []
        #: ordered-commit gate: seal seq -> (name, offset, uri, docs)
        #: ready to checkpoint; flushed strictly in seq order under
        #: _commit_lock (EVERY commit path — async build, FSM COMMIT/
        #: KEEP/DISCARD — enqueues its pre-bump seal seq here)
        self._commit_lock = threading.Lock()
        self._ready_commits: Dict[int, tuple] = {}
        self._next_commit_seq = start_seq
        # -- backpressure / ops state ------------------------------------
        self._force_requested = False
        self._manual_pause = False
        self._bp_paused = False
        self._crashed = False
        self._open_new_consuming()

    # ------------------------------------------------------------------
    def _meter(self, name: str, value: float = 1) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(name, value, labels=self._labels)

    def _gauge(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(name, value, labels=self._labels)

    # ------------------------------------------------------------------
    def _segment_name(self) -> str:
        # ref LLCSegmentName: table__partition__seq__creationTime; with a
        # completion manager the CONTROLLER assigns it so replicas agree
        if self.completion is not None:
            return self.completion.segment_name(
                self.table_config.name, self.partition_id, self._seq)
        return (f"{self.table_config.name}__{self.partition_id}__{self._seq}"
                f"__{int(time.time())}")

    def _open_new_consuming(self) -> None:
        self.mutable = MutableSegment(self._segment_name(), self.table_config,
                                      self.schema)
        self._force_requested = False
        self.tdm.add_segment(self.mutable)  # immediately queryable
        if self.on_open is not None:
            try:
                self.on_open(self.mutable.segment_name)
            except Exception:  # noqa: BLE001 — registration is advisory
                log.exception("on_open callback failed")

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._consume_loop, daemon=True,
            name=f"consumer-{self.table_config.name}-{self.partition_id}")
        self._thread.start()

    def stop(self, timeout: float = 10.0, drain: bool = False) -> None:
        """drain=True force-commits a non-empty mutable (through the
        completion FSM when present) and waits for in-flight builds +
        checkpoints BEFORE joining the thread — a rolling restart then
        loses zero rows and persists its final checkpoint (the old
        stop() abandoned the mutable's rows)."""
        if drain and not self._crashed:
            self.drain(timeout)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._build_pool is not None:
            self._build_pool.shutdown(wait=True)
        self.consumer.close()
        if self.delay_tracker is not None:
            self.delay_tracker.remove_partition(self.partition_id)

    def drain(self, timeout: float = 10.0) -> bool:
        """Flush everything consumable to durable form: force-commit the
        mutable, then wait for pending builds, retries, and checkpoints.
        Returns True when fully drained within the timeout."""
        deadline = time.time() + timeout
        if self.mutable is not None and self.mutable.num_docs > 0:
            self.force_commit(wait_s=max(0.0, deadline - time.time()))
        while time.time() < deadline:
            with self._seal_lock:
                idle = not self._pending_sealed
            with self._retry_lock:
                idle = idle and not self._retry_seals \
                    and not self._retry_checkpoints
            with self._commit_lock:
                idle = idle and not self._ready_commits
            if idle:
                return True
            # no consumer thread: drive retries inline
            if self._thread is None or not self._thread.is_alive():
                self._drain_seal_retries()
            time.sleep(0.02)
        return False

    # -- pause / resume (ops surface) -----------------------------------
    def pause(self) -> None:
        """Ops hook: stop fetching (indexed rows keep serving)."""
        self._manual_pause = True
        self._gauge("ingest_paused", 1.0)

    def resume(self) -> None:
        self._manual_pause = False
        self._gauge("ingest_paused", 1.0 if self.paused else 0.0)

    @property
    def paused(self) -> bool:
        """True while the consumer is not fetching — manual pause or
        memory backpressure."""
        return self._manual_pause or self._bp_paused

    def _set_bp_paused(self, flag: bool) -> None:
        if flag != self._bp_paused:
            self._bp_paused = flag
            self._gauge("ingest_paused", 1.0 if self.paused else 0.0)
            if flag:
                self._meter("ingest_backpressure_pauses")

    # -- backpressure ----------------------------------------------------
    def ingest_bytes(self) -> int:
        """Bytes this partition holds in non-durable form: the consuming
        mutable plus every sealed mutable whose build has not committed."""
        with self._seal_lock:
            total = self.mutable.size_bytes if self.mutable is not None else 0
            total += sum(s.size_bytes for s in self._pending_sealed)
        return total

    def _fetch_budget(self) -> int:
        """Rows the next fetch may carry; 0 = pause this tick. Fetch
        size shrinks linearly as the memory budget fills (adaptive fetch
        -> pause -> resume), so the consumer decelerates into the wall
        instead of slamming it."""
        if self._manual_pause:
            return 0
        used = self.ingest_bytes()
        # the gauge reports regardless of budget: the UNbudgeted default
        # is exactly where operators need to watch mutable growth
        self._gauge("ingest_mutable_bytes", float(used))
        budget = self.memory_budget_bytes
        if budget <= 0:
            return self.fetch_max_rows
        if used >= budget:
            # over budget: pause — unless the pause has pushed lag past
            # the ceiling, in which case shed memory by force-sealing
            # the mutable into the build pipeline (bounded lag AND
            # bounded bytes beats silently falling behind or OOMing)
            if self.lag_pause_ms > 0 and self.delay_tracker is not None:
                d = self.delay_tracker.delay_ms(self.partition_id)
                if d is not None and d > self.lag_pause_ms \
                        and self.mutable.num_docs > 0:
                    self._meter("ingest_lag_shed_seals")
                    self._try_commit()
            return 0
        frac = 1.0 - used / budget
        return max(1, min(self.fetch_max_rows,
                          int(self.fetch_max_rows * frac)))

    # ------------------------------------------------------------------
    def _consume_loop(self) -> None:
        try:
            self._consume_loop_inner()
        except SimulatedCrash:
            # chaos kill: VANISH mid-batch — no checkpoint, no cleanup
            # handshake, exactly as if the process had been SIGKILLed.
            # Recovery is a NEW manager resuming from the last committed
            # offset + persisted validDocIds snapshots (exactly-once
            # convergence asserted by the --ingest chaos leg).
            self._crashed = True

    def _consume_loop_inner(self) -> None:
        while not self._stop.is_set():
            self._drain_seal_retries()
            fetch_rows = self._fetch_budget()
            if fetch_rows <= 0:
                self._set_bp_paused(not self._manual_pause)
                if self._force_requested and self.mutable.num_docs > 0:
                    # a force/drain must not starve behind a pause: seal
                    # what we hold (it also sheds memory into the build
                    # pipeline, which is how a paused consumer un-wedges)
                    self._try_commit()
                if self._stop.wait(0.02):
                    break
                continue
            self._set_bp_paused(False)
            try:
                # chaos site: a slow/failing upstream fetch — the
                # consumer must back off and resume, never die (seeded
                # FaultSchedules drive it deterministically)
                fire("ingest.realtime.consume",
                     table=self.table_config.name,
                     partition=self.partition_id)
                batch = self.consumer.fetch_messages(
                    self.current_offset, 100, max_messages=fetch_rows)
            except SimulatedCrash:
                raise
            except Exception:  # noqa: BLE001
                log.exception("fetch failed; backing off")
                time.sleep(1.0)
                continue
            self._index_batch(batch)
            if self._restart_fetch:
                self._restart_fetch = False
                continue  # refetch from the rewound offset
            if batch.next_offset is not None and len(batch):
                self.current_offset = batch.next_offset
            if self._end_criteria_reached():
                self._try_commit()
                self._restart_fetch = False
            if len(batch) == 0:
                if self._force_requested and self.mutable.num_docs > 0:
                    self._try_commit()
                if self._stop.wait(0.05):
                    break

    def _index_batch(self, batch) -> None:
        """Columnar fast path: transform the WHOLE fetched batch in one
        pipeline pass (ingest/transforms.transform_batch — poison rows
        come back as per-row exceptions, never failing their batch), then
        index under the seal lock in flush-threshold-sized chunks so the
        end-criteria seal still fires at exactly the configured row
        count mid-batch."""
        msgs = batch.messages
        if not msgs:
            return
        outs = self.pipeline.transform_batch([m.value for m in msgs])
        i = 0
        n = len(msgs)
        while i < n and not self._restart_fetch:
            with self._seal_lock:
                room = max(1, self.stream_config.flush_threshold_rows
                           - self.mutable.num_docs)
                end = min(n, i + room)
                indexed = skipped = 0
                for msg, rec in zip(msgs[i:end], outs[i:end]):
                    if self._index_one(msg, rec):
                        indexed += 1
                    else:
                        skipped += 1
                chunk = msgs[i:end]
                i = end
            # metering + lag OUTSIDE the seal lock, once per chunk: the
            # per-row loop must stay free of registry/gauge work (the
            # same discipline that moved transforms to the batch path)
            if indexed:
                self._meter("ingest_rows_indexed", indexed)
            if skipped:
                self._meter("ingest_rows_skipped", skipped)
            if self.delay_tracker is not None:
                for msg in reversed(chunk):
                    if msg.timestamp_ms:
                        # the newest timestamped message carries the
                        # chunk's lag (offsets are monotone)
                        self.delay_tracker.record(self.partition_id,
                                                  msg.timestamp_ms)
                        break
            if self._end_criteria_reached():
                self._try_commit()

    def _index_one(self, msg, rec) -> bool:
        """Apply one transformed row (called under the seal lock). `rec`
        is a dict (index), None (filtered), or the Exception its
        transform raised (poison: skip, offset still advances). Returns
        True when the row was indexed (the chunk loop meters in bulk)."""
        try:
            if isinstance(rec, Exception):
                raise rec
            if rec is not None and (self.dedup_manager is None
                                    or self.dedup_manager.check_and_add(rec)):
                doc_id = self.mutable.num_docs
                if self.upsert_manager is not None:
                    # chaos site BEFORE any state lands: an armed error
                    # skips the row whole, never half-applied (per-row so
                    # a seeded SimulatedCrash can kill truly MID-batch;
                    # unarmed it costs one dict lookup)
                    fire("ingest.upsert.apply",
                         table=self.table_config.name,
                         partition=self.partition_id, doc=doc_id)
                self.mutable.index(rec)
                if self.upsert_manager is not None:
                    self.upsert_manager.add_row(self.mutable, doc_id, rec)
                self.rows_indexed += 1
                self.current_offset = msg.offset.next()
                return True
            self.current_offset = msg.offset.next()
            return False
        except SimulatedCrash:
            raise
        except Exception:  # noqa: BLE001 — one bad row must not kill the
            # partition consumer (ref: reference skips untransformable
            # rows and meters them)
            self.error_count += 1
            self.current_offset = msg.offset.next()  # skip poison row
            if self.error_count <= 10 or self.error_count % 1000 == 0:
                log.exception("skipping bad record at offset %s",
                              msg.offset)
            return False

    def _try_commit(self) -> None:
        try:
            if self.completion is not None:
                self._try_commit_protocol()
                return
            self._seal_async()
        except SimulatedCrash:
            raise
        except Exception:  # noqa: BLE001 — seal failure must not kill the
            # consumer; the segment keeps consuming and the next criteria
            # check retries the build
            log.exception("segment commit failed; will retry")

    # ------------------------------------------------------------------
    # zero-gap seal pipeline (local-commit path)
    # ------------------------------------------------------------------
    def _seal_async(self) -> None:
        """Seal = snapshot + rotate under the lock, build OFF-thread:
        the consumer keeps consuming into the next CONSUMING segment
        while the immutable builds; the sealed mutable keeps serving
        queries until the warmed replacement swaps in."""
        with self._seal_lock:
            if self.mutable.num_docs <= 0:
                self._force_requested = False
                return
            sealed = self.mutable
            seal_offset = self.current_offset
            seal_seq = self._seq
            self._pending_sealed.append(sealed)
            self._seq += 1
            self._open_new_consuming()
        # the holder tracks which segment object currently OWNS the
        # upsert map entries/bitmap across build retries: an attempt that
        # ran replace_segment and then failed (e.g. at the swap chaos
        # site) has already redirected the entries, so the retry must
        # replace from THAT object, not the original sealed mutable
        self._submit_build(sealed, seal_offset, seal_seq,
                           {"upsert_owner": sealed})

    def _submit_build(self, sealed, seal_offset, seal_seq: int,
                      holder: dict) -> None:
        if self._build_pool is None:
            # one worker: builds (and their commits) stay in seal order
            self._build_pool = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=(f"seg-build-{self.table_config.name}"
                                    f"-{self.partition_id}"))
        self._build_pool.submit(self._build_and_swap, sealed, seal_offset,
                                seal_seq, holder)

    def _build_and_swap(self, sealed, seal_offset, seal_seq: int,
                        holder: dict) -> None:
        name = sealed.segment_name
        try:
            out_dir = self._build_immutable(sealed)
            uri = out_dir
            if self.deep_store is not None:
                # single-replica durability: upload before checkpointing
                # so the advertised location outlives this server
                uri = self.deep_store.upload(
                    out_dir, self.table_config.table_name_with_type, name)
            immutable = load_segment(out_dir)
            if self.upsert_manager is not None:
                # transfer validity: the immutable is a row-for-row
                # rebuild of the mutable, so it SHARES the valid bitmap
                # and takes over the map entries in place — no recompute,
                # so concurrent queries never observe cleared bits on
                # either copy. Replace from the CURRENT owner (a failed
                # earlier attempt already moved the entries off `sealed`)
                self.upsert_manager.replace_segment(
                    holder["upsert_owner"], immutable)
                holder["upsert_owner"] = immutable
                from pinot_tpu.segment.upsert import persist_valid_doc_ids
                persist_valid_doc_ids(immutable)
            # chaos site: the swap itself — an armed error retries the
            # whole build; the sealed mutable keeps serving meanwhile
            fire("ingest.seal.swap", table=self.table_config.name,
                 segment=name, partition=self.partition_id)
            # swap AFTER warmup: add_segment replays logged plans +
            # residency seeding BEFORE publishing, and replaces the
            # sealed mutable by name atomically — the seal is never
            # query-visible (no cold window, no missing-rows window)
            self.tdm.add_segment(immutable)
            with self._seal_lock:
                try:
                    self._pending_sealed.remove(sealed)
                except ValueError:
                    pass
            self._meter("ingest_segments_sealed")
            self._enqueue_commit(seal_seq, name, seal_offset, uri,
                                 immutable.num_docs)
        except Exception:  # noqa: BLE001 — the consumer must survive any
            # build failure; the sealed mutable keeps serving and the
            # build retries with backoff
            log.exception("seal build failed for %s; will retry", name)
            self._meter("ingest_seal_build_failures")
            with self._retry_lock:
                self._retry_seals.append(
                    (time.time() + self.SEAL_RETRY_S, sealed, seal_offset,
                     seal_seq, holder))

    def _drain_seal_retries(self) -> None:
        """Re-submit failed builds / torn checkpoints whose backoff
        expired (called from the consume loop, and inline by drain())."""
        now = time.time()
        with self._retry_lock:
            due = [r for r in self._retry_seals if r[0] <= now]
            self._retry_seals = [r for r in self._retry_seals if r[0] > now]
            cdue = [r for r in self._retry_checkpoints if r[0] <= now]
            self._retry_checkpoints = [r for r in self._retry_checkpoints
                                       if r[0] > now]
        for _nb, sealed, seal_offset, seal_seq, holder in due:
            self._submit_build(sealed, seal_offset, seal_seq, holder)
        for _nb, seal_seq, name, offset, uri, docs in cdue:
            self._enqueue_commit(seal_seq, name, offset, uri, docs)

    def _enqueue_commit(self, seal_seq: int, name: str, offset,
                        uri: Optional[str], docs: int) -> None:
        """Ordered-commit gate: checkpoints fire strictly in seal order
        (under _commit_lock, so a build-pool flush and a consumer-thread
        retry can never interleave out of order) — a later segment's
        offset can never persist while an earlier segment is still
        unbuilt/uncommitted, so a crash in that window re-consumes the
        earlier rows instead of losing them. uri/docs travel WITH the
        commit: last_commit_uri/docs are assigned just before on_commit
        fires, so a retried out-of-order build can never leave a later
        segment's callback reading an earlier segment's location."""
        retry = None
        with self._commit_lock:
            self._ready_commits[seal_seq] = (name, offset, uri, docs)
            while self._next_commit_seq in self._ready_commits:
                seq = self._next_commit_seq
                cname, coffset, curi, cdocs = self._ready_commits[seq]
                if self._checkpoint(cname, coffset, curi, cdocs):
                    del self._ready_commits[seq]
                    self._next_commit_seq += 1
                else:
                    # torn checkpoint: the gate stays closed at this seq
                    # (later commits queue behind it in _ready_commits)
                    # and the checkpoint retries WITHOUT rebuilding
                    retry = (time.time() + self.SEAL_RETRY_S, seq, cname,
                             coffset, curi, cdocs)
                    break
        if retry is not None:
            with self._retry_lock:
                self._retry_checkpoints.append(retry)

    def _checkpoint(self, name: str, offset, uri: Optional[str] = None,
                    docs: Optional[int] = None) -> bool:
        """Persist the replay checkpoint through the chaos payload hook:
        a torn payload (or an armed error) means the write did NOT land —
        persist nothing, so a restart resumes from the previous durable
        offset and re-consumes (never adopts a corrupt checkpoint)."""
        payload = str(offset).encode()
        try:
            out = fire("ingest.checkpoint", payload=payload,
                       table=self.table_config.name, segment=name,
                       partition=self.partition_id)
        except SimulatedCrash:
            raise
        except Exception:  # noqa: BLE001 — chaos error = write lost
            log.warning("checkpoint write failed for %s; will retry", name)
            self._meter("ingest_checkpoint_torn")
            return False
        if out != payload:
            log.warning("torn checkpoint write for %s; will retry", name)
            self._meter("ingest_checkpoint_torn")
            return False
        if uri is not None:
            self.last_commit_uri = uri
        if docs is not None:
            self.last_commit_docs = docs
        try:
            if self.on_commit is not None:
                self.on_commit(name, offset)
        except SimulatedCrash:
            raise
        except Exception:  # noqa: BLE001 — a transient callback failure
            # (coordinator unreachable) retries the CHECKPOINT, never the
            # build: escaping here would re-enter _build_and_swap's
            # except and rebuild the whole segment in a loop
            log.warning("commit callback failed for %s; will retry", name,
                        exc_info=True)
            self._meter("ingest_checkpoint_torn")
            return False
        return True

    # ------------------------------------------------------------------
    # completion-FSM (multi-replica) path — synchronous on the consumer
    # thread: the FSM round-trip dominates and KEEP/DISCARD semantics
    # need the un-rotated mutable
    # ------------------------------------------------------------------
    def _try_commit_protocol(self) -> None:
        """One FSM interaction per end-criteria check (the consume loop
        re-polls, so HOLD/CATCHUP never block the consumer thread)."""
        name = self.mutable.segment_name
        offset = int(str(self.current_offset))
        if self._catchup_target is not None and offset < self._catchup_target:
            # keep consuming toward the committer's offset — but re-report
            # after a deadline anyway: the target may be unreachable (stream
            # truncation, committer re-elected at a lower offset) and a
            # silent replica would deadlock the segment
            if time.time() < self._catchup_deadline:
                return
            self._catchup_target = None
        resp = self.completion.segment_consumed(self.instance_id, name,
                                                offset)
        if resp.action == "HOLD":
            time.sleep(0.02)
            return
        if resp.action == "CATCHUP":
            self._catchup_target = resp.offset
            self._catchup_deadline = time.time() + 10.0
            return
        self._catchup_target = None
        if resp.action == "COMMIT":
            try:
                with self._seal_lock:
                    sealed = self.mutable
                    out_dir = self._build_immutable(sealed)
                # deep-store upload BEFORE declaring success: the
                # advertised download path must be durable (ref
                # SplitSegmentCommitter's upload-then-commitEnd ordering)
                advertised = out_dir
                if self.deep_store is not None:
                    # unique=True: a stale de-elected committer finishing
                    # late must not overwrite the winner's tar
                    advertised = self.deep_store.upload(
                        out_dir, self.table_config.table_name_with_type,
                        sealed.segment_name, unique=True)
            except Exception:
                # report the failure so the FSM re-elects instead of the
                # other replicas HOLDing behind a dead claim
                self.completion.segment_commit_end(
                    self.instance_id, name, 0, success=False)
                raise
            status = self.completion.segment_commit_end(
                self.instance_id, name, int(str(self.current_offset)),
                download_path=advertised)
            if status == COMMIT_SUCCESS:
                with self._seal_lock:
                    # the mutable cannot rotate during the unlocked
                    # controller round-trip anymore (force_commit routes
                    # through this same consumer thread now), but keep
                    # the identity check as defense in depth
                    if self.mutable is sealed:
                        self.last_commit_uri = advertised
                        self._finalize_commit(out_dir)
            else:
                # de-elected while building (slow committer past the
                # deadline): discard the build; the next end-criteria
                # check re-enters segment_consumed and reconciles via
                # KEEP/DISCARD against the actual committer's copy
                with self._seal_lock:
                    if self.mutable is sealed:
                        import shutil
                        shutil.rmtree(out_dir, ignore_errors=True)
            return
        if resp.action == "KEEP":
            # offsets match the committed segment: seal the LOCAL copy
            # (row-identical) without re-reporting (ref SlowCommitter KEEP)
            with self._seal_lock:
                self._commit()
            return
        if resp.action == "DISCARD":
            if self.dedup_manager is not None or self.upsert_manager is not None:
                # dedup/upsert metadata registered rows during the
                # now-discarded consumption and cannot unwind; adopting
                # the committed copy would silently drop them on refetch.
                # Keep the local (superset) build instead — replicas
                # diverge by a few rows rather than losing data (the
                # reference rebuilds metadata from segments on restart, a
                # deep-store capability this path does not have yet)
                log.warning("DISCARD on a dedup/upsert table: sealing the "
                            "local copy of %s instead", name)
                with self._seal_lock:
                    self._commit()
                return
            # behind/ahead of the commit: adopt the committed copy and
            # resume from the committed offset — a deep-store URI fetches
            # through PinotFS (ref peer download), a plain path loads
            # directly (shared-FS stand-in)
            from pinot_tpu.segment.fs import download_segment, is_store_uri
            path = resp.download_path
            if is_store_uri(path):
                path = download_segment(
                    path, os.path.join(self.store_dir, "_downloads"))
            with self._seal_lock:
                immutable = load_segment(path)
                self.tdm.add_segment(immutable)
                self.current_offset = LongMsgOffset(resp.offset)
                self._restart_fetch = True
                # through the ordered gate: a torn checkpoint write
                # retries (the consume loop drains it) instead of being
                # dropped with a "will retry" log that never retried
                self._enqueue_commit(self._seq, immutable.name,
                                     self.current_offset,
                                     resp.download_path,
                                     immutable.num_docs)
                self._seq += 1
                self._open_new_consuming()
            return
        raise ValueError(f"unknown completion action {resp.action!r}")

    def _end_criteria_reached(self) -> bool:
        if self.mutable.num_docs <= 0:
            return False
        if self._force_requested:
            return True
        if self.mutable.num_docs >= self.stream_config.flush_threshold_rows:
            return True
        age_ms = (time.time() - self.mutable.start_consumption_time) * 1000
        return age_ms >= self.stream_config.flush_threshold_time_ms

    # ------------------------------------------------------------------
    def _commit(self) -> str:
        """Synchronous seal (completion-protocol KEEP/DISCARD paths):
        mutable -> immutable on disk -> swap -> checkpoint
        (ref commitSegment, RealtimeSegmentDataManager.java:856,1164).
        Returns the built segment directory (the completion protocol
        advertises it as the peer-download location)."""
        out_dir = self._build_immutable(self.mutable)
        self.last_commit_uri = out_dir
        if self.deep_store is not None and self.completion is None:
            # single-replica durability (the protocol path uploads before
            # commit-end instead; KEEP re-uploads would be redundant)
            self.last_commit_uri = self.deep_store.upload(
                out_dir, self.table_config.table_name_with_type,
                self.mutable.segment_name)
        self._finalize_commit(out_dir)
        return out_dir

    def _build_immutable(self, sealed) -> str:
        """Build the immutable copy on disk WITHOUT sealing/advancing —
        under the completion protocol the seal only happens after the
        controller accepts the commit (COMMIT_SUCCESS)."""
        # chaos site: the expensive build leg — an armed error/delay
        # exercises the retry path while the mutable keeps serving
        fire("ingest.seal.build", table=self.table_config.name,
             segment=sealed.segment_name, partition=self.partition_id)
        out_dir = os.path.join(self.store_dir, sealed.segment_name)
        creator = SegmentCreator(self.table_config, self.schema)
        creator.build(sealed.to_columns(), out_dir, sealed.segment_name)
        if self.upsert_manager is not None:
            # snapshot BEFORE any deep-store upload so the stored tar
            # carries validDocIds (a recovering server must not replay)
            valid = getattr(sealed, "valid_doc_ids", None)
            if valid is not None:
                from pinot_tpu.segment.meta import SegmentMetadata
                from pinot_tpu.segment.upsert import write_valid_doc_ids
                import json as _json
                with open(os.path.join(out_dir, "metadata.json")) as f:
                    crc = SegmentMetadata.from_dict(_json.load(f)).crc
                write_valid_doc_ids(out_dir, valid, crc)
        return out_dir

    def _finalize_commit(self, out_dir: str) -> None:
        sealed = self.mutable
        immutable = load_segment(out_dir)
        self.last_commit_docs = immutable.num_docs
        if self.upsert_manager is not None:
            # transfer validity: the immutable is a row-for-row rebuild of
            # the mutable, so it SHARES the valid bitmap and takes over the
            # map entries in place — no recompute, so concurrent queries
            # never observe cleared bits on either copy
            self.upsert_manager.replace_segment(sealed, immutable)
            # persist the validDocIds snapshot so a restarted server
            # resumes upsert state without replaying (ref upsert/ snapshot)
            from pinot_tpu.segment.upsert import persist_valid_doc_ids
            persist_valid_doc_ids(immutable)
        fire("ingest.seal.swap", table=self.table_config.name,
             segment=sealed.segment_name, partition=self.partition_id)
        # swap BEFORE removing: add_segment replaces by name atomically
        self.tdm.add_segment(immutable)
        # through the ordered gate, like the async path: a torn
        # checkpoint retries from the consume loop, never drops silently
        self._enqueue_commit(self._seq, sealed.segment_name,
                             self.current_offset, self.last_commit_uri,
                             immutable.num_docs)
        self._seq += 1
        self._open_new_consuming()

    def force_commit(self, wait_s: float = 10.0) -> bool:
        """Ops hook (ref forceCommit REST): seal now regardless of
        criteria — THROUGH the completion FSM when one is present. The
        old implementation called _commit() directly even on FSM-managed
        tables, which force-sealed ONE replica outside the election and
        split the replica set; the request is now served by the consumer
        thread (the only FSM driver), falling back to an inline drive
        only when no consumer thread is running. Returns True once the
        targeted mutable has rotated (its build may still be in flight —
        drain() waits for full durability)."""
        with self._seal_lock:
            if self.mutable.num_docs <= 0:
                return True
            target = self.mutable
        self._force_requested = True
        alive = self._thread is not None and self._thread.is_alive()
        deadline = time.time() + wait_s
        while time.time() < deadline and not self._crashed:
            if self.mutable is not target:
                return True
            if not alive:
                # no consumer thread: drive the seal (and, for FSM
                # tables, the protocol state machine) inline
                self._try_commit()
                time.sleep(0.02)
            else:
                time.sleep(0.01)
        return self.mutable is not target


class IngestionDelayTracker:
    """Ref core/data/manager/realtime/IngestionDelayTracker.java — per
    partition end-to-end ingestion lag, metrics-wired.

    The `ingestion_delay_ms{partition=...}` gauge refreshes on every
    record(); `remove_partition` (wired to consumer stop) drops state and
    REMOVES the labeled gauge series so a reassigned/stopped partition
    never reports stale lag forever — zeroing it kept the dead series on
    /metrics, where dashboards aggregated it as live data; record()
    clamps event timestamps against clock skew — an event stamped in
    the future would otherwise surface as negative lag."""

    def __init__(self, metrics=None, labels: Optional[Dict[str, str]] = None):
        self._latest: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._metrics = metrics
        self._labels = dict(labels or {})

    def _gauge(self, partition_id: int, value: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(
                "ingestion_delay_ms", value,
                labels={**self._labels, "partition": str(partition_id)})

    def record(self, partition_id: int, event_ts_ms: int) -> None:
        now_ms = time.time() * 1000
        # clock-skew clamp: a producer ahead of this server's clock must
        # not register as negative lag (which would mask real lag until
        # the skew drains)
        event_ts_ms = min(int(event_ts_ms), int(now_ms))
        with self._lock:
            self._latest[partition_id] = event_ts_ms
        self._gauge(partition_id, max(0.0, now_ms - event_ts_ms))

    def delay_ms(self, partition_id: int) -> Optional[float]:
        with self._lock:
            ts = self._latest.get(partition_id)
        if ts is None:
            return None
        return max(0.0, time.time() * 1000 - ts)

    def remove_partition(self, partition_id: int) -> None:
        """Wired to consumer stop: a reassigned partition's lag must not
        linger (the labeled series leaves /metrics; delay_ms returns
        None). Dropping the series — not zeroing it — matters: a zeroed
        gauge stays in the exposition forever and reads as a live
        partition with zero lag."""
        with self._lock:
            self._latest.pop(partition_id, None)
        if self._metrics is not None:
            self._metrics.remove_gauge(
                "ingestion_delay_ms",
                labels={**self._labels, "partition": str(partition_id)})

    def partitions(self) -> List[int]:
        with self._lock:
            return sorted(self._latest)

    def max_delay_ms(self) -> Optional[float]:
        """Worst lag across live partitions (the server-level signal)."""
        delays = [self.delay_ms(p) for p in self.partitions()]
        delays = [d for d in delays if d is not None]
        return max(delays) if delays else None
