"""Realtime segment lifecycle: consume -> queryable -> seal -> immutable.

Reference parity: pinot-core
data/manager/realtime/RealtimeSegmentDataManager.java:122 — one consumer
thread per stream partition (:716,1450), consumeLoop fetching batches
(:439,765), end-criteria (rows/time) triggering segment completion: build
the immutable segment, swap it into the table data manager, persist the
stream offset as the replay checkpoint, open the next CONSUMING segment
(SURVEY.md §3.3). The controller-side completion FSM is collapsed into the
local commit callback until multi-instance coordination lands
(controller-lite owns it then).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from pinot_tpu.controller.completion import COMMIT_SUCCESS
from pinot_tpu.ingest.mutable_segment import MutableSegment
from pinot_tpu.ingest.stream import (
    LongMsgOffset, StreamConfig, get_stream_factory)
from pinot_tpu.ingest.transforms import TransformPipeline
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.server.data_manager import TableDataManager
from pinot_tpu.utils.failpoints import fire

log = logging.getLogger(__name__)


class RealtimeSegmentDataManager:
    """One stream partition's consumer + segment rotation."""

    def __init__(self, table_config: TableConfig, schema: Schema,
                 stream_config: StreamConfig, partition_id: int,
                 table_data_manager: TableDataManager, segment_store_dir: str,
                 start_offset: Optional[LongMsgOffset] = None,
                 on_commit: Optional[Callable[[str, LongMsgOffset], None]] = None,
                 ingestion_delay_tracker=None,
                 completion_manager=None, instance_id: str = "server_0",
                 deep_store=None,
                 on_open: Optional[Callable[[str], None]] = None,
                 start_seq: int = 0):
        """completion_manager: a controller SegmentCompletionManager for
        multi-replica coordination (exactly one replica commits per
        segment, ref BlockingSegmentCompletionFSM); None = single-replica
        local commits, the prior behavior.
        deep_store: a segment.fs.SegmentDeepStore — committed segments
        upload there and the completion protocol advertises the STORE URI
        as the download path, so a replica (or restarted server) recovers
        the committed copy without a shared build directory (ref
        SplitSegmentCommitter uploading via PinotFS)."""
        self.table_config = table_config
        self.schema = schema
        self.stream_config = stream_config
        self.partition_id = partition_id
        self.tdm = table_data_manager
        self.store_dir = segment_store_dir
        self.on_commit = on_commit
        self.completion = completion_manager
        self.instance_id = instance_id
        self.deep_store = deep_store
        #: fires with the new CONSUMING segment's name at each rotation —
        #: cluster roles register it so brokers route consuming rows
        self.on_open = on_open
        #: durable location of the most recent commit (deep-store URI when
        #: one is configured, else the local build dir); cluster roles
        #: persist it in SegmentState so restarted servers can recover
        self.last_commit_uri: Optional[str] = None
        #: row count of the most recently committed segment (cluster roles
        #: report it in SegmentState so merge bucketing sees real sizes)
        self.last_commit_docs: int = 0
        self._catchup_target: Optional[int] = None
        self._catchup_deadline = 0.0
        #: a DISCARD rewound current_offset: the in-flight fetched batch
        #: is stale and must be abandoned (or rows between the committed
        #: offset and the batch cursor would be skipped)
        self._restart_fetch = False
        self.pipeline = TransformPipeline(table_config, schema)
        self.delay_tracker = ingestion_delay_tracker
        # upsert/dedup metadata (ref RealtimeTableDataManager wiring)
        self.upsert_manager = None
        self.dedup_manager = None
        if table_config.upsert is not None:
            from pinot_tpu.segment.upsert import PartitionUpsertMetadataManager
            cmp_col = (table_config.upsert.comparison_column
                       or table_config.retention.time_column)
            self.upsert_manager = PartitionUpsertMetadataManager(
                schema.primary_key_columns, cmp_col)
        elif table_config.dedup is not None:
            from pinot_tpu.segment.upsert import PartitionDedupMetadataManager
            self.dedup_manager = PartitionDedupMetadataManager(
                schema.primary_key_columns)

        factory = get_stream_factory(stream_config)
        self.consumer = factory.create_partition_consumer(stream_config, partition_id)
        if start_offset is None:
            meta = factory.create_metadata_provider(stream_config)
            start_offset = meta.start_offset(partition_id,
                                             stream_config.offset_criteria)
        self.current_offset = start_offset
        self.error_count = 0
        #: start_seq: sequence of the next CONSUMING segment — a restarted
        #: server resumes AFTER its committed segments (ref LLCSegmentName
        #: sequencing), never replaying seq 0
        self._seq = start_seq
        #: index/seal mutual exclusion: a commit snapshots + swaps the
        #: mutable segment; rows must not land in it concurrently or they
        #: are lost while the checkpoint advances past them
        self._seal_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.mutable: Optional[MutableSegment] = None
        self._open_new_consuming()

    # ------------------------------------------------------------------
    def _segment_name(self) -> str:
        # ref LLCSegmentName: table__partition__seq__creationTime; with a
        # completion manager the CONTROLLER assigns it so replicas agree
        if self.completion is not None:
            return self.completion.segment_name(
                self.table_config.name, self.partition_id, self._seq)
        return (f"{self.table_config.name}__{self.partition_id}__{self._seq}"
                f"__{int(time.time())}")

    def _open_new_consuming(self) -> None:
        self.mutable = MutableSegment(self._segment_name(), self.table_config,
                                      self.schema)
        self.tdm.add_segment(self.mutable)  # immediately queryable
        if self.on_open is not None:
            try:
                self.on_open(self.mutable.segment_name)
            except Exception:  # noqa: BLE001 — registration is advisory
                log.exception("on_open callback failed")

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._consume_loop, daemon=True,
            name=f"consumer-{self.table_config.name}-{self.partition_id}")
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self.consumer.close()

    def _consume_loop(self) -> None:
        while not self._stop.is_set():
            try:
                # chaos site: a slow/failing upstream fetch — the
                # consumer must back off and resume, never die (seeded
                # FaultSchedules drive it deterministically)
                fire("ingest.realtime.consume",
                     table=self.table_config.name,
                     partition=self.partition_id)
                batch = self.consumer.fetch_messages(self.current_offset, 100)
            except Exception:  # noqa: BLE001
                log.exception("fetch failed; backing off")
                time.sleep(1.0)
                continue
            for msg in batch.messages:
                try:
                    with self._seal_lock:
                        rec = self.pipeline.transform(msg.value)
                        if rec is not None and (
                                self.dedup_manager is None
                                or self.dedup_manager.check_and_add(rec)):
                            doc_id = self.mutable.num_docs
                            self.mutable.index(rec)
                            if self.upsert_manager is not None:
                                self.upsert_manager.add_row(
                                    self.mutable, doc_id, rec)
                        self.current_offset = msg.offset.next()
                except Exception:  # noqa: BLE001 — one bad row must not
                    # kill the partition consumer (ref: reference skips
                    # untransformable rows and meters them)
                    self.error_count += 1
                    self.current_offset = msg.offset.next()  # skip poison row
                    if self.error_count <= 10 or self.error_count % 1000 == 0:
                        log.exception("skipping bad record at offset %s",
                                      msg.offset)
                if self.delay_tracker is not None and msg.timestamp_ms:
                    self.delay_tracker.record(self.partition_id, msg.timestamp_ms)
                if self._end_criteria_reached():
                    self._try_commit()
                    if self._restart_fetch:
                        break
            if self._restart_fetch:
                self._restart_fetch = False
                continue  # refetch from the rewound offset
            if batch.next_offset is not None:
                self.current_offset = batch.next_offset
            if self._end_criteria_reached():
                self._try_commit()
                self._restart_fetch = False
            if len(batch) == 0:
                if self._stop.wait(0.05):
                    break

    def _try_commit(self) -> None:
        try:
            if self.completion is not None:
                self._try_commit_protocol()
                return
            with self._seal_lock:
                self._commit()
        except Exception:  # noqa: BLE001 — seal failure must not kill the
            # consumer; the segment keeps consuming and the next criteria
            # check retries the build
            log.exception("segment commit failed; will retry")

    def _try_commit_protocol(self) -> None:
        """One FSM interaction per end-criteria check (the consume loop
        re-polls, so HOLD/CATCHUP never block the consumer thread)."""
        name = self.mutable.segment_name
        offset = int(str(self.current_offset))
        if self._catchup_target is not None and offset < self._catchup_target:
            # keep consuming toward the committer's offset — but re-report
            # after a deadline anyway: the target may be unreachable (stream
            # truncation, committer re-elected at a lower offset) and a
            # silent replica would deadlock the segment
            if time.time() < self._catchup_deadline:
                return
            self._catchup_target = None
        resp = self.completion.segment_consumed(self.instance_id, name,
                                                offset)
        if resp.action == "HOLD":
            time.sleep(0.02)
            return
        if resp.action == "CATCHUP":
            self._catchup_target = resp.offset
            self._catchup_deadline = time.time() + 10.0
            return
        self._catchup_target = None
        if resp.action == "COMMIT":
            try:
                with self._seal_lock:
                    sealed = self.mutable
                    out_dir = self._build_immutable()
                # deep-store upload BEFORE declaring success: the
                # advertised download path must be durable (ref
                # SplitSegmentCommitter's upload-then-commitEnd ordering)
                advertised = out_dir
                if self.deep_store is not None:
                    # unique=True: a stale de-elected committer finishing
                    # late must not overwrite the winner's tar
                    advertised = self.deep_store.upload(
                        out_dir, self.table_config.table_name_with_type,
                        sealed.segment_name, unique=True)
            except Exception:
                # report the failure so the FSM re-elects instead of the
                # other replicas HOLDing behind a dead claim
                self.completion.segment_commit_end(
                    self.instance_id, name, 0, success=False)
                raise
            status = self.completion.segment_commit_end(
                self.instance_id, name, int(str(self.current_offset)),
                download_path=advertised)
            if status == COMMIT_SUCCESS:
                with self._seal_lock:
                    # a force_commit may have rotated self.mutable during
                    # the unlocked controller round-trip — finalize only
                    # the segment this build actually sealed
                    if self.mutable is sealed:
                        self.last_commit_uri = advertised
                        self._finalize_commit(out_dir)
            else:
                # de-elected while building (slow committer past the
                # deadline): discard the build; the next end-criteria
                # check re-enters segment_consumed and reconciles via
                # KEEP/DISCARD against the actual committer's copy
                with self._seal_lock:
                    if self.mutable is sealed:
                        # (if a force_commit rotated the mutable meanwhile,
                        # out_dir now backs a live registered segment —
                        # leave it alone)
                        import shutil
                        shutil.rmtree(out_dir, ignore_errors=True)
            return
        if resp.action == "KEEP":
            # offsets match the committed segment: seal the LOCAL copy
            # (row-identical) without re-reporting (ref SlowCommitter KEEP)
            with self._seal_lock:
                self._commit()
            return
        if resp.action == "DISCARD":
            if self.dedup_manager is not None or self.upsert_manager is not None:
                # dedup/upsert metadata registered rows during the
                # now-discarded consumption and cannot unwind; adopting
                # the committed copy would silently drop them on refetch.
                # Keep the local (superset) build instead — replicas
                # diverge by a few rows rather than losing data (the
                # reference rebuilds metadata from segments on restart, a
                # deep-store capability this path does not have yet)
                log.warning("DISCARD on a dedup/upsert table: sealing the "
                            "local copy of %s instead", name)
                with self._seal_lock:
                    self._commit()
                return
            # behind/ahead of the commit: adopt the committed copy and
            # resume from the committed offset — a deep-store URI fetches
            # through PinotFS (ref peer download), a plain path loads
            # directly (shared-FS stand-in)
            from pinot_tpu.segment.fs import download_segment, is_store_uri
            path = resp.download_path
            if is_store_uri(path):
                path = download_segment(
                    path, os.path.join(self.store_dir, "_downloads"))
            with self._seal_lock:
                self.last_commit_uri = resp.download_path
                immutable = load_segment(path)
                self.last_commit_docs = immutable.num_docs
                self.tdm.add_segment(immutable)
                self.current_offset = LongMsgOffset(resp.offset)
                self._restart_fetch = True
                if self.on_commit is not None:
                    self.on_commit(immutable.name, self.current_offset)
                self._seq += 1
                self._open_new_consuming()
            return
        raise ValueError(f"unknown completion action {resp.action!r}")

    def _end_criteria_reached(self) -> bool:
        if self.mutable.num_docs >= self.stream_config.flush_threshold_rows:
            return True
        age_ms = (time.time() - self.mutable.start_consumption_time) * 1000
        return (self.mutable.num_docs > 0
                and age_ms >= self.stream_config.flush_threshold_time_ms)

    # ------------------------------------------------------------------
    def _commit(self) -> str:
        """Seal: mutable -> immutable on disk -> swap -> checkpoint
        (ref commitSegment, RealtimeSegmentDataManager.java:856,1164).
        Returns the built segment directory (the completion protocol
        advertises it as the peer-download location)."""
        out_dir = self._build_immutable()
        self.last_commit_uri = out_dir
        if self.deep_store is not None and self.completion is None:
            # single-replica durability (the protocol path uploads before
            # commit-end instead; KEEP re-uploads would be redundant)
            self.last_commit_uri = self.deep_store.upload(
                out_dir, self.table_config.table_name_with_type,
                self.mutable.segment_name)
        self._finalize_commit(out_dir)
        return out_dir

    def _build_immutable(self) -> str:
        """Build the immutable copy on disk WITHOUT sealing/advancing —
        under the completion protocol the seal only happens after the
        controller accepts the commit (COMMIT_SUCCESS)."""
        sealed = self.mutable
        out_dir = os.path.join(self.store_dir, sealed.segment_name)
        creator = SegmentCreator(self.table_config, self.schema)
        creator.build(sealed.to_columns(), out_dir, sealed.segment_name)
        if self.upsert_manager is not None:
            # snapshot BEFORE any deep-store upload so the stored tar
            # carries validDocIds (a recovering server must not replay)
            valid = getattr(sealed, "valid_doc_ids", None)
            if valid is not None:
                from pinot_tpu.segment.meta import SegmentMetadata
                from pinot_tpu.segment.upsert import write_valid_doc_ids
                import json as _json
                with open(os.path.join(out_dir, "metadata.json")) as f:
                    crc = SegmentMetadata.from_dict(_json.load(f)).crc
                write_valid_doc_ids(out_dir, valid, crc)
        return out_dir

    def _finalize_commit(self, out_dir: str) -> None:
        sealed = self.mutable
        immutable = load_segment(out_dir)
        self.last_commit_docs = immutable.num_docs
        if self.upsert_manager is not None:
            # transfer validity: the immutable is a row-for-row rebuild of
            # the mutable, so it SHARES the valid bitmap and takes over the
            # map entries in place — no recompute, so concurrent queries
            # never observe cleared bits on either copy
            self.upsert_manager.replace_segment(sealed, immutable)
            # persist the validDocIds snapshot so a restarted server
            # resumes upsert state without replaying (ref upsert/ snapshot)
            from pinot_tpu.segment.upsert import persist_valid_doc_ids
            persist_valid_doc_ids(immutable)
        # swap BEFORE removing: add_segment replaces by name atomically
        self.tdm.add_segment(immutable)
        if self.on_commit is not None:
            self.on_commit(sealed.segment_name, self.current_offset)
        self._seq += 1
        self._open_new_consuming()

    def force_commit(self) -> None:
        """Ops hook (ref forceCommit REST): seal now regardless of criteria."""
        with self._seal_lock:
            if self.mutable.num_docs > 0:
                self._commit()


class IngestionDelayTracker:
    """Ref core/data/manager/realtime/IngestionDelayTracker.java — per
    partition end-to-end ingestion lag."""

    def __init__(self):
        self._latest: Dict[int, int] = {}
        self._lock = threading.Lock()

    def record(self, partition_id: int, event_ts_ms: int) -> None:
        with self._lock:
            self._latest[partition_id] = event_ts_ms

    def delay_ms(self, partition_id: int) -> Optional[float]:
        with self._lock:
            ts = self._latest.get(partition_id)
        if ts is None:
            return None
        return max(0.0, time.time() * 1000 - ts)
