"""Stream consumer SPI.

Reference parity: pinot-spi stream/ — StreamConfig, StreamConsumerFactory,
PartitionGroupConsumer.fetchMessages, MessageBatch, StreamPartitionMsgOffset
(monotonic, comparable, string-serializable so it can live in segment
metadata as the replay checkpoint — SURVEY.md §5 checkpoint/resume).
Concrete plugins (in-memory, kafka) implement this contract.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence


@dataclass(frozen=True, order=True)
class LongMsgOffset:
    """Ref LongMsgOffset — kafka-style numeric offset."""
    offset: int

    def __str__(self) -> str:
        return str(self.offset)

    @classmethod
    def parse(cls, s: str) -> "LongMsgOffset":
        return cls(int(s))

    def next(self) -> "LongMsgOffset":
        return LongMsgOffset(self.offset + 1)


@dataclass
class StreamMessage:
    value: Dict[str, Any]          # decoded record (RecordExtractor output)
    offset: LongMsgOffset
    key: Optional[str] = None
    timestamp_ms: Optional[int] = None


@dataclass
class MessageBatch:
    """Ref MessageBatch — one fetch's worth of messages."""
    messages: List[StreamMessage] = field(default_factory=list)
    #: offset to resume from after consuming this batch
    next_offset: Optional[LongMsgOffset] = None
    end_of_partition: bool = False

    def __len__(self) -> int:
        return len(self.messages)


@dataclass
class StreamConfig:
    """Ref StreamConfig — parsed from table streamConfigs map."""
    stream_type: str = "inmemory"       # kafka | kinesis | pulsar | inmemory
    topic: str = ""
    consumer_factory: str = ""
    decoder: str = "json"
    #: segment flush thresholds (ref StreamConfig flush settings)
    flush_threshold_rows: int = 100_000
    flush_threshold_time_ms: int = 6 * 3600 * 1000
    offset_criteria: str = "smallest"   # smallest | largest
    properties: Dict[str, str] = field(default_factory=dict)


class PartitionGroupConsumer(abc.ABC):
    """Ref PartitionGroupConsumer — one stream partition's consumer.

    ``max_messages`` is the backpressure lever: the realtime manager's
    adaptive fetch sizing shrinks it as the mutable-bytes budget fills
    (ref Kafka max.poll.records). Implementations may treat it as a
    hint; the default preserves pre-existing batch sizes."""

    @abc.abstractmethod
    def fetch_messages(self, start_offset: LongMsgOffset,
                       timeout_ms: int,
                       max_messages: int = 10_000) -> MessageBatch: ...

    def close(self) -> None:
        pass


class StreamMetadataProvider(abc.ABC):
    @abc.abstractmethod
    def partition_ids(self) -> List[int]: ...

    @abc.abstractmethod
    def start_offset(self, partition_id: int, criteria: str) -> LongMsgOffset: ...


class StreamConsumerFactory(abc.ABC):
    """Ref StreamConsumerFactory — resolved from StreamConfig."""

    @abc.abstractmethod
    def create_partition_consumer(self, config: StreamConfig,
                                  partition_id: int) -> PartitionGroupConsumer: ...

    @abc.abstractmethod
    def create_metadata_provider(self, config: StreamConfig) -> StreamMetadataProvider: ...


def register_stream_factory(stream_type: str, factory: StreamConsumerFactory) -> None:
    """Stream consumers register through the central plugin registry
    (ref StreamConsumerFactoryProvider over PluginManager)."""
    from pinot_tpu.utils import plugins
    plugins.register("stream", stream_type, factory)


def get_stream_factory(config: StreamConfig) -> StreamConsumerFactory:
    from pinot_tpu.utils import plugins
    try:
        return plugins.get("stream", config.stream_type)
    except KeyError as e:
        raise ValueError(str(e)) from e
