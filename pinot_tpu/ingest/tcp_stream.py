"""TCP stream connector: a real network stream speaking the consumer SPI.

Reference parity: pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0
KafkaPartitionLevelConsumer.java + KafkaStreamMetadataProvider — the
reference's ingestion connects to an EXTERNAL broker over the network;
the in-memory stream can't leave the process, so multi-process replicas
could never share a partition. This module provides:

- StreamServer: a standalone topic broker (partitioned append-only logs)
  served over TCP with length-prefixed JSON frames, runnable as its own
  process (admin StartStreamServer)
- StreamProducer: publish client
- TcpPartitionConsumer / TcpStreamMetadataProvider /
  TcpStreamConsumerFactory: the PartitionGroupConsumer SPI over the wire,
  registered as stream_type "tcp" (config properties: {"bootstrap":
  "host:port"})

Offsets are Kafka-style longs per partition; fetches are (start, max)
reads, so the replay-checkpoint semantics match the in-memory stream and
segment metadata checkpoints keep working unchanged.
"""
from __future__ import annotations

import socketserver
import threading
from typing import Any, Dict, List, Optional

from pinot_tpu.ingest.stream import (LongMsgOffset, MessageBatch,
                                     PartitionGroupConsumer, StreamConfig,
                                     StreamConsumerFactory, StreamMessage,
                                     StreamMetadataProvider,
                                     register_stream_factory)
from pinot_tpu.utils.failpoints import fire
from pinot_tpu.utils.netframe import (FramedChannel, recv_frame,
                                      send_frame)


class StreamServer:
    """Partitioned append-only topic logs over TCP (the embedded-Kafka
    analog of the reference's integration harness, network-real)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._topics: Dict[str, List[List[dict]]] = {}
        self._lock = threading.Lock()
        server_ref = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    while True:
                        req = recv_frame(sock)
                        if req is None:
                            return
                        try:
                            resp = server_ref._dispatch(req)
                        except Exception as e:  # noqa: BLE001
                            resp = {"error": f"{type(e).__name__}: {e}"}
                        send_frame(sock, resp)
                except (ConnectionError, OSError):
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="stream-server")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:  # shutdown() blocks unless serving
            self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------------
    def _dispatch(self, req: dict) -> dict:
        op = req["op"]
        if op == "create_topic":
            with self._lock:
                self._topics.setdefault(
                    req["topic"],
                    [[] for _ in range(int(req.get("partitions", 1)))])
            return {"ok": True}
        if op == "publish":
            with self._lock:
                parts = self._topics[req["topic"]]
                pid = int(req.get("partition", 0))
                if req.get("key") is not None:
                    pid = hash(req["key"]) % len(parts)
                log = parts[pid]
                offset = len(log)
                log.append({"value": req["record"], "key": req.get("key"),
                            "ts": req.get("timestamp_ms")})
                return {"offset": offset, "partition": pid}
        if op == "fetch":
            with self._lock:
                log = self._topics[req["topic"]][int(req["partition"])]
                start = int(req["start"])
                end = min(len(log), start + int(req.get("max", 500)))
                msgs = [{"offset": i, **log[i]} for i in range(start, end)]
                return {"messages": msgs, "log_end": len(log)}
        if op == "metadata":
            with self._lock:
                topic = self._topics.get(req["topic"])
                if topic is None:
                    return {"error": f"no such topic {req['topic']!r}"}
                return {"partitions": len(topic),
                        "end_offsets": [len(p) for p in topic]}
        raise ValueError(f"unknown op {op!r}")


class StreamProducer:
    """Publish client (the stream's producer edge)."""

    def __init__(self, address: str):
        self._ch = FramedChannel(address)

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        self._ch.request({"op": "create_topic", "topic": topic,
                          "partitions": partitions})

    def publish(self, topic: str, record: Dict[str, Any],
                partition: int = 0, key: Optional[str] = None,
                timestamp_ms: Optional[int] = None) -> int:
        # retry=False: publish is NOT idempotent — a reconnect-and-resend
        # could append the record twice if the server applied it before
        # the connection dropped; the caller decides whether to retry
        r = self._ch.request({"op": "publish", "topic": topic,
                              "record": record, "partition": partition,
                              "key": key, "timestamp_ms": timestamp_ms},
                             retry=False)
        return r["offset"]

    def close(self) -> None:
        self._ch.close()


def _bootstrap(config: StreamConfig) -> str:
    addr = config.properties.get("bootstrap")
    if not addr:
        raise ValueError("tcp stream needs properties['bootstrap']")
    return addr


class TcpPartitionConsumer(PartitionGroupConsumer):
    """Ref KafkaPartitionLevelConsumer.fetchMessages: (start, max) reads
    over the network, batch carries the resume offset."""

    def __init__(self, config: StreamConfig, partition_id: int):
        self._ch = FramedChannel(_bootstrap(config))
        self.topic = config.topic
        self.partition_id = partition_id

    def fetch_messages(self, start_offset: LongMsgOffset,
                       timeout_ms: int,
                       max_messages: int = 500) -> MessageBatch:
        # chaos site: delay/fail/drop a fetch frame on the wire edge —
        # errors surface to the realtime manager's backoff path exactly
        # like a dead stream broker would
        fire("ingest.tcp.frame", topic=self.topic,
             partition=self.partition_id,
             start=int(start_offset.offset))
        r = self._ch.request({"op": "fetch", "topic": self.topic,
                              "partition": self.partition_id,
                              "start": start_offset.offset,
                              "max": min(max_messages, 500)})
        msgs = [StreamMessage(value=m["value"],
                              offset=LongMsgOffset(m["offset"]),
                              key=m.get("key"),
                              timestamp_ms=m.get("ts"))
                for m in r["messages"]]
        nxt = LongMsgOffset(msgs[-1].offset.offset + 1) if msgs else None
        return MessageBatch(messages=msgs, next_offset=nxt)

    def close(self) -> None:
        self._ch.close()


class TcpStreamMetadataProvider(StreamMetadataProvider):
    def __init__(self, config: StreamConfig):
        self._ch = FramedChannel(_bootstrap(config))
        self.topic = config.topic

    def partition_ids(self) -> List[int]:
        r = self._ch.request({"op": "metadata", "topic": self.topic})
        return list(range(r["partitions"]))

    def start_offset(self, partition_id: int, criteria: str) -> LongMsgOffset:
        if criteria == "smallest":
            return LongMsgOffset(0)
        r = self._ch.request({"op": "metadata", "topic": self.topic})
        return LongMsgOffset(r["end_offsets"][partition_id])

    def close(self) -> None:
        self._ch.close()


class TcpStreamConsumerFactory(StreamConsumerFactory):
    def create_partition_consumer(self, config: StreamConfig,
                                  partition_id: int) -> TcpPartitionConsumer:
        return TcpPartitionConsumer(config, partition_id)

    def create_metadata_provider(self, config: StreamConfig
                                 ) -> TcpStreamMetadataProvider:
        return TcpStreamMetadataProvider(config)


register_stream_factory("tcp", TcpStreamConsumerFactory())
