"""Record transform pipeline (ingestion side).

Reference parity: pinot-segment-local recordtransformer/ — the
CompositeTransformer chain: filtering (skip rows), expression transforms
(derived columns), data-type conversion + null handling against the
schema, time validation, sanitization. Order mirrors
CompositeTransformer.getDefaultTransformers.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pinot_tpu.models import FieldSpec, Schema, TableConfig
from pinot_tpu.query.expressions import Expression
from pinot_tpu.query.expressions import Function as _Fn
from pinot_tpu.query.parser import _Parser, tokenize


def parse_expression(text: str) -> Expression:
    """Parse a standalone scalar expression (for transform/filter configs)."""
    return _Parser(tokenize(text)).expr()


class _ScalarProvider:
    """ColumnProvider over one record's scalars (arrays of length 1)."""

    def __init__(self, record: Dict[str, Any]):
        self.record = record

    def column(self, name: str):
        v = self.record.get(name)
        if isinstance(v, (list, tuple)):
            # MV field: keep the list as an object element — equality
            # comparisons evaluate honestly and arithmetic raises (the
            # per-record error guard skips+logs the row) instead of
            # silently computing on a bogus 0
            out = np.empty(1, dtype=object)
            out[0] = list(v)
            return out
        return np.array([v])

    @property
    def num_docs(self) -> int:
        return 1


class _BatchProvider:
    """ColumnProvider over a LIST of records (columnar batch evaluation:
    one expression pass over the whole batch instead of one per row)."""

    def __init__(self, records: List[Dict[str, Any]]):
        self._records = records

    def column(self, name: str):
        vals = [r.get(name) for r in self._records]
        # native dtype ONLY for type-homogeneous batches: np.array over a
        # mixed [5, "x"] batch silently unifies to strings, and '5' == 5
        # is elementwise-False with no exception — the per-row path would
        # have compared 5 == 5 per row. Mixed batches stay object arrays,
        # where comparisons/arithmetic run Python semantics per element
        # (matching _ScalarProvider's one-row arrays) and genuine type
        # errors raise into the demote-to-per-row guard.
        t0 = type(vals[0])
        if all(type(v) is t0 for v in vals):
            try:
                arr = np.array(vals)
                if arr.ndim == 1:
                    return arr
            except (ValueError, TypeError):
                pass
        out = np.empty(len(vals), dtype=object)
        out[:] = vals
        return out

    @property
    def num_docs(self) -> int:
        return len(self._records)


class TransformPipeline:
    """record dict -> transformed record dict (or None when filtered)."""

    def __init__(self, table_config: TableConfig, schema: Schema):
        self.schema = schema
        ing = table_config.ingestion
        self._filter_expr: Optional[Expression] = None
        if getattr(ing, "filter_function", None):
            self._filter_expr = parse_expression(ing.filter_function)
        self._transforms: List[tuple] = []
        for cfg in getattr(ing, "transform_configs", []) or []:
            self._transforms.append(
                (cfg["columnName"], parse_expression(cfg["transformFunction"])))
        self._enrichers: List[Callable[[Dict[str, Any]], None]] = []
        #: columns the filter + transform expressions read — the batch
        #: fast path applies only to rows where every one of these is a
        #: non-null scalar (null-propagation and MV special cases keep
        #: the exact per-row semantics via the slow path)
        refs: set = set()
        if self._filter_expr is not None:
            _collect_identifiers(self._filter_expr, refs)
        for _col, expr in self._transforms:
            _collect_identifiers(expr, refs)
        self._expr_refs = sorted(refs)

    def add_enricher(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Ref recordtransformer/enricher/ (e.g. CLPEncodingEnricher)."""
        self._enrichers.append(fn)

    # functions that legitimately consume nulls — null propagation must
    # not short-circuit them
    _NULL_TOLERANT = frozenset(
        {"coalesce", "case", "is_null", "is_not_null",
         "json_extract_scalar"})

    def _coerce(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Step 0: best-effort numeric coercion for schema fields arriving
        as strings (CSV readers deliver text): filters and transforms must
        compare numbers, not strings. Unparseable values stay as-is and
        surface through the per-record guards."""
        coerced = None
        for spec in self.schema.fields:
            v = record.get(spec.name)
            if isinstance(v, str) and \
                    spec.data_type.np_dtype.kind in "iuf":
                try:
                    conv = spec.data_type.convert(v)
                except (TypeError, ValueError):
                    continue
                if coerced is None:
                    coerced = record = dict(record)
                record[spec.name] = conv
        return record

    def _finalize(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Step 4: schema conversion + null handling (ref
        DataTypeTransformer / NullValueTransformer): coerce to stored
        type, defaults for nulls."""
        out_rec: Dict[str, Any] = {}
        for spec in self.schema.fields:
            if spec.virtual:
                continue
            v = record.get(spec.name)
            if spec.single_value:
                out_rec[spec.name] = (spec.data_type.convert(v)
                                      if v is not None else None)
            else:
                if v is None:
                    v = []
                elif not isinstance(v, (list, tuple)):
                    v = [v]
                out_rec[spec.name] = [spec.data_type.convert(x) for x in v]
        return out_rec

    def transform(self, record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        from pinot_tpu.query import transform as texpr

        record = self._coerce(record)

        # 1. filter (ref FilterTransformer): truthy filter result -> DROP.
        # SQL three-valued logic: a SIMPLE predicate over NULL is not
        # matched (row kept, no evaluation); composed filters (AND/OR/NOT)
        # still evaluate — 'a = 1 OR b = 2' with b NULL can be TRUE via a —
        # with null-caused evaluation errors meaning not-matched. Filters
        # with no null inputs let genuine type errors (MV misconfig)
        # propagate to the per-record guards.
        if self._filter_expr is not None:
            refs_null = _references_null(self._filter_expr, record)
            composed = isinstance(self._filter_expr, _Fn) and \
                self._filter_expr.name in ("and", "or", "not")
            if not refs_null or composed:
                if refs_null:
                    try:
                        out = texpr.evaluate(self._filter_expr,
                                             _ScalarProvider(record))
                    except TypeError:
                        out = False  # NULL branch decided: not matched
                else:
                    out = texpr.evaluate(self._filter_expr,
                                         _ScalarProvider(record))
                if bool(np.asarray(out).reshape(-1)[0]):
                    return None
        # 2. expression transforms (ref ExpressionTransformer): SQL null
        # propagation — an expression whose input column is NULL yields
        # NULL (-> the type default in step 4) unless the top-level
        # function is null-tolerant (coalesce/case/is_null)
        if self._transforms:
            record = dict(record)
            for col, expr in self._transforms:
                if record.get(col) is None:
                    if _references_null(expr, record):
                        record[col] = None
                        continue
                    out = texpr.evaluate(expr, _ScalarProvider(record))
                    record[col] = _scalar(out)
        # 3. enrichers
        for fn in self._enrichers:
            fn(record)
        # 4. schema conversion + null handling
        return self._finalize(record)

    # ------------------------------------------------------------------
    # columnar batch path (the realtime consume loop's hot path)
    # ------------------------------------------------------------------
    def transform_batch(self, records: List[Dict[str, Any]]) -> List[Any]:
        """Vectorized transform: ONE evaluation per filter/transform
        expression over the whole batch (the per-row path re-walks the
        expression tree per record — parser/evaluator overhead dominates
        ingestion CPU at stream rates). Returns a list aligned with
        `records`; each element is one of:

          dict       — the transformed record (index it)
          None       — filtered out (skip it, advance the offset)
          Exception  — this row poisoned (skip + meter it); poison rows
                       are isolated PER ROW, the batch always survives

        Exactness: rows whose expression-referenced columns are null or
        multi-valued take the per-row path (SQL three-valued logic and MV
        semantics live there); a batch-evaluation failure demotes the
        whole fast set to per-row so one poison value can't take down its
        neighbours. transform_batch(rs)[i] == transform(rs[i]) for every
        non-poison row by construction (property-tested)."""
        from pinot_tpu.query import transform as texpr

        n = len(records)
        if n == 0:
            return []
        out: List[Any] = [None] * n
        recs: List[Optional[Dict[str, Any]]] = [None] * n
        fast_idx: List[int] = []
        slow_idx: List[int] = []
        for i, r in enumerate(records):
            try:
                rr = self._coerce(r)
            except Exception as e:  # noqa: BLE001 — isolate the row
                out[i] = e
                continue
            recs[i] = rr
            ok = True
            for c in self._expr_refs:
                v = rr.get(c)
                if v is None or isinstance(v, (list, tuple)):
                    ok = False
                    break
            (fast_idx if ok else slow_idx).append(i)

        if fast_idx and (self._filter_expr is not None or self._transforms):
            batch = [recs[i] for i in fast_idx]
            provider = _BatchProvider(batch)
            try:
                keep = np.ones(len(batch), dtype=bool)
                if self._filter_expr is not None:
                    drop = np.asarray(
                        texpr.evaluate(self._filter_expr, provider))
                    keep = ~np.broadcast_to(
                        drop.astype(bool).reshape(-1)
                        if drop.ndim else drop.astype(bool),
                        (len(batch),))
                for col, expr in self._transforms:
                    apply_rows = [j for j, r in enumerate(batch)
                                  if keep[j] and r.get(col) is None]
                    if not apply_rows:
                        continue
                    vals = np.asarray(texpr.evaluate(expr, provider))
                    if vals.ndim == 0:
                        vals = np.broadcast_to(vals, (len(batch),))
                    for j in apply_rows:
                        if batch[j] is records[fast_idx[j]] \
                                or batch[j] is recs[fast_idx[j]]:
                            batch[j] = dict(batch[j])
                        batch[j][col] = _scalar(vals[j])
                for j, i in enumerate(fast_idx):
                    if not keep[j]:
                        out[i] = None
                        continue
                    rec = batch[j]
                    try:
                        for fn in self._enrichers:
                            if rec is records[i] or rec is recs[i]:
                                rec = dict(rec)
                            fn(rec)
                        out[i] = self._finalize(rec)
                    except Exception as e:  # noqa: BLE001 — per-row
                        out[i] = e
            except Exception:  # noqa: BLE001 — a poison value broke the
                # BATCH evaluation: demote every fast row to the per-row
                # path, where each row's own guard isolates it
                slow_idx.extend(fast_idx)
        else:
            # no expressions (or no eligible rows): finalize directly
            for i in fast_idx:
                try:
                    rec = recs[i]
                    for fn in self._enrichers:
                        if rec is records[i] or rec is recs[i]:
                            rec = dict(rec)
                        fn(rec)
                    out[i] = self._finalize(rec)
                except Exception as e:  # noqa: BLE001
                    out[i] = e

        for i in slow_idx:
            try:
                out[i] = self.transform(records[i])
            except Exception as e:  # noqa: BLE001 — poison row isolated
                out[i] = e
        return out


def _scalar(v: Any) -> Any:
    arr = np.asarray(v).reshape(-1)
    x = arr[0]
    return x.item() if isinstance(x, np.generic) else x


def _collect_identifiers(expr, out: set) -> None:
    """Column names an expression reads (batch fast-path eligibility)."""
    from pinot_tpu.query.expressions import Function, Identifier
    if isinstance(expr, Identifier):
        out.add(expr.name)
    elif isinstance(expr, Function):
        for a in expr.args:
            _collect_identifiers(a, out)


def _references_null(expr, record) -> bool:
    """True when the expression reads a column that is NULL in this record
    — SQL null-propagation test. Null-tolerant functions (coalesce/case/
    is_null) consume DIRECT null column references, but nulls inside
    their non-trivial sub-expressions still propagate
    ('coalesce(a, b + 1)' with b NULL is NULL)."""
    from pinot_tpu.query.expressions import Function, Identifier

    def walk(e) -> bool:
        if isinstance(e, Identifier):
            return record.get(e.name) is None
        if isinstance(e, Function):
            if e.name in TransformPipeline._NULL_TOLERANT:
                # the function's own evaluator handles nulls (coalesce
                # treats a null-propagating argument as missing per-arg)
                return False
            return any(walk(a) for a in e.args)
        return False

    return walk(expr)
