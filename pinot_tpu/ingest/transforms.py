"""Record transform pipeline (ingestion side).

Reference parity: pinot-segment-local recordtransformer/ — the
CompositeTransformer chain: filtering (skip rows), expression transforms
(derived columns), data-type conversion + null handling against the
schema, time validation, sanitization. Order mirrors
CompositeTransformer.getDefaultTransformers.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pinot_tpu.models import FieldSpec, Schema, TableConfig
from pinot_tpu.query.expressions import Expression
from pinot_tpu.query.expressions import Function as _Fn
from pinot_tpu.query.parser import _Parser, tokenize


def parse_expression(text: str) -> Expression:
    """Parse a standalone scalar expression (for transform/filter configs)."""
    return _Parser(tokenize(text)).expr()


class _ScalarProvider:
    """ColumnProvider over one record's scalars (arrays of length 1)."""

    def __init__(self, record: Dict[str, Any]):
        self.record = record

    def column(self, name: str):
        v = self.record.get(name)
        if isinstance(v, (list, tuple)):
            # MV field: keep the list as an object element — equality
            # comparisons evaluate honestly and arithmetic raises (the
            # per-record error guard skips+logs the row) instead of
            # silently computing on a bogus 0
            out = np.empty(1, dtype=object)
            out[0] = list(v)
            return out
        return np.array([v])

    @property
    def num_docs(self) -> int:
        return 1


class TransformPipeline:
    """record dict -> transformed record dict (or None when filtered)."""

    def __init__(self, table_config: TableConfig, schema: Schema):
        self.schema = schema
        ing = table_config.ingestion
        self._filter_expr: Optional[Expression] = None
        if getattr(ing, "filter_function", None):
            self._filter_expr = parse_expression(ing.filter_function)
        self._transforms: List[tuple] = []
        for cfg in getattr(ing, "transform_configs", []) or []:
            self._transforms.append(
                (cfg["columnName"], parse_expression(cfg["transformFunction"])))
        self._enrichers: List[Callable[[Dict[str, Any]], None]] = []

    def add_enricher(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Ref recordtransformer/enricher/ (e.g. CLPEncodingEnricher)."""
        self._enrichers.append(fn)

    # functions that legitimately consume nulls — null propagation must
    # not short-circuit them
    _NULL_TOLERANT = frozenset(
        {"coalesce", "case", "is_null", "is_not_null",
         "json_extract_scalar"})

    def transform(self, record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        from pinot_tpu.query import transform as texpr

        # 0. best-effort numeric coercion for schema fields arriving as
        # strings (CSV readers deliver text): filters and transforms must
        # compare numbers, not strings. Unparseable values stay as-is and
        # surface through the per-record guards.
        coerced = None
        for spec in self.schema.fields:
            v = record.get(spec.name)
            if isinstance(v, str) and \
                    spec.data_type.np_dtype.kind in "iuf":
                try:
                    conv = spec.data_type.convert(v)
                except (TypeError, ValueError):
                    continue
                if coerced is None:
                    coerced = record = dict(record)
                record[spec.name] = conv

        # 1. filter (ref FilterTransformer): truthy filter result -> DROP.
        # SQL three-valued logic: a SIMPLE predicate over NULL is not
        # matched (row kept, no evaluation); composed filters (AND/OR/NOT)
        # still evaluate — 'a = 1 OR b = 2' with b NULL can be TRUE via a —
        # with null-caused evaluation errors meaning not-matched. Filters
        # with no null inputs let genuine type errors (MV misconfig)
        # propagate to the per-record guards.
        if self._filter_expr is not None:
            refs_null = _references_null(self._filter_expr, record)
            composed = isinstance(self._filter_expr, _Fn) and \
                self._filter_expr.name in ("and", "or", "not")
            if not refs_null or composed:
                if refs_null:
                    try:
                        out = texpr.evaluate(self._filter_expr,
                                             _ScalarProvider(record))
                    except TypeError:
                        out = False  # NULL branch decided: not matched
                else:
                    out = texpr.evaluate(self._filter_expr,
                                         _ScalarProvider(record))
                if bool(np.asarray(out).reshape(-1)[0]):
                    return None
        # 2. expression transforms (ref ExpressionTransformer): SQL null
        # propagation — an expression whose input column is NULL yields
        # NULL (-> the type default in step 4) unless the top-level
        # function is null-tolerant (coalesce/case/is_null)
        if self._transforms:
            record = dict(record)
            for col, expr in self._transforms:
                if record.get(col) is None:
                    if _references_null(expr, record):
                        record[col] = None
                        continue
                    out = texpr.evaluate(expr, _ScalarProvider(record))
                    record[col] = _scalar(out)
        # 3. enrichers
        for fn in self._enrichers:
            fn(record)
        # 4. schema conversion + null handling (ref DataTypeTransformer /
        #    NullValueTransformer): coerce to stored type, defaults for nulls
        out_rec: Dict[str, Any] = {}
        for spec in self.schema.fields:
            if spec.virtual:
                continue
            v = record.get(spec.name)
            if spec.single_value:
                out_rec[spec.name] = (spec.data_type.convert(v)
                                      if v is not None else None)
            else:
                if v is None:
                    v = []
                elif not isinstance(v, (list, tuple)):
                    v = [v]
                out_rec[spec.name] = [spec.data_type.convert(x) for x in v]
        return out_rec


def _scalar(v: Any) -> Any:
    arr = np.asarray(v).reshape(-1)
    x = arr[0]
    return x.item() if isinstance(x, np.generic) else x


def _references_null(expr, record) -> bool:
    """True when the expression reads a column that is NULL in this record
    — SQL null-propagation test. Null-tolerant functions (coalesce/case/
    is_null) consume DIRECT null column references, but nulls inside
    their non-trivial sub-expressions still propagate
    ('coalesce(a, b + 1)' with b NULL is NULL)."""
    from pinot_tpu.query.expressions import Function, Identifier

    def walk(e) -> bool:
        if isinstance(e, Identifier):
            return record.get(e.name) is None
        if isinstance(e, Function):
            if e.name in TransformPipeline._NULL_TOLERANT:
                # the function's own evaluator handles nulls (coalesce
                # treats a null-propagating argument as missing per-arg)
                return False
            return any(walk(a) for a in e.args)
        return False

    return walk(expr)
