"""Record transform pipeline (ingestion side).

Reference parity: pinot-segment-local recordtransformer/ — the
CompositeTransformer chain: filtering (skip rows), expression transforms
(derived columns), data-type conversion + null handling against the
schema, time validation, sanitization. Order mirrors
CompositeTransformer.getDefaultTransformers.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pinot_tpu.models import FieldSpec, Schema, TableConfig
from pinot_tpu.query.expressions import Expression
from pinot_tpu.query.parser import _Parser, tokenize


def parse_expression(text: str) -> Expression:
    """Parse a standalone scalar expression (for transform/filter configs)."""
    return _Parser(tokenize(text)).expr()


class _ScalarProvider:
    """ColumnProvider over one record's scalars (arrays of length 1)."""

    def __init__(self, record: Dict[str, Any]):
        self.record = record

    def column(self, name: str):
        v = self.record.get(name)
        if isinstance(v, (list, tuple)):
            # MV field: keep the list as an object element — equality
            # comparisons evaluate honestly and arithmetic raises (the
            # per-record error guard skips+logs the row) instead of
            # silently computing on a bogus 0
            out = np.empty(1, dtype=object)
            out[0] = list(v)
            return out
        return np.array([v])

    @property
    def num_docs(self) -> int:
        return 1


class TransformPipeline:
    """record dict -> transformed record dict (or None when filtered)."""

    def __init__(self, table_config: TableConfig, schema: Schema):
        self.schema = schema
        ing = table_config.ingestion
        self._filter_expr: Optional[Expression] = None
        if getattr(ing, "filter_function", None):
            self._filter_expr = parse_expression(ing.filter_function)
        self._transforms: List[tuple] = []
        for cfg in getattr(ing, "transform_configs", []) or []:
            self._transforms.append(
                (cfg["columnName"], parse_expression(cfg["transformFunction"])))
        self._enrichers: List[Callable[[Dict[str, Any]], None]] = []

    def add_enricher(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Ref recordtransformer/enricher/ (e.g. CLPEncodingEnricher)."""
        self._enrichers.append(fn)

    def transform(self, record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        from pinot_tpu.query import transform as texpr

        # 1. filter (ref FilterTransformer): truthy filter result -> DROP;
        # a filter over a null input cannot be truthy (SQL three-valued
        # logic: NULL predicate = not matched = keep the row)
        if self._filter_expr is not None:
            try:
                out = texpr.evaluate(self._filter_expr,
                                     _ScalarProvider(record))
            except TypeError:
                out = False
            if bool(np.asarray(out).reshape(-1)[0]):
                return None
        # 2. expression transforms (ref ExpressionTransformer); an
        # expression over a null input yields null (-> the null default
        # in step 4), never a crash
        if self._transforms:
            record = dict(record)
            for col, expr in self._transforms:
                if record.get(col) is None:
                    try:
                        out = texpr.evaluate(expr, _ScalarProvider(record))
                    except TypeError:
                        record[col] = None
                        continue
                    record[col] = _scalar(out)
        # 3. enrichers
        for fn in self._enrichers:
            fn(record)
        # 4. schema conversion + null handling (ref DataTypeTransformer /
        #    NullValueTransformer): coerce to stored type, defaults for nulls
        out_rec: Dict[str, Any] = {}
        for spec in self.schema.fields:
            if spec.virtual:
                continue
            v = record.get(spec.name)
            if spec.single_value:
                out_rec[spec.name] = (spec.data_type.convert(v)
                                      if v is not None else None)
            else:
                if v is None:
                    v = []
                elif not isinstance(v, (list, tuple)):
                    v = [v]
                out_rec[spec.name] = [spec.data_type.convert(x) for x in v]
        return out_rec


def _scalar(v: Any) -> Any:
    arr = np.asarray(v).reshape(-1)
    x = arr[0]
    return x.item() if isinstance(x, np.generic) else x
