"""Minion role: the distributed background-task worker.

Reference parity: pinot-minion (ServiceRole.MINION, SURVEY.md L7) — the
fourth runtime role. Workers register with the controller, lease tasks
matching their declared task types from the controller's durable queue
(controller/task_manager.py), run the existing TaskExecutors
(controller/tasks.py) in a sandboxed work dir, stream progress +
lease-renewal heartbeats over the coordination channel, and commit
results through the atomic segment-replace protocol: upload outputs to
the deep store, then one controller-side swap that moves the routing
epoch (invalidating result caches) and lets servers warm the new segment
before it serves.
"""
from pinot_tpu.minion.worker import MinionTaskContext, MinionWorker

__all__ = ["MinionWorker", "MinionTaskContext"]
