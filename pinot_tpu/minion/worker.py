"""Minion worker: lease -> execute -> upload -> atomic swap -> complete.

The fault-tolerant segment lifecycle, end to end:

1. **Lease.** The worker polls ``task_lease`` with its declared task
   types. The controller grants the oldest leasable PENDING task and
   starts the lease TTL clock.
2. **Heartbeat.** While the task runs, a heartbeat thread renews the
   lease (``task_renew``) every few seconds, streaming a progress string
   and learning about cancel requests. A worker that dies simply stops
   renewing — the controller's expiry sweep requeues the task with
   capped exponential backoff, and another worker picks it up.
3. **Execute.** The existing TaskExecutors (controller/tasks.py) run
   unchanged against a ``MinionTaskContext`` — a collecting context over
   the controller's state snapshot: ``publish_segment``/``retire_segment``
   record the intended swap instead of mutating anything.
4. **Commit (idempotent).** Output segments upload to the deep store
   under their deterministic names, then a MANIFEST (the commit intent:
   adds + removes + result) is written at a task-id-keyed store URI, and
   finally ONE ``segment_replace`` asks the controller for the atomic
   swap. A task re-leased after a crash anywhere in this sequence
   converges: before the manifest exists it re-executes (deterministic
   names make re-upload an overwrite, not a duplicate); after, the
   worker skips execution entirely and replays the swap, which the
   controller applies idempotently.

Chaos: the ``minion.task.execute`` failpoint fires as execution starts;
arming it with a ``SimulatedCrash`` error makes the worker vanish
mid-task without reporting anything — the lease-expiry recovery path in
one deterministic test.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import threading
import time
from typing import List, Optional, Tuple

from pinot_tpu.controller.cluster_state import SegmentState
from pinot_tpu.controller.coordination import CoordinationClient
from pinot_tpu.controller.tasks import TaskConfig, run_task
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.segment.loader import ImmutableSegment, load_segment
from pinot_tpu.utils.failpoints import SimulatedCrash, fire

log = logging.getLogger(__name__)


class _TaskAborted(RuntimeError):
    """Raised inside a task run when the controller requested cancel."""


class MinionTaskContext:
    """TaskContext over a cluster-state SNAPSHOT: reads resolve from the
    controller's state blob; publish/retire COLLECT the swap instead of
    applying it (the worker commits through segment_replace)."""

    def __init__(self, blob: dict, output_dir: str, task_id: str = ""):
        self.blob = blob
        self.output_dir = output_dir
        self.task_id = task_id
        self.published: List[SegmentState] = []
        self.retired: List[Tuple[str, str]] = []

    def table_config(self, physical_table: str) -> TableConfig:
        base = physical_table.rsplit("_", 1)[0]
        return TableConfig.from_dict(self.blob["tables"][base])

    def schema_for(self, physical_table: str) -> Schema:
        base = physical_table.rsplit("_", 1)[0]
        return Schema.from_dict(self.blob["schemas"][base])

    def segment_state(self, table: str, name: str) -> SegmentState:
        return SegmentState.from_dict(
            self.blob["segments"].get(table, {})[name])

    def publish_segment(self, st: SegmentState) -> None:
        self.published.append(st)

    def retire_segment(self, table: str, name: str) -> None:
        self.retired.append((table, name))

    def load(self, table: str, name: str) -> ImmutableSegment:
        from pinot_tpu.segment.fs import localize_segment
        st = self.segment_state(table, name)
        local = localize_segment(
            st.dir_path, os.path.join(self.output_dir, "_downloads"))
        return load_segment(local)


class MinionWorker:
    """One minion worker instance (ref MinionStarter + TaskFactoryRegistry
    executor threads): an executor POOL runs up to
    ``pinot.minion.executor.concurrency`` tasks concurrently — each with
    its own lease-heartbeat thread — with per-type caps layered on via
    ``pinot.minion.executor.concurrency.<TaskType>`` (a heavyweight type
    like MergeRollupTask can be capped to 1 while cheap purges fill the
    remaining slots). The lease request only names types with a free
    slot, so the controller never hands this worker work it would have
    to sit on."""

    def __init__(self, instance_id: str, coordinator: str,
                 work_dir: Optional[str] = None,
                 task_types: Optional[List[str]] = None,
                 config=None, metrics=None):
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.metrics import get_registry
        cfg = config or PinotConfiguration()
        self._config = cfg
        self.instance_id = instance_id
        self.client = CoordinationClient(coordinator)
        self.poll_s = cfg.get_float("pinot.minion.poll.seconds")
        self.heartbeat_s = cfg.get_float("pinot.minion.heartbeat.seconds")
        types = task_types
        if types is None:
            raw = cfg.get_str("pinot.minion.task.types")
            types = [t.strip() for t in raw.split(",") if t.strip()] or None
        self.task_types = types  # None = all registered task types
        self.concurrency = max(
            1, cfg.get_int("pinot.minion.executor.concurrency"))
        #: distributed tracing: every task runs under a span tree (the
        #: submitter's TraceContext from params when shipped, else a
        #: fresh trace id); the tree returns in task_complete's result
        self.trace_enabled = cfg.get_bool("pinot.trace.enabled", True)
        self._slow_task_ms = cfg.get_float(
            "pinot.minion.slow.task.threshold.ms")
        self._trace_capacity = cfg.get_int("pinot.trace.store.capacity")
        self.work_dir = work_dir or cfg.get_str("pinot.minion.work.dir") \
            or tempfile.mkdtemp(prefix=f"pinot_tpu_minion_{instance_id}_")
        self._metrics = metrics if metrics is not None \
            else get_registry("minion")
        self._labels = {"minion": instance_id}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: running task_id -> (task_type, thread); the pool's ledger
        self._running: dict = {}
        self._rlock = threading.Lock()
        #: set on a SimulatedCrash: the whole worker vanished — no task
        #: thread may report/commit anything from that point on
        self._vanished = threading.Event()
        #: observability for tests: tasks this worker actually EXECUTED
        #: vs. commits it merely replayed from a found manifest
        self.executed = 0
        self.manifest_resumes = 0
        self.crashed = False

    # ------------------------------------------------------------------
    def start(self) -> None:
        os.makedirs(self.work_dir, exist_ok=True)
        self.client.register_instance(self.instance_id, "127.0.0.1", 0,
                                      tags=["minion"])
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"minion-{self.instance_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._rlock:
            threads = [t for _type, t in self._running.values()]
        for t in threads:
            t.join(timeout=10)
        self.client.close()

    # -- executor pool --------------------------------------------------
    def _type_cap(self, task_type: str) -> int:
        cap = self._config.get_int(
            f"pinot.minion.executor.concurrency.{task_type}",
            self.concurrency)
        return max(1, min(cap, self.concurrency))

    def _leasable_types(self) -> List[str]:
        """Types this worker can take RIGHT NOW: declared (or all
        registered) types whose per-type running count is under its cap.
        Empty when the pool is full."""
        from pinot_tpu.controller.tasks import registered_task_types
        with self._rlock:
            if len(self._running) >= self.concurrency:
                return []
            counts: dict = {}
            for ttype, _t in self._running.values():
                counts[ttype] = counts.get(ttype, 0) + 1
        types = self.task_types if self.task_types is not None \
            else registered_task_types()
        return [t for t in types if counts.get(t, 0) < self._type_cap(t)]

    def running_tasks(self) -> int:
        with self._rlock:
            return len(self._running)

    def _loop(self) -> None:
        last_hb = 0.0
        while not self._stop.is_set():
            # instance-level liveness heartbeat (distinct from per-task
            # lease renewal): the controller's sweep and the REST
            # /instances fleet-health view tag this worker live/stale
            # from it, so even an idle minion keeps reporting
            if time.monotonic() - last_hb >= self.heartbeat_s:
                try:
                    self.client.request("heartbeat",
                                        instance_id=self.instance_id)
                    last_hb = time.monotonic()
                except (ConnectionError, OSError, RuntimeError):
                    pass
            eligible = self._leasable_types()
            if not eligible:
                self._stop.wait(self.poll_s)
                continue
            try:
                r = self.client.request("task_lease",
                                        worker=self.instance_id,
                                        task_types=eligible)
                entry = r.get("task")
            except (ConnectionError, OSError, RuntimeError):
                entry = None  # controller briefly unreachable: keep polling
            if entry is None:
                self._stop.wait(self.poll_s)
                continue
            t = threading.Thread(
                target=self._task_thread, args=(entry,), daemon=True,
                name=f"minion-task-{entry['task_id'][:24]}")
            with self._rlock:
                self._running[entry["task_id"]] = (entry["task_type"], t)
            self._metrics.set_gauge("minion_running_tasks",
                                    len(self._running),
                                    labels=self._labels)
            t.start()

    def _run_traced(self, entry: dict) -> None:
        """Run one task under a span tree: the submitter's TraceContext
        (params["traceContext"]) joins the submitting query's trace when
        shipped; otherwise the task gets its own trace id. The tree
        ships back in task_complete's result (retrievable via
        /tasks/{id}) and tail-captures into the minion trace store when
        the task runs over pinot.minion.slow.task.threshold.ms."""
        if not self.trace_enabled:
            return self._run_task(entry)
        from pinot_tpu.utils import tracing, trace_store
        tc = tracing.TraceContext.from_wire(
            (entry.get("params") or {}).get("traceContext"))
        rt = tracing.RequestTrace(
            request_id=entry["task_id"], operator="MinionTask",
            trace_id=tc.trace_id if tc is not None else None,
            sampled=bool(tc is not None and tc.sampled),
            minion=self.instance_id, taskType=entry["task_type"],
            table=entry["table"])
        created = entry.get("created_at") or 0.0
        if created:
            rt.handle().set(queueWaitMs=round(
                max(0.0, time.time() - created) * 1000.0, 3))
        try:
            with rt:
                self._run_task(entry)
        finally:
            dur = rt.root.duration_ms
            slow = self._slow_task_ms > 0 and dur >= self._slow_task_ms
            if rt.sampled or slow:
                trace_store.get_store("minion", self._trace_capacity).record(
                    rt.trace_id, rt.to_dict(),
                    sql=f"task:{entry['task_type']}", duration_ms=dur,
                    slow=slow, extra={"taskId": entry["task_id"],
                                      "minion": self.instance_id})
                if slow:
                    trace_store.log_slow_query(
                        "minion", rt.trace_id,
                        f"task:{entry['task_type']}", dur,
                        self._slow_task_ms, taskId=entry["task_id"])

    def _task_thread(self, entry: dict) -> None:
        try:
            self._run_traced(entry)
        except SimulatedCrash:
            # chaos kill: vanish WITHOUT reporting — recovery must
            # come from lease expiry, exactly like a dead process.
            # Sibling tasks on this worker die with it (their report
            # paths are gated on _vanished).
            self.crashed = True
            self._vanished.set()
            self._stop.set()
            log.warning("minion %s simulated crash on %s",
                        self.instance_id, entry["task_id"])
        finally:
            with self._rlock:
                self._running.pop(entry["task_id"], None)
                self._metrics.set_gauge("minion_running_tasks",
                                        len(self._running),
                                        labels=self._labels)

    # ------------------------------------------------------------------
    def _run_task(self, entry: dict) -> None:
        task = TaskConfig(entry["task_type"], entry["table"],
                          list(entry["segments"]), dict(entry["params"]),
                          task_id=entry["task_id"])
        task_id = task.task_id
        sandbox = os.path.join(self.work_dir, task_id)
        os.makedirs(sandbox, exist_ok=True)
        cancel = threading.Event()
        lost = threading.Event()
        hb_stop = threading.Event()
        hb = threading.Thread(
            target=self._heartbeat_loop,
            args=(task_id, hb_stop, cancel, lost),
            daemon=True, name=f"minion-hb-{task_id[:18]}")
        hb.start()
        t0 = time.perf_counter()
        try:
            # chaos site: the canonical place to kill/delay a worker
            # mid-task (ISSUE 5 acceptance scenario)
            fire("minion.task.execute", worker=self.instance_id,
                 task_id=task_id, task_type=task.task_type)
            blob = self.client.get_state()
            store = self._store(blob)
            manifest = self._read_manifest(store, task_id)
            if manifest is None:
                from pinot_tpu.utils import tracing
                with tracing.Scope("TaskExecute",
                                   taskType=task.task_type):
                    adds, removes, result = self._execute(
                        task, blob, sandbox, cancel)
                self._report_progress(task_id, "uploading")
                with tracing.Scope("TaskUpload", outputs=len(adds)):
                    adds = self._upload_outputs(store, adds)
                manifest = {"taskId": task_id,
                            "adds": [a.to_dict() for a in adds],
                            "removes": [list(r) for r in removes],
                            "result": result}
                self._write_manifest(store, task_id, manifest)
            else:
                # crash-mid-commit recovery: outputs are already durable
                # — skip execution, just replay the swap
                self.manifest_resumes += 1
                self._metrics.add_meter("minion_manifest_resumes",
                                        labels=self._labels)
            if cancel.is_set():
                raise _TaskAborted("cancelled by controller")
            if lost.is_set():
                return  # lease lost: someone else owns the task now
            if self._vanished.is_set():
                return  # a sibling crashed the worker: commit nothing
            self._report_progress(task_id, "committing")
            from pinot_tpu.utils import tracing
            with tracing.Scope("TaskCommit",
                               adds=len(manifest["adds"]),
                               removes=len(manifest["removes"])):
                # the COMMIT is the swap; task_complete below is the
                # reporting call that carries the finished tree
                self.client.request(
                    "segment_replace", task_id=task_id,
                    adds=manifest["adds"], removes=manifest["removes"])
            result = manifest["result"]
            req = tracing.current_request()
            if req is not None:
                # the task's span tree rides the completion record: the
                # controller stores it on the TaskEntry, so /tasks/{id}
                # shows WHERE a slow task spent its time. The enclosing
                # RequestTrace is still open — stamp the root duration
                # with the elapsed-so-far so the shipped tree's total is
                # honest, not 0.0
                req.root.duration_ms = \
                    time.perf_counter() * 1000.0 - req.root.start_ms
                result = dict(result) if isinstance(result, dict) else \
                    {"value": result}
                result["traceId"] = req.trace_id
                result["trace"] = req.to_dict()
            self.client.request("task_complete", task_id=task_id,
                                worker=self.instance_id,
                                result=result)
            self._metrics.add_timing(
                "minion_task_duration_ms",
                (time.perf_counter() - t0) * 1000.0,
                labels={"taskType": task.task_type},
                exemplar=tracing.current_trace_id())
            if store is not None:
                # outputs are durable in the deep store; without one the
                # sandbox IS the committed segments' home — keep it
                shutil.rmtree(sandbox, ignore_errors=True)
        except SimulatedCrash:
            raise
        except _TaskAborted as e:
            self._report_fail(task_id, str(e), cancelled=True)
        except Exception as e:  # noqa: BLE001 — report and move on
            log.exception("task %s failed on %s", task_id, self.instance_id)
            self._report_fail(task_id, f"{type(e).__name__}: {e}")
        finally:
            hb_stop.set()

    def _execute(self, task: TaskConfig, blob: dict, sandbox: str,
                 cancel: threading.Event):
        self.executed += 1
        self._report_progress(task.task_id, "executing")
        ctx = MinionTaskContext(blob, sandbox, task_id=task.task_id)
        result = run_task(task, ctx)
        if cancel.is_set():
            raise _TaskAborted("cancelled by controller")
        return ctx.published, ctx.retired, result

    # -- commit plumbing ------------------------------------------------
    @staticmethod
    def _store(blob: dict):
        uri = blob.get("deep_store_uri")
        if not uri:
            return None
        from pinot_tpu.segment.fs import SegmentDeepStore
        return SegmentDeepStore(uri)

    def _upload_outputs(self, store,
                        adds: List[SegmentState]) -> List[SegmentState]:
        """Push built segments to the deep store; their SegmentState then
        carries the durable URI. Without a store the local build dir is
        registered as-is (single-box deployments) — the sandbox is then
        the segment's home and must not be cleaned on failure."""
        if store is None:
            return adds
        for st in adds:
            st.dir_path = store.upload(st.dir_path, st.table, st.name)
        return adds

    def _manifest_uri(self, store, task_id: str) -> str:
        return f"{store.base_uri}/_tasks/{task_id}.json"

    def _read_manifest(self, store, task_id: str) -> Optional[dict]:
        if store is None:
            return None
        uri = self._manifest_uri(store, task_id)
        try:
            if not store.fs.exists(uri):
                return None
            with tempfile.NamedTemporaryFile(suffix=".json",
                                             delete=False) as tmp:
                tmp_path = tmp.name
            try:
                store.fs.copy_to_local(uri, tmp_path)
                with open(tmp_path, encoding="utf-8") as f:
                    return json.load(f)
            finally:
                os.remove(tmp_path)
        except (OSError, ValueError):
            return None  # torn/unreadable manifest: re-execute from scratch

    def _write_manifest(self, store, task_id: str, manifest: dict) -> None:
        if store is None:
            return
        with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False,
                                         encoding="utf-8") as tmp:
            json.dump(manifest, tmp)
            tmp_path = tmp.name
        try:
            store.fs.copy_from_local(tmp_path,
                                     self._manifest_uri(store, task_id))
        finally:
            os.remove(tmp_path)

    # -- heartbeats -----------------------------------------------------
    def _heartbeat_loop(self, task_id: str, stop: threading.Event,
                        cancel: threading.Event,
                        lost: threading.Event) -> None:
        while not stop.wait(self.heartbeat_s):
            if self._vanished.is_set():
                return  # dead workers don't renew leases
            try:
                r = self.client.request("task_renew", task_id=task_id,
                                        worker=self.instance_id)
            except (ConnectionError, OSError, RuntimeError):
                continue  # controller hiccup: the lease TTL absorbs it
            if r.get("cancelled"):
                cancel.set()
            if not r.get("ok"):
                lost.set()
                return

    def _report_progress(self, task_id: str, progress: str) -> None:
        if self._vanished.is_set():
            return
        try:
            self.client.request("task_renew", task_id=task_id,
                                worker=self.instance_id, progress=progress)
        except (ConnectionError, OSError, RuntimeError):
            pass

    def _report_fail(self, task_id: str, error: str,
                     cancelled: bool = False) -> None:
        if self._vanished.is_set():
            return
        try:
            self.client.request("task_fail", task_id=task_id,
                                worker=self.instance_id, error=error,
                                cancelled=cancelled)
        except (ConnectionError, OSError, RuntimeError):
            log.warning("could not report failure for %s (lease will "
                        "expire)", task_id)
