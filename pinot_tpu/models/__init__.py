"""Logical data model: field specs, schemas, table configs.

Reference parity: pinot-spi/src/main/java/org/apache/pinot/spi/data/FieldSpec.java,
Schema.java, and config/table/TableConfig.java.
"""
from pinot_tpu.models.field_spec import DataType, FieldType, FieldSpec
from pinot_tpu.models.schema import Schema
from pinot_tpu.models.table_config import (
    base_table_name,
    split_physical_table_name,
    TableConfig,
    TableType,
    IndexingConfig,
    StarTreeIndexConfig,
    IngestionConfig,
    StreamIngestionConfig,
    UpsertConfig,
    DedupConfig,
    RoutingConfig,
    TenantConfig,
    QueryConfig,
    RetentionConfig,
)

__all__ = [
    "DataType",
    "FieldType",
    "FieldSpec",
    "Schema",
    "TableConfig",
    "TableType",
    "IndexingConfig",
    "StarTreeIndexConfig",
    "IngestionConfig",
    "StreamIngestionConfig",
    "UpsertConfig",
    "DedupConfig",
    "RoutingConfig",
    "TenantConfig",
    "QueryConfig",
    "RetentionConfig",
    "base_table_name",
    "split_physical_table_name",
]
