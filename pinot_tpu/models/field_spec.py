"""Field specifications: the per-column logical type system.

Reference parity: pinot-spi/src/main/java/org/apache/pinot/spi/data/FieldSpec.java:70
(DataType enum, FieldType enum, default null values, single/multi-value flag).

TPU-first notes: every DataType carries its numpy storage dtype so segment
creation and device upload are zero-ambiguity. STRING/BYTES/JSON are always
dictionary-encoded before they reach the device; numeric types may be either
dictionary-encoded (dictIds on device) or raw (values on device).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


class DataType(enum.Enum):
    """Column storage types (ref FieldSpec.java DataType enum)."""

    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    BIG_DECIMAL = "BIG_DECIMAL"
    BOOLEAN = "BOOLEAN"
    TIMESTAMP = "TIMESTAMP"
    STRING = "STRING"
    JSON = "JSON"
    BYTES = "BYTES"

    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def is_fixed_width(self) -> bool:
        return self in _FIXED_WIDTH

    @property
    def stored_type(self) -> "DataType":
        """The physical type used for storage (ref: BOOLEAN stored as INT,
        TIMESTAMP as LONG millis, JSON as STRING)."""
        if self is DataType.BOOLEAN:
            return DataType.INT
        if self is DataType.TIMESTAMP:
            return DataType.LONG
        if self is DataType.JSON:
            return DataType.STRING
        return self

    @property
    def np_dtype(self) -> np.dtype:
        """Numpy dtype of the stored representation (object for var-width)."""
        return _NP_DTYPES[self.stored_type]

    @property
    def size_bytes(self) -> int:
        """Fixed storage width in bytes; raises for var-width types."""
        st = self.stored_type
        if st not in _FIXED_WIDTH:
            raise ValueError(f"{self} is not fixed-width")
        return _NP_DTYPES[st].itemsize

    def convert(self, value: Any) -> Any:
        """Coerce an ingested python value to this type's stored python value."""
        st = self.stored_type
        if value is None:
            return None
        if st is DataType.INT:
            if isinstance(value, str) and value.lower() in ("true", "false"):
                return 1 if value.lower() == "true" else 0  # BOOLEAN ingest form
            return int(value)
        if st is DataType.LONG:
            return int(value)
        if st in (DataType.FLOAT, DataType.DOUBLE):
            return float(value)
        if st is DataType.BIG_DECIMAL:
            return float(value)
        if st is DataType.STRING:
            return value if isinstance(value, str) else str(value)
        if st is DataType.BYTES:
            return bytes(value)
        raise ValueError(f"unsupported type {self}")


_NUMERIC = {
    DataType.INT,
    DataType.LONG,
    DataType.FLOAT,
    DataType.DOUBLE,
    DataType.BIG_DECIMAL,
}
_FIXED_WIDTH = {
    DataType.INT,
    DataType.LONG,
    DataType.FLOAT,
    DataType.DOUBLE,
    DataType.BIG_DECIMAL,
    DataType.BOOLEAN,
    DataType.TIMESTAMP,
}
_NP_DTYPES = {
    DataType.INT: np.dtype(np.int32),
    DataType.LONG: np.dtype(np.int64),
    DataType.FLOAT: np.dtype(np.float32),
    DataType.DOUBLE: np.dtype(np.float64),
    # BIG_DECIMAL approximated as float64 host-side (exact decimal kept in
    # dictionary string form when dictionary-encoded).
    DataType.BIG_DECIMAL: np.dtype(np.float64),
    DataType.STRING: np.dtype(object),
    DataType.BYTES: np.dtype(object),
}

# Default null placeholder values (ref FieldSpec.java DEFAULT_* constants:
# dimension INT null = Integer.MIN_VALUE, metric null = 0, string null = "null").
_DEFAULT_DIMENSION_NULL = {
    DataType.INT: np.iinfo(np.int32).min,
    DataType.LONG: np.iinfo(np.int64).min,
    DataType.FLOAT: float(np.finfo(np.float32).min),
    DataType.DOUBLE: float(np.finfo(np.float64).min),
    DataType.BIG_DECIMAL: 0.0,
    DataType.BOOLEAN: 0,
    DataType.TIMESTAMP: 0,
    DataType.STRING: "null",
    DataType.JSON: "null",
    DataType.BYTES: b"",
}
_DEFAULT_METRIC_NULL = {
    DataType.INT: 0,
    DataType.LONG: 0,
    DataType.FLOAT: 0.0,
    DataType.DOUBLE: 0.0,
    DataType.BIG_DECIMAL: 0.0,
    DataType.BOOLEAN: 0,
    DataType.TIMESTAMP: 0,
    DataType.STRING: "null",
    DataType.JSON: "null",
    DataType.BYTES: b"",
}


class FieldType(enum.Enum):
    """Role of a field (ref FieldSpec.java FieldType enum)."""

    DIMENSION = "DIMENSION"
    METRIC = "METRIC"
    TIME = "TIME"
    DATE_TIME = "DATE_TIME"
    COMPLEX = "COMPLEX"


@dataclass
class FieldSpec:
    name: str
    data_type: DataType
    field_type: FieldType = FieldType.DIMENSION
    single_value: bool = True
    default_null_value: Optional[Any] = None
    # DATE_TIME extras (ref DateTimeFieldSpec): format/granularity strings.
    format: Optional[str] = None
    granularity: Optional[str] = None
    max_length: int = 512
    # Virtual columns ($docId, $segmentName) are not stored.
    virtual: bool = False

    def __post_init__(self):
        if isinstance(self.data_type, str):
            self.data_type = DataType(self.data_type)
        if isinstance(self.field_type, str):
            self.field_type = FieldType(self.field_type)
        if self.default_null_value is None:
            if self.field_type is FieldType.METRIC:
                self.default_null_value = _DEFAULT_METRIC_NULL[self.data_type]
            else:
                self.default_null_value = _DEFAULT_DIMENSION_NULL[self.data_type]
        else:
            self.default_null_value = self.data_type.convert(self.default_null_value)

    # -- (de)serialization --------------------------------------------------
    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "dataType": self.data_type.value,
            "fieldType": self.field_type.value,
            "singleValueField": self.single_value,
            "defaultNullValue": _json_safe(self.default_null_value),
        }
        if self.format:
            d["format"] = self.format
        if self.granularity:
            d["granularity"] = self.granularity
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FieldSpec":
        return cls(
            name=d["name"],
            data_type=DataType(d["dataType"]),
            field_type=FieldType(d.get("fieldType", "DIMENSION")),
            single_value=d.get("singleValueField", True),
            default_null_value=d.get("defaultNullValue"),
            format=d.get("format"),
            granularity=d.get("granularity"),
        )


def _json_safe(v: Any) -> Any:
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        v = float(v)
    if isinstance(v, float) and not np.isfinite(v):
        return None  # NaN/inf are not valid JSON
    return v
