"""Table schema: ordered collection of FieldSpecs.

Reference parity: pinot-spi/src/main/java/org/apache/pinot/spi/data/Schema.java:65
(dimension/metric/dateTime field grouping, JSON serde, primary-key columns).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pinot_tpu.models.field_spec import DataType, FieldSpec, FieldType


@dataclass
class Schema:
    name: str
    fields: List[FieldSpec] = field(default_factory=list)
    primary_key_columns: List[str] = field(default_factory=list)

    def __post_init__(self):
        self._by_name: Dict[str, FieldSpec] = {f.name: f for f in self.fields}

    # -- builder-style API --------------------------------------------------
    def add_field(self, spec: FieldSpec) -> "Schema":
        if spec.name in self._by_name:
            raise ValueError(f"duplicate field {spec.name!r} in schema {self.name!r}")
        self.fields.append(spec)
        self._by_name[spec.name] = spec
        return self

    def add_dimension(self, name: str, data_type: DataType, **kw) -> "Schema":
        return self.add_field(FieldSpec(name, data_type, FieldType.DIMENSION, **kw))

    def add_metric(self, name: str, data_type: DataType, **kw) -> "Schema":
        return self.add_field(FieldSpec(name, data_type, FieldType.METRIC, **kw))

    def add_date_time(self, name: str, data_type: DataType, fmt: str = "1:MILLISECONDS:EPOCH",
                      granularity: str = "1:MILLISECONDS", **kw) -> "Schema":
        return self.add_field(
            FieldSpec(name, data_type, FieldType.DATE_TIME, format=fmt,
                      granularity=granularity, **kw))

    # -- lookups ------------------------------------------------------------
    def field_spec(self, name: str) -> FieldSpec:
        spec = self._by_name.get(name)
        if spec is None:
            raise KeyError(f"column {name!r} not in schema {self.name!r}")
        return spec

    def has_column(self, name: str) -> bool:
        return name in self._by_name

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def dimension_names(self) -> List[str]:
        return [f.name for f in self.fields if f.field_type is FieldType.DIMENSION]

    @property
    def metric_names(self) -> List[str]:
        return [f.name for f in self.fields if f.field_type is FieldType.METRIC]

    @property
    def date_time_names(self) -> List[str]:
        return [f.name for f in self.fields
                if f.field_type in (FieldType.TIME, FieldType.DATE_TIME)]

    # -- serde --------------------------------------------------------------
    def to_dict(self) -> dict:
        d: dict = {"schemaName": self.name}
        dims, mets, dts = [], [], []
        for f in self.fields:
            if f.field_type is FieldType.METRIC:
                mets.append(f.to_dict())
            elif f.field_type in (FieldType.TIME, FieldType.DATE_TIME):
                dts.append(f.to_dict())
            else:
                dims.append(f.to_dict())
        if dims:
            d["dimensionFieldSpecs"] = dims
        if mets:
            d["metricFieldSpecs"] = mets
        if dts:
            d["dateTimeFieldSpecs"] = dts
        if self.primary_key_columns:
            d["primaryKeyColumns"] = self.primary_key_columns
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Schema":
        schema = cls(name=d.get("schemaName", ""))
        for fd in d.get("dimensionFieldSpecs", []):
            fd.setdefault("fieldType", "DIMENSION")
            schema.add_field(FieldSpec.from_dict(fd))
        for fd in d.get("metricFieldSpecs", []):
            fd["fieldType"] = "METRIC"
            schema.add_field(FieldSpec.from_dict(fd))
        for fd in d.get("dateTimeFieldSpecs", []):
            fd["fieldType"] = "DATE_TIME"
            schema.add_field(FieldSpec.from_dict(fd))
        schema.primary_key_columns = d.get("primaryKeyColumns", [])
        return schema

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "Schema":
        return cls.from_dict(json.loads(s))
