"""Table configuration: indexing, ingestion, upsert/dedup, routing, retention.

Reference parity: pinot-spi/src/main/java/org/apache/pinot/spi/config/table/
TableConfig.java:38 and its sub-configs (IndexingConfig, UpsertConfig,
DedupConfig, RoutingConfig, TenantConfig, StarTreeIndexConfig...).
"""
from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class TableType(enum.Enum):
    OFFLINE = "OFFLINE"
    REALTIME = "REALTIME"


@dataclass
class StarTreeIndexConfig:
    """Ref: spi/config/table/StarTreeIndexConfig.java."""

    dimensions_split_order: List[str] = field(default_factory=list)
    skip_star_node_creation: List[str] = field(default_factory=list)
    function_column_pairs: List[str] = field(default_factory=list)  # e.g. "SUM__revenue"
    max_leaf_records: int = 10_000

    def to_dict(self) -> dict:
        return {
            "dimensionsSplitOrder": self.dimensions_split_order,
            "skipStarNodeCreationForDimensions": self.skip_star_node_creation,
            "functionColumnPairs": self.function_column_pairs,
            "maxLeafRecords": self.max_leaf_records,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StarTreeIndexConfig":
        return cls(
            dimensions_split_order=d.get("dimensionsSplitOrder", []),
            skip_star_node_creation=d.get("skipStarNodeCreationForDimensions", []),
            function_column_pairs=d.get("functionColumnPairs", []),
            max_leaf_records=d.get("maxLeafRecords", 10_000),
        )


@dataclass
class IndexingConfig:
    """Ref: spi/config/table/IndexingConfig.java."""

    inverted_index_columns: List[str] = field(default_factory=list)
    range_index_columns: List[str] = field(default_factory=list)
    bloom_filter_columns: List[str] = field(default_factory=list)
    sorted_column: Optional[str] = None
    no_dictionary_columns: List[str] = field(default_factory=list)
    on_heap_dictionary_columns: List[str] = field(default_factory=list)
    json_index_columns: List[str] = field(default_factory=list)
    text_index_columns: List[str] = field(default_factory=list)
    fst_index_columns: List[str] = field(default_factory=list)
    vector_index_columns: List[str] = field(default_factory=list)
    geo_index_columns: List[str] = field(default_factory=list)
    map_index_columns: List[str] = field(default_factory=list)
    clp_columns: List[str] = field(default_factory=list)
    star_tree_configs: List[StarTreeIndexConfig] = field(default_factory=list)
    # Chunk compression for raw (no-dictionary) columns.
    compression: str = "LZ4"  # PASS_THROUGH | LZ4 | GZIP | ZSTANDARD
    segment_flush_rows: int = 500_000  # realtime seal threshold
    segment_flush_seconds: int = 6 * 3600

    def to_dict(self) -> dict:
        return {
            "invertedIndexColumns": self.inverted_index_columns,
            "rangeIndexColumns": self.range_index_columns,
            "bloomFilterColumns": self.bloom_filter_columns,
            "sortedColumn": self.sorted_column,
            "noDictionaryColumns": self.no_dictionary_columns,
            "onHeapDictionaryColumns": self.on_heap_dictionary_columns,
            "jsonIndexColumns": self.json_index_columns,
            "textIndexColumns": self.text_index_columns,
            "fstIndexColumns": self.fst_index_columns,
            "vectorIndexColumns": self.vector_index_columns,
            "geoIndexColumns": self.geo_index_columns,
            "mapIndexColumns": self.map_index_columns,
            "clpColumns": self.clp_columns,
            "starTreeIndexConfigs": [c.to_dict() for c in self.star_tree_configs],
            "compression": self.compression,
            "segmentFlushRows": self.segment_flush_rows,
            "segmentFlushSeconds": self.segment_flush_seconds,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "IndexingConfig":
        return cls(
            inverted_index_columns=d.get("invertedIndexColumns", []),
            range_index_columns=d.get("rangeIndexColumns", []),
            bloom_filter_columns=d.get("bloomFilterColumns", []),
            sorted_column=d.get("sortedColumn"),
            no_dictionary_columns=d.get("noDictionaryColumns", []),
            on_heap_dictionary_columns=d.get("onHeapDictionaryColumns", []),
            json_index_columns=d.get("jsonIndexColumns", []),
            text_index_columns=d.get("textIndexColumns", []),
            fst_index_columns=d.get("fstIndexColumns", []),
            vector_index_columns=d.get("vectorIndexColumns", []),
            geo_index_columns=d.get("geoIndexColumns", []),
            map_index_columns=d.get("mapIndexColumns", []),
            clp_columns=d.get("clpColumns", []),
            star_tree_configs=[StarTreeIndexConfig.from_dict(c)
                               for c in d.get("starTreeIndexConfigs", [])],
            compression=d.get("compression", "LZ4"),
            segment_flush_rows=d.get("segmentFlushRows", 500_000),
            segment_flush_seconds=d.get("segmentFlushSeconds", 6 * 3600),
        )


@dataclass
class StreamIngestionConfig:
    """Ref: spi/stream/StreamConfig.java — stream type + consumer factory props."""

    stream_type: str = "memory"  # memory | kafka | file
    topic: str = ""
    consumer_factory: str = ""
    properties: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"streamType": self.stream_type, "topic": self.topic,
                "consumerFactory": self.consumer_factory, "properties": self.properties}

    @classmethod
    def from_dict(cls, d: dict) -> "StreamIngestionConfig":
        return cls(stream_type=d.get("streamType", "memory"), topic=d.get("topic", ""),
                   consumer_factory=d.get("consumerFactory", ""),
                   properties=d.get("properties", {}))


@dataclass
class IngestionConfig:
    """Ref: spi/config/table/ingestion/IngestionConfig.java — transforms + filters."""

    # list of {"columnName": ..., "transformFunction": ...}
    transform_configs: List[Dict[str, str]] = field(default_factory=list)
    filter_function: Optional[str] = None
    stream: Optional[StreamIngestionConfig] = None

    def to_dict(self) -> dict:
        d: dict = {"transformConfigs": self.transform_configs}
        if self.filter_function:
            d["filterFunction"] = self.filter_function
        if self.stream:
            d["streamIngestionConfig"] = self.stream.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "IngestionConfig":
        stream = d.get("streamIngestionConfig")
        return cls(
            transform_configs=d.get("transformConfigs", []),
            filter_function=d.get("filterFunction"),
            stream=StreamIngestionConfig.from_dict(stream) if stream else None,
        )


@dataclass
class UpsertConfig:
    """Ref: spi/config/table/UpsertConfig.java — FULL or PARTIAL mode."""

    mode: str = "FULL"  # FULL | PARTIAL
    comparison_column: Optional[str] = None
    partial_upsert_strategies: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"mode": self.mode, "comparisonColumn": self.comparison_column,
                "partialUpsertStrategies": self.partial_upsert_strategies}

    @classmethod
    def from_dict(cls, d: dict) -> "UpsertConfig":
        return cls(mode=d.get("mode", "FULL"), comparison_column=d.get("comparisonColumn"),
                   partial_upsert_strategies=d.get("partialUpsertStrategies", {}))


@dataclass
class DedupConfig:
    """Ref: spi/config/table/DedupConfig.java."""

    enabled: bool = True
    hash_function: str = "NONE"

    def to_dict(self) -> dict:
        return {"dedupEnabled": self.enabled, "hashFunction": self.hash_function}

    @classmethod
    def from_dict(cls, d: dict) -> "DedupConfig":
        return cls(enabled=d.get("dedupEnabled", True),
                   hash_function=d.get("hashFunction", "NONE"))


@dataclass
class RoutingConfig:
    """Ref: spi/config/table/RoutingConfig.java — segment pruner + selector
    types, plus the replica-group strategy knobs the reference carries in
    ReplicaGroupStrategyConfig (partitionColumn, numReplicaGroups)."""

    segment_pruner_types: List[str] = field(default_factory=lambda: ["time", "partition"])
    instance_selector_type: str = "balanced"  # balanced | replicaGroup | adaptive
    #: >= 2 makes the table replica-group routed: assignment places one
    #: full copy per group, the broker scatters each query to ONE group
    num_replica_groups: int = 0
    #: column whose EQ/IN literals prune segments before scatter
    partition_column: Optional[str] = None

    def to_dict(self) -> dict:
        return {"segmentPrunerTypes": self.segment_pruner_types,
                "instanceSelectorType": self.instance_selector_type,
                "numReplicaGroups": self.num_replica_groups,
                "partitionColumn": self.partition_column}

    @classmethod
    def from_dict(cls, d: dict) -> "RoutingConfig":
        return cls(segment_pruner_types=d.get("segmentPrunerTypes", ["time", "partition"]),
                   instance_selector_type=d.get("instanceSelectorType", "balanced"),
                   num_replica_groups=d.get("numReplicaGroups", 0) or 0,
                   partition_column=d.get("partitionColumn"))


@dataclass
class TenantConfig:
    """Ref: spi/config/table/TenantConfig.java — which tagged server pool
    serves this table, plus the scheduler weight its queries carry in the
    per-tenant weighted-fair queue (server/scheduler.py)."""

    server: str = "DefaultTenant"
    weight: float = 1.0

    def to_dict(self) -> dict:
        return {"server": self.server, "weight": self.weight}

    @classmethod
    def from_dict(cls, d: dict) -> "TenantConfig":
        return cls(server=d.get("server", "DefaultTenant") or "DefaultTenant",
                   weight=float(d.get("weight", 1.0)))


@dataclass
class QueryConfig:
    """Ref: spi/config/table/QueryConfig.java — per-table query overrides."""

    timeout_ms: Optional[int] = None
    max_rows_in_join: Optional[int] = None
    #: broker-enforced QPS quota (ref QuotaConfig maxQueriesPerSecond)
    max_queries_per_second: Optional[float] = None
    expression_override_map: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"timeoutMs": self.timeout_ms, "maxRowsInJoin": self.max_rows_in_join,
                "maxQueriesPerSecond": self.max_queries_per_second,
                "expressionOverrideMap": self.expression_override_map}

    @classmethod
    def from_dict(cls, d: dict) -> "QueryConfig":
        return cls(timeout_ms=d.get("timeoutMs"), max_rows_in_join=d.get("maxRowsInJoin"),
                   max_queries_per_second=d.get("maxQueriesPerSecond"),
                   expression_override_map=d.get("expressionOverrideMap", {}))


@dataclass
class RetentionConfig:
    """Ref: SegmentsValidationAndRetentionConfig — retention + replication."""

    retention_time_unit: str = "DAYS"
    retention_time_value: Optional[int] = None
    replication: int = 1
    time_column: Optional[str] = None

    def to_dict(self) -> dict:
        return {"retentionTimeUnit": self.retention_time_unit,
                "retentionTimeValue": self.retention_time_value,
                "replication": self.replication, "timeColumnName": self.time_column}

    @classmethod
    def from_dict(cls, d: dict) -> "RetentionConfig":
        return cls(retention_time_unit=d.get("retentionTimeUnit", "DAYS"),
                   retention_time_value=d.get("retentionTimeValue"),
                   replication=d.get("replication", 1),
                   time_column=d.get("timeColumnName"))


def split_physical_table_name(table: str):
    """(logical name, 'OFFLINE' | 'REALTIME' | None) for a possibly
    type-suffixed table name — the one shared strip so the many callers
    (routing, quotas, caches, task fabric) can't drift."""
    for suffix in ("_OFFLINE", "_REALTIME"):
        if table.endswith(suffix):
            return table[: -len(suffix)], suffix[1:]
    return table, None


def base_table_name(table: str) -> str:
    """Logical name with any _OFFLINE/_REALTIME suffix stripped."""
    return split_physical_table_name(table)[0]


@dataclass
class TableConfig:
    """Ref: spi/config/table/TableConfig.java:38."""

    name: str  # raw table name, without type suffix
    table_type: TableType = TableType.OFFLINE
    indexing: IndexingConfig = field(default_factory=IndexingConfig)
    ingestion: IngestionConfig = field(default_factory=IngestionConfig)
    routing: RoutingConfig = field(default_factory=RoutingConfig)
    tenants: TenantConfig = field(default_factory=TenantConfig)
    query: QueryConfig = field(default_factory=QueryConfig)
    retention: RetentionConfig = field(default_factory=RetentionConfig)
    upsert: Optional[UpsertConfig] = None
    dedup: Optional[DedupConfig] = None
    # partition column -> (function, numPartitions), ref SegmentPartitionConfig
    partition_config: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    tier_configs: List[Dict[str, Any]] = field(default_factory=list)
    task_configs: Dict[str, Dict[str, str]] = field(default_factory=dict)

    def __post_init__(self):
        if isinstance(self.table_type, str):
            self.table_type = TableType(self.table_type)
        self.validate()

    def validate(self) -> None:
        """Reject config combinations with no correct execution (ref
        TableConfigUtils.validateUpsertAndDedupConfig: upsert tables
        forbid star-tree — pre-agg records cannot honor validDocIds)."""
        if self.upsert and self.indexing.star_tree_configs:
            raise ValueError(
                "star-tree index is not supported on upsert tables: "
                "pre-aggregated records cannot apply validDocIds")

    @property
    def table_name_with_type(self) -> str:
        return f"{self.name}_{self.table_type.value}"

    def to_dict(self) -> dict:
        d = {
            "tableName": self.name,
            "tableType": self.table_type.value,
            "segmentsConfig": self.retention.to_dict(),
            "tableIndexConfig": self.indexing.to_dict(),
            "ingestionConfig": self.ingestion.to_dict(),
            "routing": self.routing.to_dict(),
            "tenants": self.tenants.to_dict(),
            "query": self.query.to_dict(),
            "segmentPartitionConfig": self.partition_config,
            "tierConfigs": self.tier_configs,
            "task": self.task_configs,
        }
        if self.upsert:
            d["upsertConfig"] = self.upsert.to_dict()
        if self.dedup:
            d["dedupConfig"] = self.dedup.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TableConfig":
        return cls(
            name=d["tableName"].rsplit("_OFFLINE", 1)[0].rsplit("_REALTIME", 1)[0],
            table_type=TableType(d.get("tableType", "OFFLINE")),
            indexing=IndexingConfig.from_dict(d.get("tableIndexConfig", {})),
            ingestion=IngestionConfig.from_dict(d.get("ingestionConfig", {})),
            routing=RoutingConfig.from_dict(d.get("routing", {})),
            tenants=TenantConfig.from_dict(d.get("tenants", {})),
            query=QueryConfig.from_dict(d.get("query", {})),
            retention=RetentionConfig.from_dict(d.get("segmentsConfig", {})),
            upsert=UpsertConfig.from_dict(d["upsertConfig"]) if d.get("upsertConfig") else None,
            dedup=DedupConfig.from_dict(d["dedupConfig"]) if d.get("dedupConfig") else None,
            partition_config=d.get("segmentPartitionConfig", {}),
            tier_configs=d.get("tierConfigs", []),
            task_configs=d.get("task", {}),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "TableConfig":
        return cls.from_dict(json.loads(s))
