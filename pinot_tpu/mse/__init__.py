"""Multi-stage query engine (v2): planner + distributed runtime.

Reference parity: pinot-query-planner (QueryEnvironment.java:100 — SQL ->
distributed stage DAG) and pinot-query-runtime (QueryRunner.java:94 —
per-stage operator chains shuffling blocks through mailboxes). The TPU-first
re-design: all intermediate data is COLUMNAR numpy blocks (not row
iterators), operators are vectorized (factorize/searchsorted hash joins,
bincount aggregates), and leaf stages reuse the single-stage device engine
(the reference blesses exactly this layering, QueryRunner.java:258).
"""
from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.sql import parse_mse_sql
from pinot_tpu.mse.planner import plan_query
from pinot_tpu.mse.dispatcher import QueryDispatcher

__all__ = ["Block", "parse_mse_sql", "plan_query", "QueryDispatcher"]
