"""Columnar record batches shuffled between stages.

Reference parity: pinot-common datablock (RowDataBlock/ColumnarDataBlock +
ZeroCopyDataBlockSerde) and pinot-query-runtime TransferableBlock. Here a
block IS a columnar batch (dict-of-numpy-arrays), so every downstream
operator works vectorized; the wire format is a typed binary layout with
raw little-endian numeric buffers (zero-copy on read for numerics).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

_U32 = struct.Struct("<I")

# dtype tag on the wire -> numpy dtype for raw-buffer columns
_NUMERIC_TAGS = {
    b"i4": np.int32, b"i8": np.int64, b"f4": np.float32, b"f8": np.float64,
    b"b1": np.bool_,
}
_DTYPE_TO_TAG = {np.dtype(v): k for k, v in _NUMERIC_TAGS.items()}


class Block:
    """One columnar batch: parallel (name, array) columns of equal length.

    Object-dtype arrays hold strings/None/bytes (variable-width values stay
    host-side per SURVEY §7 hard-parts). Also implements the ColumnProvider
    protocol (query/transform.py) so expressions evaluate directly over it.
    """

    __slots__ = ("names", "arrays", "_index")

    def __init__(self, names: Sequence[str], arrays: Sequence[np.ndarray]):
        assert len(names) == len(arrays)
        if arrays:
            n = len(arrays[0])
            assert all(len(a) == n for a in arrays), \
                [len(a) for a in arrays]
        self.names: List[str] = list(names)
        self.arrays: List[np.ndarray] = [np.asarray(a) for a in arrays]
        self._index: Dict[str, int] = {c: i for i, c in enumerate(self.names)}

    # -- ColumnProvider protocol -------------------------------------------
    def column(self, name: str) -> np.ndarray:
        i = self._index.get(name)
        if i is None:
            # unqualified lookup: match a unique "alias.name" suffix
            hits = [j for j, c in enumerate(self.names)
                    if c.endswith("." + name)]
            if len(hits) == 1:
                i = hits[0]
            elif len(hits) > 1:
                raise KeyError(f"ambiguous column {name!r} in {self.names}")
            else:
                raise KeyError(f"no column {name!r} in {self.names}")
        return self.arrays[i]

    @property
    def num_docs(self) -> int:
        return self.num_rows

    # ----------------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return len(self.arrays[0]) if self.arrays else 0

    def has_column(self, name: str) -> bool:
        try:
            self.column(name)
            return True
        except KeyError:
            return False

    def take(self, idx: np.ndarray) -> "Block":
        return Block(self.names, [a[idx] for a in self.arrays])

    def mask(self, m: np.ndarray) -> "Block":
        return Block(self.names, [a[m] for a in self.arrays])

    def select(self, names: Sequence[str]) -> "Block":
        return Block(list(names), [self.column(c) for c in names])

    def rename(self, names: Sequence[str]) -> "Block":
        return Block(list(names), self.arrays)

    def rows(self) -> List[tuple]:
        return [tuple(_py(a[i]) for a in self.arrays)
                for i in range(self.num_rows)]

    @staticmethod
    def empty(names: Sequence[str]) -> "Block":
        return Block(list(names), [np.empty(0, object) for _ in names])

    @staticmethod
    def concat(blocks: Sequence["Block"]) -> "Block":
        blocks = [b for b in blocks if b is not None]
        if not blocks:
            return Block([], [])
        if len(blocks) == 1:
            return blocks[0]
        names = blocks[0].names
        arrays = []
        for i in range(len(names)):
            cols = [b.arrays[i] for b in blocks]
            dt = np.result_type(*[c.dtype for c in cols]) \
                if all(c.dtype.kind != "O" for c in cols) else np.dtype(object)
            arrays.append(np.concatenate(
                [c.astype(dt, copy=False) for c in cols]))
        return Block(names, arrays)

    def __repr__(self) -> str:
        return f"Block({self.names}, rows={self.num_rows})"

    # -- wire format --------------------------------------------------------
    def to_bytes(self) -> bytes:
        out = [_U32.pack(len(self.names)), _U32.pack(self.num_rows)]
        for name, arr in zip(self.names, self.arrays):
            nb = name.encode()
            out.append(_U32.pack(len(nb)))
            out.append(nb)
            tag = _DTYPE_TO_TAG.get(arr.dtype.base)
            if tag is not None:
                out.append(tag)
                out.append(np.ascontiguousarray(arr).tobytes())
            elif arr.dtype.kind in "iu":
                out.append(b"i8")
                out.append(np.ascontiguousarray(arr, np.int64).tobytes())
            elif arr.dtype.kind == "f":
                out.append(b"f8")
                out.append(np.ascontiguousarray(arr, np.float64).tobytes())
            elif arr.dtype.kind in ("U", "S", "O"):
                out.append(b"vo")
                out.append(_encode_objects(arr))
            else:
                raise TypeError(f"unsupported column dtype {arr.dtype}")
        return b"".join(out)

    @staticmethod
    def from_bytes(buf: bytes) -> "Block":
        pos = 0
        ncols = _U32.unpack_from(buf, pos)[0]; pos += 4
        nrows = _U32.unpack_from(buf, pos)[0]; pos += 4
        names, arrays = [], []
        for _ in range(ncols):
            ln = _U32.unpack_from(buf, pos)[0]; pos += 4
            names.append(buf[pos:pos + ln].decode()); pos += ln
            tag = buf[pos:pos + 2]; pos += 2
            if tag in _NUMERIC_TAGS:
                dt = np.dtype(_NUMERIC_TAGS[tag])
                nb = dt.itemsize * nrows
                arrays.append(np.frombuffer(buf, dt, nrows, pos).copy())
                pos += nb
            elif tag == b"vo":
                arr, pos = _decode_objects(buf, pos, nrows)
                arrays.append(arr)
            else:
                raise ValueError(f"bad column tag {tag!r}")
        return Block(names, arrays)


# -- object-column value serde (str | bytes | int | float | bool | None) ----

def _encode_objects(arr: np.ndarray) -> bytes:
    out = []
    for v in arr:
        v = _py(v)
        if v is None:
            out.append(b"n")
        elif isinstance(v, bool):
            out.append(b"t" if v else b"F")
        elif isinstance(v, int):
            out.append(b"i" + struct.pack("<q", v))
        elif isinstance(v, float):
            out.append(b"d" + struct.pack("<d", v))
        elif isinstance(v, str):
            b = v.encode()
            out.append(b"s" + _U32.pack(len(b)) + b)
        elif isinstance(v, bytes):
            out.append(b"b" + _U32.pack(len(v)) + v)
        else:
            raise TypeError(f"unsupported object value {type(v)}")
    return b"".join(out)


def _decode_objects(buf: bytes, pos: int, n: int):
    vals = np.empty(n, object)
    for i in range(n):
        t = buf[pos:pos + 1]; pos += 1
        if t == b"n":
            vals[i] = None
        elif t == b"t":
            vals[i] = True
        elif t == b"F":
            vals[i] = False
        elif t == b"i":
            vals[i] = struct.unpack_from("<q", buf, pos)[0]; pos += 8
        elif t == b"d":
            vals[i] = struct.unpack_from("<d", buf, pos)[0]; pos += 8
        elif t == b"s":
            ln = _U32.unpack_from(buf, pos)[0]; pos += 4
            vals[i] = buf[pos:pos + ln].decode(); pos += ln
        elif t == b"b":
            ln = _U32.unpack_from(buf, pos)[0]; pos += 4
            vals[i] = buf[pos:pos + ln]; pos += ln
        else:
            raise ValueError(f"bad object tag {t!r}")
    return vals, pos


def _py(v):
    return v.item() if isinstance(v, np.generic) else v
