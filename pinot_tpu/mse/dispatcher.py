"""Broker-side multi-stage dispatch: plan -> workers -> root stage -> rows.

Reference parity: pinot-query-runtime
service/dispatch/QueryDispatcher.java:92 (submitAndReduce: dispatch each
stage to its workers over gRPC, then runReducer pulls the final-stage
mailbox). Here dispatch hands stage JSON to MseWorker endpoints (direct
call in-process; the data plane between workers is real TCP mailboxes)
and the broker runs stage 0 inline.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.logical import Catalog, build_logical
from pinot_tpu.mse.mailbox import MailboxService
from pinot_tpu.mse.planner import QueryPlan, plan_query
from pinot_tpu.mse.runtime import MseWorker, ScanFn, StageContext, run_stage
from pinot_tpu.mse.sql import parse_mse_sql
from pinot_tpu.query.reduce import BrokerResponse, ResultTable
from pinot_tpu.query.results import ExecutionStats

_QUERY_SEQ = itertools.count(1)
_SEQ_LOCK = threading.Lock()


def _resolve_table(data_manager, table: str):
    """Logical name -> TableDataManager (OFFLINE preferred, ref hybrid
    routing; MSE hybrid time-split lands with the time-boundary work)."""
    tdm = data_manager.table(table, create=False)
    if tdm is None:
        for suffix in ("_OFFLINE", "_REALTIME"):
            tdm = data_manager.table(table + suffix, create=False)
            if tdm is not None:
                break
    return tdm


def make_scan_fn(data_manager, engine_fn=None) -> ScanFn:
    """Leaf scan over an instance's local segments: filtered doc ids come
    from the device top-K/selection kernel when an engine is available
    (ref QueryRunner.java:258 — ALL leaf stages ride the v1 engine), with
    numpy fallback per segment; only winning rows materialize."""
    from pinot_tpu.query.filter import SegmentColumnProvider, evaluate_filter
    from pinot_tpu.segment.loader import ImmutableSegment

    def scan(table: str, columns: List[str], filt) -> Block:
        tdm = _resolve_table(data_manager, table)
        if tdm is None:
            return Block(columns, [np.empty(0, object) for _ in columns])
        sdms = tdm.acquire_segments(None)
        try:
            segs = [s.segment for s in sdms]
            # device pushdown for stageable immutable segments
            device_ids: dict = {}
            engine = engine_fn() if engine_fn is not None else None
            if engine is not None and filt is not None:
                candidates = [
                    s for s in segs
                    if isinstance(s, ImmutableSegment)
                    and getattr(s, "valid_doc_ids", None) is None]
                if candidates:
                    ids = engine.filtered_doc_ids(candidates, filt)
                    device_ids = {id(s): ix
                                  for s, ix in zip(candidates, ids)
                                  if ix is not None}
            blocks = []
            for seg in segs:
                provider = SegmentColumnProvider(seg)
                idx = device_ids.get(id(seg))
                if idx is None:
                    mask = evaluate_filter(seg, filt, provider)
                    valid = getattr(seg, "valid_doc_ids", None)
                    if valid is not None:
                        vmask = valid.to_mask()
                        if len(vmask) < seg.num_docs:
                            vmask = np.concatenate(
                                [vmask,
                                 np.zeros(seg.num_docs - len(vmask), bool)])
                        mask = mask & vmask[:seg.num_docs]
                    idx = np.flatnonzero(mask)
                arrays = []
                for c in columns:
                    vals = np.asarray(provider.column(c))
                    if vals.ndim == 0:
                        vals = np.broadcast_to(vals, (seg.num_docs,))
                    arrays.append(vals[idx])
                blocks.append(Block(columns, arrays))
            return Block.concat(blocks) if blocks else \
                Block(columns, [np.empty(0, object) for _ in columns])
        finally:
            type(tdm).release_all(sdms)

    return scan


def make_leaf_query_fn(data_manager, engine_fn=None):
    """Leaf-stage bridge to the single-stage executor (ref
    LeafStageTransferableBlockOperator / QueryRunner.java:258): the leaf
    aggregate runs over the instance's segments through QueryExecutor —
    stacked-device-block TPU path included when engine_fn yields one."""
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.server.data_manager import TableDataManager

    def leaf_query(table: str, qctx):
        tdm = _resolve_table(data_manager, table)
        if tdm is None:
            return []
        sdms = tdm.acquire_segments(None)
        try:
            engine = engine_fn() if engine_fn is not None else None
            ex = QueryExecutor([s.segment for s in sdms],
                               use_tpu=engine is not None, engine=engine)
            results, _ = ex.execute_context(qctx)
            return results
        finally:
            TableDataManager.release_all(sdms)

    return leaf_query


class QueryDispatcher:
    """Multi-stage query entry point on the broker."""

    def __init__(self,
                 workers: Dict[str, MseWorker],
                 catalog_fn: Callable[[], Catalog],
                 table_workers_fn: Callable[[str], List[str]],
                 broker_mailbox: Optional[MailboxService] = None):
        self.workers = workers
        self.catalog_fn = catalog_fn
        self.table_workers_fn = table_workers_fn
        if broker_mailbox is None:
            broker_mailbox = MailboxService("broker")
            broker_mailbox.start()
        self.mailbox = broker_mailbox

    def stop(self) -> None:
        self.mailbox.stop()

    # ------------------------------------------------------------------
    def plan_sql(self, sql: str, parsed=None) -> QueryPlan:
        q = parsed if parsed is not None else parse_mse_sql(sql)
        if q.limit is None:
            q.limit = 10  # Pinot default applies to the outermost query
        logical = build_logical(q, self.catalog_fn())
        return plan_query(logical, q.options, self.table_workers_fn,
                          intermediate_workers=sorted(self.workers))

    def submit(self, sql: str, parsed=None) -> BrokerResponse:
        start = time.time()
        try:
            plan = self.plan_sql(sql, parsed)
            block = self._execute(plan)
        except Exception as e:  # noqa: BLE001 — broker answers, never dies
            resp = BrokerResponse(
                result_table=None,
                exceptions=[{"errorCode": 200,
                             "message": f"{type(e).__name__}: {e}"}],
                stats=ExecutionStats())
            resp.time_used_ms = (time.time() - start) * 1000.0
            return resp
        table = ResultTable(
            columns=list(plan.root.schema),
            column_types=[_infer_type(a) for a in block.arrays],
            rows=block.rows())
        resp = BrokerResponse(result_table=table, exceptions=[],
                              stats=ExecutionStats())
        resp.num_servers_queried = resp.num_servers_responded = \
            len(self.workers)
        resp.time_used_ms = (time.time() - start) * 1000.0
        return resp

    # ------------------------------------------------------------------
    def _execute(self, plan: QueryPlan) -> Block:
        with _SEQ_LOCK:
            qid = f"mse_{next(_QUERY_SEQ)}_{int(time.time() * 1000)}"
        timeout = float(plan.options.get("timeoutMs", 60000)) / 1000.0

        addresses: Dict[str, str] = {}
        for s in plan.stages:
            for w, inst in enumerate(s.workers):
                addr = self.mailbox.address if inst == "broker" \
                    else self.workers[inst].mailbox_address
                addresses[f"{s.stage_id}:{w}"] = addr

        plan_json = {"stages": [s.to_json() for s in plan.stages],
                     "options": plan.options}
        for s in plan.stages[1:]:
            sj = s.to_json()
            for w, inst in enumerate(s.workers):
                self.workers[inst].submit_stage(
                    qid, plan_json, sj, w, addresses, timeout=timeout)

        ctx = StageContext(
            query_id=qid, plan=plan, worker_id="broker", worker_idx=0,
            mailbox=self.mailbox, addresses=addresses, scan_fn=None,
            timeout=timeout)
        block = run_stage(ctx, plan.root)
        assert block is not None
        return block


def _infer_type(arr: np.ndarray) -> str:
    k = arr.dtype.kind
    if k in "iu":
        return "LONG"
    if k == "f":
        return "DOUBLE"
    if k == "b":
        return "BOOLEAN"
    for v in arr:
        if v is None:
            continue
        if isinstance(v, bool):
            return "BOOLEAN"
        if isinstance(v, int):
            return "LONG"
        if isinstance(v, float):
            return "DOUBLE"
        if isinstance(v, bytes):
            return "BYTES"
        return "STRING"
    return "STRING"
