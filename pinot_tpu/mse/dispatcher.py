"""Broker-side multi-stage dispatch: plan -> workers -> root stage -> rows.

Reference parity: pinot-query-runtime
service/dispatch/QueryDispatcher.java:92 (submitAndReduce: dispatch each
stage to its workers over gRPC, then runReducer pulls the final-stage
mailbox). Here dispatch hands stage JSON to MseWorker endpoints (direct
call in-process; the data plane between workers is real TCP mailboxes)
and the broker runs stage 0 inline.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.logical import Catalog, build_logical
from pinot_tpu.mse.mailbox import (
    MailboxAborted, MailboxError, MailboxService, MailboxTimeout)
from pinot_tpu.mse.planner import QueryPlan, plan_query
from pinot_tpu.mse.runtime import MseWorker, ScanFn, StageContext, run_stage
from pinot_tpu.mse.sql import parse_mse_sql
from pinot_tpu.query.reduce import BrokerResponse, ResultTable
from pinot_tpu.query.results import ExecutionStats
from pinot_tpu.utils import errorcodes, tracing
from pinot_tpu.utils.accounting import (
    BrokerTimeoutError, QueryCancelledError)
from pinot_tpu.utils.failpoints import fire

_QUERY_SEQ = itertools.count(1)
_SEQ_LOCK = threading.Lock()


def _resolve_table(data_manager, table: str):
    """Logical name -> TableDataManager (OFFLINE preferred, ref hybrid
    routing; MSE hybrid time-split lands with the time-boundary work)."""
    tdm = data_manager.table(table, create=False)
    if tdm is None:
        for suffix in ("_OFFLINE", "_REALTIME"):
            tdm = data_manager.table(table + suffix, create=False)
            if tdm is not None:
                break
    return tdm


def make_scan_fn(data_manager, engine_fn=None) -> ScanFn:
    """Leaf scan over an instance's local segments: filtered doc ids come
    from the device top-K/selection kernel when an engine is available
    (ref QueryRunner.java:258 — ALL leaf stages ride the v1 engine), with
    numpy fallback per segment; only winning rows materialize."""
    from pinot_tpu.query.filter import SegmentColumnProvider, evaluate_filter
    from pinot_tpu.segment.loader import ImmutableSegment

    def scan(table: str, columns: List[str], filt) -> Block:
        tdm = _resolve_table(data_manager, table)
        if tdm is None:
            return Block(columns, [np.empty(0, object) for _ in columns])
        sdms = tdm.acquire_segments(None)
        try:
            segs = [s.segment for s in sdms]
            # device pushdown for stageable immutable segments
            device_ids: dict = {}
            engine = engine_fn() if engine_fn is not None else None
            if engine is not None and filt is not None:
                # upsert segments included: the device top-K kernel ANDs
                # their validDocIds mask into doc validity, so superseded
                # rows never appear in the returned indices
                candidates = [
                    s for s in segs if isinstance(s, ImmutableSegment)]
                if candidates:
                    ids = engine.filtered_doc_ids(candidates, filt)
                    device_ids = {id(s): ix
                                  for s, ix in zip(candidates, ids)
                                  if ix is not None}
            blocks = []
            for seg in segs:
                snap = getattr(seg, "snapshot", None)
                if snap is not None:
                    seg = snap()  # one consistent doc count per query
                provider = SegmentColumnProvider(seg)
                idx = device_ids.get(id(seg))
                if idx is None:
                    mask = evaluate_filter(seg, filt, provider)
                    valid = getattr(seg, "valid_doc_ids", None)
                    if valid is not None:
                        vmask = valid.to_mask()
                        if len(vmask) < seg.num_docs:
                            vmask = np.concatenate(
                                [vmask,
                                 np.zeros(seg.num_docs - len(vmask), bool)])
                        mask = mask & vmask[:seg.num_docs]
                    idx = np.flatnonzero(mask)
                arrays = []
                for c in columns:
                    vals = np.asarray(provider.column(c))
                    if vals.ndim == 0:
                        vals = np.broadcast_to(vals, (seg.num_docs,))
                    arrays.append(vals[idx])
                blocks.append(Block(columns, arrays))
            return Block.concat(blocks) if blocks else \
                Block(columns, [np.empty(0, object) for _ in columns])
        finally:
            type(tdm).release_all(sdms)

    return scan


def make_leaf_query_fn(data_manager, engine_fn=None):
    """Leaf-stage bridge to the single-stage executor (ref
    LeafStageTransferableBlockOperator / QueryRunner.java:258): the leaf
    aggregate runs over the instance's segments through QueryExecutor —
    stacked-device-block TPU path included when engine_fn yields one."""
    from pinot_tpu.query.executor import QueryExecutor
    from pinot_tpu.server.data_manager import TableDataManager

    def leaf_query(table: str, qctx):
        tdm = _resolve_table(data_manager, table)
        if tdm is None:
            return []
        sdms = tdm.acquire_segments(None)
        try:
            engine = engine_fn() if engine_fn is not None else None
            ex = QueryExecutor([s.segment for s in sdms],
                               use_tpu=engine is not None, engine=engine)
            results, _ = ex.execute_context(qctx)
            return results
        finally:
            TableDataManager.release_all(sdms)

    return leaf_query


def make_segment_versions_fn(data_manager):
    """Version-set provider for the leaf-stage output cache: the sorted
    (name, version) tuple of the instance's local segments for a table,
    or None when ANY segment is non-cacheable (consuming / live upsert
    bitmap) — mirroring cache/segment_cache.py's cacheability rule so a
    mutable tail always re-executes."""
    from pinot_tpu.cache.segment_cache import (
        is_cacheable_segment, segment_version)

    def versions(table: str):
        tdm = _resolve_table(data_manager, table)
        if tdm is None:
            return ()
        sdms = tdm.acquire_segments(None)
        try:
            out = []
            for s in sdms:
                seg = s.segment
                if not is_cacheable_segment(seg):
                    return None
                out.append((seg.name, segment_version(seg)))
            return tuple(sorted(out))
        finally:
            type(tdm).release_all(sdms)

    return versions


class MseQueryTimeout(BrokerTimeoutError):
    """The multi-stage query missed its end-to-end budget."""


def _is_leaf_op(op: Dict[str, object]) -> bool:
    """True when the stage's op tree reads only LOCAL data (no receive
    anywhere) — the only stages a hedge may re-issue: an intermediate's
    mailbox frames were addressed to the primary and cannot be replayed."""
    if op.get("op") == "receive":
        return False
    for k in ("child", "left", "right"):
        child = op.get(k)
        if isinstance(child, dict) and not _is_leaf_op(child):
            return False
    return True


class _HedgeBook:
    """Per-query hedge accounting: which attempts of each (stage,
    worker-slot) are in flight, and which attempt CLAIMED the output.

    The claim is the dedup: `run_stage` asks before sending, exactly one
    attempt per slot is granted, so the receiving mailbox sees exactly
    one EOS per sender slot no matter how many attempts ran. A clean
    finish claims immediately; an errored attempt is granted only when
    every other attempt has already errored or finished — a straggling
    twin might still deliver the rows."""

    def __init__(self):
        self.lock = threading.Lock()
        #: (sid, widx) -> {attempt: instance} still in flight
        self.pending: Dict[tuple, Dict[int, str]] = {}
        #: (sid, widx) -> attempt granted the output
        self.claimed: Dict[tuple, int] = {}
        #: (sid, widx) -> attempts that reached their error claim
        self.errored: Dict[tuple, set] = {}
        #: (sid, widx) -> True once any attempt finished ok
        self.completed: Dict[tuple, bool] = {}
        #: keys with a hedge attempt issued
        self.hedged: set = set()

    def start(self, key: tuple, attempt: int, instance: str) -> None:
        with self.lock:
            self.pending.setdefault(key, {})[attempt] = instance
            if attempt > 0:
                self.hedged.add(key)

    def finish(self, key: tuple, attempt: int, ok: bool) -> bool:
        """Returns True when this finish leaves the slot DEAD: every
        attempt is gone, none completed clean, and no attempt ever
        claimed the output (so neither rows nor an error frame went
        out) — e.g. the primary's error claim was denied while the
        hedge was alive, then the hedge died crash-silent. The caller
        must abort the query, or the receiver blocks to the deadline."""
        with self.lock:
            tracked = key in self.pending  # claim-gated (leaf) slots only
            self.pending.get(key, {}).pop(attempt, None)
            if ok:
                self.completed[key] = True
            return (tracked and not ok and key not in self.claimed
                    and not self.pending.get(key)
                    and not self.completed.get(key))

    def should_hedge(self, key: tuple) -> bool:
        with self.lock:
            return (not self.completed.get(key)
                    and key not in self.claimed
                    and key not in self.hedged)

    def claim(self, key: tuple, attempt: int, clean: bool):
        """Returns (granted, loser) — loser is the (attempt, instance)
        of a still-pending twin the caller should cancel."""
        with self.lock:
            got = self.claimed.get(key)
            if got is not None:
                return got == attempt, None
            if not clean:
                errs = self.errored.setdefault(key, set())
                errs.add(attempt)
                others = {a: i for a, i in self.pending.get(key, {}).items()
                          if a != attempt and a not in errs}
                if others:
                    return False, None  # a live twin may still win
            self.claimed[key] = attempt
            loser = next(
                ((a, i) for a, i in self.pending.get(key, {}).items()
                 if a != attempt), None)
            return True, loser


class QueryDispatcher:
    """Multi-stage query entry point on the broker.

    Reliability (ISSUE 7): one budget — resolved exactly like the
    single-stage handler (``OPTION(timeoutMs)`` >
    ``pinot.broker.mse.timeout.ms`` > ``pinot.broker.timeout.ms``) —
    enters here, travels in every ``submit_stage``, and is enforced
    cooperatively in each stage plus as a hard wall on mailbox waits. A
    miss or a client ``cancel`` fans an out-of-band cancel to every
    worker (aborting in-flight stages and poisoning their mailboxes) and
    the broker answers a typed errorCode-250 partial — never a hang.
    """

    def __init__(self,
                 workers: Dict[str, MseWorker],
                 catalog_fn: Callable[[], Catalog],
                 table_workers_fn: Callable[[str], List[str]],
                 broker_mailbox: Optional[MailboxService] = None,
                 config=None, enforce_deadlines: bool = True,
                 hedge_peers_fn: Optional[
                     Callable[[str, str], List[str]]] = None):
        from pinot_tpu.broker.adaptive import AdaptiveServerSelector
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.metrics import get_registry
        self.workers = workers
        self.catalog_fn = catalog_fn
        self.table_workers_fn = table_workers_fn
        if broker_mailbox is None:
            broker_mailbox = MailboxService(
                "broker", metrics=get_registry("broker"))
            broker_mailbox.start()
        self.mailbox = broker_mailbox
        self.config = config
        #: bench escape hatch: False runs the legacy no-deadline plumbing
        #: so the A/B can price the checks (bench.py --mse)
        self.enforce_deadlines = enforce_deadlines
        self._metrics = get_registry("broker")
        #: query_id -> cancel fan-out record for in-flight queries
        self._inflight: Dict[str, threading.Event] = {}
        self._inflight_lock = threading.Lock()
        # -- stage hedging (ISSUE 10) ----------------------------------
        cfg = config or PinotConfiguration()
        self.hedge_enabled = cfg.get_bool("pinot.broker.mse.hedge.enabled")
        self._hedge_delay_min_s = cfg.get_float(
            "pinot.broker.mse.hedge.delay.min.ms") / 1e3
        self._hedge_delay_max_s = cfg.get_float(
            "pinot.broker.mse.hedge.delay.max.ms") / 1e3
        self._hedge_q = cfg.get_float("pinot.broker.mse.hedge.quantile")
        #: (table, primary instance) -> alternate instances holding an
        #: IDENTICAL local segment view — the only legal hedge targets
        #: for a leaf stage (a different shard would change the rows)
        self.hedge_peers_fn = hedge_peers_fn
        #: per-worker STAGE-latency reservoirs (the same
        #: AdaptiveServerSelector.latency_quantile plumbing the
        #: single-stage hedged scatter uses): every stage completion
        #: feeds them, and the hedge delay is the fleet's q-quantile
        self.stage_latency = AdaptiveServerSelector()

    def _hedge_delay_s(self) -> float:
        base = self.stage_latency.latency_quantile(self._hedge_q)
        return min(self._hedge_delay_max_s,
                   max(self._hedge_delay_min_s, base))

    def stop(self) -> None:
        self.mailbox.stop()

    # ------------------------------------------------------------------
    def _alive_workers(self) -> Dict[str, MseWorker]:
        return {k: w for k, w in self.workers.items() if w.alive}

    def _timeout_ms(self, options: Dict[str, str],
                    default_timeout_ms: Optional[float] = None) -> float:
        """Same precedence as BrokerRequestHandler._timeout_ms:
        OPTION(timeoutMs) first, then the MSE-specific config knob, then
        the delegating broker's resolved default (``default_timeout_ms``
        — it already folded in that broker's own config), then this
        dispatcher's config, then 60s."""
        opt = options.get("timeoutMs")
        if opt:
            try:
                return max(1.0, float(opt))
            except ValueError:
                pass
        if self.config is not None:
            mse_ms = self.config.get("pinot.broker.mse.timeout.ms")
            if mse_ms not in (None, ""):
                try:
                    return max(1.0, float(mse_ms))
                except (TypeError, ValueError):
                    pass  # malformed knob: fall through, don't fail queries
        if default_timeout_ms is not None:
            return max(1.0, float(default_timeout_ms))
        if self.config is not None:
            return max(1.0, float(
                self.config.get_int("pinot.broker.timeout.ms")))
        return 60000.0

    def plan_sql(self, sql: str, parsed=None) -> QueryPlan:
        q = parsed if parsed is not None else parse_mse_sql(sql)
        if q.limit is None:
            q.limit = 10  # Pinot default applies to the outermost query
        logical = build_logical(q, self.catalog_fn())
        alive = self._alive_workers()

        def alive_table_workers(table: str) -> List[str]:
            # route leaf stages around chaos-killed workers; a table
            # whose every host is dead is a routing error, not a hang
            hosts = [w for w in self.table_workers_fn(table) if w in alive]
            if not hosts:
                raise ValueError(
                    f"no live workers host table {table!r}")
            return hosts

        return plan_query(logical, q.options, alive_table_workers,
                          intermediate_workers=sorted(alive))

    def submit(self, sql: str, parsed=None,
               default_timeout_ms: Optional[float] = None) -> BrokerResponse:
        start = time.time()
        self._metrics.add_meter("mse_queries")
        try:
            plan = self.plan_sql(sql, parsed)
            block = self._execute(plan, default_timeout_ms)
        except (MseQueryTimeout, BrokerTimeoutError, MailboxTimeout,
                QueryCancelledError, MailboxError) as e:
            # typed partial: the budget expired, a worker died
            # mid-shuffle, a frame tore, or the client cancelled — the
            # answer is known-incomplete (ref EXECUTION_TIMEOUT 250).
            # A client cancel surfaces as QueryCancelledError from an op
            # boundary OR MailboxAborted from a blocked receive — both
            # meter as cancelled, not as a deadline miss
            self._metrics.add_meter(
                "mse_cancelled"
                if isinstance(e, (QueryCancelledError, MailboxAborted))
                else "mse_deadline_expired")
            resp = BrokerResponse(
                result_table=None,
                exceptions=[{
                    "errorCode": BrokerTimeoutError.ERROR_CODE,
                    "message": f"{type(e).__name__}: {e}"}],
                stats=ExecutionStats())
            resp.partial_result = True
            resp.time_used_ms = (time.time() - start) * 1000.0
            return resp
        except Exception as e:  # noqa: BLE001 — broker answers, never dies
            resp = BrokerResponse(
                result_table=None,
                exceptions=[{"errorCode": errorcodes.QUERY_EXECUTION,
                             "message": f"{type(e).__name__}: {e}"}],
                stats=ExecutionStats())
            resp.time_used_ms = (time.time() - start) * 1000.0
            return resp
        table = ResultTable(
            columns=list(plan.root.schema),
            column_types=[_infer_type(a) for a in block.arrays],
            rows=block.rows())
        resp = BrokerResponse(result_table=table, exceptions=[],
                              stats=ExecutionStats())
        resp.num_servers_queried = resp.num_servers_responded = \
            len(self._alive_workers())
        resp.time_used_ms = (time.time() - start) * 1000.0
        return resp

    # ------------------------------------------------------------------
    def inflight(self) -> List[str]:
        with self._inflight_lock:
            return sorted(self._inflight)

    def cancel(self, query_id: str, reason: str = "cancelled by client") \
            -> bool:
        """Client-initiated cancel: aborts the broker-side root stage and
        fans the cancel out to every worker. Safe to call for unknown or
        already-finished ids (returns False)."""
        with self._inflight_lock:
            ev = self._inflight.get(query_id)
        if ev is None:
            return False
        ev.set()
        self._fan_out_cancel(query_id, reason)
        return True

    def _fan_out_cancel(self, query_id: str, reason: str) -> None:
        """Out-of-band cancel op to every worker + the broker mailbox:
        in-flight stages abort at their next op boundary, their mailbox
        queues are poisoned/dropped, and downstream receivers fail fast
        instead of blocking on a sender that will never speak."""
        for w in self.workers.values():
            try:
                w.cancel(query_id, reason)
            except Exception:  # noqa: BLE001 — best effort, per worker
                pass
        self.mailbox.abort_query(query_id, reason)

    def _stage_progress(self, query_id: str) -> str:
        """Honest per-stage accounting for a partial answer: which
        stages were still in flight on each worker when the query died."""
        pending = {inst: w.active_stages(query_id)
                   for inst, w in self.workers.items()
                   if w.alive and w.active_stages(query_id)}
        dead = sorted(inst for inst, w in self.workers.items()
                      if not w.alive)
        parts = []
        if pending:
            parts.append("stages in flight: " + ", ".join(
                f"{inst}:{n}" for inst, n in sorted(pending.items())))
        if dead:
            parts.append(f"dead workers: {dead}")
        return "; ".join(parts) if parts else "all stages drained"

    def _execute(self, plan: QueryPlan,
                 default_timeout_ms: Optional[float] = None) -> Block:
        with _SEQ_LOCK:
            qid = f"mse_{next(_QUERY_SEQ)}_{int(time.time() * 1000)}"
        timeout_ms = self._timeout_ms(plan.options, default_timeout_ms)
        timeout = timeout_ms / 1000.0
        start = time.time()
        deadline = start + timeout if self.enforce_deadlines else None

        # -- distributed tracing (ISSUE 12) ----------------------------
        # the MSE rides the enclosing BrokerRequest trace: every stage
        # dispatch ships a TraceContext, workers return per-attempt span
        # trees over the control plane, and they stitch under one
        # MseQuery span here. trace=true parses MSE-side, so the
        # upgrade to sampled happens here too.
        req_trace = tracing.current_request()
        if req_trace is not None and \
                plan.options.get("trace", "").lower() == "true":
            req_trace.sampled = True
        root_h = tracing.capture()
        mse_h = None
        trace_wire = None
        stage_trees: List[dict] = []
        trees_cond = threading.Condition()
        #: stage attempts dispatched with a sink: the stitch barrier
        #: below waits (briefly) until each has reported its tree — a
        #: worker's trace_sink fires just AFTER its final EOS send, so
        #: the broker's root stage can finish first
        trees_expected = [0]
        trace_sink = None
        if root_h is not None and req_trace is not None:
            mse_h = root_h.child("MseQuery", queryId=qid,
                                 stages=len(plan.stages))
            trace_wire = req_trace.wire_context()

            def trace_sink(_inst, _sid, _widx, _attempt, tree):
                with trees_cond:
                    stage_trees.append(tree)
                    trees_cond.notify_all()

        def note_dispatched():
            # called AFTER submit_stage returns: every dispatched
            # attempt now reports through trace_sink exactly once
            # (tree, rejection stub, or untraced stub), so the barrier
            # count is exact; a sink firing before the increment only
            # overshoots len(), which releases the wait early — safe
            if trace_sink is not None:
                with trees_cond:
                    trees_expected[0] += 1

        addresses: Dict[str, str] = {}
        for s in plan.stages:
            for w, inst in enumerate(s.workers):
                addr = self.mailbox.address if inst == "broker" \
                    else self.workers[inst].mailbox_address
                addresses[f"{s.stage_id}:{w}"] = addr

        cancel_event = threading.Event()
        with self._inflight_lock:
            self._inflight[qid] = cancel_event

        plan_json = {"stages": [s.to_json() for s in plan.stages],
                     "options": plan.options}
        # hedging needs BOTH the knob and a peers resolver: without
        # hedge_peers_fn no hedge can ever be issued, so the book, the
        # claim wrapping, and the per-query monitor thread would be
        # pure overhead
        book = _HedgeBook() if (
            self.hedge_enabled and self.hedge_peers_fn is not None) \
            else None
        done_event = threading.Event()
        leaf_sids = {s.stage_id for s in plan.stages[1:]
                     if _is_leaf_op(s.root)}

        def on_done(inst, sid, widx, attempt, ok, elapsed_s):
            # per-worker stage-latency reservoirs feed the adaptive
            # hedge delay whether or not hedging is on (they must be
            # warm the moment the knob flips). ONLY leaf (hedgeable)
            # stages feed them: an intermediate's elapsed time is
            # mostly receive-blocked waiting on its children, i.e.
            # whole-query latency — pooling it would pin the delay at
            # the clamp ceiling and fire every hedge far too late
            if sid in leaf_sids:
                self.stage_latency.record_end(inst, elapsed_s)
            if book is not None and book.finish((sid, widx), attempt, ok):
                # DEAD slot: every attempt of a claim-gated stage died
                # without sending rows OR an error frame (e.g. denied
                # error claim + crash-silent twin) — abort the query
                # now so the receiver fails typed instead of blocking
                # out the whole deadline
                self._fan_out_cancel(
                    qid, f"stage {sid} lost every attempt")

        def make_claim(key, attempt):
            def claim(clean: bool) -> bool:
                granted, loser = book.claim(key, attempt, clean)
                if granted and key in book.hedged:
                    self._metrics.add_meter(
                        "mse_stage_hedge_won" if attempt > 0
                        else "mse_stage_hedge_wasted")
                if granted and loser is not None:
                    l_attempt, l_inst = loser
                    w = self.workers.get(l_inst)
                    if w is not None:
                        try:
                            w.cancel_stage(qid, key[0], attempt=l_attempt)
                        except Exception:  # noqa: BLE001 — best effort
                            pass
                return granted
            return claim

        try:
            for s in plan.stages[1:]:
                sj = s.to_json()
                leaf = _is_leaf_op(s.root)
                for w, inst in enumerate(s.workers):
                    # chaos site: delay/fail the dispatch of one stage
                    fire("mse.dispatch.stage", instance=inst,
                         query_id=qid, stage=s.stage_id)
                    claim_fn = None
                    if book is not None and leaf:
                        book.start((s.stage_id, w), 0, inst)
                        claim_fn = make_claim((s.stage_id, w), 0)
                    self.workers[inst].submit_stage(
                        qid, plan_json, sj, w, addresses, timeout=timeout,
                        deadline=deadline, claim_fn=claim_fn,
                        on_done=on_done, trace_ctx=trace_wire,
                        trace_sink=trace_sink)
                    note_dispatched()
            if book is not None:
                threading.Thread(
                    target=self._hedge_monitor,
                    args=(qid, plan, plan_json, addresses, timeout,
                          deadline, book, done_event, on_done, make_claim,
                          trace_wire, trace_sink, note_dispatched),
                    daemon=True, name=f"mse-hedge-{qid}").start()

            ctx = StageContext(
                query_id=qid, plan=plan, worker_id="broker", worker_idx=0,
                mailbox=self.mailbox, addresses=addresses, scan_fn=None,
                timeout=timeout, deadline=deadline,
                cancel_event=cancel_event)
            try:
                if mse_h is not None:
                    # the broker-side root stage's op scopes land under
                    # the MseQuery span, beside the stitched stage trees
                    with mse_h.activate():
                        block = run_stage(ctx, plan.root)
                else:
                    block = run_stage(ctx, plan.root)
            except (BrokerTimeoutError, MailboxTimeout) as e:
                # broker-side miss: answer typed, with honest per-stage
                # progress (the BaseException hook below fans out the
                # cancel so no mailbox queue outlives the query)
                raise MseQueryTimeout(
                    f"query {qid} missed its {timeout_ms:.0f}ms budget "
                    f"({self._stage_progress(qid)})") from e
            except MailboxError as e:
                if deadline is not None and time.time() >= deadline:
                    # a WORKER's deadline trip propagated as an error
                    # frame and beat the broker's own wall (a race the
                    # pipelined chunk cadence retimes): the budget DID
                    # expire, so answer with the same honest accounting
                    # as a broker-side miss
                    raise MseQueryTimeout(
                        f"query {qid} missed its {timeout_ms:.0f}ms "
                        f"budget ({self._stage_progress(qid)})") from e
                raise
            assert block is not None
            return block
        except BaseException:
            # ANY failure — deadline, client cancel, worker death, torn
            # frame, dispatch chaos, op error — aborts the rest of the
            # query everywhere: stages still running would otherwise
            # block on receivers that are never drained
            self._fan_out_cancel(qid, "query aborted")
            raise
        finally:
            done_event.set()
            with self._inflight_lock:
                self._inflight.pop(qid, None)
            if mse_h is not None:
                # stitch: every stage attempt's shipped tree grafts under
                # the MseQuery span; hedged slots tag winner/loser from
                # the claim book (the claimed attempt sent the output).
                # BARRIER: a worker's trace_sink fires just after its
                # final EOS send, so the broker can get here first —
                # wait (bounded; normally sub-ms) for the dispatched
                # attempts' trees on the success path. Failure paths
                # skip the wait: a cancelled query's workers may never
                # report, and stitching a partial tree is fine there.
                import sys as _sys
                with trees_cond:
                    if _sys.exc_info()[0] is None:
                        wall = time.time() + 0.25
                        while len(stage_trees) < trees_expected[0] \
                                and time.time() < wall:
                            trees_cond.wait(0.02)
                    got = list(stage_trees)
                for tree in got:
                    if book is not None:
                        key = (tree.get("stage"), tree.get("workerIdx"))
                        with book.lock:
                            hedged = key in book.hedged
                            won = book.claimed.get(key) == \
                                tree.get("attempt")
                        if hedged:
                            tree["outcome"] = \
                                "winner" if won else "loser"
                    mse_h.graft(tree)
                mse_h.end()

    def _hedge_monitor(self, qid, plan, plan_json, addresses, timeout,
                       deadline, book: _HedgeBook, done_event, on_done,
                       make_claim, trace_wire=None, trace_sink=None,
                       note_dispatched=None) -> None:
        """After the adaptive delay, re-issue every still-straggling LEAF
        stage instance on an alive peer with an identical local segment
        view; first clean attempt claims the output, the loser is
        cancelled through the per-stage cancel (PR 7 fan-out machinery,
        stage-granular). Best-effort by design: any failure here leaves
        the primary running untouched."""
        from pinot_tpu.mse.stage_cache import collect_scan_tables
        if done_event.wait(self._hedge_delay_s()):
            return  # query already finished: nothing worth hedging
        if deadline is not None and time.time() >= deadline:
            return
        alive = self._alive_workers()
        #: (table, instance) -> peers, resolved once per monitor pass —
        #: hedge_peers_fn may walk cluster placement, so a straggling
        #: multi-table stage must not re-derive it per slot
        peer_memo: Dict[tuple, set] = {}
        for s in plan.stages[1:]:
            if not _is_leaf_op(s.root):
                continue
            tables = collect_scan_tables(s.root)
            sj = None
            for w, inst in enumerate(s.workers):
                key = (s.stage_id, w)
                if not book.should_hedge(key):
                    continue
                peers: Optional[set] = None
                if self.hedge_peers_fn is not None:
                    for t in tables:
                        p = peer_memo.get((t, inst))
                        if p is None:
                            p = set(self.hedge_peers_fn(t, inst))
                            peer_memo[(t, inst)] = p
                        peers = p if peers is None else peers & p
                peers = (peers or set()) & set(alive)
                peers -= set(s.workers)
                if not peers:
                    continue
                target = sorted(peers)[0]
                try:
                    # chaos site: the seeded journal decides/records
                    # whether this hedge fires (same-seed replay is
                    # byte-identical); an armed error policy aborts
                    # JUST this hedge — the primary is untouched
                    fire("mse.stage.hedge", instance=inst,
                         target=target, query_id=qid, stage=s.stage_id)
                    book.start(key, 1, target)
                    self._metrics.add_meter("mse_stage_hedge_issued")
                    if sj is None:
                        sj = s.to_json()
                    alive[target].submit_stage(
                        qid, plan_json, sj, w, addresses,
                        timeout=timeout, deadline=deadline, attempt=1,
                        claim_fn=make_claim(key, 1), on_done=on_done,
                        trace_ctx=trace_wire, trace_sink=trace_sink)
                    if note_dispatched is not None:
                        # hedge attempts count toward the stitch
                        # barrier too — an uncounted hedge tree would
                        # release the len()-based wait while a primary
                        # tree is still in flight
                        note_dispatched()
                except Exception:  # noqa: BLE001 — hedge is best effort
                    book.finish(key, 1, False)


def _infer_type(arr: np.ndarray) -> str:
    k = arr.dtype.kind
    if k in "iu":
        return "LONG"
    if k == "f":
        return "DOUBLE"
    if k == "b":
        return "BOOLEAN"
    for v in arr:
        if v is None:
            continue
        if isinstance(v, bool):
            return "BOOLEAN"
        if isinstance(v, int):
            return "LONG"
        if isinstance(v, float):
            return "DOUBLE"
        if isinstance(v, bytes):
            return "BYTES"
        return "STRING"
    return "STRING"
