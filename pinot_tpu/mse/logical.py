"""Logical plan: relational tree built from the parsed MseQuery.

Reference parity: pinot-query-planner's Calcite logical planning
(QueryEnvironment.java:100 -> RelNode tree via logical rules). Here the
tree is built directly (no cost-based optimizer): left-deep joins in FROM
order, filter pushdown of single-scope conjuncts into scans, equi-key
extraction from ON conditions, aggregate/having/project/sort layering.
All identifiers are resolved to qualified "alias.column" names during the
build, so later stages never re-resolve.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from pinot_tpu.mse.sql import FromItem, MseQuery
from pinot_tpu.query.expressions import (
    Expression, Function, Identifier, Literal, func, ident)
from pinot_tpu.query.aggregation import is_aggregation


class PlanError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Nodes. Every node exposes .schema — ordered qualified output column names.
# ---------------------------------------------------------------------------

@dataclass
class LogicalNode:
    schema: List[str] = field(default_factory=list, init=False)

    @property
    def inputs(self) -> List["LogicalNode"]:
        return []


@dataclass
class Scan(LogicalNode):
    table: str
    alias: str
    columns: List[str]                    # physical column names to read
    filter: Optional[Expression] = None   # pushed-down, UNQUALIFIED names

    def __post_init__(self):
        self.schema = [f"{self.alias}.{c}" for c in self.columns]


@dataclass
class SubqueryScan(LogicalNode):
    """Derived table: re-exposes a child plan under an alias."""
    child: LogicalNode
    alias: str
    names: List[str]                      # child output -> alias.name

    def __post_init__(self):
        self.schema = [f"{self.alias}.{n}" for n in self.names]

    @property
    def inputs(self):
        return [self.child]


@dataclass
class Join(LogicalNode):
    left: LogicalNode
    right: LogicalNode
    join_type: str                        # inner | left | right | full | cross
    left_keys: List[Expression]
    right_keys: List[Expression]
    residual: Optional[Expression] = None  # non-equi remainder of ON

    def __post_init__(self):
        self.schema = list(self.left.schema) + list(self.right.schema)

    @property
    def inputs(self):
        return [self.left, self.right]


@dataclass
class Filter(LogicalNode):
    child: LogicalNode
    condition: Expression

    def __post_init__(self):
        self.schema = list(self.child.schema)

    @property
    def inputs(self):
        return [self.child]


@dataclass
class Aggregate(LogicalNode):
    child: LogicalNode
    group_exprs: List[Expression]
    agg_nodes: List[Function]             # resolved aggregation calls

    def __post_init__(self):
        self.schema = [str(e) for e in self.group_exprs] + \
                      [str(a) for a in self.agg_nodes]

    @property
    def inputs(self):
        return [self.child]


@dataclass
class Project(LogicalNode):
    child: LogicalNode
    exprs: List[Expression]
    names: List[str]

    def __post_init__(self):
        self.schema = list(self.names)

    @property
    def inputs(self):
        return [self.child]


@dataclass
class SetOp(LogicalNode):
    """UNION / INTERSECT / EXCEPT (ref runtime/operator/SetOperator.java).
    Output schema takes the left input's names (SQL rule)."""
    left: LogicalNode
    right: LogicalNode
    op: str                               # union | intersect | except
    all: bool

    def __post_init__(self):
        if len(self.left.schema) != len(self.right.schema):
            raise PlanError(
                f"{self.op.upper()} arity mismatch: "
                f"{len(self.left.schema)} vs {len(self.right.schema)} columns")
        self.schema = list(self.left.schema)

    @property
    def inputs(self):
        return [self.left, self.right]


@dataclass
class Window(LogicalNode):
    """One window spec + its functions (ref WindowAggregateOperator.java;
    Calcite groups OVER calls by identical window). Appends one output
    column per function to the child's schema."""
    child: LogicalNode
    partition: List[Expression]
    order_keys: List[Expression]
    ascs: List[bool]
    over_nodes: List[Function]            # full over(...) expressions

    def __post_init__(self):
        self.schema = list(self.child.schema) + \
            [str(o) for o in self.over_nodes]

    @property
    def inputs(self):
        return [self.child]


@dataclass
class Sort(LogicalNode):
    child: LogicalNode
    keys: List[Expression]
    ascs: List[bool]
    limit: int = -1                       # -1 = no limit
    offset: int = 0

    def __post_init__(self):
        self.schema = list(self.child.schema)

    @property
    def inputs(self):
        return [self.child]


# ---------------------------------------------------------------------------
# Catalog: table -> ordered physical column names
# ---------------------------------------------------------------------------

Catalog = Dict[str, List[str]]


# ---------------------------------------------------------------------------
# Identifier resolution
# ---------------------------------------------------------------------------

class _Scope:
    """Visible relations: alias -> (column names)."""

    def __init__(self):
        self.relations: Dict[str, List[str]] = {}

    def add(self, alias: str, columns: Sequence[str]) -> None:
        if alias in self.relations:
            raise PlanError(f"duplicate alias {alias!r}")
        self.relations[alias] = list(columns)

    def resolve(self, name: str) -> str:
        """name or alias.name -> qualified 'alias.column'."""
        if "." in name:
            alias, col = name.split(".", 1)
            cols = self.relations.get(alias)
            if cols is not None:
                if col not in cols:
                    raise PlanError(f"column {col!r} not in {alias!r}")
                return f"{alias}.{col}"
            # fall through: the dot may be part of an unusual column name
        hits = [a for a, cols in self.relations.items() if name in cols]
        if len(hits) == 1:
            return f"{hits[0]}.{name}"
        if len(hits) > 1:
            raise PlanError(f"ambiguous column {name!r} (in {hits})")
        raise PlanError(f"unknown column {name!r}")

    def side_of(self, qualified: str, left_aliases: set) -> str:
        alias = qualified.split(".", 1)[0]
        return "left" if alias in left_aliases else "right"


def _qualify(e: Expression, scope: _Scope) -> Expression:
    if isinstance(e, Identifier):
        if e.name == "*":
            return e
        return ident(scope.resolve(e.name))
    if isinstance(e, Function):
        return Function(e.name, tuple(_qualify(a, scope) for a in e.args))
    return e


def _conjuncts(e: Optional[Expression]) -> List[Expression]:
    if e is None:
        return []
    if isinstance(e, Function) and e.name == "and":
        out: List[Expression] = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _and_all(cs: List[Expression]) -> Optional[Expression]:
    if not cs:
        return None
    if len(cs) == 1:
        return cs[0]
    return func("and", *cs)


def _aliases_in(e: Expression) -> set:
    return {c.split(".", 1)[0] for c in e.columns()}


def _strip_alias(e: Expression, alias: str) -> Expression:
    """alias.col -> col (for pushdown into a single scan)."""
    if isinstance(e, Identifier) and e.name.startswith(alias + "."):
        return ident(e.name[len(alias) + 1:])
    if isinstance(e, Function):
        return Function(e.name, tuple(_strip_alias(a, alias) for a in e.args))
    return e


def _contains_agg(e: Expression) -> bool:
    if isinstance(e, Function):
        if e.name == "over":
            return False  # window-owned aggs are not grouping aggs
        if is_aggregation(e.name) or e.name == "filter_agg":
            return True
        return any(_contains_agg(a) for a in e.args)
    return False


def _collect_aggs(e: Expression, out: List[Function]) -> None:
    if isinstance(e, Function):
        if e.name == "over":
            return  # the inner agg belongs to the window operator
        if is_aggregation(e.name) or e.name == "filter_agg":
            if e not in out:
                out.append(e)
            return
        for a in e.args:
            _collect_aggs(a, out)


#: window-only functions (aggregations are additionally valid OVER fns)
WINDOW_FNS = {"row_number", "rank", "dense_rank", "ntile", "lag", "lead",
              "first_value", "last_value"}


def _collect_overs(e: Expression, out: List[Function]) -> None:
    if isinstance(e, Function):
        if e.name == "over":
            if e not in out:
                inner = e.args[0]
                if not (isinstance(inner, Function)
                        and (inner.name in WINDOW_FNS
                             or is_aggregation(inner.name))):
                    raise PlanError(
                        f"{inner} is not a window function")
                out.append(e)
            return
        for a in e.args:
            _collect_overs(a, out)


# ---------------------------------------------------------------------------
# Builder
# ---------------------------------------------------------------------------

def build_logical(q, catalog: Catalog) -> LogicalNode:
    """MseQuery | MseSetQuery -> logical plan tree with resolved names."""
    from pinot_tpu.mse.sql import MseSetQuery
    if isinstance(q, MseSetQuery):
        return _build_set_query(q, catalog)
    scope = _Scope()

    # 1. FROM items -> scans (filters pushed in later)
    items: List[Tuple[FromItem, LogicalNode]] = []
    for fi in [q.from_item] + [j.item for j in q.joins]:
        node = _build_from_item(fi, catalog)
        items.append((fi, node))
        scope.add(fi.alias, _local_names(node))

    where = [_qualify(c, scope) for c in _conjuncts(q.filter)]

    # 2. single-scope WHERE conjuncts push into their scan — EXCEPT when the
    # alias sits on the null-supplying side of an outer join: filtering
    # before the join would turn should-be-eliminated rows into NULL-padded
    # matches (must filter after the join instead)
    null_supplying: set = set()
    seen_aliases = [items[0][0].alias]
    for jc, (fi, _n) in zip(q.joins, items[1:]):
        if jc.join_type in ("left", "full"):
            null_supplying.add(fi.alias)
        if jc.join_type in ("right", "full"):
            null_supplying.update(seen_aliases)
        seen_aliases.append(fi.alias)

    remaining: List[Expression] = []
    pushed: Dict[str, List[Expression]] = {}
    for c in where:
        aliases = _aliases_in(c)
        if len(aliases) == 1 and not (aliases & null_supplying):
            pushed.setdefault(next(iter(aliases)), []).append(c)
        else:
            remaining.append(c)
    for (fi, node) in items:
        fs = pushed.get(fi.alias)
        if fs and isinstance(node, Scan):
            node.filter = _and_all(
                [_strip_alias(f, fi.alias) for f in fs])
        elif fs:
            remaining.extend(fs)

    # 3. left-deep joins in FROM order
    plan: LogicalNode = items[0][1]
    left_aliases = {items[0][0].alias}
    for jc, (fi, right) in zip(q.joins, items[1:]):
        on = [_qualify(c, scope) for c in _conjuncts(jc.condition)]
        lk, rk, residual = _split_equi_keys(on, left_aliases, fi.alias)
        if jc.join_type != "cross" and not lk:
            # no equi keys: keep as residual-only join (nested-loop semantics
            # via single-key constant partition)
            residual = _and_all(on)
        plan = Join(plan, right, jc.join_type, lk, rk, residual)
        left_aliases.add(fi.alias)

    # 4. remaining WHERE above the joins
    rem = _and_all(remaining)
    if rem is not None:
        plan = Filter(plan, rem)

    # 5. select/having/order expressions, aggregate detection
    select, aliases = [], []
    for e in q.select_list:
        if isinstance(e, Function) and e.name == "as":
            select.append(_qualify(e.args[0], scope))
            aliases.append(e.args[1].value)  # type: ignore[union-attr]
        else:
            qe = _qualify(e, scope)
            select.append(qe)
            aliases.append(None)
    group_by = [_qualify(e, scope) for e in q.group_by]
    having = _qualify(q.having, scope) if q.having is not None else None
    # an ORDER BY identifier may be a select alias rather than a column
    order_by = []
    for e, asc in q.order_by:
        if isinstance(e, Identifier) and e.name in aliases:
            order_by.append((e, asc))
        else:
            order_by.append((_qualify(e, scope), asc))

    agg_nodes: List[Function] = []
    for e in select + [e for e, _ in order_by] + \
            ([having] if having is not None else []):
        _collect_aggs(e, agg_nodes)

    if agg_nodes or group_by:
        plan = Aggregate(plan, group_by, agg_nodes)
        # above the aggregate, agg calls and group exprs are plain columns
        select = [_post_agg(e, plan.schema) for e in select]
        having = _post_agg(having, plan.schema) if having is not None else None
        order_by = [(_post_agg(e, plan.schema), asc) for e, asc in order_by]

    if having is not None:
        plan = Filter(plan, having)
        having = None

    # window functions evaluate after GROUP BY/HAVING but before DISTINCT;
    # one Window node per distinct OVER spec (the way Calcite groups
    # windows — ref WindowAggregateOperator)
    over_nodes: List[Function] = []
    for e in select + [e for e, _ in order_by]:
        _collect_overs(e, over_nodes)
    if over_nodes:
        specs: Dict[Tuple, List[Function]] = {}
        for o in over_nodes:
            specs.setdefault((o.args[1], o.args[2]), []).append(o)
        for (part_f, order_f), nodes in specs.items():
            partition = list(part_f.args)
            okeys = [k.args[0] for k in order_f.args]
            ascs = [k.name == "asc" for k in order_f.args]
            plan = Window(plan, partition, okeys, ascs, nodes)
            select = [_post_agg(e, plan.schema) for e in select]
            order_by = [(_post_agg(e, plan.schema), asc)
                        for e, asc in order_by]

    if q.distinct and not (agg_nodes or group_by):
        plan = Aggregate(plan, list(select), [])
        select = [_post_agg(e, plan.schema) for e in select]
        order_by = [(_post_agg(e, plan.schema), asc) for e, asc in order_by]

    # 6. final projection
    names = []
    for e, alias, raw in zip(select, aliases, q.select_list):
        if alias is not None:
            names.append(alias)
        else:
            base = raw.args[0] if (isinstance(raw, Function)
                                   and raw.name == "as") else raw
            names.append(_display_name(base))
    if len(select) == 1 and isinstance(select[0], Identifier) \
            and select[0].name == "*":
        select = [ident(c) for c in plan.schema]
        names = [c.split(".", 1)[-1] for c in plan.schema]

    # 7. sort keys resolve against the projection: a key matching a select
    # expression (or its alias) reuses that output column; any other key is
    # carried as a hidden __sortN column dropped after the sort
    keys: List[Expression] = []
    ascs: List[bool] = []
    visible = len(select)
    proj_exprs, proj_names = list(select), list(names)
    for i, (e, asc) in enumerate(order_by):
        name = None
        for se, sn in zip(select, names):
            if e == se or (isinstance(e, Identifier) and e.name == sn):
                name = sn
                break
        if name is None:
            name = f"__sort{i}"
            proj_exprs.append(e)
            proj_names.append(name)
        keys.append(ident(name))
        ascs.append(asc)
    plan = Project(plan, proj_exprs, proj_names)
    limit = -1 if q.limit is None else q.limit
    if keys or limit >= 0 or q.offset:
        plan = Sort(plan, keys, ascs, limit, q.offset)
    if len(proj_exprs) > visible:
        vis = proj_names[:visible]
        plan = Project(plan, [ident(n) for n in vis], vis)
    _prune_scan_columns(plan)
    return plan


def _build_set_query(q, catalog: Catalog) -> LogicalNode:
    """MseSetQuery -> SetOp (+ Sort for compound ORDER BY/LIMIT)."""
    left = build_logical(q.left, catalog)
    right = build_logical(q.right, catalog)
    plan: LogicalNode = SetOp(left, right, q.op, q.all)
    keys: List[Expression] = []
    ascs: List[bool] = []
    for e, asc in q.order_by:
        if isinstance(e, Identifier) and e.name in plan.schema:
            keys.append(e)
        else:
            raise PlanError(
                f"compound ORDER BY key {e} must be an output column "
                f"of the first operand ({plan.schema})")
        ascs.append(asc)
    limit = -1 if q.limit is None else q.limit
    if keys or limit >= 0 or q.offset:
        plan = Sort(plan, keys, ascs, limit, q.offset)
    return plan


def _node_exprs(n: LogicalNode) -> List[Optional[Expression]]:
    """Expressions a node evaluates over its INPUT schema (scan filters are
    excluded: they run inside the scan against physical columns)."""
    if isinstance(n, Join):
        return list(n.left_keys) + list(n.right_keys) + [n.residual]
    if isinstance(n, Filter):
        return [n.condition]
    if isinstance(n, Aggregate):
        return list(n.group_exprs) + list(n.agg_nodes)
    if isinstance(n, Window):
        return list(n.partition) + list(n.order_keys) + \
            [o.args[0] for o in n.over_nodes]
    if isinstance(n, Project):
        return list(n.exprs)
    if isinstance(n, Sort):
        return list(n.keys)
    return []


def _prune_scan_columns(root: LogicalNode) -> None:
    """Narrow every Scan's output to columns referenced above it, then
    recompute derived schemas bottom-up (less scan materialization and
    mailbox wire traffic)."""
    used: set = set()

    def collect(n: LogicalNode) -> None:
        for e in _node_exprs(n):
            if e is not None:
                used.update(e.columns())
        for c in n.inputs:
            collect(c)

    collect(root)

    def prune(n: LogicalNode) -> None:
        for c in n.inputs:
            prune(c)
        if isinstance(n, Scan):
            kept = [c for c in n.columns if f"{n.alias}.{c}" in used]
            n.columns = kept or n.columns[:1]  # COUNT(*)-only: keep one
        n.__post_init__()  # refresh schema from (possibly pruned) children

    prune(root)


def _build_from_item(fi: FromItem, catalog: Catalog) -> LogicalNode:
    if fi.subquery is not None:
        child = build_logical(fi.subquery, catalog)
        return SubqueryScan(child, fi.alias, list(child.schema))
    cols = catalog.get(fi.table)
    if cols is None:
        raise PlanError(f"unknown table {fi.table!r}")
    return Scan(fi.table, fi.alias, list(cols))


def _local_names(node: LogicalNode) -> List[str]:
    """Names visible under the relation's alias (unqualified)."""
    if isinstance(node, Scan):
        return list(node.columns)
    if isinstance(node, SubqueryScan):
        return list(node.names)
    raise PlanError(f"bad from item {node}")


def _split_equi_keys(on: List[Expression], left_aliases: set,
                     right_alias: str):
    """Partition ON conjuncts into equi-join key pairs + residual."""
    lk: List[Expression] = []
    rk: List[Expression] = []
    residual: List[Expression] = []
    for c in on:
        if isinstance(c, Function) and c.name == "equals" \
                and len(c.args) == 2:
            a, b = c.args
            aa, ba = _aliases_in(a), _aliases_in(b)
            if aa and aa <= left_aliases and ba == {right_alias}:
                lk.append(a)
                rk.append(b)
                continue
            if ba and ba <= left_aliases and aa == {right_alias}:
                lk.append(b)
                rk.append(a)
                continue
        residual.append(c)
    return lk, rk, _and_all(residual)


def _post_agg(e: Expression, agg_schema: List[str]) -> Expression:
    """Rewrite agg calls / group exprs into references to aggregate output
    columns (matched by canonical string form)."""
    s = str(e)
    if s in agg_schema:
        return ident(s)
    if isinstance(e, Function):
        return Function(e.name, tuple(_post_agg(a, agg_schema) for a in e.args))
    return e


def _display_name(e: Expression) -> str:
    if isinstance(e, Identifier):
        return e.name.split(".", 1)[-1] if "." in e.name else e.name
    return str(e)
