"""Mailbox data plane: block shuffle between stage workers.

Reference parity: pinot-query-runtime query/mailbox/ —
MailboxService.java:40 (id'd mailboxes), GrpcSendingMailbox /
InMemorySendingMailbox / ReceivingMailbox. Here: one asyncio TCP listener
per instance; frames are

  u32 len | u16 keyLen | key utf8 | u8 flags | payload

flags: 1 = EOS (sender-worker done), 2 = ERROR (payload = utf8 message).
Same-instance sends short-circuit the socket (the InMemory mailbox path).
Mailbox key: "<queryId>|<senderStage>|<receiverStage>|<receiverWorker>".
Each sender worker sends its partition blocks then one EOS; the receiver
drains until it counts EOS from every sender worker.

Reliability (ISSUE 7):

* ``receive_all`` takes a hard wall (absolute ``deadline``) and a
  ``cancel_event`` — a deadline miss raises ``MailboxTimeout`` and a
  cancel raises ``MailboxAborted``, both typed, never a silent hang.
* A **sender-death detector**: while blocked, the receiver periodically
  TCP-probes the pending senders' mailbox addresses; a dead endpoint
  (worker crashed, listener gone) raises ``MailboxError`` immediately
  instead of waiting out the full timeout.
* ``abort_query`` poisons every mailbox of a query id: blocked receivers
  wake with an ERROR frame, later receivers fail fast, and late frames
  from in-flight senders are dropped — so a cancelled query leaves zero
  orphaned queues.
* ``send`` retries exactly once on a fresh socket (a pooled connection
  to a restarted peer is stale) before surfacing the failure.
* Failpoint sites ``mse.mailbox.send`` / ``mse.mailbox.recv`` tear,
  delay, or fail individual frames deterministically (utils/failpoints).
"""
from __future__ import annotations

import queue
import socket
import struct
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import asyncio

from pinot_tpu.utils.failpoints import fire

_LEN = struct.Struct("<I")
_KEYLEN = struct.Struct("<H")

FLAG_EOS = 1
FLAG_ERROR = 2

#: cadence of the sender-death probe while a receiver is blocked
_PROBE_INTERVAL_S = 0.25
#: per-endpoint TCP connect timeout for one probe
_PROBE_CONNECT_S = 0.2


class MailboxError(RuntimeError):
    pass


class MailboxTimeout(MailboxError):
    """The receive deadline expired with senders still pending."""


class MailboxAborted(MailboxError):
    """The query was cancelled/aborted out of band (poisoned mailbox)."""


def mailbox_key(query_id: str, sender_stage: int, receiver_stage: int,
                receiver_worker: int) -> str:
    return f"{query_id}|{sender_stage}|{receiver_stage}|{receiver_worker}"


def _qid_of(key: str) -> str:
    return key.split("|", 1)[0]


class MailboxService:
    """Per-instance mailbox endpoint: TCP listener + local queues."""

    #: aborted-query memo size: late frames for these ids are dropped.
    #: Sized so eviction needs this many aborts while a frame of the
    #: evicted query is STILL in flight (an in-flight window of seconds)
    #: — past it, a straggler frame could recreate a queue nobody drains
    MAX_ABORTED = 4096

    def __init__(self, instance_id: str, host: str = "127.0.0.1",
                 port: int = 0, metrics=None):
        from pinot_tpu.utils.metrics import get_registry
        self.instance_id = instance_id
        self.host = host
        self.port = port
        self._queues: Dict[str, "queue.Queue[Tuple[int, bytes]]"] = {}
        self._qlock = threading.Lock()
        #: query_id -> abort reason; frames for these ids are dropped and
        #: receivers fail fast (bounded FIFO memo)
        self._aborted: "OrderedDict[str, str]" = OrderedDict()
        self._conns: Dict[str, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stopped = False
        self._metrics = metrics if metrics is not None \
            else get_registry("server")
        self._labels = {"instance": instance_id}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def main():
                self._server = await asyncio.start_server(
                    self._handle, self.host, self.port)
                self.port = self._server.sockets[0].getsockname()[1]
                self._started.set()
                async with self._server:
                    await self._server.serve_forever()

            try:
                loop.run_until_complete(main())
            except asyncio.CancelledError:
                pass
            finally:
                loop.close()

        self._thread = threading.Thread(
            target=run, daemon=True, name=f"mailbox-{self.instance_id}")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("mailbox service failed to start")

    def stop(self) -> None:
        """Idempotent: a chaos-crashed worker stops its own mailbox, and
        the cluster teardown stops it again."""
        if self._stopped:
            return
        self._stopped = True
        if self._loop is not None and not self._loop.is_closed():
            def shutdown():
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()
            try:
                self._loop.call_soon_threadsafe(shutdown)
            except RuntimeError:
                pass  # loop already closed
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._conn_lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- receiving ----------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(4)
                n = _LEN.unpack(hdr)[0]
                frame = await reader.readexactly(n)
                klen = _KEYLEN.unpack_from(frame, 0)[0]
                key = frame[2:2 + klen].decode()
                flags = frame[2 + klen]
                payload = frame[3 + klen:]
                self._deliver(key, flags, payload)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def _deliver(self, key: str, flags: int, payload: bytes) -> None:
        """Route one inbound frame to its queue — unless the query was
        aborted, in which case the frame is dropped (a poisoned query
        must not resurrect its queues)."""
        with self._qlock:
            if _qid_of(key) in self._aborted:
                return
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = queue.Queue()
        q.put((flags, payload))

    def receive_all(self, key: str, num_senders: int,
                    timeout: float = 60.0,
                    deadline: Optional[float] = None,
                    cancel_event: Optional[threading.Event] = None,
                    sender_addresses: Optional[List[str]] = None):
        """Yield payload bytes until EOS from every sender; raises on an
        ERROR frame. Removes the queue when drained.

        deadline: absolute wall-clock hard wall (overrides ``timeout``).
        cancel_event: cooperative out-of-band cancel — raises
        MailboxAborted at the next wait slice.
        sender_addresses: mailbox endpoints of the pending senders; while
        blocked, they are TCP-probed every ~250ms and a dead endpoint
        raises MailboxError instead of waiting out the timeout."""
        import time as _time
        qid = _qid_of(key)
        # memo check + queue registration are ATOMIC: an abort landing
        # between them would otherwise poison the popped queues, then
        # this receiver registers a fresh unpoisoned queue and blocks
        # while every later frame is dropped by the memo
        with self._qlock:
            reason = self._aborted.get(qid)
            if reason is None:
                q = self._queues.get(key)
                if q is None:
                    q = self._queues[key] = queue.Queue()
        if reason is not None:
            raise MailboxAborted(f"mailbox {key}: {reason}")
        wall = deadline if deadline is not None \
            else _time.time() + timeout
        budget = wall - _time.time()
        eos_seen = 0
        next_probe = _time.time() + _PROBE_INTERVAL_S
        try:
            while eos_seen < num_senders:
                now = _time.time()
                if cancel_event is not None and cancel_event.is_set():
                    raise MailboxAborted(f"mailbox {key}: query cancelled")
                if now >= wall:
                    raise MailboxTimeout(
                        f"mailbox {key}: timed out after {budget:.3f}s "
                        f"({eos_seen}/{num_senders} senders done)")
                slice_s = min(wall - now, _PROBE_INTERVAL_S)
                try:
                    flags, payload = q.get(timeout=slice_s)
                except queue.Empty:
                    if sender_addresses and _time.time() >= next_probe \
                            and wall - _time.time() > _PROBE_CONNECT_S:
                        dead = self._probe_senders(sender_addresses,
                                                   stop_at=wall)
                        if dead:
                            raise MailboxError(
                                f"mailbox {key}: sender(s) {dead} are "
                                f"dead ({eos_seen}/{num_senders} senders "
                                f"done)") from None
                        next_probe = _time.time() + _PROBE_INTERVAL_S
                    continue
                payload = fire("mse.mailbox.recv", payload=payload,
                               instance=self.instance_id, key=key)
                self._metrics.add_meter("mse_mailbox_recv_frames",
                                        labels=self._labels)
                self._metrics.add_meter("mse_mailbox_recv_bytes",
                                        len(payload), labels=self._labels)
                if flags & FLAG_ERROR:
                    msg = payload.decode(errors="replace")
                    with self._qlock:
                        aborted = qid in self._aborted
                    if aborted:
                        # the poison frame abort_query used to wake this
                        # receiver — surface it TYPED as an abort, not as
                        # a generic upstream error
                        raise MailboxAborted(f"mailbox {key}: {msg}")
                    raise MailboxError(msg)
                if payload:
                    yield payload
                if flags & FLAG_EOS:
                    eos_seen += 1
        finally:
            with self._qlock:
                self._queues.pop(key, None)

    def _probe_senders(self, addresses: List[str],
                       stop_at: Optional[float] = None) -> List[str]:
        """TCP-connect to each (unique, remote) sender endpoint; returns
        the addresses that refused — a closed listener means the sender
        process/worker is gone and its EOS will never come.

        Frames carry no sender identity, so a sender that died AFTER
        delivering its EOS is indistinguishable from one that died
        pending; the probe is deliberately conservative the other way —
        fail fast with a typed partial (a retry converges) rather than
        block a completable query on an ambiguous corpse.

        stop_at: hard cap — probing never overruns the receive wall even
        when many endpoints each eat the full connect timeout."""
        import time as _time
        dead = []
        for addr in sorted(set(addresses)):
            if addr == self.address:
                continue  # self is trivially alive
            if stop_at is not None and _time.time() >= stop_at:
                break  # the deadline check owns anything past the wall
            host, port = addr.rsplit(":", 1)
            try:
                s = socket.create_connection((host, int(port)),
                                             timeout=_PROBE_CONNECT_S)
                s.close()
            except OSError:
                dead.append(addr)
        return dead

    def discard(self, key: str) -> None:
        """Drop a queue (undrained partition after an error elsewhere)."""
        with self._qlock:
            self._queues.pop(key, None)

    def abort_query(self, query_id: str, reason: str = "cancelled") -> int:
        """Poison every mailbox of a query: blocked receivers wake with an
        ERROR frame (they hold the queue reference, so popping the map
        first still reaches them), later receivers fail fast on the
        aborted memo, and in-flight senders' late frames are dropped.
        Returns the number of queues poisoned."""
        payload = reason.encode()
        with self._qlock:
            self._aborted[query_id] = reason
            self._aborted.move_to_end(query_id)
            while len(self._aborted) > self.MAX_ABORTED:
                self._aborted.popitem(last=False)
            victims = [self._queues.pop(k)
                       for k in list(self._queues)
                       if _qid_of(k) == query_id]
        for q in victims:
            q.put((FLAG_ERROR, payload))
        if victims:
            self._metrics.add_meter("mse_mailbox_poisoned", len(victims),
                                    labels=self._labels)
        return len(victims)

    def queue_count(self, query_id: Optional[str] = None) -> int:
        """Live queue count (optionally for one query) — the orphan
        guard tests assert this drains to zero."""
        with self._qlock:
            if query_id is None:
                return len(self._queues)
            return sum(1 for k in self._queues if _qid_of(k) == query_id)

    # -- sending ------------------------------------------------------------
    def send(self, dest_address: str, key: str, payload: bytes,
             flags: int = 0) -> None:
        # chaos edge: tear (truncate) / delay / fail the payload before
        # framing — truncating INSIDE a frame would desync the stream,
        # so the torn payload still frames cleanly and surfaces as a
        # typed decode error on the receiver
        payload = fire("mse.mailbox.send", payload=payload,
                       instance=self.instance_id, key=key,
                       dest=dest_address)
        self._metrics.add_meter("mse_mailbox_sent_frames",
                                labels=self._labels)
        self._metrics.add_meter("mse_mailbox_sent_bytes", len(payload),
                                labels=self._labels)
        if dest_address == self.address:
            self._deliver(key, flags, payload)
            return
        kb = key.encode()
        frame = _KEYLEN.pack(len(kb)) + kb + bytes([flags]) + payload
        msg = _LEN.pack(len(frame)) + frame
        with self._conn_lock:
            try:
                sock = self._conns.get(dest_address)
                if sock is None:
                    sock = self._connect_locked(dest_address)
                sock.sendall(msg)
            except (ConnectionError, OSError):
                # one retry on a FRESH socket: the pooled connection (or
                # the first dial) hit a restarted/flaky peer — a second
                # dial catches the common stale-socket case without
                # masking a genuinely dead endpoint
                self._drop_locked(dest_address)
                self._metrics.add_meter("mse_mailbox_retries",
                                        labels=self._labels)
                try:
                    sock = self._connect_locked(dest_address)
                    sock.sendall(msg)
                except (ConnectionError, OSError):
                    self._drop_locked(dest_address)
                    raise

    def _connect_locked(self, dest_address: str) -> socket.socket:
        host, port = dest_address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=30)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conns[dest_address] = sock
        return sock

    def _drop_locked(self, dest_address: str) -> None:
        sock = self._conns.pop(dest_address, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
