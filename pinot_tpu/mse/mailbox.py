"""Mailbox data plane: block shuffle between stage workers.

Reference parity: pinot-query-runtime query/mailbox/ —
MailboxService.java:40 (id'd mailboxes), GrpcSendingMailbox /
InMemorySendingMailbox / ReceivingMailbox. Here: one asyncio TCP listener
per instance; frames are

  u32 len | u16 keyLen | key utf8 | u8 flags | payload

flags: 1 = EOS (sender-worker done), 2 = ERROR (payload = utf8 message).
Same-instance sends short-circuit the socket (the InMemory mailbox path).
Mailbox key: "<queryId>|<senderStage>|<receiverStage>|<receiverWorker>".
Each sender worker sends its partition blocks then one EOS; the receiver
drains until it counts EOS from every sender worker.
"""
from __future__ import annotations

import asyncio
import queue
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

_LEN = struct.Struct("<I")
_KEYLEN = struct.Struct("<H")

FLAG_EOS = 1
FLAG_ERROR = 2


class MailboxError(RuntimeError):
    pass


class MailboxTimeout(MailboxError):
    pass


def mailbox_key(query_id: str, sender_stage: int, receiver_stage: int,
                receiver_worker: int) -> str:
    return f"{query_id}|{sender_stage}|{receiver_stage}|{receiver_worker}"


class MailboxService:
    """Per-instance mailbox endpoint: TCP listener + local queues."""

    def __init__(self, instance_id: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.instance_id = instance_id
        self.host = host
        self.port = port
        self._queues: Dict[str, "queue.Queue[Tuple[int, bytes]]"] = {}
        self._qlock = threading.Lock()
        self._conns: Dict[str, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def main():
                self._server = await asyncio.start_server(
                    self._handle, self.host, self.port)
                self.port = self._server.sockets[0].getsockname()[1]
                self._started.set()
                async with self._server:
                    await self._server.serve_forever()

            try:
                loop.run_until_complete(main())
            except asyncio.CancelledError:
                pass
            finally:
                loop.close()

        self._thread = threading.Thread(
            target=run, daemon=True, name=f"mailbox-{self.instance_id}")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("mailbox service failed to start")

    def stop(self) -> None:
        if self._loop is not None:
            def shutdown():
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()
            self._loop.call_soon_threadsafe(shutdown)
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._conn_lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- receiving ----------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(4)
                n = _LEN.unpack(hdr)[0]
                frame = await reader.readexactly(n)
                klen = _KEYLEN.unpack_from(frame, 0)[0]
                key = frame[2:2 + klen].decode()
                flags = frame[2 + klen]
                payload = frame[3 + klen:]
                self._queue(key).put((flags, payload))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def _queue(self, key: str) -> "queue.Queue[Tuple[int, bytes]]":
        with self._qlock:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = queue.Queue()
            return q

    def receive_all(self, key: str, num_senders: int,
                    timeout: float = 60.0):
        """Yield payload bytes until EOS from every sender; raises on an
        ERROR frame. Removes the queue when drained."""
        q = self._queue(key)
        eos_seen = 0
        try:
            while eos_seen < num_senders:
                try:
                    flags, payload = q.get(timeout=timeout)
                except queue.Empty:
                    raise MailboxTimeout(
                        f"mailbox {key}: timed out after {timeout}s "
                        f"({eos_seen}/{num_senders} senders done)") from None
                if flags & FLAG_ERROR:
                    raise MailboxError(payload.decode(errors="replace"))
                if payload:
                    yield payload
                if flags & FLAG_EOS:
                    eos_seen += 1
        finally:
            with self._qlock:
                self._queues.pop(key, None)

    def discard(self, key: str) -> None:
        """Drop a queue (undrained partition after an error elsewhere)."""
        with self._qlock:
            self._queues.pop(key, None)

    # -- sending ------------------------------------------------------------
    def send(self, dest_address: str, key: str, payload: bytes,
             flags: int = 0) -> None:
        if dest_address == self.address:
            self._queue(key).put((flags, payload))
            return
        kb = key.encode()
        frame = _KEYLEN.pack(len(kb)) + kb + bytes([flags]) + payload
        msg = _LEN.pack(len(frame)) + frame
        with self._conn_lock:
            sock = self._conns.get(dest_address)
            try:
                if sock is None:
                    sock = self._connect(dest_address)
                sock.sendall(msg)
            except (ConnectionError, OSError):
                # one reconnect attempt (peer restarted)
                self._drop(dest_address)
                sock = self._connect(dest_address)
                sock.sendall(msg)

    def _connect(self, dest_address: str) -> socket.socket:
        host, port = dest_address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=30)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conns[dest_address] = sock
        return sock

    def _drop(self, dest_address: str) -> None:
        sock = self._conns.pop(dest_address, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
