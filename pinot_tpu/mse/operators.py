"""Vectorized operators over columnar blocks.

Reference parity: pinot-query-runtime runtime/operator/ —
HashJoinOperator.java, AggregateOperator.java, SortOperator.java,
FilterOperator, TransformOperator. The TPU-first re-design: operators are
whole-block vectorized numpy (factorize + searchsorted joins, bincount
aggregates) rather than row iterators — the same decomposition the device
kernels use, so hot intermediate ops can later migrate onto the chip.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from pinot_tpu.mse.blocks import Block, _py
from pinot_tpu.query import transform
from pinot_tpu.query.aggregation import get_aggregation
from pinot_tpu.query.expressions import Expression, Function, Identifier


# ---------------------------------------------------------------------------
# expression evaluation over a block
# ---------------------------------------------------------------------------

def eval_expr(e: Expression, block: Block) -> np.ndarray:
    """Evaluate an expression columnwise over a block (broadcasts scalars)."""
    v = transform.evaluate(e, block)
    if not isinstance(v, np.ndarray):
        v = np.full(block.num_rows, v)
    elif v.ndim == 0:
        v = np.full(block.num_rows, v.item())
    return v


def eval_predicate(e: Expression, block: Block) -> np.ndarray:
    m = eval_expr(e, block)
    if m.dtype != np.bool_:
        m = m.astype(bool)
    return m


def filter_block(block: Block, condition: Expression) -> Block:
    if block.num_rows == 0:
        return block
    return block.mask(eval_predicate(condition, block))


def project_block(block: Block, exprs: Sequence[Expression],
                  names: Sequence[str]) -> Block:
    return Block(list(names), [eval_expr(e, block) for e in exprs])


# ---------------------------------------------------------------------------
# key encoding: N key columns -> one int64 code per row (factorized)
# ---------------------------------------------------------------------------

def _factorize_pair(left_cols: List[np.ndarray],
                    right_cols: List[np.ndarray]):
    """Jointly factorize left/right key columns into comparable int64 codes."""
    nl = len(left_cols[0]) if left_cols else 0
    codes_l = np.zeros(nl, np.int64)
    codes_r = np.zeros(len(right_cols[0]) if right_cols else 0, np.int64)
    for lc, rc in zip(left_cols, right_cols):
        both = _concat_keys(lc, rc)
        _, inv = np.unique(both, return_inverse=True)
        card = int(inv.max()) + 1 if len(inv) else 1
        codes_l = codes_l * card + inv[:nl]
        codes_r = codes_r * card + inv[nl:]
    return codes_l, codes_r


def _concat_keys(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if a.dtype.kind == "O" or b.dtype.kind == "O" \
            or a.dtype.kind in "US" or b.dtype.kind in "US":
        return np.concatenate([_as_str(a), _as_str(b)])
    dt = np.result_type(a.dtype, b.dtype)
    return np.concatenate([a.astype(dt, copy=False),
                           b.astype(dt, copy=False)])


def _as_str(a: np.ndarray) -> np.ndarray:
    if a.dtype.kind in "US":
        return a.astype(str)
    return np.array([str(v) for v in a], dtype=object).astype(str)


def factorize(cols: List[np.ndarray]):
    """Key columns -> (codes per row, num_uniques, first-row index per code)."""
    n = len(cols[0]) if cols else 0
    codes = np.zeros(n, np.int64)
    for c in cols:
        if c.dtype.kind == "O":
            c = _as_str(c)
        _, inv = np.unique(c, return_inverse=True)
        card = int(inv.max()) + 1 if len(inv) else 1
        codes = codes * card + inv
    uniq, first, dense = np.unique(codes, return_index=True,
                                   return_inverse=True)
    return dense, len(uniq), first


# ---------------------------------------------------------------------------
# hash join (ref HashJoinOperator.java) — sort/searchsorted build+probe
# ---------------------------------------------------------------------------

def hash_join(left: Block, right: Block, join_type: str,
              left_keys: Sequence[Expression],
              right_keys: Sequence[Expression],
              residual: Optional[Expression],
              schema: List[str]) -> Block:
    """Equi-join two blocks. schema = left.names + right.names."""
    if join_type == "cross" or not left_keys:
        li, ri = _cross_pairs(left.num_rows, right.num_rows)
        lmatch = np.zeros(left.num_rows, bool)
        rmatch = np.zeros(right.num_rows, bool)
    else:
        lcols = [eval_expr(e, left) for e in left_keys]
        rcols = [eval_expr(e, right) for e in right_keys]
        cl, cr = _factorize_pair(lcols, rcols)
        # build on right: sort right codes, probe left via searchsorted
        order = np.argsort(cr, kind="stable")
        sorted_r = cr[order]
        start = np.searchsorted(sorted_r, cl, side="left")
        stop = np.searchsorted(sorted_r, cl, side="right")
        counts = stop - start
        li = np.repeat(np.arange(left.num_rows), counts)
        # ranges [start, stop) into order -> right row indices
        ri = _expand_ranges(start, counts, order)
        lmatch = np.zeros(left.num_rows, bool)
        rmatch = np.zeros(right.num_rows, bool)

    # semi/anti output only the left side; build the probe pairs over the
    # combined namespace either way so residuals can reference both sides
    combined = left.names + right.names
    joined = Block(combined,
                   [a[li] for a in left.arrays] + [a[ri] for a in right.arrays])
    if residual is not None and joined.num_rows:
        keep = eval_predicate(residual, joined)
        li, ri = li[keep], ri[keep]
        joined = joined.mask(keep)
    if joined.num_rows:
        lmatch[li] = True
        rmatch[ri] = True

    if join_type in ("left", "full"):
        joined = Block.concat([joined, _outer_rows(
            left, right, ~lmatch, schema, left_side=True)])
    if join_type in ("right", "full"):
        joined = Block.concat([joined, _outer_rows(
            left, right, ~rmatch, schema, left_side=False)])
    if join_type == "semi":
        return Block(schema, [a[lmatch] for a in left.arrays])
    if join_type == "anti":
        return Block(schema, [a[~lmatch] for a in left.arrays])
    return joined.rename(schema)


def _expand_ranges(start: np.ndarray, counts: np.ndarray,
                   order: np.ndarray) -> np.ndarray:
    if counts.sum() == 0:
        return np.empty(0, np.int64)
    # offsets within each probe's [start, start+count) range
    offs = np.arange(counts.sum()) - np.repeat(
        np.cumsum(counts) - counts, counts)
    pos = np.repeat(start, counts) + offs
    return order[pos]


def _cross_pairs(nl: int, nr: int):
    li = np.repeat(np.arange(nl), nr)
    ri = np.tile(np.arange(nr), nl)
    return li, ri


def _outer_rows(left: Block, right: Block, unmatched: np.ndarray,
                schema: List[str], left_side: bool) -> Block:
    n = int(unmatched.sum())
    if n == 0:
        return Block.empty(schema)
    if left_side:
        cols = [a[unmatched] for a in left.arrays] + \
               [_nulls(a, n) for a in right.arrays]
    else:
        cols = [_nulls(a, n) for a in left.arrays] + \
               [a[unmatched] for a in right.arrays]
    return Block(schema, cols)


def _nulls(like: np.ndarray, n: int) -> np.ndarray:
    if like.dtype.kind == "f":
        return np.full(n, np.nan, like.dtype)
    out = np.empty(n, object)
    out[:] = None
    return out


# ---------------------------------------------------------------------------
# aggregate (ref AggregateOperator.java) — one-phase final after key shuffle
# ---------------------------------------------------------------------------

def _prepare_aggs(block: Block, agg_nodes: Sequence[Function]):
    """Resolve agg nodes against a block: (fns, arg values, FILTER masks)."""
    n = block.num_rows
    fns, arg_vals, filt_masks = [], [], []
    for node in agg_nodes:
        inner, fmask = node, None
        if node.name == "filter_agg":
            inner = node.args[0]
            fmask = eval_predicate(node.args[1], block) if n else \
                np.zeros(0, bool)
        fn = get_aggregation(inner.name, inner.args)
        fns.append(fn)
        arg = None
        if fn.multi_arg:
            from pinot_tpu.query.expressions import Literal
            # list (not np.stack): stacking unifies dtypes and would alias
            # i64 timestamps above 2^53 through f64
            arg = [eval_expr(a, block) if n else np.empty(0)
                   for a in inner.args if not isinstance(a, Literal)]
        elif fn.mv_input or inner.name == "countmv":
            # MV columns arrive as object arrays of per-doc value lists;
            # flatten and remember per-doc entry counts for mask expansion
            # (countmv consumes the per-doc counts directly, executor-style)
            col = eval_expr(inner.args[0], block) if n else np.empty(0, object)
            lists = [np.asarray(v) for v in col]
            counts = np.array([len(v) for v in lists], np.int64)
            if inner.name == "countmv":
                arg = counts
            else:
                flat = np.concatenate(lists) if lists else np.empty(0)
                arg = (flat, counts)
        elif inner.args and not (isinstance(inner.args[0], Identifier)
                                 and inner.args[0].name == "*"):
            arg = eval_expr(inner.args[0], block) if n else np.empty(0)
        arg_vals.append(arg)
        filt_masks.append(fmask)
    return fns, arg_vals, filt_masks


def aggregate_block(block: Block, group_exprs: Sequence[Expression],
                    agg_nodes: Sequence[Function],
                    schema: List[str]) -> Block:
    """Full (final) aggregation: every distinct key is wholly local (the
    planner hash-exchanges rows on the group key), so extract_final here is
    exact for every function incl. sketches."""
    n = block.num_rows
    fns, arg_vals, filt_masks = _prepare_aggs(block, agg_nodes)

    if not group_exprs:
        vals = []
        base = np.ones(n, bool)
        for fn, arg, fmask in zip(fns, arg_vals, filt_masks):
            mask = base if fmask is None else fmask
            if fn.mv_input and arg is not None:
                flat, counts = arg
                mask = np.repeat(mask, counts)
                arg = flat
            inter = fn.aggregate(arg, mask) if n else fn.identity()
            vals.append(fn.extract_final(inter))
        return Block(schema, [np.array([v], object) for v in vals])

    if n == 0:
        return Block.empty(schema)
    key_cols = [eval_expr(e, block) for e in group_exprs]
    codes, num_groups, first = factorize(key_cols)
    base = np.ones(n, bool)
    out: List[np.ndarray] = [kc[first] for kc in key_cols]
    for fn, arg, fmask in zip(fns, arg_vals, filt_masks):
        mask = base if fmask is None else fmask
        keys = codes
        if fn.mv_input and arg is not None:
            flat, counts = arg
            mask = np.repeat(mask, counts)
            keys = np.repeat(codes, counts)
            arg = flat
        inters = fn.aggregate_grouped(arg, keys, num_groups, mask)
        finals = np.empty(num_groups, object)
        for g in range(num_groups):
            finals[g] = fn.extract_final(inters[g])
        out.append(finals)
    return Block(schema, out)


# ---------------------------------------------------------------------------
# two-phase aggregation (ref AggregateOperator intermediate/final modes +
# LeafStageTransferableBlockOperator) — the leaf stage partially aggregates
# and ships per-group INTERMEDIATES (serialized, sketch-capable) instead of
# raw rows; the receiving stage merges and finalizes
# ---------------------------------------------------------------------------

def partial_aggregate_block(block: Block, group_exprs: Sequence[Expression],
                            agg_nodes: Sequence[Function],
                            schema: List[str]) -> Block:
    """Host fallback for the leaf_agg op (when no leaf executor is bound):
    group values + one serialized intermediate cell per (group, agg)."""
    from pinot_tpu.server.datatable import serialize_value
    n = block.num_rows
    fns, arg_vals, filt_masks = _prepare_aggs(block, agg_nodes)

    if not group_exprs:
        base = np.ones(n, bool)
        cells = []
        for fn, arg, fmask in zip(fns, arg_vals, filt_masks):
            mask = base if fmask is None else fmask
            if fn.mv_input and arg is not None:
                flat, counts = arg
                mask = np.repeat(mask, counts)
                arg = flat
            inter = fn.aggregate(arg, mask) if n else fn.identity()
            cells.append(serialize_value(inter))
        return Block(schema, [np.array([c], object) for c in cells])

    if n == 0:
        return Block.empty(schema)
    key_cols = [eval_expr(e, block) for e in group_exprs]
    codes, num_groups, first = factorize(key_cols)
    base = np.ones(n, bool)
    out: List[np.ndarray] = [kc[first] for kc in key_cols]
    for fn, arg, fmask in zip(fns, arg_vals, filt_masks):
        mask = base if fmask is None else fmask
        keys = codes
        if fn.mv_input and arg is not None:
            flat, counts = arg
            mask = np.repeat(mask, counts)
            keys = np.repeat(codes, counts)
            arg = flat
        inters = fn.aggregate_grouped(arg, keys, num_groups, mask)
        cells = np.empty(num_groups, object)
        for g in range(num_groups):
            cells[g] = serialize_value(inters[g])
        out.append(cells)
    return Block(schema, out)


def final_merge_block(block: Block, num_group_cols: int,
                      agg_nodes: Sequence[Function],
                      schema: List[str]) -> Block:
    """Merge serialized partial intermediates (leaf_agg output, possibly
    from many workers) and extract final values."""
    from pinot_tpu.server.datatable import deserialize_value
    fns = []
    for node in agg_nodes:
        inner = node.args[0] if node.name == "filter_agg" else node
        fns.append(get_aggregation(inner.name, inner.args))
    n = block.num_rows

    if num_group_cols == 0:
        merged = [fn.identity() for fn in fns]
        for i, fn in enumerate(fns):
            col = block.arrays[i]
            for r in range(n):
                merged[i] = fn.merge(merged[i], deserialize_value(col[r]))
        return Block(schema, [np.array([fn.extract_final(m)], object)
                              for fn, m in zip(fns, merged)])

    if n == 0:
        return Block.empty(schema)
    key_cols = list(block.arrays[:num_group_cols])
    codes, num_groups, first = factorize(key_cols)
    out: List[np.ndarray] = [kc[first] for kc in key_cols]
    for i, fn in enumerate(fns):
        col = block.arrays[num_group_cols + i]
        merged = [None] * num_groups
        for r in range(n):
            g = codes[r]
            inter = deserialize_value(col[r])
            merged[g] = inter if merged[g] is None \
                else fn.merge(merged[g], inter)
        finals = np.empty(num_groups, object)
        for g in range(num_groups):
            finals[g] = fn.extract_final(
                merged[g] if merged[g] is not None else fn.identity())
        out.append(finals)
    return Block(schema, out)


# ---------------------------------------------------------------------------
# pipelined (chunk-at-a-time) folds — the incremental twins of
# aggregate_block / final_merge_block: intermediate stages consume mailbox
# frames AS THEY ARRIVE (runtime.py chunks sender output and bounds the
# receive buffer by a watermark), so upstream compute overlaps downstream
# merge and fan-in no longer serializes on the slowest sender. Correctness
# rides the same partial/merge contract the two-phase leaf split already
# uses: per-chunk grouped intermediates merge associatively, and the
# output re-sorts groups into the barrier path's factorize order so frame
# ARRIVAL order never leaks into the result row order.
# ---------------------------------------------------------------------------

def _agg_fns(agg_nodes: Sequence[Function]):
    fns = []
    for node in agg_nodes:
        inner = node.args[0] if node.name == "filter_agg" else node
        fns.append(get_aggregation(inner.name, inner.args))
    return fns


def _key_obj_columns(keys: List[tuple], nk: int) -> List[np.ndarray]:
    cols = []
    for i in range(nk):
        col = np.empty(len(keys), object)
        for r, k in enumerate(keys):
            col[r] = k[i]
        cols.append(col)
    return cols


def _restore_dtype(col: np.ndarray) -> np.ndarray:
    """The fold's key columns accumulate as object arrays; restore the
    numeric dtype the barrier path would have carried (kc[first] keeps
    eval_expr's dtype) — downstream sorts/joins compare numerically,
    and a silent object column would string-order 11 before 2."""
    try:
        arr = np.asarray(col.tolist())
        return arr if arr.dtype.kind in "iufb" else col
    except (ValueError, TypeError):
        return col


def _sorted_group_order(key_cols: List[np.ndarray]) -> np.ndarray:
    """Row order matching the barrier path's factorize group order
    (np.unique sorts codes): frame ARRIVAL order must not leak into the
    output row order, or same-seed replays stop being byte-identical."""
    codes, _ng, _first = factorize(key_cols)
    return np.argsort(codes, kind="stable")


def _finalize_fold(state: "dict[tuple, list]", fns, nk: int,
                   schema: List[str]) -> Block:
    """Shared fold tail: key columns (original dtypes restored) +
    extract_final per (group, agg), rows in the barrier path's sorted
    group order."""
    if not state:
        return Block.empty(schema)
    keys = list(state)
    out = [_restore_dtype(c) for c in _key_obj_columns(keys, nk)]
    for i, fn in enumerate(fns):
        col = np.empty(len(keys), object)
        for r, kt in enumerate(keys):
            col[r] = fn.extract_final(state[kt][i])
        out.append(col)
    order = _sorted_group_order(out[:nk])
    return Block(schema, [c[order] for c in out])


def fold_aggregate_chunks(chunks, group_exprs: Sequence[Expression],
                          agg_nodes: Sequence[Function],
                          schema: List[str]) -> Block:
    """Incremental final aggregation over an iterator of Blocks —
    result-equivalent to ``aggregate_block(Block.concat(chunks))``."""
    fns0 = _agg_fns(agg_nodes)

    if not group_exprs:
        merged = [fn.identity() for fn in fns0]
        for block in chunks:
            n = block.num_rows
            if not n:
                continue
            fns, arg_vals, filt_masks = _prepare_aggs(block, agg_nodes)
            base = np.ones(n, bool)
            for i, (fn, arg, fmask) in enumerate(
                    zip(fns, arg_vals, filt_masks)):
                mask = base if fmask is None else fmask
                if fn.mv_input and arg is not None:
                    flat, counts = arg
                    mask = np.repeat(mask, counts)
                    arg = flat
                merged[i] = fn.merge(merged[i], fn.aggregate(arg, mask))
        vals = [fn.extract_final(m) for fn, m in zip(fns0, merged)]
        return Block(schema, [np.array([v], object) for v in vals])

    state: "dict[tuple, list]" = {}
    for block in chunks:
        n = block.num_rows
        if not n:
            continue
        key_cols = [eval_expr(e, block) for e in group_exprs]
        codes, num_groups, first = factorize(key_cols)
        fns, arg_vals, filt_masks = _prepare_aggs(block, agg_nodes)
        base = np.ones(n, bool)
        per = []
        for fn, arg, fmask in zip(fns, arg_vals, filt_masks):
            mask = base if fmask is None else fmask
            keys = codes
            if fn.mv_input and arg is not None:
                flat, counts = arg
                mask = np.repeat(mask, counts)
                keys = np.repeat(codes, counts)
                arg = flat
            per.append(fn.aggregate_grouped(arg, keys, num_groups, mask))
        for g in range(num_groups):
            kt = tuple(_py(kc[first[g]]) for kc in key_cols)
            cur = state.get(kt)
            if cur is None:
                state[kt] = [per[i][g] for i in range(len(fns))]
            else:
                for i, fn in enumerate(fns):
                    cur[i] = fn.merge(cur[i], per[i][g])
    return _finalize_fold(state, fns0, len(group_exprs), schema)


def fold_final_merge_chunks(chunks, num_group_cols: int,
                            agg_nodes: Sequence[Function],
                            schema: List[str]) -> Block:
    """Incremental merge of serialized leaf_agg intermediates —
    result-equivalent to ``final_merge_block(Block.concat(chunks))``.
    The per-cell deserialize+merge loop (the dominant intermediate-stage
    cost on wide fan-in) now runs while later senders still compute."""
    from pinot_tpu.server.datatable import deserialize_value
    fns = _agg_fns(agg_nodes)

    if num_group_cols == 0:
        merged = [fn.identity() for fn in fns]
        for block in chunks:
            for i, fn in enumerate(fns):
                col = block.arrays[i]
                for r in range(block.num_rows):
                    merged[i] = fn.merge(merged[i],
                                         deserialize_value(col[r]))
        return Block(schema, [np.array([fn.extract_final(m)], object)
                              for fn, m in zip(fns, merged)])

    state: "dict[tuple, list]" = {}
    for block in chunks:
        n = block.num_rows
        if not n:
            continue
        kcols = block.arrays[:num_group_cols]
        acols = block.arrays[num_group_cols:num_group_cols + len(fns)]
        for r in range(n):
            kt = tuple(_py(kc[r]) for kc in kcols)
            cur = state.get(kt)
            if cur is None:
                state[kt] = [deserialize_value(ac[r]) for ac in acols]
            else:
                for i, fn in enumerate(fns):
                    cur[i] = fn.merge(cur[i], deserialize_value(acols[i][r]))
    return _finalize_fold(state, fns, num_group_cols, schema)


# ---------------------------------------------------------------------------
# sort / limit (ref SortOperator.java)
# ---------------------------------------------------------------------------

def _sort_key_encode(c: np.ndarray, asc: bool) -> np.ndarray:
    """Encode one sort-key column for np.lexsort honoring direction."""
    if c.dtype.kind == "O":
        c = _as_str(c)
    if not asc:
        if c.dtype.kind in "US":
            # lexsort has no descending option for strings: rank them
            _, inv = np.unique(c, return_inverse=True)
            c = -inv
        elif c.dtype.kind in "iu":
            # negate as int64: the float64 detour aliases above 2^53
            c = -c.astype(np.int64, copy=False)
        else:
            c = -c.astype(np.float64, copy=False)
    return c


def sort_block(block: Block, keys: Sequence[Expression], ascs: Sequence[bool],
               limit: int, offset: int) -> Block:
    if keys and block.num_rows > 1:
        cols = [_sort_key_encode(eval_expr(e, block), asc)
                for e, asc in zip(reversed(list(keys)),
                                  reversed(list(ascs)))]
        idx = np.lexsort(cols)
        block = block.take(idx)
    if offset:
        block = block.take(np.arange(offset, block.num_rows))
    if limit >= 0 and block.num_rows > limit:
        block = block.take(np.arange(limit))
    return block


# ---------------------------------------------------------------------------
# window functions (ref runtime/operator/WindowAggregateOperator.java +
# operator/window/ rank/value/aggregate families) — whole-block vectorized:
# sort rows by (partition, order keys), compute per-row results with
# prefix-scan doubling, scatter back to input order
# ---------------------------------------------------------------------------

def _segmented_scan(vals: np.ndarray, start: np.ndarray, op) -> np.ndarray:
    """Inclusive running `op` (np.minimum/np.maximum) within segments whose
    per-row segment start position is `start` — Hillis-Steele doubling, so
    O(n log n) without a Python loop over partitions."""
    out = vals.copy()
    n = len(out)
    pos = np.arange(n)
    d = 1
    while d < n:
        take = pos >= start + d
        shifted = np.empty_like(out)
        shifted[d:] = out[:-d]
        out = np.where(take, op(out, shifted), out)
        d *= 2
    return out


def window_block(block: Block, partition: Sequence[Expression],
                 order_keys: Sequence[Expression], ascs: Sequence[bool],
                 over_nodes: Sequence[Function],
                 schema: List[str]) -> Block:
    """Evaluate one window spec; appends one column per over node.

    Default SQL frame semantics: with ORDER BY, aggregates use RANGE
    UNBOUNDED PRECEDING..CURRENT ROW (peers included); without, the whole
    partition. first_value/last_value follow the same frame (the standard
    last_value-gotcha included); lag/lead are row-based.
    """
    n = block.num_rows
    if n == 0:
        return Block(schema, list(block.arrays)
                     + [np.empty(0, object) for _ in over_nodes])

    okey_vals = [eval_expr(e, block) for e in order_keys]
    if partition:
        pcodes, _np_, _ = factorize([eval_expr(e, block) for e in partition])
    else:
        pcodes = np.zeros(n, np.int64)
    if order_keys:
        ocodes, _no_, _ = factorize(list(okey_vals))
    else:
        ocodes = np.zeros(n, np.int64)

    # sort: partition primary, then order keys with direction
    sort_cols = [_sort_key_encode(c, asc)
                 for c, asc in zip(reversed(okey_vals), reversed(list(ascs)))]
    sort_cols.append(pcodes)
    idx = np.lexsort(sort_cols) if len(sort_cols) > 1 \
        else np.argsort(pcodes, kind="stable")

    pcs = pcodes[idx]
    ocs = ocodes[idx]
    pos = np.arange(n)
    pstart_mark = np.r_[True, pcs[1:] != pcs[:-1]]
    part_start = np.maximum.accumulate(np.where(pstart_mark, pos, 0))
    peer_mark = pstart_mark | np.r_[True, ocs[1:] != ocs[:-1]]
    peer_gid = np.cumsum(peer_mark) - 1
    peer_last = np.zeros(peer_gid[-1] + 1, np.int64)
    np.maximum.at(peer_last, peer_gid, pos)
    peer_end = peer_last[peer_gid]          # last row of the peer group
    pgid = np.cumsum(pstart_mark) - 1
    plast = np.zeros(pgid[-1] + 1, np.int64)
    np.maximum.at(plast, pgid, pos)
    part_end = plast[pgid]

    framed_end = peer_end if order_keys else part_end

    arg_cache: Dict[Expression, np.ndarray] = {}

    def sorted_arg(e: Expression) -> np.ndarray:
        got = arg_cache.get(e)
        if got is None:
            got = eval_expr(e, block)[idx]
            arg_cache[e] = got
        return got

    def frame_bounds(over: Function):
        """Explicit ROWS BETWEEN frame -> (fstart, fend, empty) arrays,
        or None for the default frame (ref operator/window/ frame
        handling: RowBasedWindowFrame)."""
        if len(over.args) < 4:
            return None
        fr = over.args[3]
        assert isinstance(fr, Function) and fr.name == "__frame"
        lo = fr.args[1].value  # type: ignore[union-attr]
        hi = fr.args[2].value  # type: ignore[union-attr]
        if lo == "uf" or hi == "up":
            raise ValueError("invalid ROWS frame bounds")
        if lo != "up" and hi != "uf" and int(lo) > int(hi):
            raise ValueError(
                f"ROWS frame start after end ({lo} > {hi})")
        fstart = part_start if lo == "up" else \
            np.clip(pos + int(lo), part_start, part_end)
        fend = part_end if hi == "uf" else \
            np.clip(pos + int(hi), part_start, part_end)
        # truly-empty frames (entirely before/after the partition)
        empty = np.zeros(n, bool)
        if lo not in ("up",) and hi not in ("uf",):
            empty |= (pos + int(hi) < part_start) | \
                (pos + int(lo) > part_end)
        elif hi not in ("uf",):
            empty |= pos + int(hi) < part_start
        elif lo not in ("up",):
            empty |= pos + int(lo) > part_end
        return fstart, fend, empty, lo, hi

    def framed_agg(name, inner, bounds):
        fstart, fend, empty, lo, hi = bounds
        if name == "count":
            res = (fend - fstart + 1).astype(np.float64)
            res[empty] = 0
            return res.astype(np.int64)
        v = sorted_arg(inner.args[0])
        if name in ("first_value", "last_value"):
            res = np.empty(n, object)
            src = fstart if name == "first_value" else fend
            res[~empty] = v[src[~empty]]
            res[empty] = None
            return res
        v = v.astype(np.float64, copy=False)
        if name in ("sum", "avg"):
            cum = np.cumsum(v)
            total = cum[fend] - cum[fstart] + v[fstart]
            if name == "avg":
                total = total / np.maximum(fend - fstart + 1, 1)
            out = np.empty(n, object)
            out[~empty] = total[~empty]
            out[empty] = None
            return out
        assert name in ("min", "max")
        op = np.minimum if name == "min" else np.maximum
        if lo == "up":
            sc = _segmented_scan(v, part_start, op)
            res = sc[fend]
        elif hi == "uf":
            # backward scan: reverse, scan with reversed partition marks
            rv = v[::-1]
            rstart = (n - 1) - part_end[::-1]
            sc = _segmented_scan(rv, rstart, op)
            res = sc[::-1][fstart]
        else:
            width = int(hi) - int(lo)
            if width > 65536:
                raise ValueError("ROWS frame too wide")
            ident = np.inf if name == "min" else -np.inf
            res = np.full(n, ident)
            for d in range(int(lo), int(hi) + 1):
                src = pos + d
                ok = (src >= part_start) & (src <= part_end)
                shifted = v[np.clip(src, 0, n - 1)]
                res = np.where(ok, op(res, shifted), res)
        out = np.empty(n, object)
        out[~empty] = res[~empty]
        out[empty] = None
        return out

    FRAMEABLE = ("sum", "count", "avg", "min", "max",
                 "first_value", "last_value")

    out_cols: List[np.ndarray] = []
    for over in over_nodes:
        inner = over.args[0]
        assert isinstance(inner, Function)
        name = inner.name
        bounds = frame_bounds(over)
        if bounds is not None and name in FRAMEABLE:
            res = framed_agg(name, inner, bounds)
        elif name == "row_number":
            res = (pos - part_start + 1).astype(np.int64)
        elif name == "rank":
            peer_first = np.maximum.accumulate(np.where(peer_mark, pos, 0))
            res = (peer_first - part_start + 1).astype(np.int64)
        elif name == "dense_rank":
            csum = np.cumsum(peer_mark)
            res = (csum - csum[part_start] + 1).astype(np.int64)
        elif name == "ntile":
            buckets = int(_literal_arg(inner, 0, required=True))
            size = part_end - part_start + 1
            rel = pos - part_start
            res = (rel * buckets // size + 1).astype(np.int64)
        elif name in ("lag", "lead"):
            vals = sorted_arg(inner.args[0])
            off = int(_literal_arg(inner, 1, default=1))
            default = _literal_arg(inner, 2, default=None)
            if name == "lag":
                src = pos - off
                ok = src >= part_start
            else:
                src = pos + off
                ok = src <= part_end
            src = np.clip(src, 0, n - 1)
            res = np.empty(n, object)
            res[ok] = vals[src[ok]]
            res[~ok] = default
        elif name == "first_value":
            res = sorted_arg(inner.args[0])[part_start]
        elif name == "last_value":
            res = sorted_arg(inner.args[0])[framed_end]
        elif name in ("sum", "count", "avg", "min", "max"):
            cnt_run = (pos - part_start + 1).astype(np.float64)
            if name == "count":
                res = cnt_run[framed_end].astype(np.int64)
            else:
                v = sorted_arg(inner.args[0]).astype(np.float64, copy=False)
                if name in ("sum", "avg"):
                    cum = np.cumsum(v)
                    base = cum[part_start] - v[part_start]
                    run = cum - base
                    res = run[framed_end]
                    if name == "avg":
                        res = res / cnt_run[framed_end]
                elif name == "min":
                    res = _segmented_scan(v, part_start, np.minimum)[framed_end]
                else:
                    res = _segmented_scan(v, part_start, np.maximum)[framed_end]
        else:
            raise ValueError(f"unsupported window function {name!r}")
        # scatter back to input row order
        unsorted = np.empty(n, dtype=object if res.dtype.kind == "O"
                            else res.dtype)
        unsorted[idx] = res
        out_cols.append(unsorted)
    return Block(schema, list(block.arrays) + out_cols)


def _literal_arg(fn: Function, i: int, default=None, required: bool = False):
    from pinot_tpu.query.expressions import Literal
    if len(fn.args) > i and isinstance(fn.args[i], Literal):
        return fn.args[i].value
    if required:
        raise ValueError(f"{fn.name} needs a literal argument {i}")
    return default


# ---------------------------------------------------------------------------
# set operators (ref runtime/operator/SetOperator.java +
# Union/Intersect/MinusOperator) — rows hashed to workers on all columns,
# so per-worker multiset logic is globally exact
# ---------------------------------------------------------------------------

def set_op_block(left: Block, right: Block, kind: str, all_: bool,
                 schema: List[str]) -> Block:
    if kind == "union":
        both = Block.concat([left, right.rename(left.names)])
        if all_ or both.num_rows == 0:
            return both.rename(schema)
        _codes, _k, first = factorize(list(both.arrays))
        return both.take(np.sort(first)).rename(schema)

    cl, cr = _factorize_pair(list(left.arrays), list(right.arrays))
    k = int(max(cl.max() if len(cl) else -1,
                cr.max() if len(cr) else -1)) + 1
    lcount = np.bincount(cl, minlength=k)
    rcount = np.bincount(cr, minlength=k)
    if kind == "intersect":
        keep_per_code = np.minimum(lcount, rcount) if all_ \
            else np.minimum(np.minimum(lcount, rcount), 1)
    else:  # except
        keep_per_code = np.maximum(lcount - rcount, 0) if all_ \
            else (np.minimum(lcount, 1) * (rcount == 0))
    # emit the first keep_per_code[c] left rows of each code, stable order
    order = np.argsort(cl, kind="stable")
    sorted_codes = cl[order]
    rank_in_code = np.arange(len(cl)) - np.searchsorted(
        sorted_codes, sorted_codes, side="left")
    keep_sorted = rank_in_code < keep_per_code[sorted_codes]
    keep_idx = np.sort(order[keep_sorted])
    return left.take(keep_idx).rename(schema)

def hash_partition(block: Block, key_exprs: Sequence[Expression],
                   num_partitions: int) -> List[Block]:
    """Deterministic value-based partitioning: equal values land on the
    same partition regardless of sender (int identity / utf-8 crc32)."""
    if num_partitions == 1:
        return [block]
    n = block.num_rows
    h = np.zeros(n, np.uint64)
    for e in key_exprs:
        h = h * np.uint64(1000003) + _value_hash(eval_expr(e, block))
    part = (h % np.uint64(num_partitions)).astype(np.int64)
    return [block.mask(part == p) for p in range(num_partitions)]


def _value_hash(c: np.ndarray) -> np.ndarray:
    """Per-VALUE canonical hash, identical across dtypes: integral values
    (int, bool, integral float, int-in-object) hash by int64 identity;
    everything else by crc32 of str(value). An int64 column and an
    object-dtype aggregate output holding the same numbers must agree, or
    the two sides of a join land on different workers."""
    if c.dtype.kind in "iub":
        return c.astype(np.int64, copy=False).view(np.uint64)
    if c.dtype.kind == "f":
        cf = c.astype(np.float64, copy=False)
        ints = np.isfinite(cf) & (cf == np.floor(cf)) & \
            (np.abs(cf) < 2 ** 62)
        ci = np.where(ints, cf, 0).astype(np.int64)
        crc = np.array([np.uint64(zlib.crc32(str(float(v)).encode()))
                        for v in cf], np.uint64)
        return np.where(ints, ci.view(np.uint64), crc)
    out = np.empty(len(c), np.uint64)
    for i, v in enumerate(c):
        if isinstance(v, np.generic):
            v = v.item()
        if isinstance(v, bool):
            out[i] = np.int64(int(v)).astype(np.uint64)
        elif isinstance(v, int):
            out[i] = np.int64(v).astype(np.uint64)
        elif isinstance(v, float):
            if np.isfinite(v) and v == int(v) and abs(v) < 2 ** 62:
                out[i] = np.int64(int(v)).astype(np.uint64)
            else:
                out[i] = np.uint64(zlib.crc32(str(float(v)).encode()))
        elif v is None:
            out[i] = np.uint64(0)
        else:
            out[i] = np.uint64(zlib.crc32(str(v).encode()))
    return out
