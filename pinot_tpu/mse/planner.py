"""Physical planner: logical tree -> distributed stage DAG.

Reference parity: pinot-query-planner planner/physical/ — fragmenting the
logical plan into stages at exchange boundaries and assigning workers
(DispatchablePlanFragment). Rules here (v1):

  * every Scan / SubqueryScan is its own leaf stage
  * every Join is a stage; both inputs hash-exchange on the join keys
    (cross / residual-only joins use singleton exchange)
  * every Aggregate is a stage; input hash-exchanges on the group keys
    (no keys -> singleton), so each worker owns whole key groups and
    one-phase FINAL aggregation is exact for every function incl. sketches
  * Filter / Project fuse into the stage that PRODUCES their input
    (pushdown: less data on the wire)
  * the topmost Sort (global order/limit) and anything above it run in the
    root stage (stage 0) on the broker; senders pre-apply a local
    sort+limit when a limit exists (root re-sorts, so this is safe)

Stages serialize to JSON for the dispatch wire.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from pinot_tpu.mse import logical as L
from pinot_tpu.mse.serde import expr_to_json, exprs_to_json


@dataclass
class StagePlan:
    stage_id: int
    root: Dict[str, Any] = field(default_factory=dict)  # physical op tree
    workers: List[str] = field(default_factory=list)
    out_kind: Optional[str] = None       # hash | singleton | broadcast
    out_keys: List[Any] = field(default_factory=list)   # expr JSON
    receiver_stage: int = -1
    schema: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "stageId": self.stage_id, "root": self.root,
            "workers": self.workers, "outKind": self.out_kind,
            "outKeys": self.out_keys, "receiverStage": self.receiver_stage,
            "schema": self.schema,
        }

    @staticmethod
    def from_json(j: Dict[str, Any]) -> "StagePlan":
        return StagePlan(
            stage_id=j["stageId"], root=j["root"], workers=j["workers"],
            out_kind=j.get("outKind"), out_keys=j.get("outKeys", []),
            receiver_stage=j.get("receiverStage", -1),
            schema=j.get("schema", []))


@dataclass
class QueryPlan:
    stages: List[StagePlan]              # stages[0] is the root
    options: Dict[str, str] = field(default_factory=dict)

    @property
    def root(self) -> StagePlan:
        return self.stages[0]

    def stage(self, sid: int) -> StagePlan:
        for s in self.stages:
            if s.stage_id == sid:
                return s
        raise KeyError(sid)

    def senders_to(self, sid: int) -> List[StagePlan]:
        return [s for s in self.stages if s.receiver_stage == sid]


class _Fragmenter:
    def __init__(self, table_workers: Callable[[str], List[str]],
                 intermediate_workers: List[str]):
        self.table_workers = table_workers
        self.intermediate = intermediate_workers
        self.stages: List[StagePlan] = []
        self._next_id = 0

    def new_stage(self, workers: List[str]) -> StagePlan:
        s = StagePlan(stage_id=self._next_id, workers=workers)
        self._next_id += 1
        self.stages.append(s)
        return s

    # ------------------------------------------------------------------
    def fragment_to_stage(self, node: L.LogicalNode) -> StagePlan:
        """Produce a stage whose root op computes `node` in full (fusing
        Filter/Project chains into the producing stage)."""
        if isinstance(node, L.Scan):
            s = self.new_stage(self.table_workers(node.table))
            s.root = {"op": "scan", "table": node.table,
                      "alias": node.alias, "columns": node.columns,
                      "filter": expr_to_json(node.filter),
                      "schema": node.schema}
            s.schema = node.schema
            return s

        if isinstance(node, L.SubqueryScan):
            s = self.fragment_to_stage(node.child)
            s.root = {"op": "rename", "child": s.root,
                      "schema": node.schema}
            s.schema = node.schema
            return s

        if isinstance(node, L.Join):
            s = self.new_stage(list(self.intermediate))
            left = self.fragment_to_stage(node.left)
            right = self.fragment_to_stage(node.right)
            lk = exprs_to_json(node.left_keys)
            rk = exprs_to_json(node.right_keys)
            self._connect(left, s, lk)
            self._connect(right, s, rk)
            out_schema = node.left.schema if node.join_type in ("semi", "anti") \
                else node.schema
            s.root = {"op": "join", "type": node.join_type,
                      "left": _receive(left), "right": _receive(right),
                      "leftKeys": lk, "rightKeys": rk,
                      "residual": expr_to_json(node.residual),
                      "schema": out_schema}
            s.schema = out_schema
            return s

        if isinstance(node, L.Aggregate):
            # no group keys -> singleton exchange: exactly ONE worker must
            # aggregate (a second would emit a spurious identity row)
            workers = list(self.intermediate) if node.group_exprs \
                else list(self.intermediate)[:1]
            s = self.new_stage(workers)
            child = self.fragment_to_stage(node.child)
            gk = exprs_to_json(node.group_exprs)
            aggs = exprs_to_json(node.agg_nodes)
            if _is_leaf_chain(child.root):
                # two-phase aggregation (ref LeafStageTransferableBlockOperator
                # + AggregateOperator intermediate/final split): the leaf
                # stage partially aggregates ON the scanning servers — the
                # single-stage engine (TPU path included) runs the scan-agg
                # hot loop, and only per-group INTERMEDIATES cross the wire.
                child.root = {"op": "leaf_agg", "child": child.root,
                              "groupExprs": gk, "aggNodes": aggs,
                              "schema": node.schema}
                child.schema = node.schema
                group_ids = [["id", n]
                             for n in node.schema[:len(node.group_exprs)]]
                self._connect(child, s, group_ids)
                s.root = {"op": "final_agg", "child": _receive(child),
                          "numGroups": len(node.group_exprs),
                          "aggNodes": aggs, "schema": node.schema}
                s.schema = node.schema
                return s
            self._connect(child, s, gk)
            s.root = {"op": "aggregate", "child": _receive(child),
                      "groupExprs": gk,
                      "aggNodes": aggs,
                      "schema": node.schema}
            s.schema = node.schema
            return s

        if isinstance(node, L.Window):
            # all rows of a partition must land on one worker: hash
            # exchange on the partition keys (singleton when unpartitioned)
            pk = exprs_to_json(node.partition)
            workers = list(self.intermediate) if node.partition \
                else list(self.intermediate)[:1]
            s = self.new_stage(workers)
            child = self.fragment_to_stage(node.child)
            self._connect(child, s, pk)
            s.root = {"op": "window", "child": _receive(child),
                      "partition": pk,
                      "orderKeys": exprs_to_json(node.order_keys),
                      "ascs": list(node.ascs),
                      "overs": exprs_to_json(node.over_nodes),
                      "schema": node.schema}
            s.schema = node.schema
            return s

        if isinstance(node, L.SetOp):
            # hash both inputs on ALL columns so equal rows meet on one
            # worker and per-worker set semantics compose globally. Hash
            # keys must resolve POSITIONALLY (duplicate output names would
            # alias to one column), so each side renames to __setN first.
            # UNION ALL needs no co-location at all — one-column hash
            # keeps the distribution without hashing every column.
            s = self.new_stage(list(self.intermediate))
            left = self.fragment_to_stage(node.left)
            right = self.fragment_to_stage(node.right)
            pos = [f"__set{i}" for i in range(len(node.left.schema))]
            for side in (left, right):
                side.root = {"op": "rename", "child": side.root,
                             "schema": pos}
                side.schema = pos
            keys = [["id", pos[0]]] if node.op == "union" and node.all \
                else [["id", n] for n in pos]
            self._connect(left, s, keys)
            self._connect(right, s, keys)
            s.root = {"op": "setop", "kind": node.op, "all": node.all,
                      "left": _receive(left), "right": _receive(right),
                      "schema": node.schema}
            s.schema = node.schema
            return s

        if isinstance(node, L.Filter):
            s = self.fragment_to_stage(node.child)
            s.root = {"op": "filter", "child": s.root,
                      "condition": expr_to_json(node.condition),
                      "schema": node.schema}
            s.schema = node.schema
            return s

        if isinstance(node, L.Project):
            s = self.fragment_to_stage(node.child)
            s.root = {"op": "project", "child": s.root,
                      "exprs": exprs_to_json(node.exprs),
                      "names": node.names, "schema": node.schema}
            s.schema = node.schema
            return s

        if isinstance(node, L.Sort):
            # a non-topmost sort (subquery ORDER BY LIMIT) needs a global
            # view, so it gets its OWN single-worker stage fed by a
            # singleton exchange — narrowing the producing stage itself
            # would silently drop other servers' scan shards
            child = self.fragment_to_stage(node.child)
            s = self.new_stage(list(self.intermediate)[:1])
            self._connect(child, s, [])
            s.root = {"op": "sort", "child": _receive(child),
                      "keys": exprs_to_json(node.keys), "ascs": node.ascs,
                      "limit": node.limit, "offset": node.offset,
                      "schema": node.schema}
            s.schema = node.schema
            return s

        raise L.PlanError(f"cannot fragment {type(node).__name__}")

    @staticmethod
    def _connect(child: StagePlan, parent: StagePlan,
                 hash_keys: List[Any]) -> None:
        child.receiver_stage = parent.stage_id
        if hash_keys:
            child.out_kind = "hash"
            child.out_keys = hash_keys
        else:
            child.out_kind = "singleton"


def _is_leaf_chain(op: Dict[str, Any]) -> bool:
    """True when the op tree is a pure table-local chain (scan with only
    stateless row ops above) — the shape the leaf executor can take over."""
    kind = op["op"]
    if kind == "scan":
        return True
    if kind in ("filter", "project", "rename"):
        return _is_leaf_chain(op["child"])
    return False


def _receive(child: StagePlan) -> Dict[str, Any]:
    return {"op": "receive", "stage": child.stage_id, "schema": child.schema}


def plan_query(root_logical: L.LogicalNode, options: Dict[str, str],
               table_workers: Callable[[str], List[str]],
               intermediate_workers: List[str]) -> QueryPlan:
    """Fragment a logical plan into a stage DAG; stages[0] runs on the
    broker and owns the global Sort (and anything above it)."""
    f = _Fragmenter(table_workers, intermediate_workers)
    root_stage = f.new_stage(["broker"])

    # peel the chain above (and including) the topmost Sort into the root
    root_chain: List[L.LogicalNode] = []
    node = root_logical
    while isinstance(node, (L.Project, L.Sort)):
        root_chain.append(node)
        is_sort = isinstance(node, L.Sort)
        node = node.child
        if is_sort:
            break

    child = f.fragment_to_stage(node)
    f._connect(child, root_stage, [])

    # local sort+limit at the sender bounds shuffled rows; the root re-sorts
    sort = next((n for n in root_chain if isinstance(n, L.Sort)), None)
    if sort is not None and sort.limit >= 0 and child.root["op"] != "aggregate":
        child.root = {"op": "sort", "child": child.root,
                      "keys": exprs_to_json(sort.keys), "ascs": sort.ascs,
                      "limit": sort.limit + sort.offset, "offset": 0,
                      "schema": child.schema}

    op: Dict[str, Any] = _receive(child)
    for n in reversed(root_chain):
        if isinstance(n, L.Sort):
            op = {"op": "sort", "child": op,
                  "keys": exprs_to_json(n.keys), "ascs": n.ascs,
                  "limit": n.limit, "offset": n.offset, "schema": n.schema}
        else:
            op = {"op": "project", "child": op,
                  "exprs": exprs_to_json(n.exprs),
                  "names": n.names, "schema": n.schema}
    root_stage.root = op
    root_stage.schema = root_logical.schema
    return QueryPlan(stages=f.stages, options=dict(options))
