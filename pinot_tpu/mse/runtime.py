"""Stage runtime: executes one stage instance on a worker.

Reference parity: pinot-query-runtime QueryRunner.java:94 (processQuery ->
build op chain per stage, schedule) and
LeafStageTransferableBlockOperator (leaf stage runs on the single-stage
executor — QueryRunner.java:258). Here a stage instance materializes its
op tree bottom-up (receive -> vectorized block ops), partitions the output
per the stage's exchange, and pushes to the receiver workers' mailboxes.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pinot_tpu.mse import operators as ops
from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.mailbox import (
    FLAG_EOS, FLAG_ERROR, MailboxError, MailboxService, mailbox_key)
from pinot_tpu.mse.planner import QueryPlan, StagePlan
from pinot_tpu.mse.serde import expr_from_json, exprs_from_json
from pinot_tpu.utils import tracing
from pinot_tpu.utils.accounting import (
    BrokerTimeoutError, QueryCancelledError)
from pinot_tpu.utils.failpoints import SimulatedCrash, fire

#: a scan callback: (table, columns, filter_expr_or_None) -> Block with the
#: instance's local rows for the table (qualified names applied by caller)
ScanFn = Callable[[str, List[str], Optional[object]], Block]


class StageContext:
    """Everything a stage instance needs to run."""

    def __init__(self, query_id: str, plan: QueryPlan, worker_id: str,
                 worker_idx: int, mailbox: MailboxService,
                 addresses: Dict[str, str], scan_fn: Optional[ScanFn],
                 timeout: float = 60.0, leaf_query_fn=None,
                 deadline: Optional[float] = None,
                 cancel_event: Optional[threading.Event] = None,
                 stage_cache=None, segment_versions_fn=None,
                 stage_id: int = -1, attempt: int = 0, claim_fn=None,
                 pipeline: bool = True, chunk_rows: int = 8192,
                 watermark_rows: int = 8192):
        self.query_id = query_id
        self.plan = plan
        self.worker_id = worker_id
        self.worker_idx = worker_idx
        self.mailbox = mailbox
        #: "stage:workerIdx" -> mailbox address
        self.addresses = addresses
        self.scan_fn = scan_fn
        self.timeout = timeout
        #: which stage instance this context runs (hedge cancel targets
        #: one (query, stage, attempt), never the whole query)
        self.stage_id = stage_id
        #: 0 = primary, >0 = hedge re-issue of the same stage instance
        self.attempt = attempt
        #: hedge output claim: claim_fn(clean) -> bool decides whether
        #: THIS attempt may send its output (exactly one attempt per
        #: (query, stage, worker-slot) is granted — mailbox-level dedup
        #: by construction). None = unhedged, always send.
        self.claim_fn = claim_fn
        #: pipelined intermediate stages (ISSUE 10): senders chunk
        #: output into <= chunk_rows frames; fold-capable receivers
        #: merge frames as they arrive, buffering at most
        #: watermark_rows decoded rows between folds
        self.pipeline = pipeline
        self.chunk_rows = max(1, int(chunk_rows))
        self.watermark_rows = max(1, int(watermark_rows))
        #: (table, QueryContext) -> per-segment SegmentResults via the
        #: single-stage executor (TPU engine included) — the
        #: LeafStageTransferableBlockOperator bridge; None on the broker
        self.leaf_query_fn = leaf_query_fn
        #: absolute wall-clock deadline for the whole query; None = no
        #: budget (legacy callers). Enforced cooperatively at every op
        #: boundary and as a hard wall on mailbox receives.
        self.deadline = deadline
        #: out-of-band cancel (broker deadline miss / client cancel)
        self.cancel_event = cancel_event or threading.Event()
        #: set by the worker's crash handler on SIBLING stages of a
        #: SimulatedCrash: the whole worker is "dead", so this stage
        #: must die SILENTLY — no error frames, no output sends —
        #: leaving detection to the receivers' sender-death probe
        self.worker_crashed = False
        #: leaf-stage output cache (mse/stage_cache.py), worker-side only
        self.stage_cache = stage_cache
        #: table -> sorted ((name, version), ...) of the instance's local
        #: segments, or None when any is mutable — the cache key source
        self.segment_versions_fn = segment_versions_fn

    def check(self) -> None:
        """Cooperative cancel/deadline poll — the same discipline as the
        single-stage accountant's check_cancelled (utils/accounting)."""
        if self.cancel_event.is_set():
            raise QueryCancelledError(
                f"query {self.query_id} cancelled")
        if self.deadline is not None and time.time() > self.deadline:
            raise BrokerTimeoutError(
                f"query {self.query_id} exceeded its deadline")

    def remaining_s(self) -> float:
        if self.deadline is None:
            return self.timeout
        return max(0.0, self.deadline - time.time())


def run_stage(ctx: StageContext, stage: StagePlan) -> Optional[Block]:
    """Execute one stage instance. Root stage (receiver_stage < 0) returns
    its block; other stages push to their receivers and return None.

    A ``SimulatedCrash`` (chaos worker kill) escapes WITHOUT propagating
    error frames — the worker must vanish silently, leaving detection to
    the receivers' sender-death probe."""
    try:
        try:
            fire("mse.stage.execute", instance=ctx.worker_id,
                 query_id=ctx.query_id, stage=stage.stage_id)
            block = _run_leaf_cached(ctx, stage)
        except SimulatedCrash:
            raise  # vanish: no error frames, no receiver handshake
        except Exception as e:  # noqa: BLE001 — report receivers, don't hang
            if ctx.worker_crashed:
                # sibling of a crashed worker: a dead process can't send
                # error frames over its live outbound sockets either —
                # stay silent so receivers exercise the death probe
                if stage.receiver_stage < 0:
                    raise
                return None
            if ctx.claim_fn is not None and not ctx.claim_fn(False):
                # hedged attempt failed while its twin is still running:
                # die silently — the twin owns the output slot (or will
                # claim the error itself if it is the last one standing)
                return None
            _propagate_error(ctx, stage, f"{type(e).__name__}: {e}")
            if stage.receiver_stage < 0:
                raise
            return None
        if stage.receiver_stage < 0:
            return block
        if ctx.worker_crashed:
            return None  # computed past the crash: output dies with us
        if ctx.claim_fn is not None and not ctx.claim_fn(True):
            # the twin attempt already claimed this (query, stage, slot)
            # and sent; sending too would double the receiver's rows
            return None
        _send_output(ctx, stage, block)
        return None
    finally:
        # drop any mailbox queues this instance didn't fully drain (e.g. a
        # join whose OTHER input errored first) — they'd leak otherwise
        for key in _receive_keys(ctx, stage.root):
            ctx.mailbox.discard(key)


def _run_leaf_cached(ctx: StageContext, stage: StagePlan) -> Block:
    """Leaf stages (scan / leaf_agg over immutable local segments) serve
    from the stage-output cache when the (segment version set, stage-plan
    fingerprint) key hits; everything else executes directly. Only clean,
    in-deadline completions are stored — never partials."""
    cache = ctx.stage_cache
    key = cache.key_for(stage.root, ctx.segment_versions_fn) \
        if cache is not None else None
    if key is not None:
        hit = cache.get(key)
        if hit is not None:
            return hit
    block = _run_op(ctx, stage.root)
    if key is not None:
        ctx.check()  # a deadline-clipped run must not populate the cache
        cache.put(key, block)
    return block


def _propagate_error(ctx: StageContext, stage: StagePlan, msg: str) -> None:
    """Error frames flow to the receiving stage so the root fails fast."""
    if stage.receiver_stage < 0:
        return
    receivers = ctx.plan.stage(stage.receiver_stage)
    payload = msg.encode()
    for w in range(len(receivers.workers)):
        key = mailbox_key(ctx.query_id, stage.stage_id,
                          stage.receiver_stage, w)
        addr = ctx.addresses[f"{stage.receiver_stage}:{w}"]
        try:
            ctx.mailbox.send(addr, key, payload, FLAG_ERROR)
        except Exception:  # noqa: BLE001 — best effort
            pass


def _send_output(ctx: StageContext, stage: StagePlan, block: Block) -> None:
    with tracing.Scope("mse:send", kind=stage.out_kind) as sc:
        receivers = ctx.plan.stage(stage.receiver_stage)
        nw = len(receivers.workers)
        if stage.out_kind == "hash" and nw > 1:
            keys = exprs_from_json(stage.out_keys)
            parts = ops.hash_partition(block, keys, nw)
        elif stage.out_kind == "broadcast":
            parts = [block] * nw
        else:  # singleton
            parts = [block] + [None] * (nw - 1)
        frames = sent_bytes = 0
        for w in range(nw):
            key = mailbox_key(ctx.query_id, stage.stage_id,
                              stage.receiver_stage, w)
            addr = ctx.addresses[f"{stage.receiver_stage}:{w}"]
            part = parts[w]
            if part is None or not part.num_rows:
                ctx.mailbox.send(addr, key, b"", FLAG_EOS)
                frames += 1
                continue
            # pipelined sends: a large partition ships as <= chunk_rows
            # frames (EOS rides the last) so a fold-capable receiver merges
            # the head of this output while the tail is still serializing —
            # and while SLOWER sibling senders are still computing
            chunk = ctx.chunk_rows if ctx.pipeline else part.num_rows
            n = part.num_rows
            starts = list(range(0, n, chunk))
            for i, s in enumerate(starts):
                piece = part if len(starts) == 1 else \
                    part.take(np.arange(s, min(s + chunk, n)))
                flags = FLAG_EOS if i == len(starts) - 1 else 0
                payload = piece.to_bytes()
                ctx.mailbox.send(addr, key, payload, flags)
                frames += 1
                sent_bytes += len(payload)
        sc.set(frames=frames, bytes=sent_bytes, receivers=nw)


# ---------------------------------------------------------------------------
# op interpreters
# ---------------------------------------------------------------------------

def _run_op(ctx: StageContext, op: Dict[str, Any]) -> Block:
    # cooperative deadline/cancel poll at every op boundary: block ops
    # are coarse (one vectorized pass each), so this is the same "check
    # between units of work" discipline as the per-segment loop
    ctx.check()
    kind = op["op"]
    if not tracing.active():
        return _run_op_inner(ctx, op, kind)
    # one span per op — the InvocationScope-around-nextBlock parity for
    # the multi-stage engine (stage threads run under the attempt's
    # RequestTrace, so these nest into the shipped tree)
    with tracing.Scope("mse:" + kind) as sc:
        block = _run_op_inner(ctx, op, kind)
        sc.set(rows=block.num_rows)
        return block


def _run_op_inner(ctx: StageContext, op: Dict[str, Any],
                  kind: str) -> Block:
    if kind == "receive":
        return _op_receive(ctx, op)
    if kind == "scan":
        return _op_scan(ctx, op)
    if kind == "rename":
        child = _run_op(ctx, op["child"])
        return child.rename(op["schema"])
    if kind == "filter":
        child = _run_op(ctx, op["child"])
        return ops.filter_block(child, expr_from_json(op["condition"]))
    if kind == "project":
        child = _run_op(ctx, op["child"])
        return ops.project_block(child, exprs_from_json(op["exprs"]),
                                 op["names"])
    if kind == "join":
        left = _run_op(ctx, op["left"])
        right = _run_op(ctx, op["right"])
        return ops.hash_join(
            left, right, op["type"],
            exprs_from_json(op["leftKeys"]), exprs_from_json(op["rightKeys"]),
            expr_from_json(op["residual"]), op["schema"])
    if kind == "aggregate":
        from pinot_tpu.query.expressions import Function
        aggs = [a for a in exprs_from_json(op["aggNodes"])
                if isinstance(a, Function)]
        groups = exprs_from_json(op["groupExprs"])
        if ctx.pipeline and op["child"]["op"] == "receive":
            # pipelined fan-in: fold shuffled frames as they arrive
            # instead of barriering on receive_all — the merge of early
            # senders' rows overlaps the slowest sender's compute
            return ops.fold_aggregate_chunks(
                _watermarked(ctx, _receive_chunks(ctx, op["child"])),
                groups, aggs, op["schema"])
        child = _run_op(ctx, op["child"])
        return ops.aggregate_block(child, groups, aggs, op["schema"])
    if kind == "leaf_agg":
        return _op_leaf_agg(ctx, op)
    if kind == "final_agg":
        aggs = exprs_from_json(op["aggNodes"])
        if ctx.pipeline and op["child"]["op"] == "receive":
            # the per-cell deserialize+merge loop dominates wide fan-in;
            # folding it per arriving frame overlaps upstream leaf_agg
            return ops.fold_final_merge_chunks(
                _watermarked(ctx, _receive_chunks(ctx, op["child"])),
                op["numGroups"], aggs, op["schema"])
        child = _run_op(ctx, op["child"])
        return ops.final_merge_block(
            child, op["numGroups"], aggs, op["schema"])
    if kind == "sort":
        child = _run_op(ctx, op["child"])
        return ops.sort_block(child, exprs_from_json(op["keys"]),
                              op["ascs"], op["limit"], op["offset"])
    if kind == "window":
        child = _run_op(ctx, op["child"])
        return ops.window_block(
            child, exprs_from_json(op["partition"]),
            exprs_from_json(op["orderKeys"]), op["ascs"],
            exprs_from_json(op["overs"]), op["schema"])
    if kind == "setop":
        left = _run_op(ctx, op["left"])
        right = _run_op(ctx, op["right"])
        return ops.set_op_block(left, right, op["kind"], op["all"],
                                op["schema"])
    raise ValueError(f"unknown op {kind!r}")


def _receive_keys(ctx: StageContext, op: Dict[str, Any]) -> List[str]:
    out = []
    if op["op"] == "receive":
        sender = ctx.plan.stage(op["stage"])
        out.append(mailbox_key(ctx.query_id, sender.stage_id,
                               sender.receiver_stage, ctx.worker_idx))
    for k in ("child", "left", "right"):
        child = op.get(k)
        if isinstance(child, dict):
            out.extend(_receive_keys(ctx, child))
    return out


def _receive_chunks(ctx: StageContext, op: Dict[str, Any]):
    """Yield decoded Blocks for a receive op IN ARRIVAL ORDER — the
    pipelined consumption primitive (fold-capable parents merge each
    chunk while remaining senders still compute)."""
    sender = ctx.plan.stage(op["stage"])
    key = mailbox_key(ctx.query_id, sender.stage_id,
                      sender.receiver_stage, ctx.worker_idx)
    # sender endpoints feed the mailbox's death probe: a crashed worker
    # whose listener is gone raises a typed MailboxError immediately
    # instead of waiting out the whole deadline
    sender_addresses = [
        ctx.addresses[f"{sender.stage_id}:{w}"]
        for w in range(len(sender.workers))
        if f"{sender.stage_id}:{w}" in ctx.addresses]
    frames = rbytes = 0
    t0 = time.perf_counter()
    try:
        for p in ctx.mailbox.receive_all(
                key, num_senders=len(sender.workers), timeout=ctx.timeout,
                deadline=ctx.deadline, cancel_event=ctx.cancel_event,
                sender_addresses=sender_addresses):
            frames += 1
            rbytes += len(p)
            try:
                b = Block.from_bytes(p)
            except Exception as e:  # noqa: BLE001 — torn/corrupt frame
                raise MailboxError(
                    f"mailbox {key}: undecodable frame "
                    f"({type(e).__name__}: {e})") from e
            if b.num_rows:
                yield b
    finally:
        # receive-side shuffle accounting on the enclosing op span
        # (mse:receive, or the folding aggregate) — frames/bytes plus
        # how long this instance sat consuming the mailbox
        tracing.annotate(
            recvFrames=frames, recvBytes=rbytes,
            recvMs=round((time.perf_counter() - t0) * 1e3, 3))


def _watermarked(ctx: StageContext, chunks):
    """Re-chunk an arriving Block stream at the pipeline watermark: at
    most ``watermark_rows`` decoded rows sit buffered between folds (the
    fold's working-set bound), while tiny frames batch up so the
    per-fold fixed cost amortizes. Polls the deadline/cancel between
    chunks — a long stream can't outlive its budget unnoticed."""
    buf: List[Block] = []
    buffered = 0
    for b in chunks:
        ctx.check()
        buf.append(b)
        buffered += b.num_rows
        if buffered >= ctx.watermark_rows:
            yield Block.concat(buf)
            buf, buffered = [], 0
    if buf:
        yield Block.concat(buf)


def _op_receive(ctx: StageContext, op: Dict[str, Any]) -> Block:
    blocks = list(_receive_chunks(ctx, op))
    if not blocks:
        return _typed_empty(op["schema"])
    return Block.concat(blocks)


def _typed_empty(schema: List[str]) -> Block:
    return Block(schema, [np.empty(0, object) for _ in schema])


def _op_leaf_agg(ctx: StageContext, op: Dict[str, Any]) -> Block:
    """Leaf-stage partial aggregation. Preferred path: rewrite the chain
    onto the single-stage executor (which stacks segments into device
    blocks — ref QueryRunner.java:258, leaf runs on the v1 engine) and ship
    merged per-group intermediates. Fallback: scan + host partial agg."""
    groups = exprs_from_json(op["groupExprs"])
    aggs = exprs_from_json(op["aggNodes"])
    if ctx.leaf_query_fn is not None:
        block = _leaf_agg_pushdown(ctx, op, groups, aggs)
        if block is not None:
            return block
    child = _run_op(ctx, op["child"])
    return ops.partial_aggregate_block(child, groups, aggs, op["schema"])


def _leaf_chain_map(op: Dict[str, Any]):
    """Resolve a leaf-local op chain to (table, physical filter expr,
    output-name -> physical expr map), or None when it doesn't map."""
    from pinot_tpu.query.expressions import Function, Identifier
    kind = op["op"]
    if kind == "scan":
        m = {out: Identifier(col)
             for out, col in zip(op["schema"], op["columns"])}
        return op["table"], expr_from_json(op["filter"]), m
    got = _leaf_chain_map(op["child"]) if "child" in op else None
    if got is None:
        return None
    table, filt, m = got
    if kind == "rename":
        child_schema = op["child"]["schema"]
        try:
            m2 = {new: m[old]
                  for new, old in zip(op["schema"], child_schema)}
        except KeyError:
            return None
        return table, filt, m2
    if kind == "project":
        try:
            m2 = {name: _substitute(e, m) for name, e in
                  zip(op["names"], exprs_from_json(op["exprs"]))}
        except KeyError:
            return None
        return table, filt, m2
    if kind == "filter":
        try:
            cond = _substitute(expr_from_json(op["condition"]), m)
        except KeyError:
            return None
        filt = cond if filt is None else Function("and", (filt, cond))
        return table, filt, m
    return None


#: group-key tuples -> per-column object arrays (shared with the
#: pipelined folds — one transpose implementation, not two)
_key_columns = ops._key_obj_columns


def _substitute(e, m):
    from pinot_tpu.query.expressions import Function, Identifier
    if isinstance(e, Identifier):
        if e.name == "*":  # COUNT(*) — not a real column
            return e
        return m[e.name]
    if isinstance(e, Function):
        return Function(e.name, tuple(_substitute(a, m) for a in e.args))
    return e


def _leaf_agg_pushdown(ctx: StageContext, op: Dict[str, Any],
                       groups, aggs) -> Optional[Block]:
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.results import AggregationResult, GroupByResult
    from pinot_tpu.server.datatable import serialize_value

    mapped = _leaf_chain_map(op["child"])
    if mapped is None:
        return None
    table, filt, m = mapped
    try:
        groups_p = [_substitute(e, m) for e in groups]
        aggs_p = [_substitute(e, m) for e in aggs]
    except KeyError:
        return None
    schema = op["schema"]
    if not aggs:
        # agg-less group-by (DISTINCT lowering): leaf-side dedup through
        # the single-stage DISTINCT path, group values only on the wire
        from pinot_tpu.query.results import DistinctResult
        qctx = QueryContext(
            table=table, select=groups_p, aliases=[None] * len(groups_p),
            distinct=True, filter=filt, group_by=[], having=None,
            order_by=[], limit=1 << 31, offset=0, options={})
        qctx._extract_aggregations()
        seen = set()
        for r in ctx.leaf_query_fn(table, qctx):
            assert isinstance(r, DistinctResult), r
            seen.update(r.rows)
        return Block(schema, _key_columns(list(seen), len(groups)))

    select = groups_p + aggs_p
    qctx = QueryContext(
        table=table, select=select, aliases=[None] * len(select),
        distinct=False, filter=filt, group_by=groups_p, having=None,
        order_by=[], limit=1 << 31, offset=0,
        options={"numGroupsLimit": str(1 << 31)})
    try:
        qctx._extract_aggregations()
        agg_idx = [qctx.agg_index(a) for a in aggs_p]
    except Exception:  # noqa: BLE001 — unsupported agg name etc.
        return None
    results = ctx.leaf_query_fn(table, qctx)

    if not groups:
        merged = [fn.identity() for fn in qctx.agg_functions]
        for r in results:
            assert isinstance(r, AggregationResult), r
            for i, fn in enumerate(qctx.agg_functions):
                merged[i] = fn.merge(merged[i], r.intermediates[i])
        cells = [serialize_value(merged[j]) for j in agg_idx]
        return Block(schema, [np.array([c], object) for c in cells])

    combined: Dict[tuple, list] = {}
    for r in results:
        assert isinstance(r, GroupByResult), r
        for key, inters in r.groups.items():
            cur = combined.get(key)
            if cur is None:
                combined[key] = list(inters)
            else:
                for i, fn in enumerate(qctx.agg_functions):
                    cur[i] = fn.merge(cur[i], inters[i])
    keys = list(combined.keys())
    cols: List[np.ndarray] = _key_columns(keys, len(groups))
    for j in agg_idx:
        fn = qctx.agg_functions[j]
        col = np.empty(len(keys), object)
        for r_i, k in enumerate(keys):
            col[r_i] = serialize_value(combined[k][j])
        cols.append(col)
    return Block(schema, cols)


def _op_scan(ctx: StageContext, op: Dict[str, Any]) -> Block:
    if ctx.scan_fn is None:
        raise RuntimeError("no scan_fn bound (leaf stage on broker?)")
    filt = expr_from_json(op["filter"])
    block = ctx.scan_fn(op["table"], op["columns"], filt)
    return block.rename(op["schema"])


# ---------------------------------------------------------------------------
# worker endpoint
# ---------------------------------------------------------------------------

class MseWorker:
    """Per-instance multi-stage worker: mailbox endpoint + stage executor.

    Ref: pinot-query-runtime service/server/QueryServer (gRPC Submit) —
    here stages arrive as JSON (via the server transport or direct call)
    and run on a thread pool.
    """

    def __init__(self, instance_id: str, scan_fn: Optional[ScanFn],
                 leaf_query_fn=None, stage_cache=None,
                 segment_versions_fn=None, config=None):
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = config or PinotConfiguration()
        self.instance_id = instance_id
        self.scan_fn = scan_fn
        self.leaf_query_fn = leaf_query_fn
        self.mailbox = MailboxService(instance_id)
        self._lock = threading.Lock()
        #: pipelined intermediate stages (chunked sends + incremental
        #: folds); see pinot.server.mse.pipeline.* in utils/config.py
        self.pipeline = cfg.get_bool("pinot.server.mse.pipeline.enabled")
        self.chunk_rows = cfg.get_int("pinot.server.mse.pipeline.chunk.rows")
        self.watermark_rows = cfg.get_int(
            "pinot.server.mse.pipeline.watermark.rows")
        #: distributed tracing: stages run under a per-attempt span tree
        #: when the dispatcher ships a TraceContext (utils/tracing.py)
        self.trace_enabled = cfg.get_bool("pinot.trace.enabled", True)
        #: per-query parsed-plan memo: a query's N stage submits share
        #: ONE QueryPlan parse instead of re-deserializing every stage
        #: of the plan N times (a measurable slice of MSE host cost on
        #: multi-stage plans); bounded FIFO keyed by query id
        self._plan_memo: "OrderedDict[str, QueryPlan]" = OrderedDict()
        #: stage execution pool: stages REUSE idle threads instead of
        #: paying a fresh thread spawn per stage instance. The cap is
        #: deliberately enormous — receive ops BLOCK on producer stages,
        #: so a tight pool would deadlock once every worker holds a
        #: receive-blocked instance; 512 is "unbounded" for any real
        #: stage tree while still recycling threads in the steady state
        self._stage_pool = ThreadPoolExecutor(
            max_workers=512, thread_name_prefix=f"mse-{instance_id}")
        #: leaf-stage output cache + its version-set provider (may be None)
        self.stage_cache = stage_cache
        self.segment_versions_fn = segment_versions_fn
        #: query_id -> in-flight stage contexts (cancel fan-out targets)
        self._active: Dict[str, List[StageContext]] = {}
        #: recently-cancelled query ids (bounded FIFO): a submit_stage
        #: racing in AFTER the cancel fan-out must be rejected, or its
        #: fresh context (new cancel_event) would run the stage to
        #: completion on a dead query
        self._cancelled: "OrderedDict[str, None]" = OrderedDict()
        #: chaos kill flag: a SimulatedCrash vanished this worker — its
        #: mailbox is stopped and the dispatcher routes around it
        self.crashed = False

    def start(self) -> None:
        self.mailbox.start()

    def stop(self) -> None:
        self.mailbox.stop()
        self._stage_pool.shutdown(wait=False)

    @property
    def alive(self) -> bool:
        return not self.crashed

    @property
    def mailbox_address(self) -> str:
        return self.mailbox.address

    def submit_stage(self, query_id: str, plan_json: Dict[str, Any],
                     stage_json: Dict[str, Any], worker_idx: int,
                     addresses: Dict[str, str],
                     timeout: float = 60.0,
                     deadline: Optional[float] = None,
                     attempt: int = 0, claim_fn=None,
                     on_done=None, trace_ctx: Optional[dict] = None,
                     trace_sink=None) -> None:
        """Async: schedule one stage instance on the pool. ``deadline``
        is the query's absolute wall-clock budget (travels with the
        stage; enforced cooperatively and on every mailbox wait).
        ``attempt``/``claim_fn``: hedge re-issues of a stage instance
        carry attempt > 0 and an output claim (runtime.run_stage sends
        only when the claim grants). ``on_done(instance, stage_id,
        worker_idx, attempt, ok, elapsed_s)`` fires when the stage
        finishes OR is rejected/doomed — it is the dispatcher-side
        control-plane observer, so even a crashed worker's attempts
        report (data-plane silence — no frames — is unaffected): a
        leaked 'pending' attempt would make the hedge book hold a
        twin's error claim forever and turn a fast failure into a
        full-deadline hang. ``trace_ctx``/``trace_sink``: the shipped
        TraceContext wire dict and the control-plane callback
        ``trace_sink(instance, stage_id, worker_idx, attempt, tree)``
        this attempt's finished span tree reports through (the
        response-metadata analog for the in-process control plane)."""
        def _reject():
            # BOTH control-plane observers fire on rejection: a counted
            # dispatch whose sink never reports would stall the
            # dispatcher's stitch barrier for its full timeout
            if trace_sink is not None:
                try:
                    trace_sink(self.instance_id, stage_json["stageId"],
                               worker_idx, attempt,
                               {"operator": "MseStage", "durationMs": 0.0,
                                "instance": self.instance_id,
                                "stage": stage_json["stageId"],
                                "workerIdx": worker_idx,
                                "attempt": attempt, "rejected": True})
                except Exception:  # noqa: BLE001 — observer only
                    pass
            if on_done is not None:
                try:
                    on_done(self.instance_id, stage_json["stageId"],
                            worker_idx, attempt, False, 0.0)
                except Exception:  # noqa: BLE001 — observer only
                    pass

        if self.crashed:
            return _reject()  # a vanished worker accepts nothing
        with self._lock:
            plan = self._plan_memo.get(query_id)
            if plan is None:
                plan = QueryPlan(
                    stages=[StagePlan.from_json(s)
                            for s in plan_json["stages"]],
                    options=plan_json.get("options", {}))
                self._plan_memo[query_id] = plan
                while len(self._plan_memo) > 256:
                    self._plan_memo.popitem(last=False)
        stage = StagePlan.from_json(stage_json)
        ctx = StageContext(
            query_id=query_id, plan=plan, worker_id=self.instance_id,
            worker_idx=worker_idx, mailbox=self.mailbox,
            addresses=addresses, scan_fn=self.scan_fn, timeout=timeout,
            leaf_query_fn=self.leaf_query_fn, deadline=deadline,
            stage_cache=self.stage_cache,
            segment_versions_fn=self.segment_versions_fn,
            stage_id=stage.stage_id, attempt=attempt, claim_fn=claim_fn,
            pipeline=self.pipeline, chunk_rows=self.chunk_rows,
            watermark_rows=self.watermark_rows)
        # memo check + registration are atomic with cancel(): either the
        # cancel sees this context in _active, or this check sees the
        # cancelled memo — a late stage can never slip between them
        with self._lock:
            if query_id in self._cancelled:
                return
            self._active.setdefault(query_id, []).append(ctx)

        tc = tracing.TraceContext.from_wire(trace_ctx) \
            if self.trace_enabled else None

        def _run():
            t0 = time.time()
            ok = False
            rt = None
            if tc is not None:
                # per-ATTEMPT span tree: the stage runs under it so op
                # scopes (and the leaf executor's instrumentation) nest
                # into the tree the dispatcher stitches
                rt = tracing.RequestTrace(
                    request_id=query_id, operator="MseStage",
                    trace_id=tc.trace_id, sampled=tc.sampled,
                    instance=self.instance_id, stage=stage.stage_id,
                    workerIdx=worker_idx, attempt=attempt)
            try:
                # chaos kill site: SimulatedCrash here (or anywhere in
                # the stage, incl. a mid-shuffle mailbox send) makes the
                # whole worker vanish — no error frames, mailbox gone
                fire("mse.worker.crash", instance=self.instance_id,
                     query_id=query_id, stage=stage.stage_id)
                if rt is not None:
                    with rt:
                        run_stage(ctx, stage)
                else:
                    run_stage(ctx, stage)
                ok = True
            except SimulatedCrash:
                # the whole worker vanishes, not just this stage: flag
                # death first (submit_stage starts rejecting), abort
                # every in-flight stage + local queue, then drop the
                # listener — sibling stage threads die at their next op
                # boundary instead of zombie-executing on a dead worker
                self.crashed = True
                with self._lock:
                    doomed = {q: list(v) for q, v in self._active.items()}
                for q, ctxs in doomed.items():
                    for c in ctxs:
                        c.worker_crashed = True  # die SILENTLY
                        c.cancel_event.set()
                    self.mailbox.abort_query(q, "worker crashed")
                self.mailbox.stop()
            except Exception:  # noqa: BLE001 — run_stage already reported
                pass
            finally:
                with self._lock:
                    ctxs = self._active.get(query_id)
                    if ctxs is not None:
                        try:
                            ctxs.remove(ctx)
                        except ValueError:
                            pass
                        if not ctxs:
                            del self._active[query_id]
                # reported even on a chaos crash: the observer is
                # control-plane (the worker's DATA-plane silence — no
                # error frames — is what the crash semantics require)
                if trace_sink is not None:
                    # even a trace-disabled worker reports a stub: the
                    # dispatcher counted this attempt at dispatch and
                    # its stitch barrier waits for every count
                    try:
                        trace_sink(
                            self.instance_id, stage.stage_id,
                            worker_idx, attempt,
                            rt.to_dict() if rt is not None else
                            {"operator": "MseStage", "durationMs": 0.0,
                             "instance": self.instance_id,
                             "stage": stage.stage_id,
                             "workerIdx": worker_idx, "attempt": attempt,
                             "untraced": True})
                    except Exception:  # noqa: BLE001 — observer only
                        pass
                if on_done is not None:
                    try:
                        on_done(self.instance_id, stage.stage_id,
                                worker_idx, attempt,
                                ok and not self.crashed,
                                time.time() - t0)
                    except Exception:  # noqa: BLE001 — observer only
                        pass

        # one pool slot per stage instance: receive ops BLOCK on
        # producer stages, so the pool's cap is effectively unbounded
        # (512 — see __init__); the win over raw Thread() is REUSE:
        # steady-state stages skip the per-spawn thread start cost
        try:
            self._stage_pool.submit(_run)
        except RuntimeError:  # stopped worker: accepts nothing
            with self._lock:
                ctxs = self._active.get(query_id)
                if ctxs is not None:
                    try:
                        ctxs.remove(ctx)
                    except ValueError:
                        pass
                    if not ctxs:
                        del self._active[query_id]
            _reject()

    def cancel(self, query_id: str, reason: str = "cancelled") -> None:
        """Out-of-band cancel for one query: flags every in-flight stage
        context (next op boundary aborts), rejects late submits via a
        bounded memo, then poisons the mailbox so blocked receivers
        wake, later receivers fail fast, and stray frames are dropped —
        no stage ever blocks on a dead sender."""
        with self._lock:
            self._cancelled[query_id] = None
            while len(self._cancelled) > 256:
                self._cancelled.popitem(last=False)
            ctxs = list(self._active.get(query_id, ()))
        for c in ctxs:
            c.cancel_event.set()
        self.mailbox.abort_query(query_id, reason)

    def cancel_stage(self, query_id: str, stage_id: int,
                     attempt: Optional[int] = None) -> int:
        """Stage-granular cancel (the hedge loser path): flags ONLY the
        matching in-flight stage contexts — no mailbox poisoning, no
        cancelled-memo, so the query's OTHER stages on this worker keep
        running and the winner's frames still flow. Returns the number
        of contexts flagged."""
        with self._lock:
            ctxs = [c for c in self._active.get(query_id, ())
                    if c.stage_id == stage_id
                    and (attempt is None or c.attempt == attempt)]
        for c in ctxs:
            c.cancel_event.set()
        return len(ctxs)

    def active_stages(self, query_id: Optional[str] = None) -> int:
        with self._lock:
            if query_id is not None:
                return len(self._active.get(query_id, ()))
            return sum(len(v) for v in self._active.values())
