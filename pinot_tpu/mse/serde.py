"""JSON serde for expressions and stage plans (dispatch wire format).

Reference parity: pinot-query-planner serializes plan fragments to proto
(planner/serde/, plan.proto); here plans cross the dispatch boundary as
JSON — expressions as tagged s-expression lists.
"""
from __future__ import annotations

from typing import Any, List, Optional

from pinot_tpu.query.expressions import (
    Expression, Function, Identifier, Literal)


def expr_to_json(e: Optional[Expression]) -> Any:
    if e is None:
        return None
    if isinstance(e, Literal):
        return ["lit", e.value]
    if isinstance(e, Identifier):
        return ["id", e.name]
    assert isinstance(e, Function)
    return ["fn", e.name] + [expr_to_json(a) for a in e.args]


def expr_from_json(j: Any) -> Optional[Expression]:
    if j is None:
        return None
    tag = j[0]
    if tag == "lit":
        return Literal(j[1])
    if tag == "id":
        return Identifier(j[1])
    assert tag == "fn"
    return Function(j[1], tuple(expr_from_json(a) for a in j[2:]))


def exprs_to_json(es) -> List[Any]:
    return [expr_to_json(e) for e in es]


def exprs_from_json(js) -> List[Expression]:
    return [expr_from_json(j) for j in js]
