"""Multi-stage SQL dialect: JOINs + derived tables on top of the
single-stage grammar.

Reference parity: the reference hands multi-stage SQL to Calcite
(pinot-query-planner QueryEnvironment.java:100); here the hand-rolled
single-stage parser (query/parser.py) is extended with a FROM clause
grammar: table [AS alias] | (subquery) AS alias, followed by
[INNER|LEFT [OUTER]|RIGHT [OUTER]|FULL [OUTER]] JOIN ... ON <cond>.
Qualified identifiers (t.col) arrive as single tokens (the lexer's name
production includes dots) and are resolved during logical planning.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from pinot_tpu.query.expressions import Expression
from pinot_tpu.query.parser import (
    PinotQuery, SqlParseError, Token, _Parser, tokenize)


@dataclass
class FromItem:
    alias: str
    table: Optional[str] = None            # base table scan ...
    subquery: Optional["MseQuery"] = None  # ... or derived table


@dataclass
class JoinClause:
    item: FromItem
    join_type: str                  # inner | left | right | full
    condition: Optional[Expression]


@dataclass
class MseQuery:
    """Multi-table query tree (ref: Calcite SqlSelect + joins)."""
    from_item: FromItem = None  # type: ignore[assignment]
    joins: List[JoinClause] = field(default_factory=list)
    select_list: List[Expression] = field(default_factory=list)
    distinct: bool = False
    filter: Optional[Expression] = None
    group_by: List[Expression] = field(default_factory=list)
    having: Optional[Expression] = None
    order_by: List[Tuple[Expression, bool]] = field(default_factory=list)
    #: None = no explicit LIMIT. The dispatcher applies the Pinot default
    #: (10) to the OUTERMOST query only; subqueries stay unlimited.
    limit: Optional[int] = None
    offset: int = 0
    options: Dict[str, str] = field(default_factory=dict)
    explain: bool = False

    @property
    def is_single_table(self) -> bool:
        return (not self.joins and self.from_item.table is not None
                and not self._has_window())

    def _has_window(self) -> bool:
        def walk(e) -> bool:
            from pinot_tpu.query.expressions import Function
            if isinstance(e, Function):
                if e.name == "over":
                    return True
                return any(walk(a) for a in e.args)
            return False
        return any(walk(e) for e in self.select_list) or \
            any(walk(e) for e, _ in self.order_by)

    def to_single_stage(self) -> PinotQuery:
        """Lower a join-free query to the single-stage AST."""
        assert self.is_single_table
        return PinotQuery(
            table=self.from_item.table, select_list=self.select_list,
            distinct=self.distinct, filter=self.filter,
            group_by=self.group_by, having=self.having,
            order_by=self.order_by,
            limit=10 if self.limit is None else self.limit,
            offset=self.offset, options=self.options, explain=self.explain)


@dataclass
class MseSetQuery:
    """Compound query: UNION / INTERSECT / EXCEPT of two query trees.

    Ref: Calcite SqlSetOperator -> LogicalUnion/Intersect/Minus (the
    reference executes them in pinot-query-runtime
    runtime/operator/SetOperator.java + Union/Intersect/MinusOperator).
    ORDER BY / LIMIT parsed after the last operand bind to the compound.
    """
    op: str                     # union | intersect | except
    all: bool
    left: object                # MseQuery | MseSetQuery
    right: object
    order_by: List[Tuple[Expression, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    options: Dict[str, str] = field(default_factory=dict)
    explain: bool = False

    @property
    def is_single_table(self) -> bool:
        return False


def _combine(left, op: str, all_: bool, right,
             hoist: bool) -> MseSetQuery:
    """An UNPARENTHESIZED right operand's trailing ORDER BY/LIMIT/OPTION
    syntactically belong to the compound — hoist them. A parenthesized
    operand keeps its own (they bind inside the parens)."""
    if not hoist:
        return MseSetQuery(op=op, all=all_, left=left, right=right)
    q = MseSetQuery(op=op, all=all_, left=left, right=right,
                    order_by=list(right.order_by), limit=right.limit,
                    offset=right.offset, options=dict(right.options))
    right.order_by, right.limit, right.offset = [], None, 0
    right.options = {}
    return q


_JOIN_KWS = ("JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS")


class _MseParser(_Parser):
    def parse_mse(self):
        q = self._set_expr()
        self.accept_op(";")
        t = self.peek()
        if t.kind != "end":
            raise SqlParseError(f"trailing input at {t.pos}: {t.text!r}")
        return q

    # -- compound queries ---------------------------------------------------
    def _set_expr(self):
        left, _p = self._intersect_expr()
        while True:
            if self.accept_kw("UNION"):
                op = "union"
            elif self.accept_kw("EXCEPT"):
                op = "except"
            else:
                break
            all_ = bool(self.accept_kw("ALL"))
            self.accept_kw("DISTINCT")
            right, parens = self._intersect_expr()
            left = _combine(left, op, all_, right, hoist=not parens)
        if isinstance(left, MseSetQuery):
            # any compound (UNION/EXCEPT here or INTERSECT below) whose
            # last operand was parenthesized kept its clauses inside the
            # parens — the compound's trailing ORDER BY/LIMIT/OPTION
            # parse here (shared grammar with the single-stage tail)
            self._tail_clauses(left)
        return left

    def _intersect_expr(self):
        """Returns (query, last_operand_was_parenthesized)."""
        left, parens = self._select_operand()
        while self.accept_kw("INTERSECT"):
            all_ = bool(self.accept_kw("ALL"))
            self.accept_kw("DISTINCT")
            right, parens = self._select_operand()
            left = _combine(left, "intersect", all_, right,
                            hoist=not parens)
        return left, parens

    def _select_operand(self):
        """Returns (query, was_parenthesized)."""
        if self.peek().kind == "op" and self.peek().text == "(":
            # peek through consecutive '('s: '((SELECT 1))' is a
            # parenthesized operand just like '(SELECT 1)'
            depth = 1
            while self.peek(depth).kind == "op" \
                    and self.peek(depth).text == "(":
                depth += 1
            if self.peek(depth).upper in ("SELECT", "SET", "EXPLAIN"):
                self.next()
                q = self._set_expr()
                self.expect_op(")")
                return q, True
        return self._select_stmt(), False

    def _select_stmt(self) -> MseQuery:
        q = MseQuery()
        while self.accept_kw("SET"):
            key = self._name_text(self.next())
            self.expect_op("=")
            q.options[key] = self._literal_text(self.next())
            self.accept_op(";")
        if self.accept_kw("EXPLAIN"):
            self.expect_kw("PLAN")
            self.expect_kw("FOR")
            q.explain = True
        self.expect_kw("SELECT")
        if self.accept_kw("DISTINCT"):
            q.distinct = True
        q.select_list = self._select_list()
        self.expect_kw("FROM")
        q.from_item = self._from_item()
        while True:
            jt = self._join_type()
            if jt is None:
                break
            item = self._from_item()
            cond = None
            if jt != "cross":
                self.expect_kw("ON")
                cond = self.expr()
            q.joins.append(JoinClause(item, jt, cond))
        if self.accept_kw("WHERE"):
            q.filter = self.expr()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            q.group_by = self._expr_list()
        if self.accept_kw("HAVING"):
            q.having = self.expr()
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            q.order_by = self._order_list()
        if self.accept_kw("LIMIT"):
            a = int(self._literal_text(self.next()))
            if self.accept_op(","):
                q.offset, q.limit = a, int(self._literal_text(self.next()))
            else:
                q.limit = a
                if self.accept_kw("OFFSET"):
                    q.offset = int(self._literal_text(self.next()))
        if self.accept_kw("OPTION"):
            self.expect_op("(")
            while True:
                key = self._name_text(self.next())
                self.expect_op("=")
                q.options[key] = self._literal_text(self.next())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return q

    def _join_type(self) -> Optional[str]:
        t = self.peek()
        if t.kind != "name" or t.upper not in _JOIN_KWS:
            return None
        if self.accept_kw("JOIN"):
            return "inner"
        if self.accept_kw("CROSS"):
            self.expect_kw("JOIN")
            return "cross"
        for kw in ("INNER", "LEFT", "RIGHT", "FULL"):
            if self.accept_kw(kw):
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                return kw.lower() if kw != "INNER" else "inner"
        return None

    def _call(self, name: str) -> Expression:
        """Extend the base call grammar with the window suffix:
        fn(args) OVER (PARTITION BY e,... ORDER BY e [ASC|DESC],...
        [ROWS BETWEEN <bound> AND <bound>]).
        Encoded as over(fn, __partition(p...), __orderby(asc(k)|desc(k)...)
        [, __frame('rows', lo, hi)]) so the node stays a plain hashable
        expression tree; bounds are ints (rows preceding = negative,
        following = positive) or the strings 'up'/'uf' for unbounded."""
        from pinot_tpu.query.expressions import Literal, func
        e = super()._call(name)
        if self.accept_kw("OVER"):
            self.expect_op("(")
            parts: List[Expression] = []
            okeys: List[Expression] = []
            frame = None
            if self.accept_kw("PARTITION"):
                self.expect_kw("BY")
                parts = self._expr_list()
            if self.accept_kw("ORDER"):
                self.expect_kw("BY")
                for k, asc in self._order_list():
                    okeys.append(func("asc" if asc else "desc", k))
            if self.accept_kw("ROWS"):
                self.expect_kw("BETWEEN")
                lo = self._frame_bound()
                self.expect_kw("AND")
                hi = self._frame_bound()
                frame = func("__frame", Literal("rows"), Literal(lo),
                             Literal(hi))
            self.expect_op(")")
            args = [e, func("__partition", *parts),
                    func("__orderby", *okeys)]
            if frame is not None:
                args.append(frame)
            e = func("over", *args)
        return e

    def _frame_bound(self):
        """UNBOUNDED PRECEDING|FOLLOWING / CURRENT ROW / <n> PRECEDING /
        <n> FOLLOWING -> 'up' | 'uf' | 0 | -n | +n"""
        if self.accept_kw("UNBOUNDED"):
            if self.accept_kw("PRECEDING"):
                return "up"
            self.expect_kw("FOLLOWING")
            return "uf"
        if self.accept_kw("CURRENT"):
            self.expect_kw("ROW")
            return 0
        t = self.next()
        try:
            n = int(t.text)
        except ValueError:
            from pinot_tpu.query.parser import SqlParseError
            raise SqlParseError(
                f"expected frame bound at {t.pos}, got {t.text!r}")
        if self.accept_kw("PRECEDING"):
            return -n
        self.expect_kw("FOLLOWING")
        return n

    def _from_item(self) -> FromItem:
        if self.accept_op("("):
            sub = self._set_expr()
            self.expect_op(")")
            self.accept_kw("AS")
            alias = self._name_text(self.next())
            return FromItem(alias=alias, subquery=sub)
        table = self._table_name()
        alias = table
        t = self.peek()
        if self.accept_kw("AS"):
            alias = self._name_text(self.next())
        elif t.kind in ("name", "qident") and t.upper not in _RESERVED_AFTER_TABLE:
            self.next()
            alias = self._name_text(t)
        return FromItem(alias=alias, table=table)


# keywords that may legally follow a table name (so a bare name after the
# table is otherwise an alias)
_RESERVED_AFTER_TABLE = {
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "ON", "WHERE",
    "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "OPTION", "AS", "UNION",
    "INTERSECT", "EXCEPT",
}


def parse_mse_sql(sql: str):
    """Parse multi-stage SQL (joins, derived tables, set ops, window
    functions) into an MseQuery or MseSetQuery."""
    return _MseParser(tokenize(sql)).parse_mse()
