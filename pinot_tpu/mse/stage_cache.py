"""Leaf-stage output cache for the multi-stage engine.

Tier-2's discipline (cache/segment_cache.py) lifted one level: instead of
one segment's partial for one plan fingerprint, the cached unit is one
WORKER's whole leaf-stage output block (scan or leaf_agg over the
instance's local segments) for one stage-plan fingerprint.

Keying mirrors the tier-2 partial cache: the key carries the **version
set** of every immutable segment the stage reads — ``(sorted (name,
version) tuples per table, stage-plan fingerprint)`` — so a segment
add/replace/remove addresses a different key and the stale entry ages
out (epoch invalidation by construction). A table with ANY non-cacheable
segment (consuming / live upsert bitmap) yields no version set and the
stage re-executes every time, which is exactly what keeps hybrid tables
fresh.

Partials are never cached: the runtime only calls ``put`` after a stage
completed cleanly inside its deadline — an aborted, errored, or
deadline-clipped run stores nothing.

L2 sharing (ISSUE 10): ``backend=tiered`` mounts the SAME
``TieredCache``/ring fabric the result and segment tiers use, so one
replica's warm leaf output serves the whole fleet — a rolling restart's
cold replica answers its first leaf stage from the cache server instead
of rescanning. The key is shareable by construction: segment versions
here are content CRCs (``segment_version`` of immutable segments), never
the per-process generation stamps that must stay local, and the payload
is the typed Block wire serde — never pickle.
"""
from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Tuple

from pinot_tpu.cache.core import LruTtlCache
from pinot_tpu.mse.blocks import Block


def stage_fingerprint(stage_root: Dict[str, Any]) -> str:
    """Deterministic fingerprint of a stage's physical op tree (filter
    literals, projections, agg nodes and schemas included)."""
    return json.dumps(stage_root, sort_keys=True, separators=(",", ":"))


def collect_scan_tables(op: Dict[str, Any]) -> Tuple[str, ...]:
    """All tables a stage op tree scans (empty for non-leaf stages)."""
    out = []
    if op.get("op") == "scan":
        out.append(op["table"])
    for k in ("child", "left", "right"):
        child = op.get(k)
        if isinstance(child, dict):
            out.extend(collect_scan_tables(child))
    return tuple(out)


def remote_stage_key(key: tuple) -> Optional[str]:
    """Stable wire string for a stage-cache key: the nested version-set
    tuple + fingerprint hash identically on every replica (names,
    content CRC versions, canonical-JSON plan), so replicas sharing the
    same segment view address the same L2 entry."""
    version_sets, fingerprint = key
    blob = json.dumps(
        [[t, [[n, str(v)] for n, v in vs]] for t, vs in version_sets],
        sort_keys=True, separators=(",", ":")) + "|" + fingerprint
    return "mse_stage:" + hashlib.sha256(blob.encode()).hexdigest()


class StageOutputCache:
    """Leaf-stage output blocks keyed by
    ((table, segment version set)..., stage-plan fingerprint)."""

    def __init__(self, max_bytes: int = 64 << 20,
                 ttl_seconds: float = 300.0, enabled: bool = True,
                 metrics=None, labels: Optional[dict] = None,
                 backend=None):
        """backend: a pre-assembled byte-payload cache (TieredCache for
        the L2-shared mount); None = process-local LruTtlCache."""
        self.enabled = enabled
        self._cache = backend if backend is not None else LruTtlCache(
            max_bytes, ttl_seconds, metrics=metrics,
            metric_prefix="mse_stage_cache", labels=labels)
        self._metrics = metrics
        self._labels = labels

    @classmethod
    def from_config(cls, config, metrics=None,
                    labels: Optional[dict] = None) -> "StageOutputCache":
        backend = None
        if config.get_str(
                "pinot.server.mse.stage.cache.backend") == "tiered":
            from pinot_tpu.cache.tiered import tiered_backend_from_config
            backend = tiered_backend_from_config(
                config, "pinot.server.mse.stage.cache", "mse_stage_cache",
                remote_stage_key, metrics=metrics, labels=labels)
        return cls(
            max_bytes=config.get_int("pinot.server.mse.stage.cache.bytes"),
            ttl_seconds=config.get_float(
                "pinot.server.mse.stage.cache.ttl.seconds"),
            enabled=config.get_bool(
                "pinot.server.mse.stage.cache.enabled"),
            metrics=metrics, labels=labels, backend=backend)

    # ------------------------------------------------------------------
    def key_for(self, stage_root: Dict[str, Any],
                segment_versions_fn) -> Optional[tuple]:
        """Cache key for a leaf stage, or None when the stage must not be
        cached: not a leaf (no scans), no version provider bound, or any
        scanned table carries a non-cacheable (mutable) segment."""
        if not self.enabled or segment_versions_fn is None:
            return None
        tables = collect_scan_tables(stage_root)
        if not tables:
            return None
        version_sets = []
        for table in sorted(set(tables)):
            versions = segment_versions_fn(table)
            if versions is None:
                return None  # mutable tail present: never cache
            version_sets.append((table, versions))
        return (tuple(version_sets), stage_fingerprint(stage_root))

    def get(self, key: Optional[tuple]) -> Optional[Block]:
        block, tier = self.get_with_tier(key)
        if tier == "L2" and self._metrics is not None:
            # a COLD replica just served another replica's warm leaf
            # output — the cross-replica sharing signal
            self._metrics.add_meter("mse_stage_cache_remote_hits",
                                    labels=self._labels)
        return block

    def put(self, key: Optional[tuple], block: Block) -> bool:
        if key is None:
            return False
        return self._cache.put(key, block.to_bytes())

    def get_with_tier(self, key: Optional[tuple]):
        """(block, tier) — tier is 'L1' / 'L2' on tiered mounts, 'L1'
        on local mounts, None on miss (cross-replica hit assertions)."""
        if key is None:
            return None, None
        if hasattr(self._cache, "get_with_tier"):
            payload, tier = self._cache.get_with_tier(key)
        else:
            payload, tier = self._cache.get(key), "L1"
        if payload is None:
            return None, None
        try:
            return Block.from_bytes(payload), tier
        except Exception:  # noqa: BLE001 — undecodable entry = miss
            return None, None

    def clear(self) -> None:
        self._cache.clear()

    def close(self) -> None:
        close = getattr(self._cache, "close", None)
        if close is not None:
            close()

    @property
    def stats(self):
        return self._cache.stats

    def __len__(self) -> int:
        return len(self._cache)
