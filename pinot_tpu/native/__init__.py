"""Native (C++) fast paths, loaded via ctypes.

Holds the host-side native runtime pieces: LZ4 block codec, fixed-bit
unpack, CLP-style log encoding. Analog of the reference's native-adjacent
layer (com.yscope.clp:clp-ffi JNI, sun.misc.Unsafe buffers — SURVEY.md §2.8).

`lib` is None when the shared library hasn't been built; every caller has a
pure-python/numpy fallback. Build with: `python -m pinot_tpu.native.build`.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

_SO_PATH = os.path.join(os.path.dirname(__file__), "libpinot_tpu_native.so")


class _NativeLib:
    """ctypes wrapper over libpinot_tpu_native.so."""

    def __init__(self, dll: ctypes.CDLL):
        self._dll = dll
        dll.lz4_compress_bound.restype = ctypes.c_int
        dll.lz4_compress_bound.argtypes = [ctypes.c_int]
        dll.lz4_compress_default.restype = ctypes.c_int
        dll.lz4_compress_default.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        dll.lz4_decompress_safe.restype = ctypes.c_int
        dll.lz4_decompress_safe.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        dll.bitunpack32.restype = None
        dll.bitunpack32.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_long, ctypes.c_int]

    def lz4_compress(self, data: bytes) -> bytes:
        bound = self._dll.lz4_compress_bound(len(data))
        out = ctypes.create_string_buffer(bound)
        n = self._dll.lz4_compress_default(data, out, len(data), bound)
        if n <= 0:
            raise RuntimeError("lz4 compression failed")
        return out.raw[:n]

    def lz4_decompress(self, data: bytes, raw_size: int) -> bytes:
        out = ctypes.create_string_buffer(raw_size)
        n = self._dll.lz4_decompress_safe(data, out, len(data), raw_size)
        if n != raw_size:
            raise RuntimeError(f"lz4 decompression failed ({n} != {raw_size})")
        return out.raw

    def bitunpack32(self, buf: bytes, n: int, bits: int):
        import numpy as np
        out = np.empty(n, dtype=np.int32)
        self._dll.bitunpack32(
            buf, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n, bits)
        return out


def _load() -> Optional[_NativeLib]:
    if not os.path.exists(_SO_PATH):
        return None
    try:
        return _NativeLib(ctypes.CDLL(_SO_PATH))
    except OSError:
        return None


lib: Optional[_NativeLib] = _load()
