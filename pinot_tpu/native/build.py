"""Build libpinot_tpu_native.so with g++.

Usage: python -m pinot_tpu.native.build
"""
from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "src", "pinot_tpu_native.cpp")
OUT = os.path.join(HERE, "libpinot_tpu_native.so")


def build(verbose: bool = True) -> str:
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
           "-std=c++17", SRC, "-o", OUT]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    build()
    # smoke test through the ctypes wrapper
    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))
    from pinot_tpu.native import _load
    lib = _load()
    assert lib is not None, "built but failed to load"
    import numpy as np
    from pinot_tpu.segment import bitpack
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 1 << 7, 100_001).astype(np.uint32)
    packed = bitpack.pack(vals, 7)
    out = lib.bitunpack32(packed, len(vals), 7)
    assert np.array_equal(out, vals.astype(np.int32)), "bitunpack mismatch"
    data = rng.integers(0, 50, 1 << 20).astype(np.uint8).tobytes()
    comp = lib.lz4_compress(data)
    rt = lib.lz4_decompress(comp, len(data))
    assert rt == data, "lz4 roundtrip mismatch"
    print(f"OK {OUT} (lz4 ratio {len(comp)/len(data):.3f})")
