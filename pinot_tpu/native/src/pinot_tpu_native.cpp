// Native host-side fast paths for pinot-tpu.
//
// Reference parity: the role of the JVM's hand-tuned readers —
// pinot-segment-local io/util/FixedBitIntReaderWriterV2.java:99-124 (bulk
// fixed-bit unpack) — and of the lz4-java dependency behind
// ChunkCompressionType.LZ4 (pinot-segment-spi compression/
// ChunkCompressionType.java:21). The LZ4 block codec is a clean-room
// implementation of the public LZ4 block format (greedy hash-table
// matcher; standard token/literal/match sequence decoding).
//
// Exposed with C linkage for the ctypes wrapper in
// pinot_tpu/native/__init__.py. Build: python -m pinot_tpu.native.build
#include <cstdint>
#include <cstring>
#include <algorithm>

extern "C" {

// ---------------------------------------------------------------------------
// Fixed-bit unpack: MSB-first dense bitstream -> int32 (bitpack.py format)
// ---------------------------------------------------------------------------
void bitunpack32(const uint8_t* buf, int32_t* out, long n, int bits) {
    uint64_t acc = 0;      // bit accumulator, top-aligned consumption
    int have = 0;          // bits in accumulator
    const uint8_t* p = buf;
    const uint64_t mask = (bits == 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (long i = 0; i < n; i++) {
        while (have < bits) {
            acc = (acc << 8) | *p++;
            have += 8;
        }
        out[i] = (int32_t)((acc >> (have - bits)) & mask);
        have -= bits;
    }
}

// Gathered dictionary decode: out[i] = dict[ids[i]] for 4-byte values —
// the DataFetcher.fetchIntValues hot loop.
void dict_gather_i32(const int32_t* dict, const int32_t* ids, int32_t* out,
                     long n) {
    for (long i = 0; i < n; i++) out[i] = dict[ids[i]];
}

void dict_gather_f64(const double* dict, const int32_t* ids, double* out,
                     long n) {
    for (long i = 0; i < n; i++) out[i] = dict[ids[i]];
}

// ---------------------------------------------------------------------------
// LZ4 block format (https://github.com/lz4/lz4/blob/dev/doc/lz4_Block_format.md)
// ---------------------------------------------------------------------------

int lz4_compress_bound(int n) {
    return n + n / 255 + 16;
}

static inline uint32_t lz4_hash(uint32_t v) {
    return (v * 2654435761u) >> 20;  // 12-bit table
}

// Greedy single-pass compressor with a 4KB hash table.
int lz4_compress_default(const char* src_c, char* dst_c, int src_len,
                         int dst_cap) {
    const uint8_t* src = (const uint8_t*)src_c;
    uint8_t* dst = (uint8_t*)dst_c;
    if (src_len < 0 || dst_cap <= 0) return 0;
    int32_t table[4096];
    for (int i = 0; i < 4096; i++) table[i] = -1;

    const int MFLIMIT = 12;  // last 12 bytes are always literals
    long ip = 0, op = 0, anchor = 0;
    long mflimit = src_len - MFLIMIT;

    auto emit = [&](long literal_len, long match_len, long offset) -> bool {
        // token
        long ll = literal_len;
        long ml = match_len - 4;  // stored minus minmatch
        long need = 1 + literal_len + (literal_len >= 15 ? literal_len / 255 + 1 : 0)
                    + (match_len ? 2 + (ml >= 15 ? ml / 255 + 1 : 0) : 0);
        if (op + need + 8 > dst_cap) return false;
        uint8_t token = (uint8_t)((std::min(ll, 15L) << 4)
                                  | (match_len ? std::min(ml, 15L) : 0));
        dst[op++] = token;
        if (ll >= 15) {
            long rem = ll - 15;
            while (rem >= 255) { dst[op++] = 255; rem -= 255; }
            dst[op++] = (uint8_t)rem;
        }
        std::memcpy(dst + op, src + anchor, ll);
        op += ll;
        if (match_len) {
            dst[op++] = (uint8_t)(offset & 0xFF);
            dst[op++] = (uint8_t)(offset >> 8);
            if (ml >= 15) {
                long rem = ml - 15;
                while (rem >= 255) { dst[op++] = 255; rem -= 255; }
                dst[op++] = (uint8_t)rem;
            }
        }
        return true;
    };

    while (ip <= mflimit) {
        uint32_t seq;
        std::memcpy(&seq, src + ip, 4);
        uint32_t h = lz4_hash(seq);
        long ref = table[h];
        table[h] = (int32_t)ip;
        uint32_t refseq = 0;
        if (ref >= 0 && ip - ref <= 65535) std::memcpy(&refseq, src + ref, 4);
        if (ref >= 0 && ip - ref <= 65535 && refseq == seq) {
            // extend match
            long match_len = 4;
            while (ip + match_len <= mflimit + (MFLIMIT - 5) &&
                   src[ref + match_len] == src[ip + match_len] &&
                   ip + match_len < src_len - 5)
                match_len++;
            if (!emit(ip - anchor, match_len, ip - ref)) return 0;
            ip += match_len;
            anchor = ip;
        } else {
            ip++;
        }
    }
    // final literals
    long ll = src_len - anchor;
    long need = 1 + ll + (ll >= 15 ? ll / 255 + 1 : 0);
    if (op + need > dst_cap) return 0;
    uint8_t token = (uint8_t)(std::min(ll, 15L) << 4);
    dst[op++] = token;
    if (ll >= 15) {
        long rem = ll - 15;
        while (rem >= 255) { dst[op++] = 255; rem -= 255; }
        dst[op++] = (uint8_t)rem;
    }
    std::memcpy(dst + op, src + anchor, ll);
    op += ll;
    return (int)op;
}

int lz4_decompress_safe(const char* src_c, char* dst_c, int src_len,
                        int dst_cap) {
    const uint8_t* src = (const uint8_t*)src_c;
    uint8_t* dst = (uint8_t*)dst_c;
    long ip = 0, op = 0;
    while (ip < src_len) {
        uint8_t token = src[ip++];
        long ll = token >> 4;
        if (ll == 15) {
            uint8_t b;
            do {
                if (ip >= src_len) return -1;
                b = src[ip++];
                ll += b;
            } while (b == 255);
        }
        if (ip + ll > src_len || op + ll > dst_cap) return -1;
        std::memcpy(dst + op, src + ip, ll);
        ip += ll;
        op += ll;
        if (ip >= src_len) break;  // last sequence has no match
        long offset = src[ip] | ((long)src[ip + 1] << 8);
        ip += 2;
        if (offset == 0 || offset > op) return -1;
        long ml = (token & 0xF) + 4;
        if ((token & 0xF) == 15) {
            uint8_t b;
            do {
                if (ip >= src_len) return -1;
                b = src[ip++];
                ml += b;
            } while (b == 255);
        }
        if (op + ml > dst_cap) return -1;
        // overlapping copy must be byte-wise
        for (long i = 0; i < ml; i++) dst[op + i] = dst[op + i - offset];
        op += ml;
    }
    return (int)op;
}

}  // extern "C"
