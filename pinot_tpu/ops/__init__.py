"""Device execution backend: JAX/Pallas kernels for the query hot path.

This is the TPU-native rewrite of pinot-core's per-segment operator chain
(SURVEY.md §3.2): instead of BlockDocIdSet iterators + per-block
DataFetcher reads + scalar aggregation loops, whole columns are staged in
HBM as [num_segments, padded_docs] int32 dictId blocks and one jit'd
kernel per (query-shape, schema) computes filter masks, gathers dictionary
values, and reduces — batched across segments on the mesh's `segments`
axis (the DP analog of CombinePlanNode fan-out,
combine/BaseCombineOperator.java:54).
"""
