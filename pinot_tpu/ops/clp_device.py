"""Device-side LIKE/regex pushdown over CLP log columns.

Reference parity: the y-scope fork's ClpRewriter + CLPForwardIndexReaderV2
query path — a LIKE/regex over a CLP-encoded column never decodes the
column; the pattern is compiled against the logtype dictionary and the
variable columns instead. Here the compilation target is the unified
kernel factory (ops/kernels.py): the host compiles the pattern into a
per-segment *match plan* and the per-doc evaluation runs as a JAX kernel
over fixed-width pseudo-columns staged from the CLP forward index:

    clpid:<col>         [S, D] int32  logtype id per doc
    clpdv<j>:<col>      [S, D] int32  j-th dict-var id (sentinel = card)
    clpehi<j>:<col>     [S, D] int32  j-th encoded var, v >> 32
    clpelo<j>:<col>     [S, D] int32  j-th encoded var, low 32 bits

Soundness rests on the codec's tokenization invariants (segment/clp.py):
variables are maximal non-delimiter runs containing a digit, so in the
logtype every placeholder is delimiter-bounded; a digitless,
delimiterless needle can never span a static/variable boundary; a full
digitless token is never a variable; and int/float variable renderings
use only ``[0-9.+-e]``, so digitless text overlaps encoded-variable text
only when it consists entirely of ``+-.e`` (those degenerate patterns
fall back to the host).

Two kernel modes, picked per pattern (leaf.meta = (mode, Kd, Ke)):

mode 'a' (bare substring, the grep case): a single unanchored piece that
is digitless and delimiterless. match = needle-in-logtype LUT over the
logtype id, OR needle-in-variable LUT over every dict-var slot.

mode 'b' (generic): the pattern splits on wildcards into pieces; each
piece compiles to a regex over the LOGTYPE string with variable tokens
classified exactly as the encoder classifies them (clp.encode_token).
Per logtype, ordered non-overlapping piece placements enumerate the
candidate alignments; each alignment yields a condition set over
variable slots (encoded-var equality as an exact (hi, lo) i32 pair,
dict-var membership as a var-dictionary LUT). On device a logtype-id
match plus an all-conditions-hold check (a small one-hot matmul over
the distinct conditions — MXU-friendly, no per-group gathers) decides
each doc. A condition-free alignment makes the logtype unconditionally
matching (the candidate-logtype LUT).

Patterns the planner cannot push take a structured host fallback, like
the star-tree leg — reasons metered as ``clp_fallback{reason=}``:

    disabled     pushdown knob off
    predicate    not a LIKE/regexp_like over a literal pattern
    charWildcard LIKE ``_`` or regex ``.`` (single-char wildcards)
    regex        regex features beyond literals + ``.*`` + anchors
    wildcard     a wildcard cuts a variable-like token mid-token
    partial      facing partial tokens could co-occupy one variable
    slots        per-doc variable slots / conditions above the device cap
    alignments   candidate alignment count above the device cap
    staging      a batch segment has no loadable CLP reader
"""
from __future__ import annotations

import functools
import re
import threading
from collections import OrderedDict
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np
from jax import numpy as jnp

from pinot_tpu.segment import index_types as it
from pinot_tpu.segment.clp import (
    DICT_PH, FLOAT_PH, INT_PH, _HAS_DIGIT, _TOKEN_RE, encode_token)

#: documented fallback-reason vocabulary (README "Log analytics")
FALLBACK_REASONS = ("disabled", "predicate", "charWildcard", "regex",
                    "wildcard", "partial", "slots", "alignments", "staging")

#: device caps — beyond these the host path is cheaper than the staging
KD_MAX = 16      # dict-var slots staged per column
KE_MAX = 16      # encoded-var slots staged per column
GROUPS_MAX = 64  # (logtype, conditions) groups per segment
CONDS_MAX = 16   # distinct conditions per segment
LUTS_MAX = 8     # distinct var-dictionary LUTs per segment
_OCCS_MAX = 64   # piece occurrences per logtype
_COMBOS_MAX = 256  # raw alignments per logtype

_DELIM_RE = re.compile(r"[\s=:,\[\]\(\)\"']")
_PLACEHOLDERS = (INT_PH, DICT_PH, FLOAT_PH)
#: chars an int/float variable rendering can consist of
_FLOAT_CHARS = frozenset("0123456789+-.e")
_INT_SUB = re.compile(r"-?[0-9]+")


def _num_possible(tok: str) -> bool:
    """Could `tok` appear as a substring of an int or float variable's
    rendered text? If so, a wildcard-adjacent occurrence of tok cannot
    be decided by dict-var LUTs alone (numeric prefix/suffix predicates
    are not device-expressible) and the pattern falls back. str(int) is
    digits with an optional leading '-'; repr(float) draws from
    ``[0-9+-.e]`` with at most one each of '.', 'e', '+'."""
    if _INT_SUB.fullmatch(tok):
        return True
    return (set(tok) <= _FLOAT_CHARS and tok.count(".") <= 1
            and tok.count("e") <= 1 and tok.count("+") <= 1)


def _pow2(n: int, floor: int = 1) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def _split64(v: int) -> Tuple[int, int]:
    """int64 -> exact (hi, lo) int32 pair (hi = v >> 32, lo = low word
    reinterpreted signed) — matches the staged split planes bit-for-bit."""
    hi = v >> 32
    lo = v & 0xFFFFFFFF
    if lo >= 1 << 31:
        lo -= 1 << 32
    return int(hi), int(lo)


# ---------------------------------------------------------------------------
# pattern compilation (segment-independent, cached per pattern)
# ---------------------------------------------------------------------------

class _Template(NamedTuple):
    """One wildcard-free pattern piece compiled against logtype text.

    regex: the piece with variable-class tokens replaced by placeholder
    captures, wrapped in ``(?=(...))`` so finditer enumerates every
    (overlapping) occurrence start; group 1 spans the occurrence, groups
    2.. align with `binds`.
    binds: per capture group, the condition the occurrence imposes when
    that group matched a placeholder:
      ("enc", hi, lo)          encoded-var equality at the slot
      ("dicteq", tok)          dict-var == tok
      ("dictsub", mode, tok)   dict-var startswith/endswith/contains tok
    """
    regex: Any
    binds: Tuple[Tuple, ...]
    lead_partial: bool
    trail_partial: bool


class CompiledPattern(NamedTuple):
    key: Tuple[str, bool]
    templates: Tuple[_Template, ...]
    anchor_start: bool
    anchor_end: bool
    needle: Optional[str]   # mode 'a': bare substring
    always: bool            # matches every message ('%', '.*')
    empty_exact: bool       # matches only the empty message ('')


def _piece_template(piece: str, bound_left: bool, bound_right: bool):
    """Compile one piece -> (_Template, None) or (None, reason)."""
    parts: List[str] = ["(?=("]
    binds: List[Tuple] = []
    pos = 0
    lead_partial = trail_partial = False
    for m in _TOKEN_RE.finditer(piece):
        a, b = m.span()
        if a > pos:
            parts.append(re.escape(piece[pos:a]))
        tok = m.group()
        # a token edge is "bounded" when the message provably cannot
        # continue the token past it: an adjacent in-piece delimiter, or
        # a pattern anchor pinning the message edge
        left_b = a > 0 or bound_left
        right_b = b < len(piece) or bound_right
        kind, val = encode_token(tok)
        if not (left_b and right_b):
            # partial token: the containing message token may extend
            # past the wildcard edge
            if _num_possible(tok):
                # the extended token could be an int/float variable —
                # numeric prefix/suffix predicates don't push down
                return None, "wildcard"
            mode = ("contains" if not (left_b or right_b)
                    else "endswith" if not left_b else "startswith")
            lead_partial = lead_partial or not left_b
            trail_partial = trail_partial or not right_b
            if _HAS_DIGIT.search(tok):
                # digit-bearing: the containing message token is always
                # a variable, and numeric classes were excluded above —
                # it can only be a dict var
                parts.append("(%s)" % DICT_PH)
            else:
                # either verbatim static text, or inside a dict var
                parts.append("(?:%s|(%s))" % (re.escape(tok), DICT_PH))
            binds.append(("dictsub", mode, tok))
        elif kind == "static":
            # full digitless tokens are never variables: literal
            parts.append(re.escape(tok))
        elif kind == "dict":
            parts.append("(%s)" % DICT_PH)
            binds.append(("dicteq", val))
        else:
            ph = INT_PH if kind == "int" else FLOAT_PH
            parts.append("(%s)" % ph)
            binds.append(("enc",) + _split64(val))
        pos = b
    if pos < len(piece):
        parts.append(re.escape(piece[pos:]))
    parts.append("))")
    return _Template(re.compile("".join(parts)), tuple(binds),
                     lead_partial, trail_partial), None


def _compile_pieces(pieces: List[str], anchor_start: bool, anchor_end: bool,
                    key: Tuple[str, bool]):
    empty = CompiledPattern(key, (), anchor_start, anchor_end, None,
                            False, False)
    if any(ph in p for p in pieces for ph in _PLACEHOLDERS):
        return None, "regex"  # placeholder bytes in the pattern itself
    if not pieces:
        if anchor_start and anchor_end:
            return empty._replace(empty_exact=True), None
        return empty._replace(always=True), None
    if (len(pieces) == 1 and not anchor_start and not anchor_end
            and not _DELIM_RE.search(pieces[0])
            and not _num_possible(pieces[0])):
        # bare substring: logtype-text LUT + any-dict-var LUT suffice (a
        # digit-bearing needle never appears in static text — logtypes
        # are digit-free — so its alut is simply all-False)
        return empty._replace(needle=pieces[0]), None
    templates: List[_Template] = []
    for pi, p in enumerate(pieces):
        t, reason = _piece_template(
            p, pi == 0 and anchor_start,
            pi == len(pieces) - 1 and anchor_end)
        if t is None:
            return None, reason
        templates.append(t)
    # adjacent facing partial tokens could co-occupy ONE variable in the
    # message with no logtype-level witness — not representable
    for t1, t2 in zip(templates, templates[1:]):
        if t1.trail_partial and t2.lead_partial:
            return None, "partial"
    return empty._replace(templates=tuple(templates)), None


@functools.lru_cache(maxsize=512)
def compile_pattern(pattern: str, is_like: bool):
    """Pattern -> (CompiledPattern, None) or (None, fallback reason).

    LIKE: ``%`` splits pieces, ``_`` is unsupported. Regex: the host
    evaluates ``re.search`` (unanchored unless ``^``/``$``), so only
    literals + ``.*`` runs + edge anchors push down; everything else
    falls back."""
    key = (pattern, is_like)
    if is_like:
        if "_" in pattern:
            return None, "charWildcard"
        raw = pattern.split("%")
        return _compile_pieces([p for p in raw if p],
                               not pattern.startswith("%"),
                               not pattern.endswith("%"), key)
    anchor_start = pattern.startswith("^")
    i = 1 if anchor_start else 0
    anchor_end = pattern.endswith("$") and not pattern.endswith("\\$")
    end = len(pattern) - 1 if anchor_end else len(pattern)
    pieces: List[str] = [""]
    while i < end:
        c = pattern[i]
        if c == ".":
            if i + 1 < end and pattern[i + 1] == "*":
                pieces.append("")
                i += 2
                continue
            return None, "charWildcard"
        if c == "\\":
            if i + 1 >= end:
                return None, "regex"
            nxt = pattern[i + 1]
            if nxt.isalnum():
                return None, "regex"  # character classes (\d, \w, ...)
            pieces[-1] += nxt
            i += 2
            continue
        if c in "[]{}()|+?*^$":
            return None, "regex"
        pieces[-1] += c
        i += 1
    # leading/trailing .* runs void the adjacent anchor
    if len(pieces) > 1 and pieces[0] == "":
        anchor_start = False
    if len(pieces) > 1 and pieces[-1] == "":
        anchor_end = False
    return _compile_pieces([p for p in pieces if p],
                           anchor_start, anchor_end, key)


# ---------------------------------------------------------------------------
# per-segment match plan (cached per (segment, column, pattern))
# ---------------------------------------------------------------------------

class SegPlan:
    """One segment's compiled match plan (host-side numpy)."""
    __slots__ = ("always", "glt", "gmem", "ckind", "cslot", "chi", "clo",
                 "clut", "luts", "card", "kd_need", "ke_need")

    def __init__(self, always: np.ndarray, card: int):
        self.always = always
        self.card = card
        self.glt = np.zeros(0, np.int32)
        self.gmem = np.zeros((0, 0), bool)
        self.ckind = np.zeros(0, np.int8)
        self.cslot = np.zeros(0, np.int32)
        self.chi = np.zeros(0, np.int32)
        self.clo = np.zeros(0, np.int32)
        self.clut = np.zeros(0, np.int32)
        self.luts = np.zeros((0, card), bool)
        self.kd_need = 0
        self.ke_need = 0


def _occurrences(tmpl: _Template, lt: str, enc_pref: List[int],
                 dict_pref: List[int]):
    """Every (overlapping) occurrence of a piece in a logtype ->
    [(start, end, conds)], or None past the cap. Slot index = count of
    same-family placeholders before the matched position."""
    out = []
    for m in tmpl.regex.finditer(lt):
        conds: List[Tuple] = []
        for g, bind in enumerate(tmpl.binds, start=2):
            p = m.start(g)
            if p < 0:
                continue  # static alternative matched; no condition
            if bind[0] == "enc":
                conds.append(("enc", enc_pref[p], bind[1], bind[2]))
            elif bind[0] == "dicteq":
                conds.append(("dict", dict_pref[p], ("eq", bind[1])))
            else:  # dictsub
                conds.append(("dict", dict_pref[p], (bind[1], bind[2])))
        out.append((m.start(1), m.end(1), tuple(conds)))
        if len(out) > _OCCS_MAX:
            return None
    return out


def _combine(occs: List[list], lt_len: int, a_start: bool, a_end: bool):
    """Ordered non-overlapping placements of all pieces -> list of
    condition tuples (one per alignment), or None past the cap."""
    results: List[Tuple] = []
    n = len(occs)

    def dfs(pi: int, min_s: int, acc: List[Tuple]) -> bool:
        if len(results) > _COMBOS_MAX:
            return False
        if pi == n:
            results.append(tuple(acc))
            return True
        for s, e, conds in occs[pi]:
            if s < min_s:
                continue
            if pi == 0 and a_start and s != 0:
                continue
            if pi == n - 1 and a_end and e != lt_len:
                continue
            if not dfs(pi + 1, e, acc + list(conds)):
                return False
        return True

    if not dfs(0, 0, []):
        return None
    return results


def _lut_row(spec: Tuple[str, str], reader) -> Optional[np.ndarray]:
    """Var-dictionary LUT for one dict condition; None = unsatisfiable."""
    mode, tok = spec
    vd = reader.var_dictionary
    if mode == "eq":
        vid = reader.var_index.get(tok)
        if vid is None:
            return None
        row = np.zeros(len(vd), bool)
        row[vid] = True
        return row
    if mode == "startswith":
        row = np.fromiter((v.startswith(tok) for v in vd), bool, len(vd))
    elif mode == "endswith":
        row = np.fromiter((v.endswith(tok) for v in vd), bool, len(vd))
    else:
        row = np.fromiter((tok in v for v in vd), bool, len(vd))
    return row if row.any() else None


def _plan_segment(reader, compiled: CompiledPattern):
    """-> (SegPlan, None) or (None, reason)."""
    logtypes = reader.logtypes
    card = len(reader.var_dictionary)
    always = np.zeros(len(logtypes), bool)
    sp = SegPlan(always, card)
    if compiled.always:
        always[:] = True
        return sp, None
    if compiled.empty_exact:
        for i, lt in enumerate(logtypes):
            always[i] = lt == ""
        return sp, None
    if compiled.needle is not None:
        needle = compiled.needle
        for i, lt in enumerate(logtypes):
            always[i] = needle in lt
        kd = reader.max_dict_vars
        if kd > KD_MAX:
            return None, "slots"
        if kd:
            vd = reader.var_dictionary
            sp.luts = np.fromiter((needle in v for v in vd),
                                  bool, card).reshape(1, card)
            sp.kd_need = kd
        return sp, None

    # mode 'b': enumerate alignments per logtype
    lut_rows: Dict[Tuple, Optional[int]] = {}  # spec -> lut row (None=dead)
    luts: List[np.ndarray] = []
    cond_ix: Dict[Tuple, int] = {}  # resolved cond key -> index
    ckind: List[int] = []
    cslot: List[int] = []
    chi: List[int] = []
    clo: List[int] = []
    clut: List[int] = []
    groups: set = set()
    for ltid, lt in enumerate(logtypes):
        enc_pref = [0] * (len(lt) + 1)
        dict_pref = [0] * (len(lt) + 1)
        for p, ch in enumerate(lt):
            enc_pref[p + 1] = enc_pref[p] + (ch == INT_PH or ch == FLOAT_PH)
            dict_pref[p + 1] = dict_pref[p] + (ch == DICT_PH)
        occs = []
        feasible = True
        for tmpl in compiled.templates:
            o = _occurrences(tmpl, lt, enc_pref, dict_pref)
            if o is None:
                return None, "alignments"
            if not o:
                feasible = False
                break
            occs.append(o)
        if not feasible:
            continue
        combos = _combine(occs, len(lt), compiled.anchor_start,
                          compiled.anchor_end)
        if combos is None:
            return None, "alignments"
        for conds in combos:
            idxs = set()
            dead = False
            for cond in conds:
                if cond[0] == "dict":
                    spec = cond[2]
                    if spec not in lut_rows:
                        row = _lut_row(spec, reader)
                        if row is None:
                            lut_rows[spec] = None
                        else:
                            lut_rows[spec] = len(luts)
                            luts.append(row)
                    li = lut_rows[spec]
                    if li is None:
                        dead = True
                        break
                    key = ("dict", cond[1], li)
                    if key not in cond_ix:
                        cond_ix[key] = len(ckind)
                        ckind.append(2)
                        cslot.append(cond[1])
                        chi.append(0)
                        clo.append(0)
                        clut.append(li)
                else:
                    key = cond
                    if key not in cond_ix:
                        cond_ix[key] = len(ckind)
                        ckind.append(1)
                        cslot.append(cond[1])
                        chi.append(cond[2])
                        clo.append(cond[3])
                        clut.append(0)
                idxs.add(cond_ix[key])
            if dead:
                continue
            if not idxs:
                always[ltid] = True  # unconditional alignment wins
                break
            groups.add((ltid, tuple(sorted(idxs))))
    live = sorted((ltid, ix) for ltid, ix in groups if not always[ltid])
    if len(live) > GROUPS_MAX:
        return None, "alignments"
    if len(ckind) > CONDS_MAX:
        return None, "slots"
    if len(luts) > LUTS_MAX:
        return None, "slots"
    sp.glt = np.array([g[0] for g in live], np.int32)
    sp.gmem = np.zeros((len(live), len(ckind)), bool)
    for gi, (_, ix) in enumerate(live):
        for ci in ix:
            sp.gmem[gi, ci] = True
    sp.ckind = np.array(ckind, np.int8)
    sp.cslot = np.array(cslot, np.int32)
    sp.chi = np.array(chi, np.int32)
    sp.clo = np.array(clo, np.int32)
    sp.clut = np.array(clut, np.int32)
    if luts:
        sp.luts = np.stack(luts)
    for k, s in zip(ckind, cslot):
        if k == 2:
            sp.kd_need = max(sp.kd_need, s + 1)
        else:
            sp.ke_need = max(sp.ke_need, s + 1)
    return sp, None


#: bounded per-(segment, column, pattern) plan cache; strong segment ref
#: with identity verification (the engine's host-row-cache discipline)
_SEG_PLANS: "OrderedDict[tuple, tuple]" = OrderedDict()
_SEG_PLANS_MAX = 256
_plan_lock = threading.Lock()


def _reader(seg, col):
    try:
        return seg.data_source(col).clp_reader
    except (KeyError, ValueError, AttributeError):
        return None


def seg_plan(seg, col: str, compiled: CompiledPattern):
    key = (id(seg), col, compiled.key)
    with _plan_lock:
        hit = _SEG_PLANS.get(key)
        if hit is not None and hit[0] is seg:
            _SEG_PLANS.move_to_end(key)
            return hit[1], hit[2]
    reader = _reader(seg, col)
    if reader is None:
        return None, "staging"
    sp, reason = _plan_segment(reader, compiled)
    with _plan_lock:
        _SEG_PLANS[key] = (seg, sp, reason)
        while len(_SEG_PLANS) > _SEG_PLANS_MAX:
            _SEG_PLANS.popitem(last=False)
    return sp, reason


def clear_plan_cache() -> None:
    with _plan_lock:
        _SEG_PLANS.clear()


def is_clp_column(seg, col: str) -> bool:
    meta = getattr(seg, "metadata", None)
    columns = getattr(meta, "columns", None)
    if not columns:
        return False
    cm = columns.get(col)
    return cm is not None and it.CLP in getattr(cm, "indexes", ())


def plan_leaf(segments, col: str, pattern: str, is_like: bool):
    """Batch-level leaf planning -> ((mode, Kd, Ke), None) or
    (None, reason). Kd/Ke are pow2 slot-bucket counts folded into the
    DeviceLeaf meta (and so into the plan fingerprint)."""
    compiled, reason = compile_pattern(pattern, is_like)
    if compiled is None:
        return None, reason
    mode = "b" if compiled.templates else "a"
    kd = ke = 0
    for seg in segments:
        if not is_clp_column(seg, col):
            return None, "staging"
        sp, sreason = seg_plan(seg, col, compiled)
        if sp is None:
            return None, sreason
        kd = max(kd, sp.kd_need)
        ke = max(ke, sp.ke_need)
    if kd > KD_MAX or ke > KE_MAX:
        return None, "slots"
    return (mode, _pow2(kd) if kd else 0, _pow2(ke) if ke else 0), None


def staged_cols(leaves) -> Tuple[Tuple[str, int, int], ...]:
    """Union the clp leaves into the DevicePlan.clp_cols staging spec."""
    agg: Dict[str, Tuple[int, int]] = {}
    for lf in leaves:
        if lf.kind != "clp":
            continue
        _, kd, ke = lf.meta
        cur = agg.get(lf.column, (0, 0))
        agg[lf.column] = (max(cur[0], kd), max(cur[1], ke))
    return tuple(sorted((c, kd, ke) for c, (kd, ke) in agg.items()))


# ---------------------------------------------------------------------------
# staging: pseudo-column row fetchers (host-side, per segment)
# ---------------------------------------------------------------------------

def row_ids(reader) -> np.ndarray:
    return np.asarray(reader.logtype_ids, np.int32)


def row_dict_slot(reader, j: int) -> np.ndarray:
    """j-th dict-var id per doc; sentinel = dictionary cardinality (every
    LUT is padded past the cardinality with False, so absent slots never
    match)."""
    out = np.full(reader.num_docs, len(reader.var_dictionary), np.int32)
    starts = reader.dv_offsets[:-1] + j
    have = starts < reader.dv_offsets[1:]
    out[have] = reader.var_ids[starts[have]]
    return out


def _enc_slot(reader, j: int) -> np.ndarray:
    out = np.zeros(reader.num_docs, np.int64)
    starts = reader.enc_offsets[:-1] + j
    have = starts < reader.enc_offsets[1:]
    out[have] = reader.encoded_vars[starts[have]]
    return out


def row_enc_hi(reader, j: int) -> np.ndarray:
    return (_enc_slot(reader, j) >> 32).astype(np.int32)


def row_enc_lo(reader, j: int) -> np.ndarray:
    return (_enc_slot(reader, j) & 0xFFFFFFFF).astype(
        np.uint32).view(np.int32)


# ---------------------------------------------------------------------------
# parameter staging (padded across the batch)
# ---------------------------------------------------------------------------

def leaf_params(i: int, leaf, segments, pattern: str, is_like: bool,
                S: int) -> Dict[str, np.ndarray]:
    """Padded [S, ...] parameter arrays for one clp leaf. S is the
    engine's PADDED segment count; rows past len(segments) stay at their
    never-match defaults (alut False, glt -1)."""
    compiled, _ = compile_pattern(pattern, is_like)
    sps = []
    for seg in segments:
        sp, _ = seg_plan(seg, col=leaf.column, compiled=compiled)
        if sp is None:  # validated at plan time; cache loss re-plans
            raise ValueError(f"clp plan lost for {leaf.column!r}")
        sps.append(sp)
    mode, kd, _ke = leaf.meta
    cp = _pow2(max((len(sp.always) for sp in sps), default=1), floor=8)
    alut = np.zeros((S, cp), bool)
    for s, sp in enumerate(sps):
        alut[s, :len(sp.always)] = sp.always
    out = {f"leaf{i}:alut": alut}
    vp = _pow2(max((sp.card for sp in sps), default=0) + 1, floor=2)
    if mode == "a":
        if kd:
            dvlut = np.zeros((S, vp), bool)
            for s, sp in enumerate(sps):
                if len(sp.luts):
                    dvlut[s, :sp.card] = sp.luts[0]
            out[f"leaf{i}:dvlut"] = dvlut
        return out
    gp = _pow2(max((len(sp.glt) for sp in sps), default=0), floor=1)
    ncp = _pow2(max((len(sp.ckind) for sp in sps), default=0), floor=1)
    nlp = _pow2(max((len(sp.luts) for sp in sps), default=0), floor=1)
    glt = np.full((S, gp), -1, np.int32)
    gmem = np.zeros((S, gp, ncp), bool)
    ckind = np.zeros((S, ncp), np.int8)
    cslot = np.zeros((S, ncp), np.int32)
    chi = np.zeros((S, ncp), np.int32)
    clo = np.zeros((S, ncp), np.int32)
    clut = np.zeros((S, ncp), np.int32)
    for s, sp in enumerate(sps):
        g, nc = len(sp.glt), len(sp.ckind)
        glt[s, :g] = sp.glt
        gmem[s, :g, :nc] = sp.gmem
        ckind[s, :nc] = sp.ckind
        cslot[s, :nc] = sp.cslot
        chi[s, :nc] = sp.chi
        clo[s, :nc] = sp.clo
        clut[s, :nc] = sp.clut
    out.update({f"leaf{i}:glt": glt, f"leaf{i}:gmem": gmem,
                f"leaf{i}:ck": ckind, f"leaf{i}:cs": cslot,
                f"leaf{i}:chi": chi, f"leaf{i}:clo": clo,
                f"leaf{i}:cl": clut})
    if kd:
        dlut = np.zeros((S, nlp, vp), bool)
        for s, sp in enumerate(sps):
            if len(sp.luts):
                dlut[s, :len(sp.luts), :sp.card] = sp.luts
        out[f"leaf{i}:dlut"] = dlut
    return out


# ---------------------------------------------------------------------------
# device evaluation (runs at trace time inside the kernel factory)
# ---------------------------------------------------------------------------

def eval_leaf(i: int, leaf, cols: Dict[str, jnp.ndarray],
              params: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """[S, D] bool match mask for one clp leaf. Padded docs produce
    garbage here (like every other leaf kind) — the engine's per-segment
    doc-validity mask clips them."""
    mode, kd, ke = leaf.meta
    col = leaf.column
    ids = cols[f"clpid:{col}"]
    alut = params[f"leaf{i}:alut"]
    match = jnp.take_along_axis(alut, ids, axis=1)
    if mode == "a":
        if kd:
            dvlut = params[f"leaf{i}:dvlut"]
            for j in range(kd):
                match = match | jnp.take_along_axis(
                    dvlut, cols[f"clpdv{j}:{col}"], axis=1)
        return match
    glt = params[f"leaf{i}:glt"]
    gmem = params[f"leaf{i}:gmem"]
    ck = params[f"leaf{i}:ck"]
    cs = params[f"leaf{i}:cs"]
    S, NC = ck.shape
    D = ids.shape[1]
    ok = jnp.ones((S, NC, D), bool)
    if ke:
        ehi = jnp.stack([cols[f"clpehi{j}:{col}"] for j in range(ke)], 1)
        elo = jnp.stack([cols[f"clpelo{j}:{col}"] for j in range(ke)], 1)
        sidx = jnp.broadcast_to(
            jnp.clip(cs, 0, ke - 1)[:, :, None], (S, NC, D))
        ghi = jnp.take_along_axis(ehi, sidx, axis=1)
        glo = jnp.take_along_axis(elo, sidx, axis=1)
        enc_ok = (ghi == params[f"leaf{i}:chi"][:, :, None]) & \
                 (glo == params[f"leaf{i}:clo"][:, :, None])
        ok = jnp.where(ck[:, :, None] == 1, enc_ok, ok)
    if kd:
        dv = jnp.stack([cols[f"clpdv{j}:{col}"] for j in range(kd)], 1)
        sidx = jnp.broadcast_to(
            jnp.clip(cs, 0, kd - 1)[:, :, None], (S, NC, D))
        gvid = jnp.take_along_axis(dv, sidx, axis=1)
        dlut = params[f"leaf{i}:dlut"]
        NL, V = dlut.shape[1], dlut.shape[2]
        lidx = jnp.broadcast_to(
            jnp.clip(params[f"leaf{i}:cl"], 0, NL - 1)[:, :, None],
            (S, NC, V))
        bank = jnp.take_along_axis(dlut, lidx, axis=1)
        dict_ok = jnp.take_along_axis(bank, gvid, axis=2)
        ok = jnp.where(ck[:, :, None] == 2, dict_ok, ok)
    # group holds iff every member condition holds: count failures with
    # a one-hot matmul over the distinct conditions (counts <= CONDS_MAX,
    # exact in f32; MXU-friendly, no per-group gathers)
    nfail = jnp.einsum("sgk,skd->sgd", gmem.astype(jnp.float32),
                       (~ok).astype(jnp.float32))
    grp = (glt[:, :, None] >= 0) & (ids[:, None, :] == glt[:, :, None]) \
        & (nfail < 0.5)
    return match | grp.any(axis=1)


def make_match_kernel(i: int, leaf):
    """Standalone kernel body (tests + the purity checker's traced set)."""
    def clp_match(cols, params):
        return eval_leaf(i, leaf, cols, params)
    return clp_match


@functools.lru_cache(maxsize=64)
def compiled_match_kernel(i: int, leaf):
    return jax.jit(make_match_kernel(i, leaf))
