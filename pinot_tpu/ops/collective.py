"""Collective broker merge: the cross-segment partial fold ON DEVICE.

Reference parity: the reference broker/server merge per-segment partials
host-side (IndexedTable / the combine operators — SURVEY §2.7). On an
N-chip mesh engine that fold is the last host hop in the hot path: every
query ships [S, ...] per-segment partials over the link and reduces them
in Python. This module folds them where they already live — one
psum/pmin/pmax rendezvous over the WHOLE mesh (both the `segments` and
`docs` axes) inside the same shard_map the sharded kernels use, so a
query returns ONE merged row instead of S per-segment rows.

Layout contract (engine._assemble_merged is the only consumer):

  no group-by: [sum(slot widths) + S]   — merged slots at the same
               slot offsets _assemble uses (no leading matched column),
               then the per-segment matched counts as an [S] tail (the
               exact ExecutionStats the host fold would have summed).
  group-by:    [G * n_slots + S]        — the merged [G, n_slots] group
               block flattened row-major, then the same [S] matched tail.
  batched:     [B, L] — batch axis leading, same L per member, so the
               dispatch ring's split_packed contract holds unchanged.

Group keys are GLOBAL: per-segment dictIds/compact codes are
segment-local, so the engine factorizes a global key space once
host-side (engine._merged_group_params) and ships tiny int32 remap
params — `gmap` (compact: local code -> global index) or per-column
`gmap<i>` + traced `gstride` (dense: local dictId -> global value index,
mixed-radix over the UNION cardinalities). The kernels here only gather
through those tables; changing segment composition re-uploads a few KB
of params and never retraces.

Merge semantics per slot ride kernels._DOC_COMBINE — combining partials
across segments uses the same semiring as combining across doc shards
(sum-family psum, min pmin, max/hll pmax, hist/isum psum), so the local
segment-axis reduce + one collective over every mesh axis is exactly the
host fold's algebra, just associated differently. Bit-parity against the
host fold is property-tested in tests/test_mesh_scaling.py.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from pinot_tpu.ops import kernels
from pinot_tpu.ops.kernels import note_trace, plan_fingerprint
from pinot_tpu.ops.plan_ir import DevicePlan

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map  # type: ignore


def _merged_plan(plan: DevicePlan) -> DevicePlan:
    """Plan variant whose group keys come from cols['gkey'] (the
    injected GLOBAL keys) regardless of how the original plan keyed:
    group_compact reads gkey directly and num_groups=0 defers the group
    count to the kernel's static G (the global pow2 pad)."""
    if not plan.group_cols:
        return plan
    return dataclasses.replace(plan, group_compact=True, num_groups=0,
                               group_strides=())


def _global_keys(plan: DevicePlan, cols, params) -> jnp.ndarray:
    """Shard-local [S_loc, D_loc] GLOBAL group indices via the host-
    factorized remap params (engine._merged_group_params)."""
    if plan.group_compact:
        # local compact code -> global index: one gather per doc
        return jnp.take_along_axis(params["gmap"], cols["gkey"], axis=-1)
    keys = None
    gstride = params["gstride"]  # [S, k] global mixed-radix strides
    for ci, col in enumerate(plan.group_cols):
        idx = jnp.take_along_axis(params[f"gmap{ci}"],
                                  cols["ids:" + col], axis=-1)
        term = idx * gstride[..., ci:ci + 1]
        keys = term if keys is None else keys + term
    return keys


def _member_fn(plan: DevicePlan, doc_shards: int, has_docs: bool,
               count_j):
    """Per-member shard-local compute: slot partials reduced over the
    LOCAL segment axis (pure jnp — vmappable; collectives are applied by
    the caller AFTER any batching, so a batch pays one rendezvous).
    Returns (tuple of locally-reduced slot arrays, local matched [S_loc])."""
    mplan = _merged_plan(plan)
    grouped = bool(plan.group_cols)

    def member(cols, params, num_docs, D, G):
        d_local = D // doc_shards
        if has_docs:
            doc_pos = (jax.lax.axis_index("docs") * d_local
                       + jnp.arange(d_local, dtype=jnp.int32))[None, :]
        else:
            doc_pos = jnp.arange(D, dtype=jnp.int32)[None, :]
        valid = doc_pos < num_docs[:, None]
        if plan.valid_mask:
            valid = valid & cols["vmask"]
        if grouped:
            kcols = dict(cols)
            kcols["gkey"] = _global_keys(plan, cols, params)
            slots, _ = kernels._compute_slots(mplan, kcols, params,
                                              valid, G)
            # the guaranteed unfiltered count slot sums to the per-seg
            # matched count (every matched doc lands in exactly one key)
            matched = jnp.sum(slots[count_j][1], axis=-1)
        else:
            slots, matched = kernels._compute_slots(plan, cols, params,
                                                    valid, 0)
        # local fold over THIS shard's segments; axis 0 is the segment
        # axis for every slot shape here ([S_loc] scalar, [S_loc, w]
        # sketch, [S_loc, G] grouped)
        locs = []
        for (op, _v, _f), (_o, s) in zip(plan.agg_ops, slots):
            kind = kernels._doc_combine(op)
            if kind == "psum":
                locs.append(jnp.sum(s, axis=0))
            elif kind == "pmin":
                locs.append(jnp.min(s, axis=0))
            else:
                locs.append(jnp.max(s, axis=0))
        return tuple(locs), matched

    return member


def _collect_pack(plan: DevicePlan, locs, axes, G: int):
    """One collective per slot over EVERY mesh axis, then pack into the
    module's merged layout (rank-agnostic: a leading batch axis rides
    along untouched — the reductions already happened per member)."""
    merged = []
    for (op, _v, _f), s in zip(plan.agg_ops, locs):
        kind = kernels._doc_combine(op)
        if kind == "psum":
            merged.append(jax.lax.psum(s, axes))
        elif kind == "pmin":
            merged.append(jax.lax.pmin(s, axes))
        else:
            merged.append(jax.lax.pmax(s, axes))
    if plan.group_cols:
        out = jnp.stack(merged, axis=-1)          # [..., G, n_slots]
        return out.reshape(out.shape[:-2] + (G * len(plan.agg_ops),))
    parts = [s[..., None] if kernels.slot_width(op) == 1 else s
             for (op, _v, _f), s in zip(plan.agg_ops, merged)]
    return jnp.concatenate(parts, axis=-1)        # [..., sum(widths)]


def _mesh_geometry(mesh):
    axes = tuple(mesh.axis_names)
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return axes, "docs" in axes, shape.get("docs", 1)


def _find_count_slot(plan: DevicePlan):
    if not plan.group_cols:
        return None
    for j, (op, _v, fidx) in enumerate(plan.agg_ops):
        if op == "count" and fidx is None:
            return j
    raise ValueError("grouped plan without an unfiltered count slot")


def _matched_tail(matched, seg_shards: int, axes):
    """Global [..., S] matched-count tail, built INSIDE the body: each
    segment shard scatters its local counts into its slice of a zeroed
    [S] vector and ONE psum over every mesh axis fills it (doc shards'
    halves add; other segment shards' zeros are the identity). Folding
    the tail in-body keeps the kernel output a single fully-replicated
    array — concatenating a replicated shard_map output with a
    segment-sharded one inside the same jit miscompiles on this jax
    (the partitioner re-reduces the replicated operand over the doc
    axis, doubling every merged slot)."""
    s_loc = matched.shape[-1]
    full = list(matched.shape)
    full[-1] = s_loc * seg_shards
    off = jax.lax.axis_index("segments") * s_loc
    idx = (jnp.int32(0),) * (len(full) - 1) + (off,)
    scattered = jax.lax.dynamic_update_slice(
        jnp.zeros(tuple(full), matched.dtype), matched, idx)
    return jax.lax.psum(scattered, axes)


def make_merged_kernel(plan: DevicePlan, mesh):
    """Single-query collective merge: fn(cols, params, num_docs, D, G)
    -> ONE packed [L] row (layout in the module docstring). D is the
    padded GLOBAL doc count; G the GLOBAL group pad (0 = no group-by)."""
    from jax.sharding import PartitionSpec as P

    axes, has_docs, doc_shards = _mesh_geometry(mesh)
    seg_shards = dict(zip(mesh.axis_names,
                          mesh.devices.shape))["segments"]
    fp = plan_fingerprint(plan)
    count_j = _find_count_slot(plan)
    member = _member_fn(plan, doc_shards, has_docs, count_j)

    def local(cols, params, num_docs, D, G=0):
        # body runs at trace time: counts compiles
        note_trace("merged", fp, (int(num_docs.shape[-1]), D, G))
        locs, matched = member(cols, params, num_docs, D, G)
        flat = _collect_pack(plan, locs, axes, G)
        tail = _matched_tail(matched, seg_shards, axes)
        return jnp.concatenate([flat, tail.astype(flat.dtype)], axis=-1)

    col_spec = P("segments", "docs") if has_docs else P("segments", None)

    def fn(cols, params, num_docs, D, G=0):
        in_specs = (
            {k: col_spec for k in cols},
            {k: P("segments", *([None] * (v.ndim - 1)))
             for k, v in params.items()},
            P("segments"),
        )
        sm = shard_map(
            functools.partial(local, D=D, G=G), mesh=mesh,
            in_specs=in_specs,
            # the whole packed row is replicated by construction: every
            # slot AND the matched tail are reduced over every mesh axis
            out_specs=P(None),
        )
        return sm(cols, params, num_docs)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_merged_kernel(plan: DevicePlan, mesh):
    return make_merged_kernel(plan, mesh)


def make_batched_merged_kernel(plan: DevicePlan, mesh, B: int,
                               stacked: bool = False):
    """Batched collective merge: vmap INSIDE shard_map exactly like
    kernels.make_batched_sharded_kernel — mesh axes outermost, batch
    innermost, so B coalesced queries pay ONE set of collectives over
    the stacked per-member partials. Output [B, L]; the dispatch ring's
    pad-to-bucket + split_packed contract holds unchanged."""
    from jax.sharding import PartitionSpec as P

    axes, has_docs, doc_shards = _mesh_geometry(mesh)
    seg_shards = dict(zip(mesh.axis_names,
                          mesh.devices.shape))["segments"]
    fp = plan_fingerprint(plan)
    count_j = _find_count_slot(plan)
    member = _member_fn(plan, doc_shards, has_docs, count_j)
    kind = "merged_batched_stacked" if stacked else "merged_batched"

    def local(cols, params, num_docs, D, G=0):
        note_trace(kind, fp, (B, D, G))
        # the index array keeps vmap fed when a filterless plan has
        # EMPTY per-query params (vmap rejects an all-empty pytree)
        idx = jnp.arange(B, dtype=jnp.int32)
        in_axes = (0 if stacked else None, 0, 0 if stacked else None, 0)
        locs, matched = jax.vmap(
            lambda c, p, nd, _i: member(c, p, nd, D, G),
            in_axes=in_axes)(cols, params, num_docs, idx)
        flat = _collect_pack(plan, locs, axes, G)
        tail = _matched_tail(matched, seg_shards, axes)
        return jnp.concatenate([flat, tail.astype(flat.dtype)], axis=-1)

    def fn(cols, plist, num_docs, D, G=0):
        ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
        if stacked:
            cs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *cols)
            ns = jnp.stack(num_docs)
            col_spec = P(None, "segments", "docs") if has_docs \
                else P(None, "segments", None)
            nd_spec = P(None, "segments")
        else:
            cs, ns = cols, num_docs
            col_spec = P("segments", "docs") if has_docs \
                else P("segments", None)
            nd_spec = P("segments")
        in_specs = (
            {k: col_spec for k in cs},
            {k: P(None, "segments", *([None] * (v.ndim - 2)))
             for k, v in ps.items()},
            nd_spec,
        )
        sm = shard_map(
            functools.partial(local, D=D, G=G), mesh=mesh,
            in_specs=in_specs,
            out_specs=P(None, None),
        )
        return sm(cs, ps, ns)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_batched_merged_kernel(plan: DevicePlan, mesh, B: int,
                                   stacked: bool = False):
    """One jit per (plan, mesh, B bucket, stacked?) —
    fn(cols|clist, plist, num_docs|ndlist, D, G)."""
    return make_batched_merged_kernel(plan, mesh, B, stacked)
