"""KernelDispatcher: the engine's pipelined device-launch stage.

Reference parity: the role of pinot-core's per-server query worker pool
(query/scheduler/QueryScheduler.java submitting segment work to
executors) — but shaped like an inference-serving dispatcher, because
the hot path here is ONE device program per query, not N segment tasks:

  * dispatch ring — a single dispatch thread + bounded queue replaces
    the ad-hoc dispatch lock: callers enqueue STAGED launches (columns
    already HBM-resident, predicate params already resolved) and get
    futures. The ring orders collective-bearing programs on host
    platforms (XLA's intra-process CPU collectives deadlock when two
    partitioned programs interleave their rendezvous), while real
    accelerators keep fully concurrent submission through a launch pool.
  * shape-bucketed micro-batching — concurrent queries coalesce on the
    kernel-factory key (plan fingerprint, shape bucket): same
    `DevicePlan`, same padded (S, D, G) bucket, same staged-array shape
    signature — NOT the same concrete segment batch, so the dashboard
    fleet batches across tables and partitions. One launch carries all
    members: params always stack along a leading query axis; column
    blocks broadcast when every member staged the same batch, or stack
    along the leading axis too when members come from different tables
    (ops/kernels.py `compiled_batched_kernel(plan, B, stacked)`), and
    doc-sharded mesh engines ride `compiled_batched_sharded_kernel`
    (vmap INSIDE shard_map, one set of collectives per batch — the
    CPU-collective lock is held once per batch, not once per query).
    Results split back per caller. Batched kernels are cached per
    (plan, pow2 batch bucket, variant) — a cross-query retrace is a
    bug, and `kernels.trace_count()` / `kernels.trace_log()` / the
    per-plan-labelled `kernel_retrace` meter make one loud.
  * staging/compute overlap — device->host result fetch runs on a fetch
    pool OFF the ring, so the next launch overlaps the previous fetch;
    `execute_async` staging runs on a staging pool so host-side padding
    + `jax.device_put` for query N+1 proceed while query N's kernel
    occupies the device (`staging_overlap_ms` measures exactly that).

Deadline/cancel checks are honored while a launch waits in the ring: a
cancelled query's future fails and the query leaves its batch before
launch. Chaos tests hook the ring via the `server.dispatch.before`
failpoint site (delay a dispatch, fail it, or reorder around it) and
the per-member `server.dispatch.batch` site inside the coalesced path
(an erroring member fails only its own future; peers complete).

Knobs (utils/config.py): pinot.server.dispatch.mode (pipelined |
serialized — the latter reproduces the pre-ring inline dispatch for
A/B), .ring.size, .batch.window.ms, .batch.max, .batch.cross.table
(shape-bucket coalescing across tables; off = same-segment-batch
coalescing only), and pinot.server.dispatch.doc.bucket.max (largest doc
bucket that may stack cross-table — bounds the [B, S, D] stacked
footprint).
"""
from __future__ import annotations

import contextlib
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax

from pinot_tpu.ops import kernels
from pinot_tpu.utils.failpoints import fire

#: XLA's intra-process CPU collectives rendezvous by (devices, op) — two
#: partitioned computations RUNNING concurrently (even from different
#: engine instances: host-platform devices are process-global)
#: interleave their rendezvous and deadlock. Collective-bearing launches
#: therefore hold this process-global lock across dispatch +
#: block_until_ready; real accelerators have a hardware-ordered
#: collective queue and never take it.
_CPU_COLLECTIVE_LOCK = threading.Lock()

#: shared worker pools (module-level: fetch/launch work is engine-
#: agnostic, and per-engine pools would leak threads across the many
#: short-lived engines tests create)
_LAUNCH_THREADS = 8
_FETCH_THREADS = 4
_STAGING_THREADS = 4
_UPLOAD_THREADS = 4
_pools_lock = threading.Lock()
_launch_pool: Optional[ThreadPoolExecutor] = None
_fetch_pool: Optional[ThreadPoolExecutor] = None
_staging_pool: Optional[ThreadPoolExecutor] = None
_upload_pool: Optional[ThreadPoolExecutor] = None


def launch_pool() -> ThreadPoolExecutor:
    global _launch_pool
    with _pools_lock:
        if _launch_pool is None:
            _launch_pool = ThreadPoolExecutor(
                max_workers=_LAUNCH_THREADS,
                thread_name_prefix="kernel-launch")
        return _launch_pool


def fetch_pool() -> ThreadPoolExecutor:
    global _fetch_pool
    with _pools_lock:
        if _fetch_pool is None:
            _fetch_pool = ThreadPoolExecutor(
                max_workers=_FETCH_THREADS,
                thread_name_prefix="kernel-fetch")
        return _fetch_pool


def staging_pool() -> ThreadPoolExecutor:
    global _staging_pool
    with _pools_lock:
        if _staging_pool is None:
            _staging_pool = ThreadPoolExecutor(
                max_workers=_STAGING_THREADS,
                thread_name_prefix="kernel-staging")
        return _staging_pool


def upload_pool() -> ThreadPoolExecutor:
    """Residency row uploads (host->device device_put) fan out here so a
    multi-row miss double-buffers: row N+1's copy engines run while row
    N's transfer is in flight, and — because staging itself runs on the
    staging pool under execute_async — the whole upload burst overlaps
    the previous query's device round trip. A DEDICATED pool: staging
    tasks submit these and wait, so sharing the staging pool would
    deadlock once its workers are all waiting on their own subtasks."""
    global _upload_pool
    with _pools_lock:
        if _upload_pool is None:
            _upload_pool = ThreadPoolExecutor(
                max_workers=_UPLOAD_THREADS,
                thread_name_prefix="residency-upload")
        return _upload_pool


def _pow2(n: int) -> int:
    v = 1
    while v < n:
        v *= 2
    return v


#: chunk size for bounded future waits: long enough to stay off the
#: hot path's profile, short enough that a deadline trips promptly
_RESULT_POLL_S = 0.25

#: default hard backstop on any wait_result call. Callers with a query
#: attached pass a cancel_check that trips the deadline far sooner;
#: this exists so a caller with NO budget (warmup/prestage, a query
#:  submitted without an id) still cannot park a thread forever on a
#: wedged device link. An explicit max_wait_s=None opts out.
DEFAULT_WAIT_CAP_S = 600.0


def wait_result(future: Future, cancel_check=None,
                max_wait_s: Optional[float] = DEFAULT_WAIT_CAP_S,
                poll_s: float = _RESULT_POLL_S):
    """Deadline-bounded ``future.result()``: the unbounded-wait fix the
    hang-risk lint demands on every dispatcher wait.

    The ring promises to complete every popped launch's future, but
    that invariant lives a module away from the caller blocked in
    ``.result()`` — a producer bug (or a launch stuck on a wedged
    device) must surface as the QUERY's own deadline error, not as a
    server thread parked forever. So the wait is chunked: each poll
    runs ``cancel_check`` (the ResourceAccountant checker carrying the
    query's remaining PR-3 deadline budget — it raises
    BrokerTimeoutError/QueryCancelledError past the wall), and
    ``max_wait_s`` (DEFAULT_WAIT_CAP_S unless overridden) is the hard
    backstop for budget-less callers — prestage/warmup paths, or a
    query submitted without an id, where cancel_check is None.
    """
    deadline = None if max_wait_s is None else time.monotonic() + max_wait_s
    while True:
        try:
            return future.result(timeout=poll_s)
        except (_FutureTimeout, TimeoutError):
            if future.done():
                # either the WORK raised a timeout error, or the future
                # completed inside the poll-expiry race window (result()
                # timed out, the dispatcher thread landed the value
                # before this check) — a zero-timeout result()
                # disambiguates: the landed value if there is one, the
                # work's own exception otherwise. Never re-raise the
                # poll's timeout for a future that is done.
                return future.result(timeout=0)
            if cancel_check is not None:
                cancel_check()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"device launch incomplete after {max_wait_s}s "
                    f"(dispatcher wedged?)") from None


def split_packed(arr: np.ndarray, n: int) -> List[np.ndarray]:
    """Zero-copy per-member split of a batched result fetch (ROADMAP
    item): the N coalesced callers receive VIEWS into the ONE packed
    device->host array (basic indexing on the leading query axis), not N
    host-side copies — the fetch pool materializes each launch's bytes
    exactly once regardless of batch size. Padding members (replicated
    leader params past `n`) are simply never viewed. The view guarantee
    is asserted here because a silent regression to copies would
    multiply fetch-pool memory traffic by the batch size with no
    functional symptom."""
    members = [arr[i] for i in range(n)]
    assert all(m.base is not None and np.shares_memory(m, arr)
               for m in members), "batched split must return views"
    return members


def compiled_batched_kernel(plan, B: int, stacked: bool = False):
    """Compat alias: the batched factory now lives in ops/kernels.py as
    part of the unified kernel factory (keyed on plan fingerprint +
    shape bucket, broadcast and stacked variants)."""
    return kernels.compiled_batched_kernel(plan, B, stacked)


def split_charge(live: List["Launch"], kernel_ms: float) -> None:
    """Workload accounting for one launch: charge its device kernel ms
    across the coalesced members by DOC SHARE — a member that brought
    90% of the scanned docs bought 90% of the launch. The invariant the
    property test pins: the per-member charges sum to the launch total
    (each share is an exact fraction of kernel_ms over the live-member
    doc total). Members whose query detached (no slip: warmup, MSE
    internal calls, finished queries) still count in the denominator —
    their share is simply unrecorded, never redistributed, so an
    attributed member's bill does not depend on its neighbors'
    bookkeeping."""
    if kernel_ms is None or kernel_ms <= 0:
        return
    total_docs = sum(max(0, it.docs) for it in live)
    n = len(live)
    for it in live:
        if it.slip is None:
            continue
        share = (kernel_ms * (max(0, it.docs) / total_docs)
                 if total_docs > 0 else kernel_ms / n)
        try:
            it.slip.add(device_kernel_ms=share)
        except Exception:  # noqa: BLE001 — accounting must never
            # fail a query's result delivery
            pass


class Launch:
    """One staged device launch waiting in the ring.

    `call` runs the already-compiled single-query kernel; the batching
    fields (plan/cols/params/num_docs/D/G) are only read when
    `batch_key` is set and the ring coalesces this launch with
    fingerprint-equal peers. `batch_key` is the SHAPE-BUCKET key (plan,
    S, D, G, array-shape signature) — members of one batch may stage
    different tables; `cols_key` is the concrete staged-batch identity
    the dispatcher compares to choose broadcast (all members share one
    set of column blocks) vs stacked (each member's blocks stack along a
    leading axis) execution. `factory(B, stacked)` builds the batched
    kernel for this launch's engine (plain vmap or vmap-in-shard_map on
    doc-sharded meshes). `cancel_check` is polled while queued — raising
    removes the launch from its batch and fails the future with the
    raised error (the ResourceAccountant deadline/cancel checker)."""

    __slots__ = ("call", "plan", "cols", "params", "num_docs", "D", "G",
                 "batch_key", "cols_key", "factory", "dedup_factory",
                 "collective", "cancel_check", "site_ctx", "future",
                 "span", "enq_ts", "slip", "docs")

    def __init__(self, call: Callable[[], Any], plan=None, cols=None,
                 params=None, num_docs=None, D: int = 0, G: int = 0,
                 batch_key: Optional[tuple] = None,
                 cols_key: Optional[tuple] = None,
                 factory: Optional[Callable[[int, bool], Any]] = None,
                 dedup_factory: Optional[Callable[[int, int], Any]] = None,
                 collective: bool = False,
                 cancel_check: Optional[Callable[[], None]] = None,
                 site_ctx: Optional[Dict[str, Any]] = None,
                 span=None, slip=None, docs: int = 0):
        self.call = call
        self.plan = plan
        self.cols = cols
        self.params = params
        self.num_docs = num_docs
        self.D = D
        self.G = G
        self.batch_key = batch_key
        self.cols_key = cols_key
        self.factory = factory
        #: optional (B, U) -> kernel for SAME-COLS MEMBER GROUPING in a
        #: stacked batch: members with identity-equal staged blocks
        #: share one stack entry (engines that can't dedup leave it None)
        self.dedup_factory = dedup_factory
        self.collective = collective
        self.cancel_check = cancel_check
        self.site_ctx = site_ctx or {}
        self.future: Future = Future()
        #: tracing.SpanHandle captured on the CALLER thread (contextvars
        #: don't flow into the ring/launch/fetch pools) — the dispatcher
        #: attaches queue-wait / batch / kernel / fetch attrs through it
        self.span = span
        #: accounting.ChargeSlip captured on the CALLER thread (same
        #: discipline as span): the dispatcher charges this launch's
        #: device kernel ms through it — a coalesced launch's bill
        #: splits across members by `docs` share (split_charge)
        self.slip = slip
        #: real docs staged for this member (the cost-split weight)
        self.docs = int(docs)
        self.enq_ts = 0.0


class KernelDispatcher:
    """Owns device launches for one engine: ring + batching + overlap."""

    #: ring thread exits after this much idle time (a fresh submit
    #: respawns it) — engines are created freely in tests and a
    #: threads-forever design would leak one per instance
    IDLE_EXIT_S = 5.0

    def __init__(self, config=None, metrics=None,
                 labels: Optional[Dict[str, str]] = None):
        from pinot_tpu.utils.config import PinotConfiguration
        from pinot_tpu.utils.metrics import get_registry
        cfg = config or PinotConfiguration()
        self.mode = cfg.get_str("pinot.server.dispatch.mode") or "pipelined"
        self.ring_size = max(1, cfg.get_int("pinot.server.dispatch.ring.size"))
        self.batch_max = max(1, cfg.get_int("pinot.server.dispatch.batch.max"))
        # window.ms=auto sizes the coalesce wait from an EWMA of observed
        # caller inter-arrival times, clamped to [0.5x, 4x] of the static
        # catalog default: a bursty fleet waits about one inter-arrival
        # (just long enough for its peers to land), a lone tight-loop
        # caller converges to the floor — and lone IDLE callers never
        # consult the window at all (inline fast path)
        from pinot_tpu.utils.config import KEYS
        raw_window = cfg.get("pinot.server.dispatch.batch.window.ms")
        static_s = max(0.0, float(
            KEYS["pinot.server.dispatch.batch.window.ms"]) / 1e3)
        self.window_auto = str(raw_window).strip().lower() == "auto"
        if self.window_auto:
            self.window_s = static_s
        else:
            self.window_s = max(0.0, float(raw_window) / 1e3)
        self._window_floor_s = 0.5 * static_s
        self._window_ceil_s = 4.0 * static_s
        self._arrival_ewma_s: Optional[float] = None
        self._last_arrival: Optional[float] = None
        self._metrics = metrics if metrics is not None \
            else get_registry("server")
        self._labels = labels
        self._cv = threading.Condition()
        self._pending: List[Launch] = []
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        #: callers currently inside an engine execute for this engine —
        #: the batching window only waits when >1 (a lone client never
        #: pays window latency for a batch that cannot form)
        self._active = 0
        # device-busy clock: wall time with >=1 launch in flight, so
        # staging can measure how much of itself overlapped compute
        self._busy_lock = threading.Lock()
        self._inflight = 0
        self._busy_accum = 0.0
        self._busy_since = 0.0
        self._trace_seen = kernels.trace_count()
        self._trace_seen_by_plan = kernels.trace_count_by_plan()
        self._trace_meter_lock = threading.Lock()

    # -- caller accounting --------------------------------------------
    @contextlib.contextmanager
    def active(self):
        self.enter_active()
        try:
            yield
        finally:
            self.exit_active()

    def enter_active(self) -> None:
        with self._cv:
            self._active += 1
            self._cv.notify_all()

    def exit_active(self) -> None:
        with self._cv:
            self._active = max(0, self._active - 1)
            self._cv.notify_all()

    # -- device-busy clock --------------------------------------------
    def _busy_begin(self) -> None:
        with self._busy_lock:
            if self._inflight == 0:
                self._busy_since = time.monotonic()
            self._inflight += 1

    def _busy_end(self) -> None:
        with self._busy_lock:
            self._inflight -= 1
            if self._inflight == 0:
                self._busy_accum += time.monotonic() - self._busy_since

    def busy_ms(self) -> float:
        """Cumulative wall-ms during which >=1 launch was in flight."""
        with self._busy_lock:
            total = self._busy_accum
            if self._inflight > 0:
                total += time.monotonic() - self._busy_since
        return total * 1e3

    # -- metrics helpers ----------------------------------------------
    def observe(self, name: str, value: float) -> None:
        self._metrics.add_timing(name, value, labels=self._labels)

    def _set_depth_locked(self) -> None:
        self._metrics.set_gauge("dispatch_queue_depth", len(self._pending),
                                labels=self._labels)

    def _meter_traces(self) -> None:
        # read-modify-write under a lock: finishes land concurrently on
        # caller/launch/fetch threads, and a racy double-read would
        # double-count the retrace meter precisely under the concurrent
        # load it exists to watch
        with self._trace_meter_lock:
            now = kernels.trace_count()
            delta = now - self._trace_seen
            if delta <= 0:
                return
            self._trace_seen = now
            # per-plan-fingerprint attribution: a retrace storm names the
            # plan that churned, straight from /metrics
            by_plan = kernels.trace_count_by_plan()
            plan_deltas = {}
            for fp, n in by_plan.items():
                d = n - self._trace_seen_by_plan.get(fp, 0)
                if d > 0:
                    plan_deltas[fp] = d
            self._trace_seen_by_plan = by_plan
        self._metrics.add_meter("kernel_retrace", delta,
                                labels=self._labels)
        # attribution rides a SEPARATE series name: reusing
        # kernel_retrace with an extra label would double-count any
        # sum() across label sets (the aggregate must stay summable)
        for fp, d in plan_deltas.items():
            labels = dict(self._labels or {})
            labels["plan"] = fp
            self._metrics.add_meter("kernel_retrace_by_plan", d,
                                    labels=labels)

    # -- adaptive batching window --------------------------------------
    def _note_arrival_locked(self) -> None:
        """EWMA of submit inter-arrival gaps (auto window mode). Gaps
        past the clamp ceiling are recorded AT the ceiling: idle pauses
        must not take many queries to forget, only to remember."""
        if not self.window_auto:
            return
        now = time.monotonic()
        if self._last_arrival is not None:
            gap = min(now - self._last_arrival, self._window_ceil_s)
            cur = self._arrival_ewma_s
            self._arrival_ewma_s = gap if cur is None \
                else 0.8 * cur + 0.2 * gap
        self._last_arrival = now

    def current_window_s(self) -> float:
        """The coalesce wait in effect: static knob, or the clamped
        inter-arrival EWMA under window.ms=auto — scaled down while the
        brownout ladder's batch_shrink rung is engaged
        (health/brownout.py): under overload, queue latency buys more
        goodput than coalescing efficiency."""
        from pinot_tpu.health.brownout import window_scale
        scale = window_scale("server")
        if not self.window_auto or self._arrival_ewma_s is None:
            return self.window_s * scale
        return scale * min(self._window_ceil_s,
                           max(self._window_floor_s,
                               self._arrival_ewma_s))

    # -- submission ----------------------------------------------------
    def submit(self, launch: Launch) -> Future:
        """Enqueue a staged launch; returns its future (an np.ndarray of
        the packed kernel output, or the launch's error). Blocks for ring
        space (backpressure), polling the launch's cancel check."""
        launch.enq_ts = time.monotonic()
        if self.mode == "serialized":
            return self._submit_serialized(launch)
        with self._cv:
            self._note_arrival_locked()
            idle = (self._active <= 1 and not self._pending
                    and self._inflight == 0)
        if idle:
            # lone-query fast path: no concurrency means nothing to
            # coalesce or overlap — dispatch inline and pay ZERO ring
            # latency (single-stream p50 stays at the pre-ring floor).
            # A racing second caller just falls back to the collective
            # lock inside, which is the pre-ring behavior anyway.
            return self._submit_serialized(launch)
        with self._cv:
            while len(self._pending) >= self.ring_size and not self._closed:
                if launch.cancel_check is not None:
                    try:
                        launch.cancel_check()
                    except BaseException as e:  # noqa: BLE001
                        launch.future.set_exception(e)
                        return launch.future
                self._cv.wait(0.05)
            if self._closed:
                launch.future.set_exception(
                    RuntimeError("dispatcher closed"))
                return launch.future
            self._pending.append(launch)
            self._set_depth_locked()
            self._ensure_thread_locked()
            self._cv.notify_all()
        return launch.future

    def _submit_serialized(self, launch: Launch) -> Future:
        """Inline dispatch + fetch on the caller thread, the collective
        lock held across both: the exact pre-PR `_dispatch_guard`
        behavior. Serves both the `serialized` compat mode (A/B baseline
        + escape hatch) and the pipelined mode's lone-query fast path.
        The dispatch failpoint fires here too, so chaos schedules hit
        every dispatch regardless of path."""
        try:
            fire("server.dispatch.before", **launch.site_ctx)
            if launch.cancel_check is not None:
                launch.cancel_check()
            guard = _CPU_COLLECTIVE_LOCK if launch.collective \
                else contextlib.nullcontext()
            self._busy_begin()
            t0 = time.monotonic()
            try:
                with guard:
                    packed = np.asarray(launch.call())
            finally:
                self._busy_end()
                self._meter_traces()
            kernel_ms = (time.monotonic() - t0) * 1e3
            if launch.span is not None:
                # inline path: kernel + fetch are one sync round trip
                launch.span.set(
                    queueWaitMs=round(
                        (t0 - launch.enq_ts) * 1e3, 3)
                    if launch.enq_ts else 0.0,
                    batchSize=1, variant="inline",
                    kernelMs=round(kernel_ms, 3),
                    fetchMs=0.0)
            split_charge([launch], kernel_ms)
            launch.future.set_result(packed)
        except BaseException as e:  # noqa: BLE001 — future carries it
            launch.future.set_exception(e)
        return launch.future

    def close(self) -> None:
        with self._cv:
            self._closed = True
            for it in self._pending:
                it.future.set_exception(RuntimeError("dispatcher closed"))
            self._pending.clear()
            self._cv.notify_all()

    # -- ring thread ---------------------------------------------------
    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="kernel-dispatch")
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                end = time.monotonic() + self.IDLE_EXIT_S
                while not self._pending:
                    if self._closed:
                        self._thread = None
                        return
                    left = end - time.monotonic()
                    if left <= 0:
                        self._thread = None
                        return
                    self._cv.wait(left)
                leader = self._pending.pop(0)
                self._set_depth_locked()
                self._cv.notify_all()
            self._dispatch_one(leader)

    def _dispatch_one(self, leader: Launch) -> None:
        """Every exit path MUST complete every popped launch's future —
        a future left unset strands its caller in .result() forever (the
        unbounded-wait class the deadline work removed), so the whole
        body is guarded and failures fan out to the batch."""
        batch = [leader]
        try:
            # chaos hook: delay/fail a dispatch inside the ring (a delay
            # here also widens the coalescing window, which is exactly
            # what a chaos test wants to provoke batching determinism)
            fire("server.dispatch.before", **leader.site_ctx)
            batch = self._coalesce(leader)
            self._dispatch_batch(batch)
        except BaseException as e:  # noqa: BLE001 — futures carry it
            for it in batch:
                if not it.future.done():
                    it.future.set_exception(e)

    def _dispatch_batch(self, batch: List[Launch]) -> None:
        # deadline/cancel checks honored while queued: a cancelled query
        # leaves the batch before launch. The `server.dispatch.batch`
        # failpoint fires PER MEMBER inside the coalesced path: an
        # erroring member fails only its own future — peers stay in the
        # batch and complete (chaos tests pin this isolation).
        coalesced = len(batch) > 1
        live: List[Launch] = []
        for it in batch:
            try:
                if it.cancel_check is not None:
                    it.cancel_check()
                if coalesced:
                    fire("server.dispatch.batch", batch_size=len(batch),
                         **it.site_ctx)
                live.append(it)
            except BaseException as e:  # noqa: BLE001
                it.future.set_exception(e)
        if not live:
            return
        self.observe("dispatch_batch_size", float(len(live)))
        now = time.monotonic()
        for it in live:
            if it.span is not None:
                # each coalesced member reports into its OWN trace: the
                # shared launch's facts land on N distinct span trees
                it.span.set(
                    queueWaitMs=round((now - it.enq_ts) * 1e3, 3)
                    if it.enq_ts else 0.0,
                    batchSize=len(live))
        batched = len(live) > 1
        if batched:
            # pad to the batch-size bucket with replicated leader inputs
            # so jit's shape cache only ever sees bucketed batch sizes
            bucket = _pow2(len(live))
            lead = live[0]
            pad = bucket - len(live)
            plist = tuple(it.params for it in live) + (lead.params,) * pad
            # broadcast when every member staged the SAME column blocks
            # (one shared pass over one copy of the data); stacked when
            # members come from different tables/partitions in the same
            # shape bucket (blocks stack along a new leading axis —
            # device-resident rows, never a re-upload)
            stacked = any(it.cols_key != lead.cols_key for it in live)
            # same-cols member grouping: members whose staged blocks are
            # identity-equal (same table/segments, different literals)
            # share ONE stack entry — a mixed batch of 8 queries over 3
            # tables stacks 3 column sets, not 8
            uniq_pos: Dict[tuple, int] = {}
            for it in live:
                uniq_pos.setdefault(it.cols_key, len(uniq_pos))
            dedup = (stacked and lead.dedup_factory is not None
                     and len(uniq_pos) < len(live))
            if dedup:
                kern = lead.dedup_factory(bucket, _pow2(len(uniq_pos)))
            elif lead.factory is not None:
                kern = lead.factory(bucket, stacked)
            else:
                kern = kernels.compiled_batched_kernel(
                    lead.plan, bucket, stacked)
            if dedup:
                self._metrics.add_meter("dispatch_batch_cross_table",
                                        len(live), labels=self._labels)
                self._metrics.add_meter(
                    "dispatch_batch_dedup", len(live) - len(uniq_pos),
                    labels=self._labels)
                by_pos = [None] * len(uniq_pos)
                for it in live:
                    p = uniq_pos[it.cols_key]
                    if by_pos[p] is None:
                        by_pos[p] = it
                ubucket = _pow2(len(uniq_pos))
                upad = ubucket - len(uniq_pos)
                clist = tuple(it.cols for it in by_pos) \
                    + (lead.cols,) * upad
                ndlist = tuple(it.num_docs for it in by_pos) \
                    + (lead.num_docs,) * upad
                idx = np.asarray(
                    [uniq_pos[it.cols_key] for it in live]
                    + [uniq_pos[lead.cols_key]] * pad, np.int32)
                call = lambda: kern(clist, plist, ndlist,  # noqa: E731
                                    idx, D=lead.D, G=lead.G)
            elif stacked:
                self._metrics.add_meter("dispatch_batch_cross_table",
                                        len(live), labels=self._labels)
                clist = tuple(it.cols for it in live) + (lead.cols,) * pad
                ndlist = tuple(it.num_docs for it in live) \
                    + (lead.num_docs,) * pad
                call = lambda: kern(clist, plist, ndlist,  # noqa: E731
                                    D=lead.D, G=lead.G)
            else:
                call = lambda: kern(lead.cols, plist,  # noqa: E731
                                    lead.num_docs, D=lead.D, G=lead.G)
            variant = ("dedup" if dedup else
                       "stacked" if stacked else "broadcast")
            for it in live:
                if it.span is not None:
                    it.span.set(variant=variant)
        else:
            call = live[0].call
            if live[0].span is not None:
                live[0].span.set(variant="single")
        if live[0].collective:
            # CPU-collective ordering: ONE partitioned program in flight
            # process-wide; block on the ring (compute completion), then
            # hand the ready buffers to the fetch pool so the NEXT
            # launch overlaps this result's host assembly
            self._busy_begin()
            t0 = time.monotonic()
            try:
                with _CPU_COLLECTIVE_LOCK:
                    out = call()
                    jax.block_until_ready(out)
            except BaseException as e:  # noqa: BLE001
                self._busy_end()
                for it in live:
                    it.future.set_exception(e)
                return
            fetch_pool().submit(self._finish, live, out, batched,
                                (time.monotonic() - t0) * 1e3)
        else:
            # fully concurrent submission (real accelerators order their
            # own queue; non-partitioned host programs don't rendezvous)
            launch_pool().submit(self._run_and_finish, live, call, batched)

    def _coalesce(self, leader: Launch) -> List[Launch]:
        """Collect fingerprint-equal launches behind the leader, waiting
        up to the batching window — but only while the engine observably
        has more callers than the batch holds (a lone query never waits)."""
        batch = [leader]
        if leader.batch_key is None or self.batch_max <= 1:
            return batch
        deadline = time.monotonic() + self.current_window_s()
        with self._cv:
            while True:
                i = 0
                while i < len(self._pending) and len(batch) < self.batch_max:
                    if self._pending[i].batch_key == leader.batch_key:
                        batch.append(self._pending.pop(i))
                        self._cv.notify_all()
                    else:
                        i += 1
                target = min(self.batch_max, max(1, self._active))
                if len(batch) >= target:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            self._set_depth_locked()
        return batch

    def _run_and_finish(self, live: List[Launch], call, batched: bool) -> None:
        self._busy_begin()
        t0 = time.monotonic()
        traces0 = kernels.trace_count()
        try:
            out = call()
        except BaseException as e:  # noqa: BLE001
            self._busy_end()
            for it in live:
                it.future.set_exception(e)
            self._meter_traces()
            return
        kernel_ms = (time.monotonic() - t0) * 1e3
        # best-effort retrace attribution: a concurrent launch's trace
        # could land in this window, but a retrace on the steady path is
        # a bug worth a loud mark either way
        retraces = kernels.trace_count() - traces0
        if retraces > 0:
            for it in live:
                if it.span is not None:
                    it.span.set(retraceEvents=retraces)
        self._finish(live, out, batched, kernel_ms)

    def _finish(self, live: List[Launch], out, batched: bool,
                kernel_ms: Optional[float] = None) -> None:
        """Fetch (device->host) + split per caller; runs OFF the ring.
        The busy interval (opened at launch) closes when the fetch lands
        — and BEFORE the futures resolve: a caller woken by its result
        must observe an idle dispatcher, or its next lone submit would
        race the busy bookkeeping and needlessly take the ring path
        (the inline fast path is what keeps lone p50 at the floor)."""
        t0 = time.monotonic()
        try:
            arr = np.asarray(out)
        except BaseException as e:  # noqa: BLE001
            self._busy_end()
            self._meter_traces()
            for it in live:
                if not it.future.done():
                    it.future.set_exception(e)
            return
        self._busy_end()
        self._meter_traces()
        fetch_ms = (time.monotonic() - t0) * 1e3
        for it in live:
            if it.span is not None:
                it.span.set(fetchMs=round(fetch_ms, 3),
                            **({"kernelMs": round(kernel_ms, 3)}
                               if kernel_ms is not None else {}))
        if kernel_ms is not None:
            split_charge(live, kernel_ms)
        try:
            if batched:
                for member, it in zip(split_packed(arr, len(live)), live):
                    it.future.set_result(member)
            else:
                live[0].future.set_result(arr)
        except BaseException as e:  # noqa: BLE001
            for it in live:
                if not it.future.done():
                    it.future.set_exception(e)
