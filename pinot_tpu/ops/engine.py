"""TpuOperatorExecutor: stages segment columns into HBM and runs the
fused query kernel across segments.

Reference parity: this replaces the reference's per-segment
operator chain + combine fan-out (SURVEY.md §3.2 hot loop:
AggregationOperator/GroupByOperator over ProjectionOperator/DocIdSetOperator
with per-thread segment tasks, combine/BaseCombineOperator.java:54) with
ONE device program over stacked [num_segments, padded_docs] blocks.

Responsibilities:
  * supports(ctx): structural check — which query shapes offload
  * plan: QueryContext -> DevicePlan IR (ops/plan_ir.py)
  * staging: per-(segment, column) device arrays, cached in HBM across
    queries (the segment-cache SURVEY.md §7.5 calls for), padded to
    power-of-two doc buckets to bound retraces
  * per-segment predicate resolution -> kernel parameter arrays
  * multi-device: inputs sharded over the mesh's `segments` axis
  * result assembly back into AggregationResult/GroupByResult intermediates
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from pinot_tpu.ops import clp_device
from pinot_tpu.ops import collective
from pinot_tpu.ops import dispatch as dispatch_mod
from pinot_tpu.ops import kernels
from pinot_tpu.ops import startree_device
from pinot_tpu.ops import timeseries_device
from pinot_tpu.ops import vector_device
from pinot_tpu.ops.dispatch import KernelDispatcher, Launch
from pinot_tpu.ops.plan_ir import DeviceLeaf, DevicePlan
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    Expression, Function, Identifier, Literal)
from pinot_tpu.query.filter import resolve_predicate
from pinot_tpu.query.results import (
    AggregationResult, ExecutionStats, GroupByResult)
from pinot_tpu.segment.loader import DataSource, ImmutableSegment
from pinot_tpu.utils import tracing
from pinot_tpu.utils.failpoints import fire

MAX_DEVICE_GROUPS = 1 << 20
#: cap on the [S, G, slots] group-by result buffer (f32/f64 accumulators)
MAX_GROUP_RESULT_BYTES = 1 << 31
_LEAF_RANGE_FUNCS = {
    "equals", "between", "greater_than", "greater_than_or_equal",
    "less_than", "less_than_or_equal",
}
_LEAF_LUT_FUNCS = {"in", "not_in", "like", "regexp_like"}


def _pow2(n: int, floor: int = 128) -> int:
    v = floor
    while v < n:
        v *= 2
    return v


# doc-padding cap: counts are packed in float32 when x64 is off, which is
# exact only below 2^24; segments larger than this are rejected to host
MAX_DOCS_PER_SEGMENT = 1 << 24


class TpuOperatorExecutor:
    def __init__(self, devices: Optional[Sequence] = None, mesh=None,
                 config=None, metrics_labels=None):
        """mesh: an explicit (segments, docs) jax Mesh — blocks shard over
        BOTH axes and the kernel runs under shard_map with psum/pmin/pmax
        collectives over `docs` (SURVEY §2.6 rows 6-7). Without one, >1
        device gets a segments-only mesh (GSPMD partitions the reductions);
        one device runs the plain jit kernel.
        config: a PinotConfiguration for the cache budgets and the
        dispatch-ring knobs (the server passes its instance config
        through; None reads env/defaults).
        metrics_labels: labels for the dispatcher's metrics (the server
        passes its instance id)."""
        self._doc_axis = 1
        #: collective broker merge engages only on an EXPLICIT mesh: the
        #: implicit >1-device segments mesh below keeps per-segment
        #: partials so the tier-2 segment cache and stacked-batch dedup
        #: (both keyed per segment) work exactly as on one device
        self._explicit_mesh = mesh is not None
        if mesh is not None:
            self._mesh = mesh
            self.devices = list(mesh.devices.flat)
            shape = dict(zip(mesh.axis_names, mesh.devices.shape))
            self._seg_axis = shape.get("segments", 1)
            self._doc_axis = shape.get("docs", 1)
        else:
            self.devices = list(devices) if devices is not None \
                else jax.devices()
            self._mesh = None
            self._seg_axis = max(len(self.devices), 1)
            if len(self.devices) > 1:
                from jax.sharding import Mesh
                self._mesh = Mesh(np.array(self.devices), ("segments",))
        #: ASSEMBLED device blocks, LRU-evicted under a byte budget: the
        #: exact [S, D] arrays kernels consume, keyed by the segment
        #: batch identity (id+name pairs guard against id() reuse). A
        #: miss here no longer pays the host link — blocks assemble
        #: on-device from the per-(segment, column) residency tier below
        from collections import OrderedDict
        self._block_cache: "OrderedDict[tuple, Any]" = OrderedDict()
        self._block_bytes: Dict[tuple, int] = {}
        self._cache_bytes = 0
        #: live block count per batch identity — O(1) detection of "this
        #: batch's LAST block just left", which triggers the params purge
        self._batch_blocks: Dict[tuple, int] = {}
        #: host-side padded rows per (segment, column): rebuilding a new
        #: batch skips segment re-read/decode; LRU-evicted under its own
        #: byte budget (entries pin their segment, so eviction also
        #: releases replaced segments)
        self._host_rows: "OrderedDict[tuple, Any]" = OrderedDict()
        self._host_bytes = 0
        import os as _os

        from pinot_tpu.utils.config import PinotConfiguration
        _cfg = config or PinotConfiguration()
        # legacy short env names still win for compatibility
        self.host_budget_bytes = int(_os.environ.get(
            "PINOT_TPU_HOST_ROW_CACHE_BYTES",
            _cfg.get_int("pinot.server.host.row.cache.bytes")))
        self.cache_budget_bytes = int(_os.environ.get(
            "PINOT_TPU_HBM_CACHE_BYTES",
            _cfg.get_int("pinot.server.hbm.cache.bytes")))
        #: per-(segment, column) device-resident rows (ops/residency.py):
        #: the tier that survives batch recomposition — a changed pruned
        #: subset or a newly sealed segment uploads only rows the device
        #: has never seen; everything else assembles on-device
        from pinot_tpu.ops.residency import ResidencyManager
        resident_bytes = int(_os.environ.get(
            "PINOT_TPU_HBM_RESIDENT_BYTES",
            _cfg.get_int("pinot.server.hbm.resident.bytes")))
        if not _cfg.get_bool("pinot.server.hbm.resident.enabled", True):
            resident_bytes = 0
        self._metrics = None  # set after the dispatcher below
        self._labels = metrics_labels
        self._residency = ResidencyManager(
            resident_bytes,
            admission=_cfg.get_bool("pinot.server.hbm.admission.enabled",
                                    True),
            sample_window=_cfg.get_int("pinot.server.hbm.admission.sample"),
            labels=metrics_labels,
            devices=self.devices)
        #: staging lock only: cache mutation (plan/stage/evict) serializes,
        #: but kernel dispatch + result fetch run OUTSIDE it so concurrent
        #: queries overlap their device round trips (the host<->TPU link
        #: costs ~100ms per sync; overlapped, N queries share that latency).
        #: Eviction drops cache references WITHOUT .delete(): the staging
        #: query itself and any concurrently dispatched kernels hold the
        #: block as an input, and JAX refcounting frees the HBM as soon as
        #: the last consumer finishes — an eager delete could invalidate a
        #: buffer mid-flight, and a deferred-until-quiescent delete list
        #: would pin evicted blocks forever under sustained pipelined load
        self._engine_lock = threading.RLock()
        #: resolved predicate parameter arrays per (batch, plan, filter) —
        #: repeat queries then cost zero host->device param uploads;
        #: bounded LRU (hot filter parameters survive cache pressure
        #: instead of a wholesale clear dropping them all at once)
        self._params_cache: "OrderedDict[tuple, Any]" = OrderedDict()
        #: pipelined dispatch stage: ring + micro-batching + fetch
        #: overlap (ops/dispatch.py); owns NO engine state — staging
        #: stays under the engine lock, launches ride the ring
        self._dispatcher = KernelDispatcher(config=_cfg,
                                            labels=metrics_labels)
        #: cross-table shape-bucketed batching (the kernel-factory key):
        #: pad S to pow2 buckets so fingerprint-equal queries over
        #: DIFFERENT tables/partitions share a coalesce key; doc buckets
        #: above doc.bucket.max keep the legacy same-batch key (a stacked
        #: [B, S, D] copy of huge blocks would blow the HBM budget).
        #: Gated on batching being POSSIBLE at all — when dispatch is
        #: serialized or batch.max=1, pow2 S padding would inflate every
        #: staged block for a coalesce that can never happen
        self._cross_table = (
            _cfg.get_bool("pinot.server.dispatch.batch.cross.table", True)
            and self._dispatcher.mode != "serialized"
            and self._dispatcher.batch_max > 1)
        self._doc_bucket_max = _cfg.get_int(
            "pinot.server.dispatch.doc.bucket.max")
        #: star-tree device leg (ops/startree_device.py): fitted queries
        #: aggregate pre-agg records through the kernel factory instead
        #: of scanning raw rows; hbm.resident admits the pre-agg
        #: pseudo-columns into the per-(segment, column) residency tier
        self._startree_enabled = _cfg.get_bool(
            "pinot.server.startree.enabled", True)
        self._st_resident = _cfg.get_bool(
            "pinot.server.startree.hbm.resident", True)
        #: CLP log-column LIKE/regex pushdown (ops/clp_device.py):
        #: patterns compile to logtype LUTs + variable-slot conditions
        #: evaluated as 'clp' filter leaves through the same kernel
        #: factory; hbm.resident admits the logtype-id / var-slot
        #: pseudo-columns into the per-(segment, column) residency tier
        self._clp_enabled = _cfg.get_bool(
            "pinot.server.clp.enabled", True)
        self._clp_resident = _cfg.get_bool(
            "pinot.server.clp.hbm.resident", True)
        #: vector-similarity device leg (ops/vector_device.py): ANN
        #: top-K as one batched matmul + lax.top_k over staged vector
        #: blocks; hbm.resident admits the __vec__ pseudo-columns into
        #: the per-(segment, column) residency tier
        self._vector_enabled = _cfg.get_bool(
            "pinot.server.vector.enabled", True)
        self._vector_resident = _cfg.get_bool(
            "pinot.server.vector.hbm.resident", True)
        #: time-series device bucket leg (ops/timeseries_device.py):
        #: floor((t - start) / step) group-bys fuse the bucket id into
        #: the group-by kernel's scatter key instead of falling back to
        #: the host expression-column path
        self._ts_bucket_enabled = _cfg.get_bool(
            "pinot.server.timeseries.bucket.enabled", True)
        #: collective broker merge (ops/collective.py): on a mesh engine
        #: the per-segment partial fold becomes one on-device
        #: psum/pmin/pmax over the whole mesh; the host IndexedTable
        #: fold stays reachable as the escape hatch when this is off
        self._collective_merge = _cfg.get_bool(
            "pinot.server.mesh.collective.merge", True)
        #: host-factorized global group-key remap params per
        #: (segment batch, plan) — built once, re-used across queries
        self._gmap_cache: "OrderedDict[tuple, Any]" = OrderedDict()
        #: round-robin upload target over the mesh devices: resident
        #: rows spread across every chip's HBM instead of pooling on
        #: device 0 (per-chip budgets in ops/residency.py account them)
        import itertools as _itertools
        self._row_rr = _itertools.count()
        self._metrics = self._dispatcher._metrics
        self._residency._metrics = self._metrics

    # ------------------------------------------------------------------
    # capability check (structural)
    # ------------------------------------------------------------------
    #: cap on selection/order-by top-K offload (limit + offset)
    TOPN_MAX_K = 8192

    #: LRU capacity of the predicate-parameter cache (entries are tiny)
    PARAMS_CACHE_ENTRIES = 4096

    #: residency miss bursts at/above this many bytes upload in parallel
    #: on the upload pool (below it, thread handoff costs more than the
    #: copies themselves)
    UPLOAD_FANOUT_BYTES = 16 << 20

    #: hard backstop on any single dispatcher/upload future wait
    #: (dispatch_mod.wait_result): queries are bounded by their own
    #: deadline checker well before this — the cap exists for
    #: budget-less internal callers (warmup/prestage) so a wedged
    #: device link surfaces as an error instead of a parked thread.
    #: Aliased, not duplicated: ONE policy constant owns the backstop.
    LAUNCH_WAIT_CAP_S = dispatch_mod.DEFAULT_WAIT_CAP_S

    def supports(self, ctx: QueryContext) -> bool:
        if ctx.distinct:
            return self._supports_distinct(ctx)
        if not ctx.aggregations:
            return self._supports_selection(ctx)
        for f in ctx.agg_filters:
            # FILTER (WHERE ...) aggs offload as per-slot masks when the
            # condition has a device filter shape
            if f is not None and not self._filter_shape_ok(f):
                return False
        if any(fn.device_spec is None for fn in ctx.agg_functions):
            return False
        if ctx.group_by and any(
                ":" in op for fn in ctx.agg_functions
                for op in fn.device_spec.ops):
            # sketch slots (hll/hist) are vector-valued; the grouped packed
            # layout is scalar-per-slot — grouped sketches stay host-side
            return False
        for node in ctx.aggregations:
            if node.args and not (isinstance(node.args[0], Identifier)
                                  and node.args[0].name == "*"):
                if self._value_ir_shape(node.args[0]) is None:
                    return False
            if node.name == "countmv":
                return False
        for i, g in enumerate(ctx.group_by):
            if isinstance(g, Identifier):
                continue
            if (i == 0 and self._ts_bucket_enabled
                    and not self._explicit_mesh and self._doc_axis == 1
                    and timeseries_device.extract_bucket(g) is not None):
                # time-series leaf shape: the leading floor((t-start)/
                # step) group-by fuses into the kernel's scatter key
                # (detailed window/metadata admission happens in _plan).
                # The implicit >1-device segments mesh keeps per-segment
                # partials through the SAME group-by kernel, so it
                # qualifies; the explicit collective-merge mesh does not
                continue
            return False
        if ctx.filter is not None and not self._filter_shape_ok(ctx.filter):
            return False
        return True

    def _supports_distinct(self, ctx: QueryContext) -> bool:
        """DISTINCT over dict columns rides the group-by kernel (a
        presence-only group-by); detailed stagability checks happen in
        _plan with segment metadata in hand."""
        if not ctx.select or ctx.aggregations:
            return False
        for e in ctx.select:
            if not isinstance(e, Identifier) or e.name == "*":
                return False
        if ctx.filter is not None and not self._filter_shape_ok(ctx.filter):
            return False
        return True

    def _supports_selection(self, ctx: QueryContext) -> bool:
        """Selection (+ at most one ORDER BY key) offloads as a device
        top-K over the order value: only winning docs are materialized
        (ref SelectionOrderByOperator / MinMaxValueBasedSelection
        OrderByCombineOperator)."""
        if ctx.distinct or ctx.aggregations:
            return False
        if ctx.filter is not None \
                and vector_device.contains_vector(ctx.filter):
            # ANN leg: vector_similarity is not a scan-filter leaf — it
            # routes to the vector kernel (plan-time fallback with a
            # metered reason keeps host parity on every miss)
            return ctx.limit + ctx.offset > 0
        if len(ctx.order_by) > 1:
            return False
        if ctx.filter is None and not ctx.order_by:
            return False  # LIMIT-only: host early-exit is already O(K)
        k = ctx.limit + ctx.offset
        if k <= 0 or k > self.TOPN_MAX_K:
            return False
        if ctx.order_by:
            e, _asc = ctx.order_by[0]
            if not (isinstance(e, Identifier)
                    or self._value_ir_shape(e) is not None):
                return False
        if ctx.filter is not None and not self._filter_shape_ok(ctx.filter):
            return False
        return True

    def _filter_shape_ok(self, e: Expression) -> bool:
        if not isinstance(e, Function):
            return False
        if e.name in ("and", "or"):
            return all(self._filter_shape_ok(a) for a in e.args)
        if e.name == "not":
            return self._filter_shape_ok(e.args[0])
        if e.name in _LEAF_RANGE_FUNCS | _LEAF_LUT_FUNCS | {"not_equals"}:
            return bool(e.args) and isinstance(e.args[0], Identifier) and all(
                isinstance(a, Literal) for a in e.args[1:])
        return False

    def _value_ir_shape(self, e: Expression) -> Optional[tuple]:
        """Structural value IR (column stagability checked at execute)."""
        if isinstance(e, Identifier):
            return ("col", e.name)
        if isinstance(e, Literal):
            if isinstance(e.value, (int, float)) and not isinstance(e.value, bool):
                return ("lit", float(e.value))
            return None
        if isinstance(e, Function):
            ops = {"plus": "add", "minus": "sub", "times": "mul", "divide": "div"}
            if e.name in ops and len(e.args) == 2:
                a = self._value_ir_shape(e.args[0])
                b = self._value_ir_shape(e.args[1])
                if a is not None and b is not None:
                    return (ops[e.name], a, b)
        return None

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _needs_cpu_ordering(self, kernel) -> bool:
        """True when this kernel's execution must be ordered process-wide
        (dispatch.py's collective lock): PARTITIONED execution on host
        devices. EVERY staged kernel on a mesh engine is partitioned:
        _put stages inputs with NamedSharding, so even the plain-jit
        kernels (group-by without a docs axis, top-N) compile to GSPMD
        programs with all-gathers — the doc_axis==1 compiled_kernel path
        is exactly what deadlocked the suite, so don't narrow this to the
        shard_map branch. Single device, real accelerators, and non-XLA
        kernel stand-ins never order."""
        return self._mesh is not None and bool(self.devices) \
            and getattr(self.devices[0], "platform", "") == "cpu" \
            and isinstance(kernel, jax.stages.Wrapped)

    def _prepare_agg(self, segments: List[ImmutableSegment],
                     ctx: QueryContext, cancel_check=None,
                     parent_span=None, slip=None):
        """Plan + stage under the engine lock (they mutate the block
        caches), then wrap the launch for the dispatch ring. Returns
        (plan, slots_of_fn, S_real, Launch), or None -> host fallback.
        The staging_overlap_ms histogram records how much of this staging
        ran while another query's kernel occupied the device — the
        pipeline's third leg (staging/compute overlap).

        parent_span: explicit tracing.SpanHandle for callers off the
        request thread (execute_async stages on the staging pool, where
        the trace contextvar doesn't flow); sync callers inherit the
        contextvar. The DeviceDispatch child span carries staging ms,
        residency hit/miss counts, and host->device transfer bytes —
        exact per query because staging holds the engine lock."""
        if parent_span is None:
            parent_span = tracing.capture()
        dsp = None
        if parent_span is not None:
            dsp = parent_span.child("DeviceDispatch", table=ctx.table,
                                    mode="agg")
        from pinot_tpu.ops import residency as residency_mod
        busy0 = self._dispatcher.busy_ms()
        with self._engine_lock:
            # snapshot INSIDE the lock: the diff must cover exactly this
            # query's staging, not a concurrent stager's (the transfer
            # odometer diff below is exact per query for the same reason)
            xfer0 = residency_mod.transfer_bytes() if slip is not None else 0
            stage_info = self._staging_snapshot(dsp)
            plan_info = self._plan(segments, ctx)
            if plan_info is None:
                if dsp is not None:
                    dsp.end(outcome="hostFallback")
                return None
            plan, slots_of_fn = plan_info
            # resolve the kernel BEFORE staging: non-batchable launches
            # (non-jit kernel stand-ins) must not pay pow2 S padding for
            # a coalesce they can never join
            if self._doc_axis > 1:
                # doc-sharded engines batch too: the factory vmaps
                # INSIDE shard_map (kernels.make_batched_sharded_kernel)
                kernel = kernels.compiled_sharded_kernel(plan, self._mesh)
                batchable = isinstance(kernel, jax.stages.Wrapped)
                factory = (lambda B, stacked, _p=plan, _m=self._mesh:
                           kernels.compiled_batched_sharded_kernel(
                               _p, _m, B, stacked))
                dedup_factory = None  # sharded in_specs are per-member
            else:
                kernel = kernels.compiled_kernel(plan)
                batchable = isinstance(kernel, jax.stages.Wrapped)
                factory = (lambda B, stacked, _p=plan:
                           kernels.compiled_batched_kernel(_p, B, stacked))
                dedup_factory = (lambda B, U, _p=plan:
                                 kernels.compiled_batched_dedup_kernel(
                                     _p, B, U))
            try:
                cols, params, num_docs, S_real, D, G = self._stage(
                    segments, ctx, plan, batchable=batchable)
            except _NotStageable:
                if dsp is not None:
                    dsp.end(outcome="hostFallback")
                return None
            self._staging_attrs(dsp, stage_info, S=int(num_docs.shape[0]),
                                D=D, G=G)
            # collective broker merge (ops/collective.py): fold the
            # per-segment partials on device — one psum/pmin/pmax over
            # the whole mesh — instead of shipping [S, ...] rows to the
            # host IndexedTable fold. Any gate trips back to the
            # per-segment launch below, metered by reason
            minfo = None
            if self._explicit_mesh and len(self.devices) > 1 \
                    and batchable:
                if not self._collective_merge:
                    self._merge_fallback("disabled")
                else:
                    chaos = False
                    try:
                        fire("server.mesh.collective", table=ctx.table,
                             mode="agg")
                    except BaseException:  # noqa: BLE001 — armed chaos
                        self._merge_fallback("chaos")  # -> host fold
                        chaos = True
                    if not chaos:
                        try:
                            params, minfo = self._merged_prepare(
                                segments, plan, params, S_real,
                                int(num_docs.shape[0]), G)
                        except _MergeFallback as e:
                            self._merge_fallback(e.reason)
                        except Exception:  # noqa: BLE001 — never fail
                            self._merge_fallback("staging")  # the query
            if slip is not None:
                slip.add(transfer_bytes=int(
                    residency_mod.transfer_bytes() - xfer0))
        overlap = self._dispatcher.busy_ms() - busy0
        if overlap > 0:
            self._dispatcher.observe("staging_overlap_ms", overlap)
        G_eff = G
        if minfo is not None:
            self._meter("mesh_merge_served")
            G_eff = minfo["G"]
            kernel = collective.compiled_merged_kernel(plan, self._mesh)
            factory = (lambda B, stacked, _p=plan, _m=self._mesh:
                       collective.compiled_batched_merged_kernel(
                           _p, _m, B, stacked))
            dedup_factory = None  # merged in_specs are per-member
        # the mesh shape rides the coalesce key: launches never pair
        # across differently-sharded engines (or merged with unmerged)
        mesh_sig = ("mesh", self._mesh, self._doc_axis, minfo is not None)
        batch_key = None
        if batchable and self._dispatcher.batch_max > 1:
            if self._cross_table and D <= self._doc_bucket_max:
                # the kernel-factory coalesce key: (plan fingerprint,
                # shape bucket) — fingerprint-equal queries batch across
                # tables and partitions whenever their padded buckets
                # and staged-array shapes/dtypes line up (the signature
                # catches per-table variation: LUT cardinality pads, id
                # dtype width)
                S = int(num_docs.shape[0])
                batch_key = (plan, S, D, G_eff, _shape_sig(cols, params),
                             mesh_sig)
            else:
                # legacy key: identical staged segment batch only
                batch_key = (plan, _batch_id(segments), D, G_eff, mesh_sig)
        launch = Launch(
            call=lambda: kernel(cols, params, num_docs, D=D, G=G_eff),
            plan=plan, cols=cols, params=params, num_docs=num_docs,
            D=D, G=G_eff, batch_key=batch_key,
            cols_key=self._cols_key(segments, plan),
            factory=factory, dedup_factory=dedup_factory,
            collective=self._needs_cpu_ordering(kernel),
            cancel_check=cancel_check,
            site_ctx={"table": ctx.table, "mode": "agg"}, span=dsp,
            slip=slip, docs=sum(s.num_docs for s in segments))
        return plan, slots_of_fn, S_real, launch, minfo

    # ------------------------------------------------------------------
    # collective broker merge (ops/collective.py)
    # ------------------------------------------------------------------
    #: cap on the host-factorized group-remap params shipped per
    #: (segment batch, plan) — past this the remap upload would rival
    #: the partial rows it saves, so the host fold wins
    GMAP_MAX_BYTES = 1 << 26
    GMAP_CACHE_ENTRIES = 64

    def _merge_fallback(self, reason: str) -> None:
        """mesh_merge_fallback{reason=}: why an eligible mesh launch kept
        the host IndexedTable fold (labeled like startree_fallback)."""
        if self._metrics is None:
            return
        labels = dict(self._labels or {})
        labels["reason"] = reason
        self._metrics.add_meter("mesh_merge_fallback", 1, labels=labels)

    def _merged_prepare(self, segments, plan: DevicePlan, params,
                        S_real: int, S: int, G_local: int):
        """Gate + group-key factorization for the collective merge.
        Returns (params with the remap entries merged in, minfo) or
        raises _MergeFallback(reason). Caller holds the engine lock."""
        if kernels._value_dtype() == jnp.float32:
            # merged counts/isum halves sum ACROSS segments: exactness
            # needs total docs < 2^24 and < 4096 real segments (the
            # per-segment path only needs it per segment)
            total = sum(int(seg.num_docs) for seg in segments)
            if total >= MAX_DOCS_PER_SEGMENT or S_real >= 4096:
                raise _MergeFallback("precision")
        if not plan.group_cols:
            return params, {"S": S, "G": 0}
        gparams, G_m, n_real, decode = self._merged_group_params(
            segments, plan, S, G_local)
        params = dict(params)
        params.update(gparams)
        return params, {"S": S, "G": G_m, "n_real": n_real,
                        "decode": decode}

    def _merged_group_params(self, segments, plan: DevicePlan, S: int,
                             G_local: int):
        """Factorize a GLOBAL group-key space once host-side: dictIds
        and compact codes are segment-local, so the device can only
        merge groups through a remap to shared indices. Compact plans
        ship one [S, G_local] code->global table; dense plans ship
        per-column [S, Cpad] dictId->union-index tables plus the traced
        [S, k] global strides (mixed radix over UNION cardinalities —
        stride changes re-upload KBs, never retrace). Cached per
        (segment batch, plan); returns (params, G pad, real group
        count, decode info for _assemble_merged)."""
        key = (_batch_id(segments), plan, S, G_local)
        ent = self._gmap_cache.get(key)
        if ent is not None:
            self._gmap_cache.move_to_end(key)
            return ent
        n_slots = max(len(plan.agg_ops), 1)
        if plan.group_compact:
            per_seg = []
            for seg in segments:
                # lint: unlocked(called from _prepare_agg's merged branch, which runs under the engine RLock)
                _codes, table = self._segment_gkey_locked(seg, plan)
                dicts = [seg.data_source(c).dictionary
                         for c in plan.group_cols]
                cols_vals = [d.get_values(table[:, j])
                             for j, d in enumerate(dicts)]
                per_seg.append([tuple(_py(c[i]) for c in cols_vals)
                                for i in range(table.shape[0])])
            union = sorted(set().union(*map(set, per_seg))) \
                if per_seg else []
            n_real = len(union)
            G_m = _pow2(max(n_real, 1), floor=8)
            if G_m > MAX_DEVICE_GROUPS \
                    or S * G_m * n_slots * 8 > MAX_GROUP_RESULT_BYTES \
                    or S * G_local * 4 > self.GMAP_MAX_BYTES:
                raise _MergeFallback("groups")
            index = {t: i for i, t in enumerate(union)}
            gmap = np.zeros((S, G_local), np.int32)
            for s, tuples in enumerate(per_seg):
                for code, t in enumerate(tuples):
                    gmap[s, code] = index[t]
            gparams = {"gmap": self._put(gmap)}
            decode = union  # global index -> key value tuple
        else:
            unions = []
            per_col_vals = []
            for colname in plan.group_cols:
                vals = []
                for seg in segments:
                    card = max(
                        int(seg.metadata.columns[colname].cardinality), 1)
                    d = seg.data_source(colname).dictionary
                    vals.append(np.asarray(
                        d.get_values(np.arange(card))))
                per_col_vals.append(vals)
                unions.append(np.unique(np.concatenate(vals)))
            cards = [len(u) for u in unions]
            n_real = 1
            for c in cards:
                n_real *= max(c, 1)
                if n_real > MAX_DEVICE_GROUPS:
                    raise _MergeFallback("groups")
            G_m = _pow2(max(n_real, 1), floor=8)
            gbytes = sum(
                S * _pow2(max(len(v) for v in vals), floor=8) * 4
                for vals in per_col_vals)
            if G_m > MAX_DEVICE_GROUPS \
                    or S * G_m * n_slots * 8 > MAX_GROUP_RESULT_BYTES \
                    or gbytes > self.GMAP_MAX_BYTES:
                raise _MergeFallback("groups")
            strides = []
            st = n_real
            for c in cards:
                st //= max(c, 1)
                strides.append(st)
            gparams = {}
            for ci, (union, vals) in enumerate(zip(unions,
                                                   per_col_vals)):
                Cpad = _pow2(max(len(v) for v in vals), floor=8)
                gm = np.zeros((S, Cpad), np.int32)
                for s, v in enumerate(vals):
                    gm[s, :len(v)] = np.searchsorted(union, v)
                gparams[f"gmap{ci}"] = self._put(gm)
            gstride = np.ascontiguousarray(np.broadcast_to(
                np.asarray(strides, np.int32), (S, len(strides))))
            gparams["gstride"] = self._put(gstride)
            decode = (tuple(strides), tuple(cards), tuple(unions))
        ent = (gparams, G_m, n_real, decode)
        self._gmap_cache[key] = ent
        while len(self._gmap_cache) > self.GMAP_CACHE_ENTRIES:
            self._gmap_cache.popitem(last=False)
        return ent

    # ------------------------------------------------------------------
    # star-tree device leg (ops/startree_device.py)
    # ------------------------------------------------------------------
    def _startree_candidate(self, segments) -> bool:
        """Cheap structural gate before the star-tree planner runs: only
        batches where EVERY segment carries a tree reach the planner, so
        treeless tables pay one getattr per segment and the fallback
        meter never fires where a tree could never serve (its reason
        labels stay meaningful). Upsert guard (PR 11): a partially-valid
        bitmap means pre-agg records include retracted rows, which no
        selection mask over the PRE-AGG table can subtract — scan path
        only (the host star-tree executor applies the same rule).
        Doc-sharded meshes keep the scan leg: the star-tree kernel has
        no shard_map variant, and pre-agg tables are small enough that
        sharding them buys nothing."""
        if not self._startree_enabled or self._doc_axis > 1:
            return False
        for s in segments:
            reader = getattr(s, "star_tree", None)
            if reader is None or not reader.trees:
                return False
            vd = getattr(s, "valid_doc_ids", None)
            if vd is not None and not vd.is_full():
                return False
        return True

    def _st_fallback(self, reason: str) -> None:
        """startree_fallback{reason=}: why a tree-carrying batch went to
        the scan path (labeled like server_admission_rejected)."""
        if self._metrics is None:
            return
        labels = dict(self._labels or {})
        labels["reason"] = reason
        self._metrics.add_meter("startree_fallback", labels=labels)

    def _clp_fallback(self, reason: str) -> None:
        """clp_fallback{reason=}: why a LIKE/regex over a CLP column left
        the device path (pattern outside the pushable subset, slot caps,
        staging failure, ...) — vocabulary in clp_device.FALLBACK_REASONS."""
        if self._metrics is None:
            return
        labels = dict(self._labels or {})
        labels["reason"] = reason
        self._metrics.add_meter("clp_fallback", labels=labels)

    def _clp_leaf(self, e: Function, segments, col: str):
        """'clp' DeviceLeaf for a LIKE/regexp_like predicate over a
        CLP-indexed column, or None (fallback metered with a reason).
        The pattern itself stays OUT of the leaf — like every other leaf
        kind, constants resolve at parameter staging so fingerprint-equal
        queries with different patterns share one compiled kernel."""
        if not self._clp_enabled:
            self._clp_fallback("disabled")
            return None
        if e.name not in ("like", "regexp_like") or len(e.args) != 2 \
                or not isinstance(e.args[1], Literal):
            self._clp_fallback("predicate")
            return None
        meta, reason = clp_device.plan_leaf(
            segments, col, str(e.args[1].value), e.name == "like")
        if meta is None:
            self._clp_fallback(reason)
            return None
        return DeviceLeaf("clp", col, meta)

    # ------------------------------------------------------------------
    # vector-similarity device leg (ops/vector_device.py)
    # ------------------------------------------------------------------
    def _vector_fallback(self, reason: str) -> None:
        """vector_fallback{reason=}: why a vector_similarity query left
        the device path for the host index search — vocabulary in
        vector_device.FALLBACK_REASONS."""
        if self._metrics is None:
            return
        labels = dict(self._labels or {})
        labels["reason"] = reason
        self._metrics.add_meter("vector_fallback", labels=labels)

    def _plan_vector(self, segments, ctx: QueryContext):
        """(VectorPlan, (vector fn, qvec, k), residual ctx) when the ANN
        query admits the device path; (None, reason, None) otherwise.
        The residual ctx carries the non-vector conjuncts ONLY — _stage's
        leaf-expression walk must see exactly the tree the plan's leaves
        were built from, and vector_similarity is not a leaf."""
        if not self._vector_enabled:
            return None, "disabled", None
        fn, residual, reason = vector_device.split_filter(ctx.filter)
        if fn is None:
            return None, reason, None
        if ctx.order_by:
            # score order is implicit in the kernel; an explicit ORDER BY
            # key on top would need a second sort the leg doesn't do
            return None, "hybrid", None
        try:
            col, qvec, k = vector_device.parse_args(fn)
        except (ValueError, TypeError):
            return None, "hybrid", None
        shape, reason = vector_device.admit(
            segments, col, qvec, k, self.TOPN_MAX_K)
        if shape is None:
            return None, reason, None
        dim_pad, ivf, cells_pad = shape
        seg0 = segments[0]
        classify, dict_cols, raw_cols = self._make_classifier(seg0)
        leaves: List[DeviceLeaf] = []
        filter_ir = None
        if residual is not None:
            filter_ir = self._build_filter_ir(residual, segments, leaves,
                                              classify)
            if filter_ir is None:
                return None, "hybrid", None
        raw64 = {lf.column for lf in leaves if lf.kind == "vrange64"}
        plan = vector_device.VectorPlan(
            col=col, dim_pad=dim_pad,
            k_pad=vector_device._pow2(k), ivf=ivf, cells_pad=cells_pad,
            filter_ir=filter_ir, leaves=tuple(leaves),
            dict_cols=tuple(sorted(dict_cols)),
            raw_cols=tuple(sorted(raw_cols - raw64)),
            raw64_cols=tuple(sorted(raw64)),
            clp_cols=clp_device.staged_cols(leaves),
            valid_mask=self._needs_valid_mask(segments))
        rctx = QueryContext(
            table=ctx.table, select=ctx.select, aliases=ctx.aliases,
            distinct=False, filter=residual, group_by=[], having=None,
            order_by=[], limit=ctx.limit, offset=ctx.offset,
            options=ctx.options)
        return plan, (fn, qvec, k), rctx

    def _stage_vector_locked(self, segments, rctx: QueryContext, plan,
                             fn, qvec, k, batchable: bool = True):
        """Residual-filter staging via the generic _stage (the VectorPlan
        duck-types DevicePlan for every field it reads), plus the vector
        block / IVF cell pseudo-columns and the per-QUERY params. Query
        params cache under their own key — the vector fn expression, not
        the residual filter — so two queries sharing a residual but not
        a query vector can never alias."""
        cols, params, num_docs, S_real, D, _G = self._stage(
            segments, rctx, plan, batchable=batchable)
        S = int(num_docs.shape[0])
        dim_pad = plan.dim_pad
        row_lens = tuple(_pow2(s.num_docs) * dim_pad for s in segments)
        cols["vec:" + plan.col] = self._vec_block_locked(
            segments, S, D * dim_pad, plan.col, "block",
            (lambda seg: vector_device.vector_row(
                seg, plan.col, dim_pad, _pow2(seg.num_docs))),
            np.float32, row_lens)
        if plan.ivf:
            cols["vcell:" + plan.col] = self._vec_block_locked(
                segments, S, D, plan.col, "cells",
                (lambda seg: vector_device.cell_row(
                    seg, plan.col, _pow2(seg.num_docs))),
                np.int32, tuple(_pow2(s.num_docs) for s in segments))
        pkey = (_batch_id(segments), plan, fn, "__vec__", S)
        cached = self._params_cache.get(pkey)
        if cached is not None:
            csegs, cparams, _cnd = cached
            if all(a is b for a, b in zip(csegs, segments)):
                self._params_cache.move_to_end(pkey)
                params.update(cparams)
                return cols, params, num_docs, S_real, D
        qp = vector_device.query_params(segments, plan, qvec, k, S)
        vparams = {key: self._put(arr) for key, arr in qp.items()}
        params.update(vparams)
        self._params_cache[pkey] = (tuple(segments), vparams, num_docs)
        self._params_cache.move_to_end(pkey)
        while len(self._params_cache) > self.PARAMS_CACHE_ENTRIES:
            self._params_cache.popitem(last=False)
        return cols, params, num_docs, S_real, D

    def _vec_block_locked(self, segments, S, W, col, leg, fetch, dtype,
                          row_lens):
        """One staged [S, W] vector pseudo-column block
        (`(segment, "__vec__/<col>/<leg>")`), mirroring _st_block_locked:
        per-segment rows pad to their OWN pow2 doc bucket (times dim_pad
        for the flattened vector leg) so every batch composition shares
        the resident rows; the on-device assembler pads the tail to W.
        Residency admission honors pinot.server.vector.hbm.resident."""
        dtype_str = np.dtype(dtype).str
        bkey = (_batch_id(segments), "vector", (col, leg), S, W, dtype_str)
        entry = self._block_cache.get(bkey)
        if entry is not None and all(a is b
                                     for a, b in zip(entry[0], segments)):
            self._block_cache.move_to_end(bkey)
            self._meter("hbm_block_hit")
            return entry[1]
        self._meter("hbm_block_miss")
        name = f"__vec__/{col}/{leg}"
        if self._residency.enabled and self._vector_resident:
            dev_rows: List[Any] = []
            missing: List[int] = []
            for seg in segments:
                row = self._residency.get(seg, "vector", name, dtype_str)
                dev_rows.append(row)
                if row is None:
                    missing.append(len(dev_rows) - 1)
            if missing:
                host_rows = [self._host_row(
                    segments[i], name, "vector", fetch, dtype,
                    pad_to=row_lens[i]) for i in missing]
                if len(host_rows) > 1 and sum(
                        a.nbytes for a in host_rows
                ) >= self.UPLOAD_FANOUT_BYTES:
                    futs = [dispatch_mod.upload_pool().submit(
                        self._put_row, a) for a in host_rows]
                    uploaded = [dispatch_mod.wait_result(
                        f, max_wait_s=self.LAUNCH_WAIT_CAP_S)
                        for f in futs]
                else:
                    uploaded = [self._put_row(a) for a in host_rows]
                for i, arr, dev in zip(missing, host_rows, uploaded):
                    self._residency.admit(segments[i], "vector", name,
                                          dtype_str, dev, arr.nbytes,
                                          device=self._dev_label(dev))
                    dev_rows[i] = dev
            if self._mesh is not None and len(self.devices) > 1:
                anchor = self.devices[0]
                dev_rows = [jax.device_put(r, anchor) for r in dev_rows]
            assembler = kernels.compiled_row_assembler(
                S, W, tuple(int(r.shape[0]) for r in dev_rows), dtype_str)
            dev = self._reshard_block(assembler(tuple(dev_rows)))
            nbytes = S * W * np.dtype(dtype).itemsize
        else:
            rows = [self._host_row(seg, name, "vector", fetch, dtype,
                                   pad_to=W)
                    for seg in segments]
            block = np.stack(rows) if len(rows) == S else \
                np.concatenate([np.stack(rows),
                                np.zeros((S - len(rows), W), dtype=dtype)])
            dev = self._put(block, block=True)
            nbytes = block.nbytes
        self._insert_block(bkey, (tuple(segments), dev), nbytes)
        return dev

    def _prepare_vector(self, segments, ctx: QueryContext, cancel_check):
        """Plan + stage an ANN launch through the kernel factory: the
        launch carries the same (plan fingerprint, shape bucket) coalesce
        key as scans, and the query vector/topK ride params — so
        fingerprint-equal concurrent ANN queries (different vectors, same
        shape) batch into ONE jit(vmap) launch. Returns
        (plan, S_real, Launch) or None -> host path (reason metered)."""
        from pinot_tpu.ops import residency as residency_mod
        from pinot_tpu.utils import accounting
        dsp = None
        parent_span = tracing.capture()
        slip = accounting.current_slip()
        if parent_span is not None:
            dsp = parent_span.child("DeviceDispatch", table=ctx.table,
                                    mode="vector")
        with self._engine_lock:
            xfer0 = residency_mod.transfer_bytes() if slip is not None else 0
            stage_info = self._staging_snapshot(dsp)
            plan, qinfo, rctx = self._plan_vector(segments, ctx)
            if plan is None:
                self._vector_fallback(qinfo)
                if dsp is not None:
                    dsp.end(outcome="hostFallback", reason=qinfo)
                return None
            fn, qvec, k = qinfo
            kernel = vector_device.compiled_vector_kernel(plan)
            batchable = isinstance(kernel, jax.stages.Wrapped)
            try:
                cols, params, num_docs, S_real, D = \
                    self._stage_vector_locked(segments, rctx, plan, fn,
                                              qvec, k, batchable=batchable)
            except _NotStageable:
                self._vector_fallback("staging")
                if dsp is not None:
                    dsp.end(outcome="hostFallback", reason="staging")
                return None
            self._staging_attrs(dsp, stage_info, S=int(num_docs.shape[0]),
                                D=D)
            if slip is not None:
                slip.add(transfer_bytes=int(
                    residency_mod.transfer_bytes() - xfer0))
        self._meter("vector_served")
        batch_key = None
        if batchable and self._dispatcher.batch_max > 1:
            if self._cross_table and D <= self._doc_bucket_max:
                S = int(num_docs.shape[0])
                batch_key = (plan, S, D, 0, _shape_sig(cols, params),
                             ("mesh", self._mesh, self._doc_axis))
            else:
                batch_key = (plan, _batch_id(segments), D, 0,
                             ("mesh", self._mesh, self._doc_axis))
        launch = Launch(
            call=lambda: kernel(cols, params, num_docs, D=D),
            plan=plan, cols=cols, params=params, num_docs=num_docs,
            D=D, G=0, batch_key=batch_key,
            cols_key=self._cols_key(segments, plan),
            factory=(lambda B, stacked, _p=plan:
                     vector_device.compiled_batched_vector_kernel(
                         _p, B, stacked)),
            collective=self._needs_cpu_ordering(kernel),
            cancel_check=cancel_check,
            site_ctx={"table": ctx.table, "mode": "vector"}, span=dsp,
            slip=slip, docs=sum(s.num_docs for s in segments))
        return plan, S_real, launch

    def _execute_vector(self, segments, ctx: QueryContext,
                        cancel_check=None):
        """ANN leg of _execute_topn. Host fallback keeps exact parity:
        query/filter._vector_similarity_mask serves any batch this
        returns unserved."""
        fire("server.vector.search", table=ctx.table)
        if self._doc_axis > 1:
            self._vector_fallback("staging")
            return [], segments
        prep = self._prepare_vector(segments, ctx, cancel_check)
        if prep is None:
            return [], segments
        plan, S_real, launch = prep
        with self._dispatcher.active():
            try:
                packed = dispatch_mod.wait_result(
                    self._dispatcher.submit(launch), launch.cancel_check,
                    max_wait_s=self.LAUNCH_WAIT_CAP_S)
            finally:
                if launch.span is not None:
                    launch.span.end()
        return vector_device.assemble(segments, ctx, plan,
                                      np.asarray(packed), S_real), []

    def _prepare_startree(self, segments: List[ImmutableSegment],
                          ctx: QueryContext, cancel_check=None,
                          parent_span=None, slip=None):
        """Star-tree leg of prepare: fit check + host tree traversal
        (startree_device.plan_startree), then stage the fitted trees'
        pre-agg pseudo-columns and wrap the residual-aggregation launch
        for the dispatch ring. Returns (plan, needed, fits, S_real,
        Launch), or None -> the caller falls through to the scan-path
        prepare (and transitively to the host path). Mirrors
        _prepare_agg's lock/span/odometer discipline exactly; the
        DeviceDispatch span carries starTree=true so traces distinguish
        pre-agg serves from scans."""
        if parent_span is None:
            parent_span = tracing.capture()
        dsp = None
        if parent_span is not None:
            dsp = parent_span.child("DeviceDispatch", table=ctx.table,
                                    mode="startree", starTree=True)
        from pinot_tpu.ops import residency as residency_mod
        busy0 = self._dispatcher.busy_ms()
        with self._engine_lock:
            xfer0 = residency_mod.transfer_bytes() if slip is not None else 0
            stage_info = self._staging_snapshot(dsp)
            plan, needed, fits, reason = startree_device.plan_startree(
                segments, ctx)
            if plan is None:
                self._st_fallback(reason)
                if dsp is not None:
                    dsp.end(outcome="scanFallback", reason=reason)
                return None
            kernel = startree_device.compiled_startree_kernel(plan)
            batchable = isinstance(kernel, jax.stages.Wrapped)
            factory = (lambda B, stacked, _p=plan:
                       startree_device.compiled_batched_startree_kernel(
                           _p, B, stacked))
            try:
                cols, params, num_docs, S_real, D = self._stage_startree_locked(
                    segments, ctx, plan, fits, batchable=batchable)
            except _NotStageable:
                self._st_fallback("staging")
                if dsp is not None:
                    dsp.end(outcome="scanFallback", reason="staging")
                return None
            self._staging_attrs(dsp, stage_info, S=int(num_docs.shape[0]),
                                D=D, G=plan.num_groups)
            if slip is not None:
                slip.add(transfer_bytes=int(
                    residency_mod.transfer_bytes() - xfer0))
        overlap = self._dispatcher.busy_ms() - busy0
        if overlap > 0:
            self._dispatcher.observe("staging_overlap_ms", overlap)
        self._meter("startree_served")
        batch_key = None
        if batchable and self._dispatcher.batch_max > 1:
            if self._cross_table and D <= self._doc_bucket_max:
                # the same kernel-factory coalesce key as scans: plan
                # fingerprint + shape bucket — fingerprint-equal
                # star-tree queries (same slots/radix, any predicate
                # constants) share ONE jit(vmap) launch
                S = int(num_docs.shape[0])
                batch_key = (plan, S, D, 0, _shape_sig(cols, params),
                             ("mesh", self._mesh, self._doc_axis))
            else:
                batch_key = (plan, _batch_id(segments), D, 0,
                             ("mesh", self._mesh, self._doc_axis))
        # the staged-block identity carries the fitted tree indexes:
        # members whose filters fit DIFFERENT trees of one segment must
        # stack, not share a broadcast block
        tis = tuple(f.ti for f in fits)
        launch = Launch(
            call=lambda: kernel(cols, params, num_docs, D=D, G=0),
            plan=plan, cols=cols, params=params, num_docs=num_docs,
            D=D, G=0, batch_key=batch_key,
            cols_key=(_batch_id(segments), tis),
            factory=factory, dedup_factory=None,
            collective=self._needs_cpu_ordering(kernel),
            cancel_check=cancel_check,
            site_ctx={"table": ctx.table, "mode": "startree"}, span=dsp,
            slip=slip, docs=sum(s.num_docs for s in segments))
        return plan, needed, fits, S_real, launch

    def _stage_startree_locked(self, segments, ctx: QueryContext, plan, fits,
                        batchable: bool = True):
        """Stage the fitted trees' pre-agg metric/dim-code rows as
        `(segment, "__startree__<ti>/<col>")` pseudo-columns through the
        same host-row / residency / assembled-block tiers as real
        columns, plus the per-query [S, D] selection mask (the traversal
        result) as kernel params. D is the pow2 bucket of the LARGEST
        fitted tree's record count: star records make num_records exceed
        num_docs, so the scan path's bucket cannot be reused."""
        S_real = len(segments)
        max_recs = max(int(f.tree.meta.num_records) for f in fits)
        if max_recs > MAX_DOCS_PER_SEGMENT:
            raise _NotStageable()
        D = _pow2(max_recs)
        if D % self._doc_axis:
            a = self._doc_axis
            D = ((D + a - 1) // a) * a
        S = self._padded_S(
            S_real, bucket=batchable and D <= self._doc_bucket_max)
        vdt = np.float64 if jax.config.read("jax_enable_x64") else np.float32

        cols: Dict[str, jnp.ndarray] = {}
        for ckey, form, dtype in startree_device.staged_columns(plan, vdt):
            cols[ckey] = self._st_block_locked(segments, fits, S, D, ckey, form,
                                        dtype)

        # selection mask + record counts: cached like predicate params —
        # a repeat query (same batch, same plan shape, same filter)
        # re-traverses nothing and uploads nothing. The fitted tree
        # indexes are deterministic in (segments, plan, filter), so the
        # scan-path key form is sufficient here too.
        pkey = (_batch_id(segments), plan, ctx.filter, "__startree__", S, D)
        cached = self._params_cache.get(pkey)
        if cached is not None:
            csegs, cparams, cnum_docs = cached
            if all(a is b for a, b in zip(csegs, segments)):
                self._params_cache.move_to_end(pkey)
                return cols, dict(cparams), cnum_docs, S_real, D
        sel = startree_device.selection_mask(fits, S, D)
        params = {"sel": self._put(sel, block=True)}
        num_docs = np.zeros(S, dtype=np.int32)
        num_docs[:S_real] = [int(f.tree.meta.num_records) for f in fits]
        num_docs_dev = self._put(num_docs)
        self._params_cache[pkey] = (tuple(segments), dict(params),
                                    num_docs_dev)
        self._params_cache.move_to_end(pkey)
        while len(self._params_cache) > self.PARAMS_CACHE_ENTRIES:
            self._params_cache.popitem(last=False)
        return cols, params, num_docs_dev, S_real, D

    def _st_block_locked(self, segments, fits, S, D, ckey, form, dtype):
        """One staged [S, D] pre-agg block. Mirrors _block /
        _assemble_resident, with per-SEGMENT pseudo-column names
        (`__startree__<ti>/<col>`): one segment can hold several trees
        materializing the same pair over different record layouts, and
        host/resident rows must key on the tree actually fitted — the
        batch-level key carries the whole ti tuple for the same reason.
        Residency admission honors pinot.server.startree.hbm.resident;
        off, blocks still cache at the assembled tier but rows don't
        compete for resident-tier bytes."""
        dtype_str = np.dtype(dtype).str
        tis = tuple(f.ti for f in fits)
        bkey = (_batch_id(segments), "startree", (ckey, tis), S, D,
                dtype_str)
        entry = self._block_cache.get(bkey)
        if entry is not None and all(a is b
                                     for a, b in zip(entry[0], segments)):
            self._block_cache.move_to_end(bkey)
            self._meter("hbm_block_hit")
            return entry[1]
        self._meter("hbm_block_miss")
        names = [f"__startree__{f.ti}/{ckey}" for f in fits]
        fetchers = [
            (lambda seg, _t=f.tree: startree_device.fetch_row(_t, form,
                                                              dtype))
            for f in fits]
        if self._residency.enabled and self._st_resident:
            dev_rows: List[Any] = []
            missing: List[int] = []
            for seg, name in zip(segments, names):
                row = self._residency.get(seg, "startree", name, dtype_str)
                dev_rows.append(row)
                if row is None:
                    missing.append(len(dev_rows) - 1)
            if missing:
                # rows pad to the tree's OWN pow2 record bucket
                # (batch-independent, so every batch composition shares
                # them); the on-device assembler pads the tail to D
                host_rows = [self._host_row(
                    segments[i], names[i], "startree", fetchers[i], dtype,
                    pad_to=_pow2(int(fits[i].tree.meta.num_records)))
                    for i in missing]
                if len(host_rows) > 1 and sum(
                        a.nbytes for a in host_rows
                ) >= self.UPLOAD_FANOUT_BYTES:
                    futs = [dispatch_mod.upload_pool().submit(
                        self._put_row, a) for a in host_rows]
                    uploaded = [dispatch_mod.wait_result(
                        f, max_wait_s=self.LAUNCH_WAIT_CAP_S)
                        for f in futs]
                else:
                    uploaded = [self._put_row(a) for a in host_rows]
                for i, arr, dev in zip(missing, host_rows, uploaded):
                    self._residency.admit(segments[i], "startree",
                                          names[i], dtype_str, dev,
                                          arr.nbytes,
                                          device=self._dev_label(dev))
                    dev_rows[i] = dev
            if self._mesh is not None and len(self.devices) > 1:
                anchor = self.devices[0]
                dev_rows = [jax.device_put(r, anchor) for r in dev_rows]
            assembler = kernels.compiled_row_assembler(
                S, D, tuple(int(r.shape[0]) for r in dev_rows), dtype_str)
            dev = self._reshard_block(assembler(tuple(dev_rows)))
            nbytes = S * D * np.dtype(dtype).itemsize
        else:
            rows = [self._host_row(seg, name, "startree", fetch, dtype,
                                   pad_to=D)
                    for seg, name, fetch in zip(segments, names, fetchers)]
            block = np.stack(rows) if len(rows) == S else \
                np.concatenate([np.stack(rows),
                                np.zeros((S - len(rows), D), dtype=dtype)])
            dev = self._put(block, block=True)
            nbytes = block.nbytes
        self._insert_block(bkey, (tuple(segments), dev), nbytes)
        return dev

    # -- staging trace attrs -------------------------------------------
    def _staging_snapshot(self, dsp):
        """Counters to diff across a traced staging pass (None span ->
        no snapshot cost). Exact per query: _stage runs under the engine
        lock, so no other query's staging interleaves."""
        if dsp is None:
            return None
        from pinot_tpu.ops import residency as residency_mod
        hits = misses = 0.0
        if self._metrics is not None:
            hits = self._metrics.meter("hbm_block_hit", labels=self._labels)
            misses = self._metrics.meter("hbm_block_miss",
                                         labels=self._labels)
        return (time.perf_counter(), residency_mod.transfer_bytes(),
                hits, misses)

    def _staging_attrs(self, dsp, snap, **dims) -> None:
        if dsp is None or snap is None:
            return
        from pinot_tpu.ops import residency as residency_mod
        t0, xfer0, hits0, misses0 = snap
        attrs = dict(
            stagingMs=round((time.perf_counter() - t0) * 1e3, 3),
            transferBytes=int(residency_mod.transfer_bytes() - xfer0),
            **dims)
        if self._metrics is not None:
            attrs["hbmBlockHits"] = int(self._metrics.meter(
                "hbm_block_hit", labels=self._labels) - hits0)
            attrs["hbmBlockMisses"] = int(self._metrics.meter(
                "hbm_block_miss", labels=self._labels) - misses0)
        dsp.set(**attrs)

    def execute(self, segments: List[ImmutableSegment], ctx: QueryContext,
                cancel_check=None
                ) -> Tuple[List[Any], List[ImmutableSegment]]:
        """Returns (device results, segments to fall back to host).

        Plan + staging run under the engine lock (they mutate the block
        caches); the launch rides the dispatch ring, which coalesces
        fingerprint-equal concurrent queries into one batched kernel and
        fetches results off-ring — N server threads overlap their device
        round trips instead of serializing behind one ~100ms sync each.
        cancel_check: polled while the launch waits in the ring (a
        cancelled/deadline-expired query leaves its batch before launch).
        """
        if ctx.distinct:
            return self._execute_distinct(segments, ctx, cancel_check)
        if not ctx.aggregations:
            return self._execute_topn(segments, ctx, cancel_check)
        from pinot_tpu.utils import accounting
        slip = accounting.current_slip()
        with self._dispatcher.active():
            # star-tree leg first: a fitted tree answers from pre-agg
            # records; any fallback reason drops through to the scan
            # prepare below (and transitively to the host path)
            st = self._prepare_startree(segments, ctx, cancel_check,
                                        slip=slip) \
                if self._startree_candidate(segments) else None
            if st is not None:
                st_plan, needed, fits, S_real, launch = st
            else:
                prep = self._prepare_agg(segments, ctx, cancel_check,
                                         slip=slip)
                if prep is None:
                    return [], segments
                plan, slots_of_fn, S_real, launch, minfo = prep
            try:
                # deadline-bounded: the checker carries the query's
                # remaining budget; the cap backstops budget-less callers
                packed = dispatch_mod.wait_result(
                    self._dispatcher.submit(launch), launch.cancel_check,
                    max_wait_s=self.LAUNCH_WAIT_CAP_S)
            finally:
                if launch.span is not None:
                    launch.span.end()
        if st is not None:
            return startree_device.assemble(segments, ctx, st_plan, needed,
                                            fits, packed), []
        if minfo is not None:
            return self._assemble_merged(segments, ctx, plan, packed,
                                         S_real, slots_of_fn, minfo), []
        results = self._assemble(segments, ctx, plan, packed, S_real, slots_of_fn)
        return results, []

    def execute_async(self, segments: List[ImmutableSegment],
                      ctx: QueryContext, cancel_check=None):
        """Future of (device results, host-fallback segments): staging
        runs on the dispatch staging pool, so the caller can execute its
        host-path segments while this query's padding + device_put (and
        then its kernel) proceed — query N+1 stages while query N
        computes. Non-agg shapes (top-N / DISTINCT) and the serialized
        compat mode run inline on the caller, exactly like execute()."""
        from concurrent.futures import Future as _Future
        if ctx.distinct or not ctx.aggregations \
                or self._dispatcher.mode == "serialized":
            fut: "_Future" = _Future()
            try:
                fut.set_result(self.execute(segments, ctx, cancel_check))
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)
            return fut
        out: "_Future" = _Future()
        self._dispatcher.enter_active()
        out.add_done_callback(lambda _f: self._dispatcher.exit_active())
        # capture on the CALLER thread: staging runs on the staging pool
        # where neither the trace contextvar nor the accounting
        # thread-local flows
        from pinot_tpu.utils import accounting
        parent_span = tracing.capture()
        slip = accounting.current_slip()

        def stage_and_enqueue():
            try:
                st = self._prepare_startree(segments, ctx, cancel_check,
                                            parent_span=parent_span,
                                            slip=slip) \
                    if self._startree_candidate(segments) else None
                if st is not None:
                    st_plan, needed, fits, _S_real, launch = st
                    lfut = self._dispatcher.submit(launch)

                    def finish_st(f):
                        try:
                            # lint: hang(done-callback: f is already resolved)
                            packed = f.result()
                            out.set_result((startree_device.assemble(
                                segments, ctx, st_plan, needed, fits,
                                packed), []))
                        except BaseException as e:  # noqa: BLE001
                            out.set_exception(e)
                        finally:
                            if launch.span is not None:
                                launch.span.end()

                    lfut.add_done_callback(finish_st)
                    return
                prep = self._prepare_agg(segments, ctx, cancel_check,
                                         parent_span=parent_span,
                                         slip=slip)
                if prep is None:
                    out.set_result(([], segments))
                    return
                plan, slots_of_fn, S_real, launch, minfo = prep
                lfut = self._dispatcher.submit(launch)

                def finish(f):
                    try:
                        # lint: hang(done-callback: f is already resolved)
                        packed = f.result()
                        if minfo is not None:
                            out.set_result((self._assemble_merged(
                                segments, ctx, plan, packed, S_real,
                                slots_of_fn, minfo), []))
                            return
                        out.set_result((self._assemble(
                            segments, ctx, plan, packed, S_real,
                            slots_of_fn), []))
                    except BaseException as e:  # noqa: BLE001
                        out.set_exception(e)
                    finally:
                        if launch.span is not None:
                            launch.span.end()

                lfut.add_done_callback(finish)
            except BaseException as e:  # noqa: BLE001
                out.set_exception(e)

        dispatch_mod.staging_pool().submit(stage_and_enqueue)
        return out

    # ------------------------------------------------------------------
    def _execute_distinct(self, segments, ctx: QueryContext,
                          cancel_check=None):
        """DISTINCT d1..dk = a presence-only GROUP BY d1..dk: reuse the
        whole group-by kernel path and convert keys to DistinctResult rows
        (ref DistinctOperator; dictionary-based distinct)."""
        sel = list(ctx.select)
        gctx = QueryContext(
            table=ctx.table, select=sel + [Function("count",
                                                    (Identifier("*"),))],
            aliases=[None] * (len(sel) + 1), distinct=False,
            filter=ctx.filter, group_by=sel, having=None, order_by=[],
            limit=ctx.limit, offset=0, options=dict(ctx.options))
        gctx._extract_aggregations()
        results, remaining = self.execute(segments, gctx, cancel_check)
        from pinot_tpu.query.results import DistinctResult
        out = [DistinctResult(set(r.groups.keys()), r.stats)
               for r in results]
        return out, remaining

    # ------------------------------------------------------------------
    def _prepare_topn(self, segments, ctx: QueryContext, cancel_check,
                      mode: str):
        """Plan + stage a top-N / doc-id-scan launch THROUGH the kernel
        factory: the launch carries the same (plan fingerprint, shape
        bucket) coalesce key as agg launches, so fingerprint-equal MSE
        leaf SCAN stages (and single-stage selection traffic sharing the
        plan + bucket) batch into one `jit(vmap)` topn kernel instead of
        paying one XLA launch per stage per query. Caller must hold no
        engine state; returns (S_real, Launch) or None -> host path.
        Must be called with doc_axis == 1 (sharded top-K stays host)."""
        from pinot_tpu.ops import residency as residency_mod
        from pinot_tpu.utils import accounting
        dsp = None
        parent_span = tracing.capture()
        slip = accounting.current_slip()
        if parent_span is not None:
            dsp = parent_span.child("DeviceDispatch", table=ctx.table,
                                    mode=mode)
        with self._engine_lock:
            xfer0 = residency_mod.transfer_bytes() if slip is not None else 0
            stage_info = self._staging_snapshot(dsp)
            plan = self._plan_topn(segments, ctx)
            if plan is None:
                if dsp is not None:
                    dsp.end(outcome="hostFallback")
                return None
            kernel = kernels.compiled_topn_kernel(plan)
            batchable = isinstance(kernel, jax.stages.Wrapped)
            try:
                cols, params, num_docs, S_real, D, _G = self._stage(
                    segments, ctx, plan, batchable=batchable)
            except _NotStageable:
                if dsp is not None:
                    dsp.end(outcome="hostFallback")
                return None
            self._staging_attrs(dsp, stage_info, S=int(num_docs.shape[0]),
                                D=D)
            if slip is not None:
                slip.add(transfer_bytes=int(
                    residency_mod.transfer_bytes() - xfer0))
        batch_key = None
        if batchable and self._dispatcher.batch_max > 1:
            if self._cross_table and D <= self._doc_bucket_max:
                S = int(num_docs.shape[0])
                batch_key = (plan, S, D, 0, _shape_sig(cols, params),
                             ("mesh", self._mesh, self._doc_axis))
            else:
                batch_key = (plan, _batch_id(segments), D, 0,
                             ("mesh", self._mesh, self._doc_axis))
        launch = Launch(
            call=lambda: kernel(cols, params, num_docs, D=D),
            plan=plan, cols=cols, params=params, num_docs=num_docs,
            D=D, G=0, batch_key=batch_key,
            cols_key=self._cols_key(segments, plan),
            factory=(lambda B, stacked, _p=plan:
                     kernels.compiled_batched_topn_kernel(_p, B, stacked)),
            collective=self._needs_cpu_ordering(kernel),
            cancel_check=cancel_check,
            site_ctx={"table": ctx.table, "mode": mode}, span=dsp,
            slip=slip, docs=sum(s.num_docs for s in segments))
        return S_real, launch

    def _execute_topn(self, segments, ctx: QueryContext, cancel_check=None):
        if ctx.filter is not None \
                and vector_device.contains_vector(ctx.filter):
            return self._execute_vector(segments, ctx, cancel_check)
        if self._doc_axis > 1:
            return [], segments  # top-K across doc shards: host path
        prep = self._prepare_topn(segments, ctx, cancel_check, "topn")
        if prep is None:
            return [], segments
        S_real, launch = prep
        with self._dispatcher.active():
            try:
                packed = dispatch_mod.wait_result(
                    self._dispatcher.submit(launch), launch.cancel_check,
                    max_wait_s=self.LAUNCH_WAIT_CAP_S)
            finally:
                if launch.span is not None:
                    launch.span.end()
        return self._assemble_topn(segments, ctx, packed, S_real), []

    # ------------------------------------------------------------------
    @staticmethod
    def _make_classifier(seg0):
        """Column stagability test; records dict/raw membership as a side
        effect (ids usable for filters/group-by regardless of value type;
        value math additionally needs a numeric dictionary)."""
        dict_cols: set = set()
        raw_cols: set = set()

        def classify(col: str) -> bool:
            if not seg0.has_column(col):
                return False
            m = seg0.metadata.columns[col]
            if not m.single_value:
                return False
            if m.has_dictionary:
                dict_cols.add(col)
                return True
            if m.data_type.np_dtype.kind in "iuf":
                raw_cols.add(col)
                return True
            return False

        return classify, dict_cols, raw_cols

    def _plan(self, segments, ctx: QueryContext):
        """Build the DevicePlan from the query + first segment's schema."""
        seg0 = segments[0]
        classify, dict_cols, raw_cols = self._make_classifier(seg0)

        # value IRs for aggregation inputs
        value_irs: List[Optional[tuple]] = []
        ir_index: Dict[tuple, int] = {}

        def intern_ir(ir: Optional[tuple]) -> Optional[int]:
            if ir is None:
                return None
            if ir not in ir_index:
                ir_index[ir] = len(value_irs)
                value_irs.append(ir)
            return ir_index[ir]

        def check_value_cols(ir) -> bool:
            if ir[0] == "col":
                col = ir[1]
                if col in raw64:
                    return False  # split-plane columns have no value block
                if not classify(col):
                    return False
                m = seg0.metadata.columns[col]
                return m.data_type.np_dtype.kind in "iuf"
            if ir[0] == "lit":
                return True
            return all(check_value_cols(c) for c in ir[1:] if isinstance(c, tuple))

        # device-HLL inputs hash i32 split planes of plain int columns —
        # they join the raw64 staging set and are excluded from value IRs
        hll_cols: set = set()
        for node, fn in zip(ctx.aggregations, ctx.agg_functions):
            spec = fn.device_spec
            if spec is None:
                return None
            if any(op.startswith("hll:") for op in spec.ops):
                col = node.args[0].name
                m0 = seg0.metadata.columns.get(col)
                if m0 is None or not m0.single_value \
                        or m0.data_type.np_dtype.kind not in "iu":
                    return None
                # the i32 hi plane wraps for |v| >= 2^55 (the vrange64
                # bound): the host fold would then diverge from the
                # device hash, so such columns stay host-side
                for seg in segments:
                    m = seg.metadata.columns.get(col)
                    if m is None or m.min_value is None \
                            or m.max_value is None or max(
                                abs(int(m.min_value)),
                                abs(int(m.max_value))) >= (1 << 55):
                        return None
                hll_cols.add(col)

        # filter IR FIRST: leaves fill in build order, so the main filter's
        # leaves precede agg-filter leaves (staging resolves in this order)
        leaves: List[DeviceLeaf] = []
        filter_ir = None
        hll64 = frozenset(hll_cols)
        if ctx.filter is not None:
            filter_ir = self._build_filter_ir(ctx.filter, segments, leaves,
                                              classify, force64=hll64)
            if filter_ir is None:
                return None

        #: columns that stage as split planes carry NO 'val:' block — they
        #: cannot feed value IRs (the whole query falls back instead)
        raw64 = {lf.column for lf in leaves
                 if lf.kind == "vrange64"} | hll_cols

        # per-aggregation FILTER (WHERE ...) trees, deduplicated
        agg_filter_irs: List[tuple] = []
        fidx_of_filter: Dict[Expression, int] = {}
        agg_fidx: List[Optional[int]] = []
        for f in ctx.agg_filters:
            if f is None:
                agg_fidx.append(None)
                continue
            if f in fidx_of_filter:
                agg_fidx.append(fidx_of_filter[f])
                continue
            ir = self._build_filter_ir(f, segments, leaves, classify,
                                       force64=hll64)
            if ir is None:
                return None
            fidx_of_filter[f] = len(agg_filter_irs)
            agg_fidx.append(len(agg_filter_irs))
            agg_filter_irs.append(ir)
        raw64 |= {lf.column for lf in leaves if lf.kind == "vrange64"}

        if ctx.group_by and any(
                ":" in op for fn in ctx.agg_functions
                for op in fn.device_spec.ops):
            return None  # grouped sketches: host path (see supports)

        # aggregation slots
        agg_ops: List[Tuple[str, Optional[int], Optional[int]]] = []
        slot_index: Dict[Tuple[str, Optional[int], Optional[int]], int] = {}
        slots_of_fn: List[Dict[str, int]] = []
        for i, (node, fn) in enumerate(zip(ctx.aggregations,
                                           ctx.agg_functions)):
            spec_ops = fn.device_spec.ops
            is_hll = any(op.startswith("hll:") for op in spec_ops)
            arg_ir = None
            if not is_hll and node.args \
                    and not (isinstance(node.args[0], Identifier)
                             and node.args[0].name == "*"):
                arg_ir = self._value_ir_shape(node.args[0])
                if arg_ir is None or not check_value_cols(arg_ir):
                    return None
            vidx = intern_ir(arg_ir)
            fidx = agg_fidx[i]
            # bit-exact SUM for plain int columns under f32 staging: swap
            # the slot to 'isum' (6-bit-plane i32 accumulation, ref
            # SumAggregationFunction's exact doubles); _assemble rebuilds
            # the scalar so the function still sees its 'sum' slot.
            # Grouped sums stay f32 (scalar-slot packing) — documented
            # approximation.
            int_bounds = None
            if not ctx.group_by and arg_ir is not None \
                    and not jax.config.read("jax_enable_x64"):
                int_bounds = self._int_ir_bounds(segments, arg_ir)
            mapping = {}
            for op in spec_ops:
                if op == "sum" and int_bounds is not None:
                    lo_b, hi_b = int_bounds
                    if lo_b >= 0:
                        # non-negative: fewer, wider unsigned planes
                        planes = max(
                            1, (max(hi_b, 1).bit_length() + 6) // 7)
                        op_key = f"isum:u{planes}"
                    else:
                        op_key = "isum"
                    key = (op_key, vidx, fidx)
                    if key not in slot_index:
                        slot_index[key] = len(agg_ops)
                        agg_ops.append(key)
                    mapping[op] = slot_index[key]
                    continue
                if op.startswith("hll:"):
                    # column rides in the op key (the kernel reads its
                    # split planes directly, no value IR)
                    key = (f"{op}:{node.args[0].name}", None, fidx)
                elif op == "count":
                    key = ("count", None, fidx)
                else:
                    if vidx is None:
                        return None
                    key = (op, vidx, fidx)
                if key not in slot_index:
                    slot_index[key] = len(agg_ops)
                    agg_ops.append(key)
                mapping[op] = slot_index[key]
            slots_of_fn.append(mapping)

        # group-by
        group_cols: List[str] = []
        group_strides: List[int] = []
        num_groups = 0
        group_compact = False
        tbucket: Tuple = ()
        if ctx.group_by:
            gb = list(ctx.group_by)
            tb_spec = None
            if gb and not isinstance(gb[0], Identifier):
                # leading floor((t - start) / step): the fused device
                # time-bucket leg (supports() admitted the shape; the
                # window/metadata admission happens here). The bucket id
                # becomes the key's LOWEST digit, so count_pad seeds the
                # mixed radix ahead of the tag cardinalities.
                tb_spec = timeseries_device.plan_bucket(
                    gb[0], ctx.filter, segments)
                if tb_spec is None:
                    return None
                if any(ir is not None and tb_spec.col in self._ir_cols(ir)
                       for ir in value_irs):
                    # the timestamp stages ONLY as split planes once the
                    # bucket leg claims it — it can't also feed a value IR
                    return None
                tbucket = (tb_spec.col, tb_spec.count_pad)
                gb = gb[1:]
            card_pads = []
            for g in gb:
                col = g.name  # Identifier, checked in supports
                if not classify(col):
                    return None
                m0 = seg0.metadata.columns[col]
                if not m0.has_dictionary:
                    return None
                card = max(seg.metadata.columns[col].cardinality
                           for seg in segments)
                group_cols.append(col)
                card_pads.append(max(card, 1))
            num_groups = tb_spec.count_pad if tb_spec is not None else 1
            for c in card_pads:
                num_groups *= c
            if tb_spec is not None and num_groups > MAX_DEVICE_GROUPS:
                # compact per-segment keys can't carry the fused bucket
                # digit — an over-wide dashboard stays on the host path
                return None
            if num_groups > MAX_DEVICE_GROUPS:
                # sparse key space: per-segment compacted keys replace the
                # dense mixed-radix product (ref DictionaryBasedGroupKey
                # Generator's map-based modes) — the OBSERVED distinct
                # count is what matters, resolved at staging
                group_compact = True
                num_groups = 0
            else:
                # memory guard: the [S, G, slots] result buffer must stay
                # sane, with S padded exactly as _stage will pad it (pow2
                # bucket only when the doc bucket is cross-table eligible,
                # then the segments-axis multiple) — an overestimate here
                # would host-fallback group-bys that actually fit
                n_slots = len(agg_ops) + 1  # +1 guaranteed count slot
                s_pad = self._padded_S(
                    len(segments),
                    bucket=self._padded_D(segments) <= self._doc_bucket_max)
                if s_pad * num_groups * n_slots * 8 > MAX_GROUP_RESULT_BYTES:
                    return None
                stride = num_groups
                for c in card_pads:
                    stride //= c
                    group_strides.append(stride)
            # group-by always needs an unfiltered count slot to detect
            # present groups
            if ("count", None, None) not in slot_index:
                slot_index[("count", None, None)] = len(agg_ops)
                agg_ops.append(("count", None, None))

        raw64 = {lf.column for lf in leaves
                 if lf.kind == "vrange64"} | hll_cols
        if tbucket:
            # the bucket kernel reads the timestamp's (hi, lo) planes
            # regardless of how its range leaf classified
            raw64 |= {tbucket[0]}
        if group_compact:
            # the gkey block replaces per-column id planes for group-only
            # columns; keep ids only where filters/values still need them
            needed = {lf.column for lf in leaves}
            for ir in value_irs:
                needed |= self._ir_cols(ir)
            dict_cols -= set(group_cols) - needed
        plan = DevicePlan(
            filter_ir=filter_ir,
            leaves=tuple(leaves),
            value_irs=tuple(value_irs),
            agg_ops=tuple(agg_ops),
            agg_filter_irs=tuple(agg_filter_irs),
            group_cols=tuple(group_cols),
            group_strides=tuple(group_strides),
            num_groups=num_groups,
            group_compact=group_compact,
            dict_cols=tuple(sorted(dict_cols)),
            raw_cols=tuple(sorted(raw_cols - raw64)),
            raw64_cols=tuple(sorted(raw64)),
            clp_cols=clp_device.staged_cols(leaves),
            valid_mask=self._needs_valid_mask(segments),
            tbucket=tbucket,
        )
        return plan, slots_of_fn

    def filtered_doc_ids(self, segments, filter_expr):
        """Device-filtered doc ids for leaf SCANS (MSE join inputs, ref
        QueryRunner.java:258 routing ALL leaf stages through the v1
        engine): the top-K kernel evaluates the filter and returns the
        first TOPN_MAX_K matching doc indices per segment. Returns a list
        parallel to `segments` of sorted int64 index arrays, or None per
        segment that must fall back (overflow / unstageable / sharded
        doc axis)."""
        nothing = [None] * len(segments)
        if self._doc_axis > 1 or not segments or filter_expr is None:
            return nothing
        ctx = QueryContext(
            table="", select=[], aliases=[], distinct=False,
            filter=filter_expr, group_by=[], having=None, order_by=[],
            limit=self.TOPN_MAX_K, offset=0, options={})
        # the launch rides the kernel factory (batch_key + batched topn
        # variants), so fingerprint-equal MSE leaf scans from concurrent
        # queries coalesce into ONE stacked/broadcast topn launch
        prep = self._prepare_topn(segments, ctx, None, "doc_ids")
        if prep is None:
            return nothing
        S_real, launch = prep
        plan = launch.plan
        with self._dispatcher.active():
            try:
                packed = dispatch_mod.wait_result(
                    self._dispatcher.submit(launch), launch.cancel_check,
                    max_wait_s=self.LAUNCH_WAIT_CAP_S)
            finally:
                if launch.span is not None:
                    launch.span.end()
        out = []
        for s, seg in enumerate(segments[:S_real]):
            matched = int(packed[s, 0])
            if matched > plan.topn_k:
                out.append(None)  # more matches than K: host path
                continue
            idx = packed[s, 1:]
            idx = idx[(idx >= 0) & (idx < seg.num_docs)].astype(np.int64)
            out.append(np.sort(idx))
        return out

    def _plan_topn(self, segments, ctx: QueryContext) -> Optional[DevicePlan]:
        """DevicePlan for selection / single-key order-by top-K."""
        seg0 = segments[0]
        classify, dict_cols, raw_cols = self._make_classifier(seg0)
        k = ctx.limit + ctx.offset
        if k <= 0 or k > self.TOPN_MAX_K:
            return None

        leaves: List[DeviceLeaf] = []
        filter_ir = None
        if ctx.filter is not None:
            filter_ir = self._build_filter_ir(ctx.filter, segments, leaves,
                                              classify)
            if filter_ir is None:
                return None
        raw64 = {lf.column for lf in leaves if lf.kind == "vrange64"}

        value_irs: Tuple[Optional[tuple], ...] = ()
        topn_asc = True
        if ctx.order_by:
            e, topn_asc = ctx.order_by[0]
            ir = None
            if isinstance(e, Identifier) and classify(e.name):
                m = seg0.metadata.columns[e.name]
                if m.has_dictionary:
                    # dictionaries are value-sorted: dictId order IS value
                    # order, and ids stay exact in f32 below 2^24
                    if max(s.metadata.columns[e.name].cardinality
                           for s in segments) >= (1 << 24):
                        return None
                    ir = ("ids", e.name)
                elif e.name not in raw64:
                    ir = ("col", e.name)
            elif isinstance(e, Function):
                ir = self._value_ir_shape(e)
                if ir is not None:
                    for col in self._ir_cols(ir):
                        if col in raw64 or not classify(col):
                            return None
                        mc = seg0.metadata.columns[col]
                        if mc.data_type.np_dtype.kind not in "iuf":
                            return None
            if ir is None:
                return None
            value_irs = (ir,)

        return DevicePlan(
            filter_ir=filter_ir,
            leaves=tuple(leaves),
            value_irs=value_irs,
            agg_ops=(),
            dict_cols=tuple(sorted(dict_cols)),
            raw_cols=tuple(sorted(raw_cols - raw64)),
            raw64_cols=tuple(sorted(raw64)),
            clp_cols=clp_device.staged_cols(leaves),
            mode="topn", topn_k=k, topn_asc=bool(topn_asc),
            valid_mask=self._needs_valid_mask(segments))

    def _assemble_topn(self, segments, ctx: QueryContext,
                       packed: np.ndarray, S_real: int) -> List[Any]:
        """packed [S, 1+K] int32 -> SelectionResults: project ONLY the
        winning docs host-side (incl. '*' and string columns)."""
        from pinot_tpu.query.executor_cpu import _project_rows, expand_star
        from pinot_tpu.query.filter import SegmentColumnProvider
        from pinot_tpu.query.results import SelectionResult
        filter_cols = len(set(ctx.filter_columns()))
        results = []
        for s, seg in enumerate(segments[:S_real]):
            matched = int(packed[s, 0])
            idx = packed[s, 1:]
            idx = idx[(idx >= 0) & (idx < seg.num_docs)].astype(np.int64)
            provider = SegmentColumnProvider(seg)
            rows = _project_rows(seg, ctx.select, provider, idx)
            order_values = None
            if ctx.order_by:
                order_values = _project_rows(
                    seg, [e for e, _ in ctx.order_by], provider, idx)
            stats = ExecutionStats(
                num_docs_scanned=matched,
                num_entries_scanned_in_filter=(
                    seg.num_docs * filter_cols
                    if ctx.filter is not None else 0),
                num_entries_scanned_post_filter=len(idx) * max(
                    len(ctx.select), 1),
                num_segments_processed=1,
                num_segments_matched=1 if matched else 0,
                total_docs=seg.num_docs)
            results.append(SelectionResult(
                rows, order_values=order_values,
                columns=expand_star(seg, ctx), stats=stats))
        return results

    def _build_filter_ir(self, e: Function, segments, leaves, classify,
                         force64: frozenset = frozenset()):
        """force64: no-dictionary int columns that stage ONLY as split
        planes (device-HLL inputs) — filter leaves on them must use
        vrange64, never the 'val:' block that won't exist."""
        seg0 = segments[0]
        if e.name in ("and", "or"):
            children = []
            for a in e.args:
                c = self._build_filter_ir(a, segments, leaves, classify,
                                          force64)
                if c is None:
                    return None
                children.append(c)
            return (e.name, *children)
        if e.name == "not":
            c = self._build_filter_ir(e.args[0], segments, leaves, classify,
                                      force64)
            return None if c is None else ("not", c)
        if not e.args or not isinstance(e.args[0], Identifier):
            return None
        col = e.args[0].name
        if clp_device.is_clp_column(seg0, col):
            # CLP log columns never classify (STRING, no dictionary
            # block) — LIKE/regex push down through their own leaf kind
            # instead, against the logtype/var-slot pseudo-columns
            leaf = self._clp_leaf(e, segments, col)
            if leaf is None:
                return None
            leaves.append(leaf)
            return ("leaf", len(leaves) - 1)
        if not classify(col):
            return None
        m = seg0.metadata.columns[col]
        if m.has_dictionary:
            if e.name in _LEAF_RANGE_FUNCS:
                kind = "range"
            elif e.name == "not_equals":
                kind = "neq"
            elif e.name in _LEAF_LUT_FUNCS:
                kind = "lut"
            else:
                return None
        else:
            if e.name not in _LEAF_RANGE_FUNCS:
                return None
            if col in force64:
                # split planes are the ONLY staged form of this column
                # (regardless of x64 — the HLL op reads them either way)
                kind = "vrange64"
            elif m.data_type.np_dtype.kind in "iu" and \
                    not jax.config.read("jax_enable_x64"):
                kind = self._int_filter_kind(segments, col)
                if kind is None:
                    return None
            else:
                kind = "vrange"
        leaves.append(DeviceLeaf(kind, col))
        return ("leaf", len(leaves) - 1)

    @staticmethod
    def _int_filter_kind(segments, col: str) -> Optional[str]:
        """Staging for a raw int filter column under x64-off:
        'vrange'   — |v| <= 2^24, exact in f32
        'vrange64' — |v| < 2^55, exact via (hi, lo) i32 split planes
        None       — range unknown or too wide: host fallback (an i32 hi
                     plane would silently wrap for |v| >= 2^55)"""
        big = False
        for seg in segments:
            m = seg.metadata.columns.get(col)
            if m is None or m.min_value is None or m.max_value is None:
                return None
            peak = max(abs(int(m.min_value)), abs(int(m.max_value)))
            if peak >= (1 << 55):
                return None
            if peak > (1 << 24):
                big = True
        return "vrange64" if big else "vrange"

    # ------------------------------------------------------------------
    def _padded_S(self, S_real: int, bucket: bool = True) -> int:
        """Padded segment-axis size: pow2-bucketed when cross-table
        batching is on AND this launch is bucket-eligible (so different
        tables' batches land in shared shape buckets — padded segments
        carry num_docs=0 and zero rows, masked out of every slot), then
        rounded up to the mesh's segment-axis multiple. bucket=False
        skips the pow2 pad: a launch that can never join a cross-table
        bucket (doc bucket above doc.bucket.max) must not pay inflated
        [S, D] blocks for it."""
        S = _pow2(S_real, floor=1) if (self._cross_table and bucket) \
            else S_real
        if self._mesh is not None:
            n = self._seg_axis
            S = ((S + n - 1) // n) * n
        return S

    def _padded_D(self, segments) -> int:
        """Pow2 doc bucket, rounded so the doc-shard axis tiles evenly
        (pow2 alone can never reach divisibility by doubling). The ONE
        definition of D: staging and the group-by memory guard both use
        it, so bucket eligibility (D <= doc.bucket.max) always agrees
        between them."""
        D = _pow2(max(s.num_docs for s in segments))
        if D % self._doc_axis:
            a = self._doc_axis
            D = ((D + a - 1) // a) * a
        return D

    def _stage(self, segments, ctx: QueryContext, plan: DevicePlan,
               batchable: bool = True):
        """batchable=False (top-N / doc-id scans — launches that never
        carry a batch_key) skips the pow2 S bucket: shape-bucket padding
        only buys cross-table coalescing, which those paths can't use."""
        S_real = len(segments)
        if max(s.num_docs for s in segments) > MAX_DOCS_PER_SEGMENT:
            raise _NotStageable()
        D = self._padded_D(segments)
        S = self._padded_S(
            S_real, bucket=batchable and D <= self._doc_bucket_max)

        cols: Dict[str, jnp.ndarray] = {}
        params: Dict[str, jnp.ndarray] = {}
        vdt = np.float64 if jax.config.read("jax_enable_x64") else np.float32

        for col in plan.dict_cols:
            # cardinality-aware id width: HBM bandwidth is the roofline,
            # so an 11-value dictionary column reads 4x fewer bytes as i8
            # (SURVEY §7 hard-parts: pick per-column by bit width)
            card = max(s.metadata.columns[col].cardinality
                       for s in segments)
            if card <= 127:
                idt = np.int8
            elif card <= 32767:
                idt = np.int16
            else:
                idt = np.int32
            cols["ids:" + col] = self._stacked(
                segments, S, D, col, f"ids{np.dtype(idt).itemsize}",
                lambda ds, _t=idt: ds.dict_ids().astype(_t), idt)
        for col in plan.raw_cols:
            self._check_value_precision(segments, col, vdt)
            cols["val:" + col] = self._stacked(
                segments, S, D, col, "val",
                lambda ds: ds.values().astype(vdt), vdt)
        for col in plan.raw64_cols:
            # big-int filter columns: (hi, lo) i32 split planes, exact
            # under x64-off where f32 staging would alias (plan_ir vrange64)
            cols["valhi:" + col] = self._stacked(
                segments, S, D, col, "valhi",
                lambda ds: (ds.values().astype(np.int64) >> 24
                            ).astype(np.int32), np.int32)
            cols["vallo:" + col] = self._stacked(
                segments, S, D, col, "vallo",
                lambda ds: (ds.values().astype(np.int64) & 0xFFFFFF
                            ).astype(np.int32), np.int32)
        for col, kd, ke in plan.clp_cols:
            # CLP log columns stage as a pseudo-column family instead of
            # values: the logtype-id row plus kd dict-var-slot id rows
            # and ke encoded-var (hi, lo) i32 split rows — the 'clp'
            # leaf matches against these without ever materializing the
            # decoded strings (ops/clp_device.py)
            def clp_fetch(fn, _c=col):
                def fetch_row(seg):
                    try:
                        r = seg.data_source(_c).clp_reader
                    except (KeyError, ValueError, AttributeError):
                        r = None
                    if r is None:
                        raise _NotStageable()
                    return fn(r)
                return fetch_row
            cols["clpid:" + col] = self._block(
                segments, S, D, col, "clpid",
                clp_fetch(clp_device.row_ids), np.int32,
                resident=self._clp_resident)
            for j in range(kd):
                cols[f"clpdv{j}:{col}"] = self._block(
                    segments, S, D, col, f"clpdv{j}",
                    clp_fetch(lambda r, _j=j: clp_device.row_dict_slot(
                        r, _j)), np.int32, resident=self._clp_resident)
            for j in range(ke):
                cols[f"clpehi{j}:{col}"] = self._block(
                    segments, S, D, col, f"clpehi{j}",
                    clp_fetch(lambda r, _j=j: clp_device.row_enc_hi(
                        r, _j)), np.int32, resident=self._clp_resident)
                cols[f"clpelo{j}:{col}"] = self._block(
                    segments, S, D, col, f"clpelo{j}",
                    clp_fetch(lambda r, _j=j: clp_device.row_enc_lo(
                        r, _j)), np.int32, resident=self._clp_resident)

        # value columns: stage MATERIALIZED values (dictionary take done
        # host-side at staging, cached in HBM) rather than in-kernel
        # take_along_axis gathers — TPU gathers run off the vector units and
        # dominated the scan kernel when measured; a dense [S, D] value
        # block turns the hot path into a pure fused multiply-reduce
        value_cols = set()
        for ir in plan.value_irs:
            value_cols |= self._ir_cols(ir)
        for col in value_cols & set(plan.dict_cols):
            if "val:" + col in cols:
                continue
            self._check_value_precision(segments, col, vdt)
            def fetch_values(ds):
                vals = ds.values()
                if vals.dtype.kind not in "iuf":
                    raise _NotStageable()
                return vals.astype(vdt)
            cols["val:" + col] = self._stacked(
                segments, S, D, col, "val", fetch_values, vdt)

        if plan.valid_mask:
            cols["vmask"] = self._stage_vmask(segments, S, D)

        G = 0
        if plan.group_compact:
            cols["gkey"], G = self._stage_gkey(segments, S, D, plan)

        # per-leaf predicate parameters (cached: filters are frozen
        # expression trees, so they key the resolved literals exactly;
        # the entry also carries hist slot bounds — they depend only on
        # (segments, plan), so a repeat query uploads NOTHING)
        pkey = (_batch_id(segments), plan, ctx.filter,
                tuple(ctx.agg_filters), S,
                tuple(ctx.group_by) if plan.tbucket else None)
        cached = self._params_cache.get(pkey)
        if cached is not None:
            csegs, cparams, cnum_docs = cached
            if all(a is b for a, b in zip(csegs, segments)):
                self._params_cache.move_to_end(pkey)  # LRU refresh
                params.update(cparams)
                if plan.clp_cols:
                    self._meter("clp_served")
                if plan.tbucket:
                    self._meter("timeseries_leaf_device")
                return cols, params, cnum_docs, S_real, D, G
        if plan.tbucket:
            # fused time-bucket cells: start's (hi, lo) planes + step +
            # live bucket count — the ONLY things that change across a
            # dashboard's sliding refresh window (pkey carries group_by
            # above: same filter + different bucket expr must not alias)
            spec = timeseries_device.plan_bucket(
                ctx.group_by[0], ctx.filter, segments)
            if spec is None or spec.count_pad != plan.tbucket[1]:
                raise _NotStageable()
            for key, arr in timeseries_device.leaf_params(spec, S).items():
                params[key] = self._put(arr)
        # histogram sketch slots: bucket bounds from segment metadata
        # (missing min/max -> host fallback)
        for j, (op, vidx, _fidx) in enumerate(plan.agg_ops):
            if not op.startswith("hist:"):
                continue
            col = plan.value_irs[vidx][1]
            lo, span = self._hist_bounds(segments, col)
            B = int(op.split(":")[1])
            params[f"slot{j}:hlo"] = self._put(np.full(S, lo, dtype=vdt))
            params[f"slot{j}:hscale"] = self._put(
                np.full(S, B / span, dtype=vdt))
        # leaf expressions in the exact order _plan appended leaves:
        # main filter first, then each distinct agg FILTER tree
        leaf_exprs: List[Function] = []
        if ctx.filter is not None:
            leaf_exprs += self._collect_leaf_exprs(ctx.filter, plan)
        seen_filters = set()
        for f in ctx.agg_filters:
            if f is not None and f not in seen_filters:
                seen_filters.add(f)
                leaf_exprs += self._collect_leaf_exprs(f, plan)
        for i, (leaf, expr) in enumerate(zip(plan.leaves, leaf_exprs)):
            if leaf.kind == "vrange":
                lo, hi = _vrange_bounds(expr, vdt)
                params[f"leaf{i}:lo"] = self._put(np.full(S, lo, dtype=vdt))
                params[f"leaf{i}:hi"] = self._put(np.full(S, hi, dtype=vdt))
                continue
            if leaf.kind == "vrange64":
                a, b = _vrange_int_bounds(expr)
                params[f"leaf{i}:lohi"] = self._put(
                    np.full(S, a >> 24, dtype=np.int32))
                params[f"leaf{i}:lolo"] = self._put(
                    np.full(S, a & 0xFFFFFF, dtype=np.int32))
                params[f"leaf{i}:hihi"] = self._put(
                    np.full(S, b >> 24, dtype=np.int32))
                params[f"leaf{i}:hilo"] = self._put(
                    np.full(S, b & 0xFFFFFF, dtype=np.int32))
                continue
            if leaf.kind == "range":
                lo = np.zeros(S, dtype=np.int32)
                hi = np.full(S, -1, dtype=np.int32)
                for s, seg in enumerate(segments):
                    p = resolve_predicate(seg, expr)
                    if p is None:
                        raise _NotStageable()
                    if p.kind == "range":
                        lo[s], hi[s] = p.lo, p.hi
                    elif p.kind == "all":
                        lo[s], hi[s] = 0, 2**31 - 1
                    elif p.kind == "none":
                        lo[s], hi[s] = 0, -1
                    elif p.kind == "set" and len(p.ids) == 1:
                        lo[s] = hi[s] = int(p.ids[0])
                    else:
                        raise _NotStageable()
                params[f"leaf{i}:lo"] = self._put(lo)
                params[f"leaf{i}:hi"] = self._put(hi)
            elif leaf.kind == "neq":
                idx = np.full(S, -1, dtype=np.int32)
                for s, seg in enumerate(segments):
                    p = resolve_predicate(seg, expr)
                    if p is None:
                        raise _NotStageable()
                    if p.kind == "notset" and len(p.ids) == 1:
                        idx[s] = int(p.ids[0])
                    elif p.kind == "all":
                        idx[s] = -1
                    else:
                        raise _NotStageable()
                params[f"leaf{i}:idx"] = self._put(idx)
            elif leaf.kind == "clp":
                try:
                    arrs = clp_device.leaf_params(
                        i, leaf, segments, str(expr.args[1].value),
                        expr.name == "like", S)
                except ValueError:
                    raise _NotStageable()
                for k, arr in arrs.items():
                    params[k] = self._put(arr)
            elif leaf.kind == "lut":
                C = _pow2(max(s.metadata.columns[leaf.column].cardinality
                              for s in segments), floor=8)
                table = np.zeros((S, C), dtype=bool)
                for s, seg in enumerate(segments):
                    p = resolve_predicate(seg, expr)
                    if p is None:
                        raise _NotStageable()
                    card = seg.metadata.columns[leaf.column].cardinality
                    if p.kind == "all":
                        table[s, :card] = True
                    elif p.kind == "none":
                        pass
                    elif p.kind == "range":
                        table[s, p.lo:p.hi + 1] = True
                    elif p.kind == "set":
                        table[s, p.ids] = True
                    elif p.kind == "notset":
                        table[s, :card] = True
                        table[s, p.ids] = False
                    else:
                        raise _NotStageable()
                params[f"leaf{i}:lut"] = self._put(table)

        num_docs = np.zeros(S, dtype=np.int32)
        num_docs[:S_real] = [s.num_docs for s in segments]
        num_docs_dev = self._put(num_docs)
        leaf_params = {k: v for k, v in params.items()
                       if k.startswith(("leaf", "slot", "tb:"))}
        self._params_cache[pkey] = (tuple(segments), leaf_params, num_docs_dev)
        self._params_cache.move_to_end(pkey)
        while len(self._params_cache) > self.PARAMS_CACHE_ENTRIES:
            self._params_cache.popitem(last=False)  # evict coldest only
        if plan.clp_cols:
            self._meter("clp_served")
        if plan.tbucket:
            self._meter("timeseries_leaf_device")
        return cols, params, num_docs_dev, S_real, D, G

    # ------------------------------------------------------------------
    # upsert validity masks (device-path upsert, SURVEY §2.3)
    # ------------------------------------------------------------------
    @staticmethod
    def _mask_stamp(seg) -> int:
        """Version stamp of a segment's validDocIds bitmap (-1 = no
        bitmap: the row is a constant all-ones and never goes stale)."""
        valid = getattr(seg, "valid_doc_ids", None)
        return -1 if valid is None else valid.version

    def _needs_valid_mask(self, segments) -> bool:
        return any(getattr(s, "valid_doc_ids", None) is not None
                   for s in segments)

    def _cols_key(self, segments, plan: DevicePlan) -> tuple:
        """Staged-column identity for batch dedup/broadcast decisions:
        for valid-mask plans the mask version stamps join the key, so
        two coalesced members whose upsert bitmaps moved between their
        stagings stack separately instead of silently sharing one
        member's snapshot through the broadcast variant."""
        base = _batch_id(segments)
        if plan.valid_mask:
            return (base, tuple(self._mask_stamp(s) for s in segments))
        return base

    def _stage_vmask(self, segments, S, D):
        """Staged bool [S, D] validity block for a batch carrying upsert
        segments — the `(segment, "__valid__")` pseudo-column. Rows ride
        the same host-row / residency / assembled tiers as column data,
        but every key carries the bitmap's mutation counter
        ('vmask:<version>'): upsert bitmaps mutate IN PLACE without the
        segment object changing, so an in-place clear() must address
        fresh keys — the staged mask can never go stale, and the cost of
        an upsert is re-staging one bool row, not a correctness hole.
        Append-only segments in a mixed batch stage all-ones rows (stamp
        -1, never mutated). Bitmap reads are snapshots: a concurrent
        upsert lands in the NEXT staging, the same discipline as the
        host executor's per-query to_mask()."""
        stamps = tuple(self._mask_stamp(s) for s in segments)
        batch = _batch_id(segments)
        bkey = (batch, "vmask", "__valid__", S, D, stamps)
        entry = self._block_cache.get(bkey)
        if entry is not None and all(a is b
                                     for a, b in zip(entry[0], segments)):
            self._block_cache.move_to_end(bkey)
            self._meter("hbm_block_hit")
            return entry[1]
        self._meter("hbm_block_miss")
        # purge blocks staged under superseded mask versions of THIS
        # batch: every future lookup carries the new stamps, so the old
        # block is unreachable and would squat in the HBM budget
        for k in [k for k in self._block_cache
                  if k[0] == batch and k[1] == "vmask" and k != bkey]:
            del self._block_cache[k]
            self._cache_bytes -= self._block_bytes.pop(k)
            self._drop_batch_block(k[0])

        def fetch_row(seg):
            valid = getattr(seg, "valid_doc_ids", None)
            if valid is None:
                return np.ones(seg.num_docs, dtype=bool)
            m = valid.to_mask()
            if len(m) < seg.num_docs:
                # defensive (engine batches are immutable, sizes fixed):
                # docs beyond the bitmap are not yet upsert-accounted
                m = np.concatenate(
                    [m, np.zeros(seg.num_docs - len(m), dtype=bool)])
            return m[:seg.num_docs]

        dtype_str = np.dtype(bool).str
        if self._residency.enabled:
            dev_rows: List[Any] = []
            missing: List[int] = []
            for seg, stamp in zip(segments, stamps):
                row = self._residency.get(seg, f"vmask:{stamp}",
                                          "__valid__", dtype_str)
                dev_rows.append(row)
                if row is None:
                    missing.append(len(dev_rows) - 1)
            for i in missing:
                seg = segments[i]
                # a miss means this stamp was never staged: purge the
                # superseded stamps' rows (host + resident) — they are
                # unreachable and would squat in both budgets
                self._residency.invalidate_superseded_kind(
                    seg, "vmask:", f"vmask:{stamps[i]}", "__valid__")
                for hk in [k for k, v in self._host_rows.items()
                           if k[0] == id(seg) and v[0] is seg
                           and isinstance(k[1], str)
                           and k[1].startswith("vmask:")
                           and k[1] != f"vmask:{stamps[i]}"]:
                    _s, payload = self._host_rows.pop(hk)
                    self._host_bytes -= _entry_nbytes(payload)
                arr = self._host_row(seg, "__valid__",
                                     f"vmask:{stamps[i]}", fetch_row, bool)
                dev = self._put_row(arr)
                self._residency.admit(seg, f"vmask:{stamps[i]}",
                                      "__valid__", dtype_str, dev,
                                      arr.nbytes,
                                      device=self._dev_label(dev))
                dev_rows[i] = dev
            if self._mesh is not None and len(self.devices) > 1:
                anchor = self.devices[0]
                dev_rows = [jax.device_put(r, anchor) for r in dev_rows]
            assembler = kernels.compiled_row_assembler(
                S, D, tuple(int(r.shape[0]) for r in dev_rows), dtype_str)
            dev = self._reshard_block(assembler(tuple(dev_rows)))
            nbytes = S * D
        else:
            rows = [self._host_row(seg, "__valid__", f"vmask:{st}",
                                   fetch_row, bool, pad_to=D)
                    for seg, st in zip(segments, stamps)]
            block = np.stack(rows) if len(rows) == S else \
                np.concatenate([np.stack(rows),
                                np.zeros((S - len(rows), D), dtype=bool)])
            dev = self._put(block, block=True)
            nbytes = block.nbytes
        self._insert_block(bkey, (tuple(segments), dev), nbytes)
        return dev

    def _stage_gkey(self, segments, S, D, plan: DevicePlan):
        """Compacted combined group keys: one int32 [S, D] code block,
        codes dense per segment over OBSERVED key tuples only (ref
        DictionaryBasedGroupKeyGenerator's map modes for sparse spaces).
        Returns (device block, G = pow2 pad of the max distinct count).
        Host rows cache (codes, decode table) per (segment, group cols)."""
        sig = ",".join(plan.group_cols)
        tables = [self._segment_gkey(seg, plan)[1] for seg in segments]
        G = _pow2(max(t.shape[0] for t in tables), floor=8)
        # guard BEFORE any upload: an over-cap key space must not pay a
        # useless HBM transfer (and LRU churn) on every repeat query
        if G > MAX_DEVICE_GROUPS \
                or S * G * len(plan.agg_ops) * 8 > MAX_GROUP_RESULT_BYTES:
            raise _NotStageable()

        def fetch_codes(seg):
            # lint: unlocked(runs synchronously inside _block on the staging thread, which holds the engine RLock)
            return self._segment_gkey_locked(seg, plan)[0]

        # host_cache=False: the (codes, table) pair is already host-cached
        # by _segment_gkey; caching the padded row too would double-store
        dev = self._block(segments, S, D, sig, "gkey", fetch_codes,
                          np.int32, host_cache=False)
        return dev, G

    def _segment_gkey(self, seg, plan: DevicePlan):
        """(codes [num_docs] int32, decode table [G_s, k] int32 dictIds)
        for one segment, via the host row cache. Takes the engine lock:
        assembly calls this outside it (the RLock makes the staging-path
        call reentrant)."""
        with self._engine_lock:
            return self._segment_gkey_locked(seg, plan)

    def _segment_gkey_locked(self, seg, plan: DevicePlan):
        sig = ",".join(plan.group_cols)
        rkey = (id(seg), "gkey", sig)
        rentry = self._host_rows.get(rkey)
        if rentry is not None and rentry[0] is seg:
            self._host_rows.move_to_end(rkey)
            return rentry[1]
        cards = []
        prod = 1
        for col in plan.group_cols:
            if not seg.has_column(col):
                raise _NotStageable()
            card = max(int(seg.metadata.columns[col].cardinality), 1)
            cards.append(card)
            prod *= card
            if prod > (1 << 62):
                raise _NotStageable()  # mixed-radix overflows int64
        combined = np.zeros(seg.num_docs, np.int64)
        for col, card in zip(plan.group_cols, cards):
            combined = combined * card + \
                seg.data_source(col).dict_ids().astype(np.int64)
        if prod <= (1 << 26) and prod <= 16 * max(seg.num_docs, 1):
            # dense-remap fast path: O(D + keyspace) beats the O(D log D)
            # sort for the cold first query (VERDICT r4 weak #6); gated
            # relative to num_docs so a tiny segment with a huge key
            # space doesn't pay an O(keyspace) scan
            present = np.zeros(prod, dtype=bool)
            present[combined] = True
            uniq = np.flatnonzero(present).astype(np.int64)
            remap = np.empty(prod, dtype=np.int32)  # only hit slots read
            remap[uniq] = np.arange(len(uniq), dtype=np.int32)
            inv = remap[combined]
        else:
            uniq, inv = np.unique(combined, return_inverse=True)
        table = np.empty((len(uniq), len(plan.group_cols)), np.int32)
        rem = uniq.copy()
        for j in range(len(plan.group_cols) - 1, -1, -1):
            table[:, j] = rem % cards[j]
            rem //= cards[j]
        codes = inv.astype(np.int32)
        self._host_rows[rkey] = (seg, (codes, table))
        self._host_bytes += codes.nbytes + table.nbytes
        while self._host_bytes > self.host_budget_bytes \
                and len(self._host_rows) > 1:
            _k, (_s, _a) = self._host_rows.popitem(last=False)
            self._host_bytes -= _entry_nbytes(_a)
        return codes, table

    def _stacked(self, segments, S, D, col, kind, fetch, dtype):
        """Stacked per-segment column block, three-level cached:

        * HOST level, per (segment, column): the padded numpy row (its
          own pow2 doc bucket) — rebuilding any batch skips segment
          re-read/re-decode.
        * RESIDENT level, per (segment, column): the same row in device
          HBM (ops/residency.py) — a changed batch (pruning picked a
          different subset, a new segment sealed) uploads ONLY rows the
          device has never seen, instead of re-shipping every column
          over the ~100ms link.
        * ASSEMBLED level, per (batch, column): the [S, D] block the
          kernel consumes, built ON-DEVICE from resident rows
          (kernels.compiled_row_assembler) — steady state is zero
          transfers and zero assembly.

        Entries at every level hold strong segment references and verify
        identity on hit, so a refreshed segment (same name, new object)
        can never serve stale data — id() is not recycled while an entry
        pins the old object, and a new object misses.
        """

        def fetch_row(seg):
            if not seg.has_column(col):
                raise _NotStageable()
            return fetch(seg.data_source(col))

        return self._block(segments, S, D, col, kind, fetch_row, dtype)

    def _block(self, segments, S, D, col, kind, fetch_row, dtype,
               host_cache: bool = True, resident: bool = True):
        """resident=False (clp.hbm.resident off): skip the per-row
        residency tier for this block family — host stack + whole-block
        upload, so opted-out pseudo-columns never evict scan columns."""
        dtype_str = np.dtype(dtype).str
        bkey = (_batch_id(segments), kind, col, S, D, dtype_str)
        entry = self._block_cache.get(bkey)
        if entry is not None and all(a is b
                                     for a, b in zip(entry[0], segments)):
            self._block_cache.move_to_end(bkey)  # LRU touch
            self._meter("hbm_block_hit")
            return entry[1]
        self._meter("hbm_block_miss")
        if self._residency.enabled and resident:
            dev = self._assemble_resident(segments, S, D, col, kind,
                                          fetch_row, dtype, host_cache)
            nbytes = S * D * np.dtype(dtype).itemsize
        else:
            # legacy path: host-side stack + one whole-block upload
            rows = [self._host_row(seg, col, kind, fetch_row, dtype,
                                   host_cache, pad_to=D)
                    for seg in segments]
            block = np.stack(rows) if len(rows) == S else \
                np.concatenate([np.stack(rows),
                                np.zeros((S - len(rows), D), dtype=dtype)])
            dev = self._put(block, block=True)
            nbytes = block.nbytes
        self._insert_block(bkey, (tuple(segments), dev), nbytes)
        return dev

    def _assemble_resident(self, segments, S, D, col, kind, fetch_row,
                           dtype, host_cache: bool):
        """[S, D] block from per-segment resident rows: misses build on
        the host and upload individually (in parallel for multi-row
        bursts — ops/dispatch.upload_pool), hits cost nothing, and the
        stack itself runs on-device."""
        dtype_str = np.dtype(dtype).str
        dev_rows: List[Any] = []
        missing: List[int] = []
        for seg in segments:
            row = self._residency.get(seg, kind, col, dtype_str)
            dev_rows.append(row)
            if row is None:
                missing.append(len(dev_rows) - 1)
        if missing:
            # host rows first: _NotStageable must surface BEFORE any
            # upload (a doomed plan should not churn the resident tier)
            host_rows = [self._host_row(segments[i], col, kind, fetch_row,
                                        dtype, host_cache)
                         for i in missing]
            if len(host_rows) > 1 and sum(
                    a.nbytes for a in host_rows) >= self.UPLOAD_FANOUT_BYTES:
                # double-buffer big bursts: row N+1's transfer overlaps
                # row N's (and, under execute_async, the previous
                # query's kernel). Small rows stay inline — thread
                # handoff costs more than the copy
                futs = [dispatch_mod.upload_pool().submit(self._put_row, a)
                        for a in host_rows]
                # pool-executed device_puts always complete; the cap
                # bounds a wedged-device-link hang (no query deadline
                # here — staging also runs under warmup/prestage)
                uploaded = [dispatch_mod.wait_result(
                    f, max_wait_s=self.LAUNCH_WAIT_CAP_S) for f in futs]
            else:
                uploaded = [self._put_row(a) for a in host_rows]
            for i, arr, dev in zip(missing, host_rows, uploaded):
                self._residency.admit(segments[i], kind, col, dtype_str,
                                      dev, arr.nbytes,
                                      device=self._dev_label(dev))
                dev_rows[i] = dev
        if self._mesh is not None and len(self.devices) > 1:
            # resident rows round-robin across chips; the jit'd
            # assembler needs colocated inputs, so anchor the stack on
            # device 0 (chip-to-chip copies — never the host link)
            anchor = self.devices[0]
            dev_rows = [jax.device_put(r, anchor) for r in dev_rows]
        assembler = kernels.compiled_row_assembler(
            S, D, tuple(int(r.shape[0]) for r in dev_rows), dtype_str)
        return self._reshard_block(assembler(tuple(dev_rows)))

    def _host_row(self, seg, col, kind, fetch_row, dtype,
                  cache: bool = True, pad_to: Optional[int] = None):
        """Padded numpy row for one (segment, column): the segment's own
        pow2 doc bucket (batch-independent, so every batch composition
        shares it), via the host row cache."""
        Dr = pad_to if pad_to is not None else _pow2(seg.num_docs)
        rkey = (id(seg), kind, col, Dr, np.dtype(dtype).str)
        rentry = self._host_rows.get(rkey)
        if rentry is not None and rentry[0] is seg:
            self._host_rows.move_to_end(rkey)
            self._meter("host_row_hit")
            return rentry[1]
        self._meter("host_row_miss")
        raw = fetch_row(seg)
        arr = np.zeros(Dr, dtype=dtype)
        arr[:len(raw)] = raw
        if cache:
            self._host_rows[rkey] = (seg, arr)
            self._host_bytes += arr.nbytes
            while self._host_bytes > self.host_budget_bytes \
                    and len(self._host_rows) > 1:
                _k, (_s, _a) = self._host_rows.popitem(last=False)
                self._host_bytes -= _entry_nbytes(_a)
                self._meter("host_row_evicted")
            self._refresh_tier_gauges()
        return arr

    def _put_row(self, arr: np.ndarray):
        """Upload ONE residency row. On a multi-chip mesh rows
        round-robin across the mesh devices so resident bytes (and the
        per-chip admission pressure they feed) spread instead of piling
        onto device 0; the assembled block is resharded over the mesh
        regardless of where its rows live. Runs on upload-pool threads
        for multi-row bursts — the shared round-robin counter is the
        only engine state touched (itertools.count is atomic)."""
        from pinot_tpu.ops import residency as residency_mod
        residency_mod.note_transfer(arr.nbytes, column=True)
        self._meter("hbm_transfer_bytes", arr.nbytes)
        if self._mesh is not None and len(self.devices) > 1:
            dev = self.devices[next(self._row_rr) % len(self.devices)]
            return jax.device_put(arr, dev)
        return jnp.asarray(arr)

    @staticmethod
    def _dev_label(arr) -> str:
        """`platform:id` label of the device holding a committed row —
        the key the per-chip residency ledger and `device=` gauges use."""
        try:
            d = next(iter(arr.devices()))
            return f"{d.platform}:{d.id}"
        except Exception:  # pragma: no cover — non-array stand-ins
            return "cpu:0"

    def _reshard_block(self, dev):
        """Move an assembled single-device block onto the mesh sharding
        kernels expect (device-to-device; never the host link)."""
        if self._mesh is None:
            return dev
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P("segments", "docs") if self._doc_axis > 1 \
            else P("segments", None)
        return jax.device_put(dev, NamedSharding(self._mesh, spec))

    def _meter(self, name: str, value: float = 1) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(name, value, labels=self._labels)

    def _refresh_tier_gauges(self) -> None:
        if self._metrics is None:
            return
        self._metrics.set_gauge(
            "hbm_cache_bytes", self._cache_bytes + self._residency.bytes,
            labels=self._labels)
        self._metrics.set_gauge("host_row_cache_bytes", self._host_bytes,
                                labels=self._labels)
        if len(self.devices) > 1:
            # per-chip split: assembled blocks are sharded evenly over
            # the mesh (equal per-chip share of _cache_bytes); resident
            # rows are committed whole to one chip each, so their bytes
            # attribute exactly (the skew admission control watches)
            by_dev = self._residency.bytes_by_device()
            share = self._cache_bytes // len(self.devices)
            for d in self.devices:
                lab = f"{d.platform}:{d.id}"
                labels = dict(self._labels or {})
                labels["device"] = lab
                self._metrics.set_gauge(
                    "hbm_cache_bytes", share + by_dev.get(lab, 0),
                    labels=labels)
                self._metrics.set_gauge(
                    "hbm_resident_bytes", by_dev.get(lab, 0),
                    labels=labels)

    def _insert_block(self, key, entry, nbytes: int) -> None:
        if key not in self._block_cache:
            self._batch_blocks[key[0]] = \
                self._batch_blocks.get(key[0], 0) + 1
        else:
            self._cache_bytes -= self._block_bytes[key]
        self._block_cache[key] = entry
        self._block_bytes[key] = nbytes
        self._cache_bytes += nbytes
        while self._cache_bytes > self.cache_budget_bytes and len(self._block_cache) > 1:
            # drop the reference only — the current query and concurrent
            # dispatches hold evicted blocks as kernel inputs; refcounting
            # frees the HBM when the last consumer finishes
            old_key, _entry = self._block_cache.popitem(last=False)
            self._cache_bytes -= self._block_bytes.pop(old_key)
            self._meter("hbm_evicted")
            self._drop_batch_block(old_key[0])
        self._refresh_tier_gauges()

    def _drop_batch_block(self, batch: tuple) -> None:
        """One block of `batch` left the cache; when it was the LAST,
        the batch's predicate params can never pair with a live block
        again — drop them now instead of stranding them until global
        LRU pressure (params key on (batch, plan, filter)). The
        refcount keeps the common case O(1); the bounded params scan
        runs once per batch death, not per eviction."""
        n = self._batch_blocks.get(batch, 1) - 1
        if n > 0:
            self._batch_blocks[batch] = n
            return
        self._batch_blocks.pop(batch, None)
        for pk in [k for k in self._params_cache if k[0] == batch]:
            del self._params_cache[pk]

    # ------------------------------------------------------------------
    # residency lifecycle (invalidation, warmup seeding, proactive load)
    # ------------------------------------------------------------------
    @property
    def residency(self):
        return self._residency

    def residency_seeding(self):
        """Context manager marking staging as warmup-driven: resident-row
        admissions bypass the frequency duel and carry the seed boost
        (cache/warmup.py replay calls this around each plan)."""
        return self._residency.seeding()

    def invalidate_segment(self, name: str, keep=None) -> None:
        """Drop every cached artifact for a replaced/removed segment
        NAME — resident rows, assembled blocks, host rows, predicate
        params — sparing entries pinned to `keep` (the just-warmed live
        object). Identity keying already makes stale entries
        unreachable; this reclaims their HBM/host bytes promptly, on the
        same epoch-moving events the result caches invalidate on."""
        with self._engine_lock:
            def stale(seg) -> bool:
                return seg.name == name and (keep is None or seg is not keep)

            for k in [k for k, (segs, _d) in self._block_cache.items()
                      if any(stale(s) for s in segs)]:
                del self._block_cache[k]
                self._cache_bytes -= self._block_bytes.pop(k)
                self._drop_batch_block(k[0])
            for k in [k for k, v in self._host_rows.items() if stale(v[0])]:
                _s, payload = self._host_rows.pop(k)
                self._host_bytes -= _entry_nbytes(payload)
            for k in [k for k, v in self._params_cache.items()
                      if any(stale(s) for s in v[0])]:
                del self._params_cache[k]
            self._residency.invalidate_segment(name, keep=keep)
            self._refresh_tier_gauges()

    def drop_caches(self, host: bool = True) -> None:
        """Bench/test hook: release the device tier (assembled blocks +
        resident rows + params); host=True also drops host rows — the
        fully cold replica state."""
        with self._engine_lock:
            self._block_cache.clear()
            self._block_bytes.clear()
            self._batch_blocks.clear()
            self._cache_bytes = 0
            self._params_cache.clear()
            self._residency.drop_all()
            if host:
                self._host_rows.clear()
                self._host_bytes = 0
            self._refresh_tier_gauges()

    def prestage(self, segments, ctx: QueryContext) -> bool:
        """Proactively stage a plan's columns into the device tier
        WITHOUT launching a kernel — the segment-load warmup path: replay
        stages the hot plans' columns into HBM before the segment
        serves, so its first routed query pays compute, not the link."""
        if not segments or ctx.distinct or not self.supports(ctx):
            return False
        with self._engine_lock:
            if ctx.aggregations and self._startree_candidate(segments):
                # star-tree leg first, mirroring execute's routing: a
                # plan that will serve from pre-agg records must warm
                # THOSE blocks, not the raw scan columns
                st_plan, _needed, fits, _reason = \
                    startree_device.plan_startree(segments, ctx)
                if st_plan is not None:
                    kern = startree_device.compiled_startree_kernel(
                        st_plan)
                    try:
                        self._stage_startree_locked(
                            segments, ctx, st_plan, fits,
                            batchable=isinstance(kern,
                                                 jax.stages.Wrapped))
                        return True
                    except _NotStageable:
                        pass
            if ctx.aggregations:
                plan_info = self._plan(segments, ctx)
                plan = plan_info[0] if plan_info is not None else None
                kern = None if plan is None \
                    else (kernels.compiled_sharded_kernel(plan, self._mesh)
                          if self._doc_axis > 1
                          else kernels.compiled_kernel(plan))
            else:
                plan = self._plan_topn(segments, ctx)
                kern = None if plan is None \
                    else kernels.compiled_topn_kernel(plan)
            if plan is None:
                return False
            try:
                # mirror the serving path's S bucket (agg AND top-N
                # launches ride the factory now) so warmed blocks are
                # the EXACT blocks the first routed query will consume
                self._stage(segments, ctx, plan,
                            batchable=isinstance(kern, jax.stages.Wrapped))
            except _NotStageable:
                return False
        return True

    @staticmethod
    def _int_ir_bounds(segments, ir) -> Optional[Tuple[int, int]]:
        """Interval bounds of an int-valued value IR over the batch's
        metadata, or None when any column is non-int / unbounded or any
        node (incl. intermediates) can overflow i32 — the admission test
        for the exact 'isum' device path (kernels._eval_value_int)."""
        LIM = (1 << 31) - 1

        def rec(node) -> Optional[Tuple[int, int]]:
            op = node[0]
            if op == "col":
                lo, hi = None, None
                for seg in segments:
                    m = seg.metadata.columns.get(node[1])
                    if m is None or m.data_type.np_dtype.kind not in "iu" \
                            or m.min_value is None or m.max_value is None:
                        return None
                    lo = int(m.min_value) if lo is None \
                        else min(lo, int(m.min_value))
                    hi = int(m.max_value) if hi is None \
                        else max(hi, int(m.max_value))
                return (lo, hi) if lo is not None else None
            if op == "lit":
                v = float(node[1])
                if not v.is_integer():
                    return None
                return _clamp((int(v), int(v)))
            if op == "neg":
                a = rec(node[1])
                return None if a is None else _clamp((-a[1], -a[0]))
            if op not in ("add", "sub", "mul"):
                return None
            a, b = rec(node[1]), rec(node[2])
            if a is None or b is None:
                return None
            if op == "add":
                out = (a[0] + b[0], a[1] + b[1])
            elif op == "sub":
                out = (a[0] - b[1], a[1] - b[0])
            else:
                corners = [x * y for x in a for y in b]
                out = (min(corners), max(corners))
            return _clamp(out)

        def _clamp(bounds):
            return bounds if -LIM <= bounds[0] and bounds[1] <= LIM else None

        return rec(ir)

    @staticmethod
    def _hist_bounds(segments, col: str) -> Tuple[float, float]:
        """Global (lo, span) histogram bounds over the batch's segment
        metadata min/max; span clamped positive so scale stays finite."""
        lo, hi = np.inf, -np.inf
        for seg in segments:
            m = seg.metadata.columns.get(col)
            if m is None or m.min_value is None or m.max_value is None:
                raise _NotStageable()
            lo = min(lo, float(m.min_value))
            hi = max(hi, float(m.max_value))
        return lo, max(hi - lo, 1e-30)

    def _check_value_precision(self, segments, col: str, vdt) -> None:
        """float32 staging (x64 off, the TPU default) is exact only for
        integers with |v| <= 2^24; larger int/long columns (e.g. epoch
        millis) would silently round, so they fall back to the exact-f64
        host path. Float columns stay f32: they are approximate either way.
        """
        if vdt is np.float64:
            return
        for seg in segments:
            m = seg.metadata.columns.get(col)
            if m is None or m.data_type.np_dtype.kind not in "iu":
                continue
            lo, hi = m.min_value, m.max_value
            if lo is None or hi is None or \
                    max(abs(int(lo)), abs(int(hi))) > (1 << 24):
                raise _NotStageable()

    def _put(self, arr: np.ndarray, block: bool = False):
        """block=True marks [S, D] column blocks, which also shard over the
        docs axis on a 2-axis mesh; params/bounds shard over segments only.
        Every byte through here feeds the host->device transfer odometer
        (residency.transfer_bytes) — steady state must keep it flat."""
        from pinot_tpu.ops import residency as residency_mod
        residency_mod.note_transfer(arr.nbytes, column=block)
        self._meter("hbm_transfer_bytes", arr.nbytes)
        if self._mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P
        if block and self._doc_axis > 1 and arr.ndim == 2:
            spec = P("segments", "docs")
        else:
            spec = P("segments", *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self._mesh, spec))

    @staticmethod
    def _ir_cols(ir) -> set:
        if ir is None:
            return set()
        if ir[0] == "col":
            return {ir[1]}
        out = set()
        for c in ir[1:]:
            if isinstance(c, tuple):
                out |= TpuOperatorExecutor._ir_cols(c)
        return out

    def _collect_leaf_exprs(self, e: Expression, plan: DevicePlan) -> List[Function]:
        """Leaf expressions in the same order _build_filter_ir assigned
        indexes (depth-first, left-to-right)."""
        out: List[Function] = []

        def walk(node):
            assert isinstance(node, Function)
            if node.name in ("and", "or"):
                for a in node.args:
                    walk(a)
            elif node.name == "not":
                walk(node.args[0])
            else:
                out.append(node)
        walk(e)
        return out

    # ------------------------------------------------------------------
    def _assemble(self, segments, ctx: QueryContext, plan: DevicePlan,
                  packed: np.ndarray, S_real: int,
                  mappings: List[Dict[str, int]]) -> List[Any]:
        filter_cols = len(set(ctx.filter_columns()))
        # parity with executor_cpu: COUNT(*) materializes no column, so it
        # doesn't contribute to entries-scanned-post-filter
        n_valued_aggs = sum(
            1 for node in ctx.aggregations
            if node.args and not (isinstance(node.args[0], Identifier)
                                  and node.args[0].name == "*"))
        count_j = None
        widths = [kernels.slot_width(op) for op, _v, _f in plan.agg_ops]
        slot_offsets = np.concatenate(
            [[0], np.cumsum(widths)]).astype(int)
        # hist bucket bounds are batch-global: compute once per slot, not
        # per segment x function
        hist_bounds = {
            j: self._hist_bounds(segments, plan.value_irs[vidx][1])
            for j, (op, vidx, _f) in enumerate(plan.agg_ops)
            if op.startswith("hist:")}
        is_group = bool(plan.num_groups or plan.group_compact)
        if is_group:
            for j, (op, _vidx, fidx) in enumerate(plan.agg_ops):
                if op == "count" and fidx is None:
                    count_j = j
                    break
            assert count_j is not None  # _plan guarantees a count slot
        results = []
        for s, seg in enumerate(segments[:S_real]):
            if is_group:
                matched = int(round(float(packed[s, :, count_j].sum())))
            else:
                matched = int(round(float(packed[s, 0])))
            stats = ExecutionStats(
                num_docs_scanned=matched,
                num_entries_scanned_in_filter=(
                    seg.num_docs * filter_cols if ctx.filter is not None else 0),
                num_entries_scanned_post_filter=matched * n_valued_aggs,
                num_segments_processed=1,
                num_segments_matched=1 if matched else 0,
                total_docs=seg.num_docs)
            if is_group:
                results.append(self._assemble_group(
                    seg, s, ctx, plan, packed, count_j, mappings, stats))
            else:
                inters = []
                for fn, mapping in zip(ctx.agg_functions, mappings):
                    slots = {}
                    for op, j in mapping.items():
                        off = 1 + slot_offsets[j]
                        w = widths[j]
                        plan_op = plan.agg_ops[j][0]
                        if plan_op == "isum":
                            slots[op] = _isum_value(packed[s, off:off + w])
                            continue
                        if plan_op.startswith("isum:u"):
                            slots[op] = _isum_u_value(
                                packed[s, off:off + w])
                            continue
                        slots[op] = packed[s, off] if w == 1 \
                            else packed[s, off:off + w]
                        if op.startswith("hist:"):
                            lo, span = hist_bounds[j]
                            slots["hist_lo"] = lo
                            slots["hist_width"] = span / w
                    inters.append(fn.from_device_slots(slots))
                results.append(AggregationResult(inters, stats))
        return results

    def _assemble_group(self, seg, s, ctx, plan, packed, count_j, mappings, stats):
        present = np.nonzero(packed[s, :, count_j] > 0)[0]

        dicts = [seg.data_source(c).dictionary for c in plan.group_cols]
        buckets = None
        if plan.group_compact:
            # compacted codes -> per-column dictIds via the decode table
            _codes, table = self._segment_gkey(seg, plan)
            present = present[present < table.shape[0]]
            ids_per_col = [table[present, j]
                           for j in range(len(plan.group_cols))]
        else:
            # decode combined keys (mixed radix) -> per-column dictIds
            cards = [seg.metadata.columns[c].cardinality
                     for c in plan.group_cols]
            rem = present.copy()
            ids_per_col = []
            for stride in plan.group_strides:
                ids_per_col.append(rem // stride)
                rem = rem % stride
            if plan.tbucket:
                # the fused time bucket is the key's lowest digit: after
                # peeling every tag stride, the remainder IS the bucket
                buckets = rem
            valid = np.ones(len(present), dtype=bool)
            for ids, card in zip(ids_per_col, cards):
                valid &= ids < card
            present = present[valid]
            ids_per_col = [ids[valid] for ids in ids_per_col]
            if buckets is not None:
                buckets = buckets[valid]

        key_cols = [d.get_values(ids) for d, ids in zip(dicts, ids_per_col)]
        groups: Dict[tuple, list] = {}
        for gi, g in enumerate(present):
            key = tuple(_py(col[gi]) for col in key_cols)
            if buckets is not None:
                # host parity: floor() over the f64 division yields a
                # float group key
                key = (float(buckets[gi]),) + key
            inters = []
            for fn, mapping in zip(ctx.agg_functions, mappings):
                slots = {op: packed[s, g, j] for op, j in mapping.items()}
                inters.append(fn.from_device_slots(slots))
            groups[key] = inters
        return GroupByResult(groups, stats)

    def _assemble_merged(self, segments, ctx: QueryContext,
                         plan: DevicePlan, packed: np.ndarray,
                         S_real: int, mappings: List[Dict[str, int]],
                         minfo) -> List[Any]:
        """ONE result covering the whole segment batch, from the
        collective-merge kernel's packed row (layout documented in
        ops/collective.py). The [S] matched tail carries exactly the
        per-segment facts the host fold would have summed, so the
        ExecutionStats equal folding the per-segment path's stats."""
        S = minfo["S"]
        matched_i = [int(round(float(m)))
                     for m in np.asarray(packed[-S:][:S_real])]
        total_matched = sum(matched_i)
        filter_cols = len(set(ctx.filter_columns()))
        n_valued_aggs = sum(
            1 for node in ctx.aggregations
            if node.args and not (isinstance(node.args[0], Identifier)
                                  and node.args[0].name == "*"))
        stats = ExecutionStats(
            num_docs_scanned=total_matched,
            num_entries_scanned_in_filter=(
                sum(seg.num_docs for seg in segments[:S_real])
                * filter_cols if ctx.filter is not None else 0),
            num_entries_scanned_post_filter=total_matched * n_valued_aggs,
            num_segments_processed=S_real,
            num_segments_matched=sum(1 for m in matched_i if m),
            total_docs=sum(seg.num_docs for seg in segments[:S_real]))
        if plan.group_cols:
            return [self._assemble_merged_group(ctx, plan, packed,
                                                mappings, minfo, stats)]
        widths = [kernels.slot_width(op) for op, _v, _f in plan.agg_ops]
        slot_offsets = np.concatenate(
            [[0], np.cumsum(widths)]).astype(int)
        hist_bounds = {
            j: self._hist_bounds(segments, plan.value_irs[vidx][1])
            for j, (op, vidx, _f) in enumerate(plan.agg_ops)
            if op.startswith("hist:")}
        inters = []
        for fn, mapping in zip(ctx.agg_functions, mappings):
            slots = {}
            for op, j in mapping.items():
                off = int(slot_offsets[j])  # no leading matched column
                w = widths[j]
                plan_op = plan.agg_ops[j][0]
                if plan_op == "isum":
                    slots[op] = _isum_value(packed[off:off + w])
                    continue
                if plan_op.startswith("isum:u"):
                    slots[op] = _isum_u_value(packed[off:off + w])
                    continue
                slots[op] = packed[off] if w == 1 \
                    else packed[off:off + w]
                if op.startswith("hist:"):
                    lo, span = hist_bounds[j]
                    slots["hist_lo"] = lo
                    slots["hist_width"] = span / w
            inters.append(fn.from_device_slots(slots))
        return [AggregationResult(inters, stats)]

    def _assemble_merged_group(self, ctx, plan: DevicePlan, packed,
                               mappings, minfo, stats):
        G = minfo["G"]
        n_slots = len(plan.agg_ops)
        gp = np.asarray(packed[:G * n_slots]).reshape(G, n_slots)
        count_j = None
        for j, (op, _vidx, fidx) in enumerate(plan.agg_ops):
            if op == "count" and fidx is None:
                count_j = j
                break
        assert count_j is not None  # _plan guarantees a count slot
        present = np.nonzero(gp[:, count_j] > 0)[0]
        present = present[present < minfo["n_real"]]
        decode = minfo["decode"]
        if plan.group_compact:
            keys = [decode[g] for g in present]
        else:
            strides, cards, unions = decode
            keys = [tuple(_py(unions[ci][(g // strides[ci]) % cards[ci]])
                          for ci in range(len(plan.group_cols)))
                    for g in present]
        groups: Dict[tuple, list] = {}
        for gi, g in enumerate(present):
            inters = []
            for fn, mapping in zip(ctx.agg_functions, mappings):
                slots = {op: gp[g, j] for op, j in mapping.items()}
                inters.append(fn.from_device_slots(slots))
            groups[keys[gi]] = inters
        return GroupByResult(groups, stats)


def _isum_value(planes: np.ndarray) -> float:
    """Rebuild the exact int sum from the isum slot's packed planes
    (kernels._isum_slot): pairs of f32-exact signed (hi, lo) halves per
    6-bit value digit, top digit sign-carrying."""
    total = 0
    for k in range(kernels.ISUM_PLANES):
        s = int(planes[2 * k]) * 4096 + int(planes[2 * k + 1])
        total += s << (6 * k)
    return float(total)


def _isum_u_value(planes: np.ndarray) -> float:
    """Rebuild the exact non-negative int sum from unsigned 7-bit plane
    halves (kernels._isum_u_slot)."""
    total = 0
    for k in range(len(planes) // 2):
        s = int(planes[2 * k]) * 4096 + int(planes[2 * k + 1])
        total += s << (kernels.ISUM_U_BITS * k)
    return float(total)


def _entry_nbytes(a) -> int:
    """Bytes of a host-row cache payload (array, or (codes, table))."""
    if isinstance(a, tuple):
        return sum(x.nbytes for x in a)
    return a.nbytes


def _batch_id(segments) -> tuple:
    """Identity of a segment batch: id() alone can be reused after GC, so
    pair it with the segment name."""
    return tuple((id(s), s.name) for s in segments)


def _shape_sig(cols: Dict[str, Any], params: Dict[str, Any]) -> tuple:
    """Shape signature of a staged launch — the part of the coalesce
    key that plan + (S, D, G) alone cannot pin down across tables: LUT
    leaf widths pad to each table's own cardinality bucket and dict-id
    blocks stage at cardinality-chosen widths (i8/i16/i32), so two
    tables with equal plans can still stage unstackable pytrees. Equal
    signatures guarantee members stack leaf-for-leaf."""
    return (
        tuple(sorted((k, tuple(map(int, v.shape)), str(v.dtype))
                     for k, v in cols.items())),
        tuple(sorted((k, tuple(map(int, v.shape)), str(v.dtype))
                     for k, v in params.items())),
    )


class _NotStageable(Exception):
    pass


class _MergeFallback(Exception):
    """A collective-merge gate tripped; the launch keeps the per-segment
    kernel and the host fold (reason feeds mesh_merge_fallback)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _vrange_bounds(e: Function, vdt=np.float64) -> Tuple[float, float]:
    """Closed [lo, hi] bounds for a raw-value comparison, computed in the
    STAGING dtype vdt: nextafter in float64 would collapse back to the
    original value when later cast to float32, silently turning strict
    comparisons into non-strict ones (x > 5 executing as x >= 5)."""
    def lv(i):
        raw = e.args[i].value  # type: ignore[union-attr]
        try:
            if isinstance(raw, str):
                raw = int(raw) if raw.lstrip("+-").isdigit() else float(raw)
            v = vdt(raw)
            # a literal not exactly representable in the staging dtype (e.g.
            # 16777217 in f32, 2^53+1 in f64) would alias to a neighbour and
            # match rows the exact host path would not — fall back instead.
            # Compare in exact Python arithmetic: int(v)/float(v) vs raw
            # avoids rounding the reference side through the staging dtype.
            exact = (int(v) if isinstance(raw, int) and float(v).is_integer()
                     else float(v))
            if exact != raw:
                raise _NotStageable()
        except (OverflowError, ValueError, TypeError):
            raise _NotStageable() from None
        return v
    if e.name == "equals":
        return lv(1), lv(1)
    if e.name == "between":
        return lv(1), lv(2)
    if e.name == "greater_than":
        return np.nextafter(lv(1), vdt(np.inf)), vdt(np.inf)
    if e.name == "greater_than_or_equal":
        return lv(1), vdt(np.inf)
    if e.name == "less_than":
        return vdt(-np.inf), np.nextafter(lv(1), vdt(-np.inf))
    if e.name == "less_than_or_equal":
        return vdt(-np.inf), lv(1)
    raise _NotStageable()


_INT_BOUND_CLAMP = 1 << 54  # split planes stay exact below 2^55


def _vrange_int_bounds(e: Function) -> Tuple[int, int]:
    """Closed [lo, hi] INTEGER bounds for a comparison on an int column
    (vrange64 leaves). Exact Python integer arithmetic throughout."""
    import math

    def lv(i):
        raw = e.args[i].value  # type: ignore[union-attr]
        try:
            if isinstance(raw, str):
                raw = int(raw) if raw.lstrip("+-").isdigit() else float(raw)
            if isinstance(raw, bool) or raw is None:
                raise _NotStageable()
            if isinstance(raw, float) and not math.isfinite(raw):
                raise _NotStageable()  # ceil/floor of inf/nan would raise
            return raw
        except (ValueError, TypeError, OverflowError):
            raise _NotStageable() from None

    def clamp(v: int) -> int:
        return max(-_INT_BOUND_CLAMP, min(_INT_BOUND_CLAMP, v))

    if e.name == "equals":
        v = lv(1)
        if isinstance(v, float):
            if not v.is_integer():
                return 1, 0  # empty interval
            v = int(v)
        return clamp(v), clamp(v)
    if e.name == "between":
        a, b = lv(1), lv(2)
        return clamp(math.ceil(a)), clamp(math.floor(b))
    if e.name == "greater_than":
        return clamp(math.floor(lv(1)) + 1), _INT_BOUND_CLAMP
    if e.name == "greater_than_or_equal":
        return clamp(math.ceil(lv(1))), _INT_BOUND_CLAMP
    if e.name == "less_than":
        return -_INT_BOUND_CLAMP, clamp(math.ceil(lv(1)) - 1)
    if e.name == "less_than_or_equal":
        return -_INT_BOUND_CLAMP, clamp(math.floor(lv(1)))
    raise _NotStageable()


def _py(v):
    return v.item() if isinstance(v, np.generic) else v
