"""jit'd device kernels built from a DevicePlan.

The kernel computes, for stacked segment blocks [S, D]:
  mask  = filter tree over dictId compares / LUT gathers     (VPU, fused)
  vals  = dictionary-value gathers + arithmetic              (fused)
  out   = masked reductions (sum/min/max/count/sumsq) or
          group-keyed scatter-add / one-hot matmul partials  (MXU for matmul)
returning per-segment partials — the host (or a psum over the mesh) merges.

Everything is shape-static: jit re-specializes per (S, D, C, G) bucket and
the engine pads inputs to bucketed sizes to bound recompiles
(SURVEY.md §7 hard-parts note on retrace storms).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from pinot_tpu.ops.plan_ir import DeviceLeaf, DevicePlan

# group-by cardinality below which the one-hot matmul path (MXU-friendly)
# is used instead of scatter-add
ONEHOT_MAX_GROUPS = 1024
_ONEHOT_CHUNK = 4096


def _value_dtype() -> jnp.dtype:
    return jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32


# ---------------------------------------------------------------------------
# IR evaluation (runs at trace time)
# ---------------------------------------------------------------------------

def _eval_filter(node, plan: DevicePlan, cols: Dict[str, jnp.ndarray],
                 params: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    op = node[0]
    if op == "and":
        out = _eval_filter(node[1], plan, cols, params)
        for child in node[2:]:
            out = out & _eval_filter(child, plan, cols, params)
        return out
    if op == "or":
        out = _eval_filter(node[1], plan, cols, params)
        for child in node[2:]:
            out = out | _eval_filter(child, plan, cols, params)
        return out
    if op == "not":
        return ~_eval_filter(node[1], plan, cols, params)
    assert op == "leaf"
    i = node[1]
    leaf = plan.leaves[i]
    if leaf.kind == "range":
        ids = cols["ids:" + leaf.column]
        lo = params[f"leaf{i}:lo"][:, None]
        hi = params[f"leaf{i}:hi"][:, None]
        return (ids >= lo) & (ids <= hi)
    if leaf.kind == "neq":
        ids = cols["ids:" + leaf.column]
        return ids != params[f"leaf{i}:idx"][:, None]
    if leaf.kind == "lut":
        ids = cols["ids:" + leaf.column]
        table = params[f"leaf{i}:lut"]  # [S, C] bool
        return jnp.take_along_axis(table, ids, axis=1)
    if leaf.kind == "vrange":
        vals = cols["val:" + leaf.column]
        lo = params[f"leaf{i}:lo"][:, None]
        hi = params[f"leaf{i}:hi"][:, None]
        return (vals >= lo) & (vals <= hi)
    if leaf.kind == "vrange64":
        # exact closed-interval compare on (hi, lo) i32 split planes:
        # lexicographic (hi strictly dominates; lo always in [0, 2^24))
        vhi = cols["valhi:" + leaf.column]
        vlo = cols["vallo:" + leaf.column]
        a_hi = params[f"leaf{i}:lohi"][:, None]
        a_lo = params[f"leaf{i}:lolo"][:, None]
        b_hi = params[f"leaf{i}:hihi"][:, None]
        b_lo = params[f"leaf{i}:hilo"][:, None]
        ge = (vhi > a_hi) | ((vhi == a_hi) & (vlo >= a_lo))
        le = (vhi < b_hi) | ((vhi == b_hi) & (vlo <= b_lo))
        return ge & le
    raise ValueError(f"unknown leaf kind {leaf.kind}")


def _eval_value(ir, cols: Dict[str, jnp.ndarray],
                params: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    op = ir[0]
    if op == "col":
        # value columns are always staged as materialized [S, D] blocks
        # (engine stages dictionary takes host-side; in-kernel gathers
        # measured ~8x slower on TPU)
        return cols["val:" + ir[1]]
    if op == "ids":
        return cols["ids:" + ir[1]]
    if op == "lit":
        return jnp.asarray(ir[1], dtype=_value_dtype())
    a = _eval_value(ir[1], cols, params)
    if op == "neg":
        return -a
    b = _eval_value(ir[2], cols, params)
    if op == "add":
        return a + b
    if op == "sub":
        return a - b
    if op == "mul":
        return a * b
    if op == "div":
        return a / b
    raise ValueError(f"unknown value ir op {ir[0]}")


# ---------------------------------------------------------------------------
# Reductions
# ---------------------------------------------------------------------------

def _masked_reduce(op: str, vals: Optional[jnp.ndarray], mask: jnp.ndarray,
                   valid: jnp.ndarray) -> jnp.ndarray:
    """[S, D] -> [S] masked reduction. `valid` excludes padding docs."""
    m = mask & valid
    dt = _value_dtype()
    if op == "count":
        return jnp.sum(m, axis=1).astype(dt)
    assert vals is not None
    if op == "sum":
        return jnp.sum(jnp.where(m, vals, 0), axis=1, dtype=dt)
    if op == "sumsq":
        return jnp.sum(jnp.where(m, vals * vals, 0), axis=1, dtype=dt)
    if op == "sum3":
        return jnp.sum(jnp.where(m, vals * vals * vals, 0), axis=1, dtype=dt)
    if op == "sum4":
        v2 = vals * vals
        return jnp.sum(jnp.where(m, v2 * v2, 0), axis=1, dtype=dt)
    if op == "min":
        return jnp.min(jnp.where(m, vals, jnp.inf), axis=1)
    if op == "max":
        return jnp.max(jnp.where(m, vals, -jnp.inf), axis=1)
    raise ValueError(f"unknown reduction {op}")


def _grouped_reduce(op: str, vals: Optional[jnp.ndarray], keys: jnp.ndarray,
                    mask: jnp.ndarray, valid: jnp.ndarray,
                    num_groups: int) -> jnp.ndarray:
    """[S, D] + keys [S, D] -> [S, G] per-group partials."""
    m = mask & valid
    dt = _value_dtype()
    safe_keys = jnp.where(m, keys, 0)
    if op == "count":
        contrib = m.astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    assert vals is not None
    if op == "sum":
        contrib = jnp.where(m, vals, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "sumsq":
        contrib = jnp.where(m, vals * vals, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "sum3":
        contrib = jnp.where(m, vals * vals * vals, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "sum4":
        v2 = vals * vals
        contrib = jnp.where(m, v2 * v2, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "min":
        init = jnp.full((vals.shape[0], num_groups), jnp.inf, dtype=vals.dtype)
        v = jnp.where(m, vals, jnp.inf)
        return _vmap_scatter(init, safe_keys, v, "min")
    if op == "max":
        init = jnp.full((vals.shape[0], num_groups), -jnp.inf, dtype=vals.dtype)
        v = jnp.where(m, vals, -jnp.inf)
        return _vmap_scatter(init, safe_keys, v, "max")
    raise ValueError(f"unknown grouped reduction {op}")


def _scatter_sum(contrib: jnp.ndarray, keys: jnp.ndarray,
                 num_groups: int) -> jnp.ndarray:
    """Sum contributions per group key.

    Small key spaces ride the MXU as a chunked one-hot matmul
    (SURVEY.md §7: group-bys become one-hot/segment-sum scatter-adds);
    large ones fall back to XLA scatter-add.
    """
    S, D = contrib.shape
    if num_groups <= ONEHOT_MAX_GROUPS and D >= _ONEHOT_CHUNK:
        nchunk = D // _ONEHOT_CHUNK
        main = nchunk * _ONEHOT_CHUNK

        def body(carry, xs):
            k, c = xs  # [S, CH]
            onehot = jax.nn.one_hot(k, num_groups, dtype=c.dtype, axis=-1)
            return carry + jnp.einsum("sdg,sd->sg", onehot, c), None

        k_chunks = keys[:, :main].reshape(S, nchunk, _ONEHOT_CHUNK).swapaxes(0, 1)
        c_chunks = contrib[:, :main].reshape(S, nchunk, _ONEHOT_CHUNK).swapaxes(0, 1)
        out, _ = jax.lax.scan(body, jnp.zeros((S, num_groups), contrib.dtype),
                              (k_chunks, c_chunks))
        if main < D:
            out = _vmap_scatter(out, keys[:, main:], contrib[:, main:], "add")
        return out
    return _vmap_scatter(jnp.zeros((S, num_groups), contrib.dtype), keys,
                         contrib, "add")


def _vmap_scatter(init: jnp.ndarray, keys: jnp.ndarray, vals: jnp.ndarray,
                  mode: str) -> jnp.ndarray:
    def one(acc, k, v):
        if mode == "add":
            return acc.at[k].add(v)
        if mode == "min":
            return acc.at[k].min(v)
        return acc.at[k].max(v)
    return jax.vmap(one)(init, keys, vals)


# ---------------------------------------------------------------------------
# Kernel assembly
# ---------------------------------------------------------------------------

def _compute_slots(plan: DevicePlan, cols, params, valid, G: int = 0):
    """Shared kernel body: filter + values + per-slot reductions over a
    (possibly shard-local) [S, D] block. Returns
    ([(op, [S]- or [S, G]-array)], matched_count [S] or None).
    G: group count for compact-key plans (plan.num_groups is 0 there)."""
    dt = _value_dtype()
    if plan.filter_ir is not None:
        mask = _eval_filter(plan.filter_ir, plan, cols, params)
    else:
        mask = jnp.ones(valid.shape, dtype=bool)
    # per-aggregation FILTER (WHERE ...) masks AND into the main mask
    # per slot (ref FilteredAggregationOperator)
    agg_masks = [_eval_filter(ir, plan, cols, params)
                 for ir in plan.agg_filter_irs]

    values = []
    for ir in plan.value_irs:
        values.append(None if ir is None else _eval_value(ir, cols, params))

    slots = []
    num_groups = plan.num_groups or G
    if num_groups:
        if plan.group_compact:
            keys = cols["gkey"]
        else:
            keys = jnp.zeros(valid.shape, dtype=jnp.int32)
            for col, stride in zip(plan.group_cols, plan.group_strides):
                keys = keys + cols["ids:" + col] * jnp.int32(stride)
        for op, vidx, fidx in plan.agg_ops:
            vals = None if vidx is None else values[vidx]
            m = mask if fidx is None else mask & agg_masks[fidx]
            slots.append((op, _grouped_reduce(op, vals, keys, m, valid,
                                              num_groups)))
        return slots, None
    matched = jnp.sum(mask & valid, axis=1).astype(dt)
    for op, vidx, fidx in plan.agg_ops:
        vals = None if vidx is None else values[vidx]
        m = mask if fidx is None else mask & agg_masks[fidx]
        slots.append((op, _masked_reduce(op, vals, m, valid)))
    return slots, matched


def make_kernel(plan: DevicePlan):
    """Build the traced kernel fn(cols, params, num_docs, D) -> packed array.

    cols:    dict of 'ids:<col>' int32 [S, D] / 'val:<col>' float [S, D]
    params:  dict of per-leaf predicate arrays ('leaf<i>:lo/hi/idx/lut')
    num_docs: int32 [S] actual docs per segment (for the padding mask).

    Returns ONE packed array — a single device->host fetch matters
    because the host<->TPU link can cost O(100ms) per round trip:
      no group-by: [S, 1 + n_slots]  (col 0 = matched doc count)
      group-by:    [S, G, n_slots]   (matched derived from the count
                                      slot host-side)
    Counts ride in the value dtype; exact while D < 2^24 (engine caps
    doc padding below that).
    """

    def kernel(cols, params, num_docs, D, G=0):
        valid = jnp.arange(D, dtype=jnp.int32)[None, :] < num_docs[:, None]
        slots, matched = _compute_slots(plan, cols, params, valid, G)
        if plan.num_groups or G:
            return jnp.stack([s for _, s in slots], axis=-1)
        return jnp.stack([matched] + [s for _, s in slots], axis=-1)

    return kernel


def make_topn_kernel(plan: DevicePlan):
    """Selection / selection-order-by kernel (ref
    operator/query/SelectionOrderByOperator + the min/max-based combine):
    per segment, the top-K doc indices by the order value (value_irs[0];
    ascending negates), or the first K matching docs when unordered.

    Output [S, 1 + K] int32: col 0 = matched doc count, cols 1.. = doc
    indices (-1 = no more matches). The host projects ONLY the winning
    docs — a large filtered SELECT never materializes losing rows.
    """

    def kernel(cols, params, num_docs, D):
        valid = jnp.arange(D, dtype=jnp.int32)[None, :] < num_docs[:, None]
        if plan.filter_ir is not None:
            mask = _eval_filter(plan.filter_ir, plan, cols, params) & valid
        else:
            mask = valid
        dt = _value_dtype()
        if plan.value_irs:
            v = _eval_value(plan.value_irs[0], cols, params).astype(dt)
            score = -v if plan.topn_asc else v
            # tie-break toward lower doc ids so results are stable
        else:
            score = jnp.broadcast_to(
                -jnp.arange(D, dtype=dt)[None, :], mask.shape)
        # clamp matched scores to the finite range so a legitimate -inf
        # score (f32 overflow of huge values, or a real +/-inf column
        # value under ASC negation) still outranks every unmatched doc's
        # -inf sentinel; validity then reads the MASK at the winning docs
        fin = jnp.finfo(dt)
        # NaN order values sort LAST (host sort parity: numpy puts NaN at
        # the end) — clip passes NaN through and top_k would rank it first,
        # so map it to the finite minimum among matched docs
        score = jnp.where(jnp.isnan(score), fin.min, score)
        score = jnp.where(mask, jnp.clip(score, fin.min, fin.max), -jnp.inf)
        k = min(plan.topn_k, D)
        _top_vals, top_idx = jax.lax.top_k(score, k)
        found = jnp.take_along_axis(mask, top_idx, axis=1)
        idx_out = jnp.where(found, top_idx, -1).astype(jnp.int32)
        matched = jnp.sum(mask, axis=1).astype(jnp.int32)
        return jnp.concatenate([matched[:, None], idx_out], axis=1)

    return kernel


@functools.lru_cache(maxsize=256)
def compiled_topn_kernel(plan: DevicePlan):
    return jax.jit(make_topn_kernel(plan), static_argnames=("D",))


@functools.lru_cache(maxsize=256)
def compiled_kernel(plan: DevicePlan):
    """jit-compiled kernel for a plan structure (shape specialization is
    handled inside jit's own cache; D is static because a filterless
    COUNT(*) stages no columns to infer it from; G is the compact-key
    group count — data-dependent, hence a static arg rather than plan
    state)."""
    return jax.jit(make_kernel(plan), static_argnames=("D", "G"))


# ---------------------------------------------------------------------------
# multi-chip: the same kernel under shard_map over a (segments, docs) mesh
# ---------------------------------------------------------------------------

_DOC_COMBINE = {"sum": "psum", "count": "psum", "sumsq": "psum",
                "sum3": "psum", "sum4": "psum",
                "min": "pmin", "max": "pmax"}


def make_sharded_kernel(plan: DevicePlan, mesh):
    """ANY DevicePlan over a (segments x docs) mesh with explicit ICI
    collectives (SURVEY §2.6 rows 6-7): column blocks shard over both axes,
    each device reduces its local [S_loc, D_loc] shard, then partials
    combine with psum/pmin/pmax over the `docs` axis. Per-segment results
    stay sharded over `segments` (the engine assembles them host-side, the
    same contract as the single-chip kernel).

    fn(cols, params, num_docs, D) -> packed array (D static: the padded
    GLOBAL doc count; each shard derives its global doc indices from
    axis_index('docs') — a shard-local arange would restart at 0 and
    mis-mask padding).
    """
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map  # type: ignore

    doc_shards = dict(zip(mesh.axis_names, mesh.devices.shape)).get("docs", 1)

    def local(cols, params, num_docs, D, G=0):
        d_local = D // doc_shards
        doc_pos = (jax.lax.axis_index("docs") * d_local
                   + jnp.arange(d_local, dtype=jnp.int32))[None, :]
        valid = doc_pos < num_docs[:, None]
        slots, matched = _compute_slots(plan, cols, params, valid, G)
        combined = []
        for op, s in slots:
            kind = _DOC_COMBINE[op]
            if kind == "psum":
                combined.append(jax.lax.psum(s, "docs"))
            elif kind == "pmin":
                combined.append(jax.lax.pmin(s, "docs"))
            else:
                combined.append(jax.lax.pmax(s, "docs"))
        if plan.num_groups or G:
            return jnp.stack(combined, axis=-1)
        matched = jax.lax.psum(matched, "docs")
        return jnp.stack([matched] + combined, axis=-1)

    def col_spec(name):
        return P("segments", "docs")  # every staged block is [S, D]

    def param_spec(arr):
        # leaf params: [S] bounds or [S, C] LUTs — segment axis only
        return P("segments", *([None] * (arr.ndim - 1)))

    def fn(cols, params, num_docs, D, G=0):
        in_specs = (
            {k: col_spec(k) for k in cols},
            {k: param_spec(v) for k, v in params.items()},
            P("segments"),
        )
        ndim_out = 3 if (plan.num_groups or G) else 2
        sm = shard_map(
            functools.partial(local, D=D, G=G), mesh=mesh,
            in_specs=in_specs,
            out_specs=P("segments", *([None] * (ndim_out - 1))),
        )
        return sm(cols, params, num_docs)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_sharded_kernel(plan: DevicePlan, mesh):
    return make_sharded_kernel(plan, mesh)
