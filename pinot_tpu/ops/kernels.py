"""jit'd device kernels built from a DevicePlan.

The kernel computes, for stacked segment blocks [S, D]:
  mask  = filter tree over dictId compares / LUT gathers     (VPU, fused)
  vals  = dictionary-value gathers + arithmetic              (fused)
  out   = masked reductions (sum/min/max/count/sumsq) or
          group-keyed scatter-add / one-hot matmul partials  (MXU for matmul)
returning per-segment partials — the host (or a psum over the mesh) merges.

Everything is shape-static: jit re-specializes per (S, D, C, G) bucket and
the engine pads inputs to bucketed sizes to bound recompiles
(SURVEY.md §7 hard-parts note on retrace storms).
"""
from __future__ import annotations

import functools
import hashlib
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from pinot_tpu.ops import clp_device, timeseries_device
from pinot_tpu.ops.plan_ir import DeviceLeaf, DevicePlan

# group-by cardinality below which the one-hot matmul path (MXU-friendly)
# is used instead of scatter-add
ONEHOT_MAX_GROUPS = 1024
_ONEHOT_CHUNK = 4096

# ---------------------------------------------------------------------------
# trace (recompile) accounting: kernel bodies run at TRACE time only, so a
# counter bumped inside them counts XLA compilations, not dispatches. The
# dispatch ring meters the delta as `kernel_retrace` — steady-state traffic
# over warmed shape buckets must keep this flat (a growing count means a
# shape/bucket leak re-compiling the hot path).
#
# Each trace also lands in a bounded log (`trace_log()`) carrying the
# kernel kind, the plan fingerprint, and the shape bucket — so a retrace
# storm is attributable from metrics alone: the per-plan-fingerprint
# `kernel_retrace{plan=...}` label says WHICH plan is churning, and the
# log says WHICH shape buckets it churned through.
# ---------------------------------------------------------------------------
_trace_lock = threading.Lock()
_trace_count = 0
_TRACE_LOG_MAX = 256
_trace_log: "deque" = deque(maxlen=_TRACE_LOG_MAX)
_trace_by_plan: Dict[str, int] = {}


# lint: impure(the compile odometer is DELIBERATELY trace-time-impure: it runs once per trace to count retraces, mutates only under _trace_lock, and contributes nothing to the traced computation)
def note_trace(kind: str = "kernel", plan_fp: str = "",
               bucket: tuple = ()) -> None:
    global _trace_count
    with _trace_lock:
        _trace_count += 1
        if plan_fp:
            _trace_by_plan[plan_fp] = _trace_by_plan.get(plan_fp, 0) + 1
        _trace_log.append({"seq": _trace_count, "kind": kind,
                           "plan": plan_fp, "bucket": tuple(bucket)})


def trace_count() -> int:
    with _trace_lock:
        return _trace_count


def trace_count_by_plan() -> Dict[str, int]:
    """Compile count per plan fingerprint (snapshot)."""
    with _trace_lock:
        return dict(_trace_by_plan)


def trace_log(n: Optional[int] = None) -> List[dict]:
    """The last `n` (default: all retained) compiles, oldest first:
    {seq, kind, plan, bucket} — kind names the kernel variant
    ('agg'/'topn'/'sharded'/'batched'/'batched_stacked'/...), plan is
    plan_fingerprint(), bucket is the traced shape key (B, S, D, G as
    applicable). Feeds retrace-storm forensics without a debugger."""
    with _trace_lock:
        entries = list(_trace_log)
    return entries[-n:] if n is not None else entries


@functools.lru_cache(maxsize=4096)
def plan_fingerprint(plan: DevicePlan) -> str:
    """Short stable id of a plan STRUCTURE (not its literals): the label
    kernels compile under, and the `plan` label on the kernel_retrace
    meter. repr() of the frozen dataclass is deterministic and total."""
    return hashlib.sha1(repr(plan).encode()).hexdigest()[:12]


def _value_dtype() -> jnp.dtype:
    return jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32


@functools.lru_cache(maxsize=1024)
def compiled_row_assembler(S: int, D: int, row_lens: Tuple[int, ...],
                           dtype_str: str):
    """jit'd ON-DEVICE assembly of per-segment resident rows into the
    kernel-ready [S, D] stacked block (ops/residency.py): each row is a
    [Dr_i] device array padded to its segment's own pow2 doc bucket, so
    assembly is a zero-fill plus one dynamic_update_slice per row — HBM
    traffic only, never the host link. One compile per (S, D, row-length
    tuple, dtype) shape; row lengths are pow2 buckets, so the cache stays
    small and steady-state traffic (which hits the assembled-block cache
    and never re-assembles) compiles nothing."""
    dtype = jnp.dtype(dtype_str)

    def assemble(rows):
        note_trace("assembler", bucket=(S, D))
        if len(rows) == S and all(ln == D for ln in row_lens):
            return jnp.stack(rows)
        out = jnp.zeros((S, D), dtype=dtype)
        for i, r in enumerate(rows):
            out = jax.lax.dynamic_update_slice(out, r[None, :], (i, 0))
        return out

    return jax.jit(assemble)


# ---------------------------------------------------------------------------
# IR evaluation (runs at trace time)
# ---------------------------------------------------------------------------

def _eval_filter(node, plan: DevicePlan, cols: Dict[str, jnp.ndarray],
                 params: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    op = node[0]
    if op == "and":
        out = _eval_filter(node[1], plan, cols, params)
        for child in node[2:]:
            out = out & _eval_filter(child, plan, cols, params)
        return out
    if op == "or":
        out = _eval_filter(node[1], plan, cols, params)
        for child in node[2:]:
            out = out | _eval_filter(child, plan, cols, params)
        return out
    if op == "not":
        return ~_eval_filter(node[1], plan, cols, params)
    assert op == "leaf"
    i = node[1]
    leaf = plan.leaves[i]
    if leaf.kind == "range":
        ids = cols["ids:" + leaf.column]
        lo = _clamp_to(params[f"leaf{i}:lo"], ids.dtype)[:, None]
        hi = _clamp_to(params[f"leaf{i}:hi"], ids.dtype)[:, None]
        return (ids >= lo) & (ids <= hi)
    if leaf.kind == "neq":
        ids = cols["ids:" + leaf.column]
        idx = params[f"leaf{i}:idx"]
        if ids.dtype != idx.dtype:
            # -1 and in-range ids fit any narrow id dtype
            idx = jnp.clip(idx, jnp.iinfo(ids.dtype).min,
                           jnp.iinfo(ids.dtype).max).astype(ids.dtype)
        return ids != idx[:, None]
    if leaf.kind == "lut":
        ids = cols["ids:" + leaf.column]
        table = params[f"leaf{i}:lut"]  # [S, C] bool
        return jnp.take_along_axis(table, ids, axis=1)
    if leaf.kind == "vrange":
        vals = cols["val:" + leaf.column]
        lo = params[f"leaf{i}:lo"][:, None]
        hi = params[f"leaf{i}:hi"][:, None]
        return (vals >= lo) & (vals <= hi)
    if leaf.kind == "vrange64":
        # exact closed-interval compare on (hi, lo) i32 split planes:
        # lexicographic (hi strictly dominates; lo always in [0, 2^24))
        vhi = cols["valhi:" + leaf.column]
        vlo = cols["vallo:" + leaf.column]
        a_hi = params[f"leaf{i}:lohi"][:, None]
        a_lo = params[f"leaf{i}:lolo"][:, None]
        b_hi = params[f"leaf{i}:hihi"][:, None]
        b_lo = params[f"leaf{i}:hilo"][:, None]
        ge = (vhi > a_hi) | ((vhi == a_hi) & (vlo >= a_lo))
        le = (vhi < b_hi) | ((vhi == b_hi) & (vlo <= b_lo))
        return ge & le
    if leaf.kind == "clp":
        # LIKE/regex over a CLP log column: candidate-logtype LUT plus
        # variable-slot conditions (ops/clp_device.py)
        return clp_device.eval_leaf(i, leaf, cols, params)
    raise ValueError(f"unknown leaf kind {leaf.kind}")


def _clamp_to(arr, dtype):
    """Compare-bound params clamp into a narrow id dtype so comparisons
    run at the block's native width (an out-of-range sentinel like
    2^31-1 clamps to 'matches everything', preserving semantics)."""
    if arr.dtype == dtype:
        return arr
    info = jnp.iinfo(dtype)
    return jnp.clip(arr, info.min, info.max).astype(dtype)


def _eval_value(ir, cols: Dict[str, jnp.ndarray],
                params: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    op = ir[0]
    if op == "col":
        # value columns are always staged as materialized [S, D] blocks
        # (engine stages dictionary takes host-side; in-kernel gathers
        # measured ~8x slower on TPU)
        return cols["val:" + ir[1]]
    if op == "ids":
        return cols["ids:" + ir[1]]
    if op == "lit":
        return jnp.asarray(ir[1], dtype=_value_dtype())
    a = _eval_value(ir[1], cols, params)
    if op == "neg":
        return -a
    b = _eval_value(ir[2], cols, params)
    if op == "add":
        return a + b
    if op == "sub":
        return a - b
    if op == "mul":
        return a * b
    if op == "div":
        return a / b
    raise ValueError(f"unknown value ir op {ir[0]}")


# ---------------------------------------------------------------------------
# Reductions
# ---------------------------------------------------------------------------

def _masked_reduce(op: str, vals: Optional[jnp.ndarray], mask: jnp.ndarray,
                   valid: jnp.ndarray) -> jnp.ndarray:
    """[S, D] -> [S] masked reduction. `valid` excludes padding docs."""
    m = mask & valid
    dt = _value_dtype()
    if op == "count":
        return jnp.sum(m, axis=1).astype(dt)
    assert vals is not None
    if op == "sum":
        return jnp.sum(jnp.where(m, vals, 0), axis=1, dtype=dt)
    if op == "sumsq":
        return jnp.sum(jnp.where(m, vals * vals, 0), axis=1, dtype=dt)
    if op == "sum3":
        return jnp.sum(jnp.where(m, vals * vals * vals, 0), axis=1, dtype=dt)
    if op == "sum4":
        v2 = vals * vals
        return jnp.sum(jnp.where(m, v2 * v2, 0), axis=1, dtype=dt)
    if op == "min":
        return jnp.min(jnp.where(m, vals, jnp.inf), axis=1)
    if op == "max":
        return jnp.max(jnp.where(m, vals, -jnp.inf), axis=1)
    raise ValueError(f"unknown reduction {op}")


def _grouped_reduce(op: str, vals: Optional[jnp.ndarray], keys: jnp.ndarray,
                    mask: jnp.ndarray, valid: jnp.ndarray,
                    num_groups: int) -> jnp.ndarray:
    """[S, D] + keys [S, D] -> [S, G] per-group partials."""
    m = mask & valid
    dt = _value_dtype()
    safe_keys = jnp.where(m, keys, 0)
    if op == "count":
        contrib = m.astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    assert vals is not None
    if op == "sum":
        contrib = jnp.where(m, vals, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "sumsq":
        contrib = jnp.where(m, vals * vals, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "sum3":
        contrib = jnp.where(m, vals * vals * vals, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "sum4":
        v2 = vals * vals
        contrib = jnp.where(m, v2 * v2, 0).astype(dt)
        return _scatter_sum(contrib, safe_keys, num_groups)
    if op == "min":
        init = jnp.full((vals.shape[0], num_groups), jnp.inf, dtype=vals.dtype)
        v = jnp.where(m, vals, jnp.inf)
        return _vmap_scatter(init, safe_keys, v, "min")
    if op == "max":
        init = jnp.full((vals.shape[0], num_groups), -jnp.inf, dtype=vals.dtype)
        v = jnp.where(m, vals, -jnp.inf)
        return _vmap_scatter(init, safe_keys, v, "max")
    raise ValueError(f"unknown grouped reduction {op}")


def _scatter_sum(contrib: jnp.ndarray, keys: jnp.ndarray,
                 num_groups: int) -> jnp.ndarray:
    """Sum contributions per group key.

    Small key spaces ride the MXU as a chunked one-hot matmul
    (SURVEY.md §7: group-bys become one-hot/segment-sum scatter-adds);
    large ones fall back to XLA scatter-add.
    """
    S, D = contrib.shape
    if num_groups <= ONEHOT_MAX_GROUPS and D >= _ONEHOT_CHUNK:
        nchunk = D // _ONEHOT_CHUNK
        main = nchunk * _ONEHOT_CHUNK

        def body(carry, xs):
            k, c = xs  # [S, CH]
            onehot = jax.nn.one_hot(k, num_groups, dtype=c.dtype, axis=-1)
            return carry + jnp.einsum("sdg,sd->sg", onehot, c), None

        k_chunks = keys[:, :main].reshape(S, nchunk, _ONEHOT_CHUNK).swapaxes(0, 1)
        c_chunks = contrib[:, :main].reshape(S, nchunk, _ONEHOT_CHUNK).swapaxes(0, 1)
        out, _ = jax.lax.scan(body, jnp.zeros((S, num_groups), contrib.dtype),
                              (k_chunks, c_chunks))
        if main < D:
            out = _vmap_scatter(out, keys[:, main:], contrib[:, main:], "add")
        return out
    return _vmap_scatter(jnp.zeros((S, num_groups), contrib.dtype), keys,
                         contrib, "add")


def _vmap_scatter(init: jnp.ndarray, keys: jnp.ndarray, vals: jnp.ndarray,
                  mode: str) -> jnp.ndarray:
    def one(acc, k, v):
        if mode == "add":
            return acc.at[k].add(v)
        if mode == "min":
            return acc.at[k].min(v)
        return acc.at[k].max(v)
    return jax.vmap(one)(init, keys, vals)


# ---------------------------------------------------------------------------
# Sketch slots (device HLL registers / histogram partials)
# ---------------------------------------------------------------------------

def slot_width(op: str) -> int:
    """Per-segment output width of a slot op (1 for scalar reductions;
    sketch ops return register/bucket vectors; isum returns exact-sum
    planes)."""
    if op.startswith("hll:"):
        return 1 << int(op.split(":")[1])
    if op.startswith("hist:"):
        return int(op.split(":")[1])
    if op == "isum":
        return ISUM_WIDTH
    if op.startswith("isum:u"):
        return 2 * int(op.split(":")[1][1:])
    return 1


#: exact integer SUM slot: 6 signed six-bit planes of the i32-evaluated
#: value (v = sum_k plane_k << 6k, top plane arithmetic-shifted so sign
#: rides it), each plane i32-summed exactly (63 * 2^24 docs < 2^31) and
#: returned as f32-exact (hi, lo) 12-bit halves — see _isum_slot
ISUM_PLANES = 6
ISUM_WIDTH = 2 * ISUM_PLANES


def _eval_value_int(ir, cols) -> jnp.ndarray:
    """Evaluate a value IR in EXACT int32 arithmetic (staged f32 blocks
    hold int-exact values <= 2^24; the engine admits only IRs whose
    interval bounds — including every intermediate node — fit i32, so no
    multiply/add here can overflow)."""
    op = ir[0]
    if op == "col":
        return cols["val:" + ir[1]].astype(jnp.int32)
    if op == "lit":
        return jnp.int32(int(ir[1]))
    a = _eval_value_int(ir[1], cols)
    if op == "neg":
        return -a
    b = _eval_value_int(ir[2], cols)
    if op == "add":
        return a + b
    if op == "sub":
        return a - b
    if op == "mul":
        return a * b
    raise ValueError(f"non-exact int ir op {op}")


def _isum_slot(vi, mv) -> jnp.ndarray:
    """Bit-exact SUM of an i32-evaluated value with x64 off: split into
    signed 6-bit planes (digits 0-4 masked, top digit arithmetic-shifted),
    reduce each plane in int32 (never overflows), then split each plane
    sum into two f32-exact 12-bit halves. Host reconstructs
    sum = sum_k (hi_k * 4096 + lo_k) << 6k  (engine _isum_value).
    Ref SumAggregationFunction's exact double accumulation."""
    vi = jnp.where(mv, vi, 0)
    dt = _value_dtype()
    parts = []
    for k in range(ISUM_PLANES):
        if k < ISUM_PLANES - 1:
            p = (vi >> jnp.int32(6 * k)) & jnp.int32(63)
        else:
            p = vi >> jnp.int32(30)  # signed top digit
        s = jnp.sum(p, axis=1, dtype=jnp.int32)
        parts.append((s >> jnp.int32(12)).astype(dt))  # signed hi half
        parts.append((s & jnp.int32(4095)).astype(dt))
    return jnp.stack(parts, axis=1)


#: unsigned isum digit width: 127 * 2^24 docs < 2^31, so 7-bit planes are
#: i32-safe at the engine's doc cap while needing ceil(bits/7) planes —
#: fewer shift+mask+sum passes than the signed 6x6 scheme
ISUM_U_BITS = 7


def _isum_u_slot(op: str, vi, mv) -> jnp.ndarray:
    """Non-negative exact SUM: ceil(bits/7) unsigned planes (plan-time
    bounds prove the value fits), same f32-exact (hi, lo) halves."""
    planes = int(op.split(":")[1][1:])
    vi = jnp.where(mv, vi, 0)
    dt = _value_dtype()
    parts = []
    for k in range(planes):
        p = (vi >> jnp.int32(ISUM_U_BITS * k)) & jnp.int32(127)
        s = jnp.sum(p, axis=1, dtype=jnp.int32)
        parts.append((s >> jnp.int32(12)).astype(dt))
        parts.append((s & jnp.int32(4095)).astype(dt))
    return jnp.stack(parts, axis=1)


def _fmix32(h):
    """murmur3 finalizer — keep in lockstep with sketches._fmix32."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _hll_slot(op: str, cols, mask) -> jnp.ndarray:
    """HLL register partials [S, m]: hash the (hi, lo) i32 split planes,
    bucket by h1's low log2m bits, rank = clz(h2)+1, max-scatter into
    registers (ref DistinctCountHLLAggregationFunction; the scatter is the
    same machinery as the group-by max path). Bit-identical to the host
    sketch (sketches.HyperLogLog.add_array)."""
    _, log2m_s, col = op.split(":", 2)
    m = 1 << int(log2m_s)
    hi = cols["valhi:" + col].astype(jnp.uint32)
    lo = cols["vallo:" + col].astype(jnp.uint32)
    h1 = _fmix32(_fmix32(lo ^ jnp.uint32(0x9E3779B9)) ^ hi)
    h2 = _fmix32(_fmix32(hi ^ jnp.uint32(0x85EBCA77)) ^ lo)
    bucket = (h1 & jnp.uint32(m - 1)).astype(jnp.int32)
    rank = jnp.where(h2 == 0, 33,
                     jax.lax.clz(h2.astype(jnp.int32)) + 1)
    dt = _value_dtype()
    rank = jnp.where(mask, rank, 0).astype(dt)  # 0 = empty register
    bucket = jnp.where(mask, bucket, 0)
    init = jnp.zeros((mask.shape[0], m), dtype=dt)
    return _vmap_scatter(init, bucket, rank, "max")


def _hist_slot(op: str, j: int, vals, params, mask) -> jnp.ndarray:
    """Fixed-bucket histogram partials [S, B] over the value block:
    bucket = clip((v - lo) * scale) then masked scatter-add (feeds
    TDigest centroids host-side, ref PercentileTDigestAggregationFunction)."""
    B = int(op.split(":")[1])
    lo = params[f"slot{j}:hlo"][:, None]
    scale = params[f"slot{j}:hscale"][:, None]
    bucket = jnp.clip((vals - lo) * scale, 0, B - 1).astype(jnp.int32)
    bucket = jnp.where(mask, bucket, 0)
    contrib = mask.astype(_value_dtype())
    return _scatter_sum(contrib, bucket, B)


# ---------------------------------------------------------------------------
# Kernel assembly
# ---------------------------------------------------------------------------

def _compute_slots(plan: DevicePlan, cols, params, valid, G: int = 0):
    """Shared kernel body: filter + values + per-slot reductions over a
    (possibly shard-local) [S, D] block. Returns
    ([(op, [S]- or [S, G]-array)], matched_count [S] or None).
    G: group count for compact-key plans (plan.num_groups is 0 there)."""
    dt = _value_dtype()
    if plan.filter_ir is not None:
        mask = _eval_filter(plan.filter_ir, plan, cols, params)
    else:
        mask = jnp.ones(valid.shape, dtype=bool)
    # per-aggregation FILTER (WHERE ...) masks AND into the main mask
    # per slot (ref FilteredAggregationOperator)
    agg_masks = [_eval_filter(ir, plan, cols, params)
                 for ir in plan.agg_filter_irs]

    values = []
    for ir in plan.value_irs:
        values.append(None if ir is None else _eval_value(ir, cols, params))

    slots = []
    num_groups = plan.num_groups or G
    if num_groups:
        if plan.group_compact:
            keys = cols["gkey"]
        else:
            keys = jnp.zeros(valid.shape, dtype=jnp.int32)
            for col, stride in zip(plan.group_cols, plan.group_strides):
                keys = keys + cols["ids:" + col] * jnp.int32(stride)
        if plan.tbucket:
            # fused time bucket: floor((t - start) / step) from the
            # (hi, lo) raw64 planes becomes the key's lowest digit;
            # out-of-window rows gate out of every slot (their wrapped
            # deltas never reach the scatter)
            tcol, count_pad = plan.tbucket
            b, tgate = timeseries_device.bucket_ids(
                cols["valhi:" + tcol], cols["vallo:" + tcol],
                params["tb:shi"], params["tb:slo"],
                params["tb:step"], params["tb:count"], count_pad)
            keys = keys + b
            mask = mask & tgate
        for op, vidx, fidx in plan.agg_ops:
            vals = None if vidx is None else values[vidx]
            m = mask if fidx is None else mask & agg_masks[fidx]
            slots.append((op, _grouped_reduce(op, vals, keys, m, valid,
                                              num_groups)))
        return slots, None
    matched = jnp.sum(mask & valid, axis=1).astype(dt)
    for j, (op, vidx, fidx) in enumerate(plan.agg_ops):
        m = mask if fidx is None else mask & agg_masks[fidx]
        if op.startswith("hll:"):
            slots.append((op, _hll_slot(op, cols, m & valid)))
            continue
        if op.startswith("hist:"):
            slots.append((op, _hist_slot(op, j, values[vidx], params,
                                         m & valid)))
            continue
        if op == "isum":
            vi = _eval_value_int(plan.value_irs[vidx], cols)
            slots.append((op, _isum_slot(vi, m & valid)))
            continue
        if op.startswith("isum:u"):
            vi = _eval_value_int(plan.value_irs[vidx], cols)
            slots.append((op, _isum_u_slot(op, vi, m & valid)))
            continue
        vals = None if vidx is None else values[vidx]
        slots.append((op, _masked_reduce(op, vals, m, valid)))
    return slots, matched


def make_kernel(plan: DevicePlan, kind: str = "agg", extra: tuple = ()):
    """Build the traced kernel fn(cols, params, num_docs, D) -> packed array.

    cols:    dict of 'ids:<col>' int32 [S, D] / 'val:<col>' float [S, D]
    params:  dict of per-leaf predicate arrays ('leaf<i>:lo/hi/idx/lut')
    num_docs: int32 [S] actual docs per segment (for the padding mask).

    Returns ONE packed array — a single device->host fetch matters
    because the host<->TPU link can cost O(100ms) per round trip:
      no group-by: [S, 1 + n_slots]  (col 0 = matched doc count)
      group-by:    [S, G, n_slots]   (matched derived from the count
                                      slot host-side)
    Counts ride in the value dtype; exact while D < 2^24 (engine caps
    doc padding below that).

    kind/extra label this build's trace-log entries (the batched
    factories pass their own kind and batch bucket through).
    """
    fp = plan_fingerprint(plan)

    def kernel(cols, params, num_docs, D, G=0):
        # body runs at trace time: counts compiles
        note_trace(kind, fp, (*extra, int(num_docs.shape[-1]), D, G))
        valid = jnp.arange(D, dtype=jnp.int32)[None, :] < num_docs[:, None]
        if plan.valid_mask:
            # upsert validDocIds ride as a staged bool block: superseded
            # rows drop out of every slot AND the matched count, exactly
            # mirroring the host executor's `mask &= valid.to_mask()`
            valid = valid & cols["vmask"]
        slots, matched = _compute_slots(plan, cols, params, valid, G)
        if plan.num_groups or G:
            return jnp.stack([s for _, s in slots], axis=-1)
        return _pack_flat(matched, slots)

    return kernel


def _pack_flat(matched, slots):
    """[S]-scalar and [S, w]-vector (sketch) slots -> one [S, 1 + sum(w)]
    array (single device->host fetch; _assemble indexes by slot offsets)."""
    parts = [matched[:, None]]
    for _op, s in slots:
        parts.append(s[:, None] if s.ndim == 1 else s)
    return jnp.concatenate(parts, axis=1)


def make_topn_kernel(plan: DevicePlan, kind: str = "topn",
                     extra: tuple = ()):
    """Selection / selection-order-by kernel (ref
    operator/query/SelectionOrderByOperator + the min/max-based combine):
    per segment, the top-K doc indices by the order value (value_irs[0];
    ascending negates), or the first K matching docs when unordered.

    Output [S, 1 + K] int32: col 0 = matched doc count, cols 1.. = doc
    indices (-1 = no more matches). The host projects ONLY the winning
    docs — a large filtered SELECT never materializes losing rows.

    kind/extra label this build's trace-log entries (the batched topn
    factory passes its own kind and batch bucket through).
    """
    fp = plan_fingerprint(plan)

    def kernel(cols, params, num_docs, D):
        # body runs at trace time: counts compiles
        note_trace(kind, fp, (*extra, int(num_docs.shape[-1]), D))
        valid = jnp.arange(D, dtype=jnp.int32)[None, :] < num_docs[:, None]
        if plan.valid_mask:
            valid = valid & cols["vmask"]
        if plan.filter_ir is not None:
            mask = _eval_filter(plan.filter_ir, plan, cols, params) & valid
        else:
            mask = valid
        dt = _value_dtype()
        if plan.value_irs:
            v = _eval_value(plan.value_irs[0], cols, params).astype(dt)
            score = -v if plan.topn_asc else v
            # tie-break toward lower doc ids so results are stable
        else:
            score = jnp.broadcast_to(
                -jnp.arange(D, dtype=dt)[None, :], mask.shape)
        # clamp matched scores to the finite range so a legitimate -inf
        # score (f32 overflow of huge values, or a real +/-inf column
        # value under ASC negation) still outranks every unmatched doc's
        # -inf sentinel; validity then reads the MASK at the winning docs
        fin = jnp.finfo(dt)
        # NaN order values sort LAST (host sort parity: numpy puts NaN at
        # the end) — clip passes NaN through and top_k would rank it first,
        # so map it to the finite minimum among matched docs
        score = jnp.where(jnp.isnan(score), fin.min, score)
        score = jnp.where(mask, jnp.clip(score, fin.min, fin.max), -jnp.inf)
        k = min(plan.topn_k, D)
        _top_vals, top_idx = jax.lax.top_k(score, k)
        found = jnp.take_along_axis(mask, top_idx, axis=1)
        idx_out = jnp.where(found, top_idx, -1).astype(jnp.int32)
        matched = jnp.sum(mask, axis=1).astype(jnp.int32)
        return jnp.concatenate([matched[:, None], idx_out], axis=1)

    return kernel


@functools.lru_cache(maxsize=256)
def compiled_topn_kernel(plan: DevicePlan):
    return jax.jit(make_topn_kernel(plan), static_argnames=("D",))


@functools.lru_cache(maxsize=256)
def compiled_kernel(plan: DevicePlan):
    """jit-compiled kernel for a plan structure (shape specialization is
    handled inside jit's own cache; D is static because a filterless
    COUNT(*) stages no columns to infer it from; G is the compact-key
    group count — data-dependent, hence a static arg rather than plan
    state)."""
    return jax.jit(make_kernel(plan), static_argnames=("D", "G"))


# ---------------------------------------------------------------------------
# multi-chip: the same kernel under shard_map over a (segments, docs) mesh
# ---------------------------------------------------------------------------

_DOC_COMBINE = {"sum": "psum", "count": "psum", "sumsq": "psum",
                "sum3": "psum", "sum4": "psum",
                "min": "pmin", "max": "pmax",
                "hll": "pmax",   # register maxima merge across doc shards
                "hist": "psum",  # bucket counts add across doc shards
                "isum": "psum"}  # exact-sum planes add (halves stay small)


def _doc_combine(op: str) -> str:
    return _DOC_COMBINE[op.split(":")[0]]


def _shard_one(plan: DevicePlan, doc_pos, G: int):
    """Per-shard compute for ONE query: the local [S_loc, D_loc] slot
    partials BEFORE any mesh collective. Shared by the single-query and
    the batched (vmap-inside-shard_map) sharded kernels so the slot
    semantics live in exactly one place. Returns the slot arrays in
    plan.agg_ops order, with the matched count appended for non-grouped
    plans (a pytree vmap can carry)."""
    def one(cols, params, num_docs):
        valid = doc_pos < num_docs[:, None]
        if plan.valid_mask:
            valid = valid & cols["vmask"]  # shard-local [S_loc, D_loc]
        slots, matched = _compute_slots(plan, cols, params, valid, G)
        arrs = tuple(s for _, s in slots)
        return arrs if (plan.num_groups or G) else arrs + (matched,)
    return one


def _shard_combine_pack(plan: DevicePlan, outs, G: int):
    """psum/pmin/pmax each slot over the mesh `docs` axis, then pack
    into the kernel's output layout. Rank-agnostic: reductions and the
    pack only touch the trailing axes, so the batched kernels' leading
    query axis rides along untouched ([S, ...] and [B, S, ...] both
    work) — reductions commute with the batch stack."""
    combined = []
    for (op, _v, _f), s in zip(plan.agg_ops, outs):
        kind = _doc_combine(op)
        if kind == "psum":
            s = jax.lax.psum(s, "docs")
        elif kind == "pmin":
            s = jax.lax.pmin(s, "docs")
        else:
            s = jax.lax.pmax(s, "docs")
        combined.append(s)
    if plan.num_groups or G:
        return jnp.stack(combined, axis=-1)   # [..., S, G, n_slots]
    matched = jax.lax.psum(outs[-1], "docs")
    parts = [matched[..., None]]
    for s in combined:
        parts.append(s[..., None] if s.ndim == matched.ndim else s)
    return jnp.concatenate(parts, axis=-1)    # [..., S, 1 + sum(w)]


def make_sharded_kernel(plan: DevicePlan, mesh):
    """ANY DevicePlan over a (segments x docs) mesh with explicit ICI
    collectives (SURVEY §2.6 rows 6-7): column blocks shard over both axes,
    each device reduces its local [S_loc, D_loc] shard, then partials
    combine with psum/pmin/pmax over the `docs` axis. Per-segment results
    stay sharded over `segments` (the engine assembles them host-side, the
    same contract as the single-chip kernel).

    fn(cols, params, num_docs, D) -> packed array (D static: the padded
    GLOBAL doc count; each shard derives its global doc indices from
    axis_index('docs') — a shard-local arange would restart at 0 and
    mis-mask padding).
    """
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map  # type: ignore

    doc_shards = dict(zip(mesh.axis_names, mesh.devices.shape)).get("docs", 1)
    fp = plan_fingerprint(plan)

    def local(cols, params, num_docs, D, G=0):
        # body runs at trace time: counts compiles
        note_trace("sharded", fp, (int(num_docs.shape[-1]), D, G))
        d_local = D // doc_shards
        doc_pos = (jax.lax.axis_index("docs") * d_local
                   + jnp.arange(d_local, dtype=jnp.int32))[None, :]
        outs = _shard_one(plan, doc_pos, G)(cols, params, num_docs)
        return _shard_combine_pack(plan, outs, G)

    def col_spec(name):
        return P("segments", "docs")  # every staged block is [S, D]

    def param_spec(arr):
        # leaf params: [S] bounds or [S, C] LUTs — segment axis only
        return P("segments", *([None] * (arr.ndim - 1)))

    def fn(cols, params, num_docs, D, G=0):
        in_specs = (
            {k: col_spec(k) for k in cols},
            {k: param_spec(v) for k, v in params.items()},
            P("segments"),
        )
        ndim_out = 3 if (plan.num_groups or G) else 2
        sm = shard_map(
            functools.partial(local, D=D, G=G), mesh=mesh,
            in_specs=in_specs,
            out_specs=P("segments", *([None] * (ndim_out - 1))),
        )
        return sm(cols, params, num_docs)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_sharded_kernel(plan: DevicePlan, mesh):
    return make_sharded_kernel(plan, mesh)


# ---------------------------------------------------------------------------
# batched kernel factory: ONE launch for B fingerprint-equal queries
# ---------------------------------------------------------------------------
#
# The coalesce key is (plan fingerprint, shape bucket) — (plan, S, D, G,
# per-array shape signature) — never a concrete segment batch, so
# same-shape queries batch ACROSS tables and partitions. Two variants:
#
#   broadcast (stacked=False): every member shares the SAME staged column
#     blocks (same segment batch — the dashboard-fleet case); only the
#     per-query predicate params carry a leading batch axis, so B queries
#     share one pass over one copy of the data.
#   stacked (stacked=True): members stage DIFFERENT tables/partitions
#     whose blocks pad into the same (S, D) bucket; each member's blocks
#     stack along a new leading axis (the rows come from the residency
#     tier — device-to-device, never a re-upload) and the kernel vmaps
#     over all three of (cols, params, num_docs).
#
# Stacking happens INSIDE the jit so GSPMD owns the resulting sharding on
# mesh engines. Dispatchers pad partial batches to the pow2 bucket B with
# replicated leader inputs, so jit's shape cache only ever sees bucketed
# batch sizes — steady state is zero retraces.

def make_batched_kernel(plan: DevicePlan, B: int, stacked: bool = False):
    kind = "batched_stacked" if stacked else "batched"
    base = make_kernel(plan, kind=kind, extra=(B,))

    if stacked:
        def fn(clist, plist, ndlist, D, G=0):
            cs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *clist)
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            ns = jnp.stack(ndlist)
            return jax.vmap(
                lambda c, p, nd: base(c, p, nd, D=D, G=G))(cs, ps, ns)
    else:
        def fn(cols, plist, num_docs, D, G=0):
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            # the index array keeps vmap fed when a filterless plan has
            # EMPTY per-query params (vmap rejects an all-empty pytree)
            idx = jnp.arange(len(plist), dtype=jnp.int32)
            return jax.vmap(
                lambda p, _i: base(cols, p, num_docs, D=D, G=G))(ps, idx)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_batched_kernel(plan: DevicePlan, B: int, stacked: bool = False):
    """One jit per (plan, batch-size bucket B, stacked?) — see the
    factory note above. fn(cols|clist, plist, num_docs|ndlist, D, G)."""
    return make_batched_kernel(plan, B, stacked)


def make_batched_dedup_kernel(plan: DevicePlan, B: int, U: int):
    """Stacked-batch variant with SAME-COLS MEMBER GROUPING: members
    whose staged column blocks are identity-equal (same table/segments,
    different predicate literals — e.g. two dashboard queries of one
    fleet landing in the same stacked batch as a third table's) share
    ONE stack entry instead of re-stacking duplicate [S, D] blocks.

    clist/ndlist carry the U UNIQUE column sets (padded to the pow2 U
    bucket with the leader's); plist carries all B member params; idx is
    an int32 [B] member->unique-slot map, a TRACED argument so changing
    member composition never retraces — jit's cache keys only the
    (B, U) buckets. Each vmapped member gathers its slot from the
    stacked uniques (dynamic_index on the leading axis), so device
    memory holds U copies of the data, not B."""
    base = make_kernel(plan, kind="batched_dedup", extra=(B, U))

    def fn(clist, plist, ndlist, idx, D, G=0):
        cs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *clist)
        ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
        ns = jnp.stack(ndlist)
        return jax.vmap(
            lambda p, i: base(
                jax.tree_util.tree_map(lambda c: c[i], cs), p, ns[i],
                D=D, G=G))(ps, idx)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_batched_dedup_kernel(plan: DevicePlan, B: int, U: int):
    """One jit per (plan, B bucket, U bucket) —
    fn(clist[U], plist[B], ndlist[U], idx[B], D, G)."""
    return make_batched_dedup_kernel(plan, B, U)


def make_batched_topn_kernel(plan: DevicePlan, B: int,
                             stacked: bool = False):
    """The batched factory for top-N / doc-id-scan plans (mode='topn'):
    MSE leaf SCAN stages resolve their filtered doc ids through this
    kernel, so fingerprint-equal leaf stages from concurrent MSE queries
    (and single-stage selection traffic sharing the plan + shape bucket)
    coalesce into ONE launch exactly like the agg factory — broadcast
    when every member staged the same column blocks, stacked across
    tables otherwise. Output [B, S, 1 + K]."""
    kind = "topn_batched_stacked" if stacked else "topn_batched"
    base = make_topn_kernel(plan, kind=kind, extra=(B,))

    if stacked:
        def fn(clist, plist, ndlist, D, G=0):
            cs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *clist)
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            ns = jnp.stack(ndlist)
            return jax.vmap(
                lambda c, p, nd: base(c, p, nd, D=D))(cs, ps, ns)
    else:
        def fn(cols, plist, num_docs, D, G=0):
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            idx = jnp.arange(len(plist), dtype=jnp.int32)  # empty-params guard
            return jax.vmap(
                lambda p, _i: base(cols, p, num_docs, D=D))(ps, idx)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_batched_topn_kernel(plan: DevicePlan, B: int,
                                 stacked: bool = False):
    return make_batched_topn_kernel(plan, B, stacked)


def make_batched_sharded_kernel(plan: DevicePlan, mesh, B: int,
                                stacked: bool = False):
    """The batched kernel for doc-sharded mesh engines: vmap INSIDE
    shard_map — mesh axes outermost, batch axis innermost — so
    multi-device engines ride the same coalesce path instead of falling
    off it (`vmap` OVER `shard_map` is unsupported; this nests the other
    way). Each device computes its local [*, S_loc, D_loc] shard for all
    B queries, then the whole batch pays ONE set of psum/pmin/pmax
    collectives over the stacked partials (reductions commute with the
    batch stack) instead of B per-query rendezvous — which also means
    host platforms hold the CPU-collective lock once per BATCH.
    """
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map  # type: ignore

    doc_shards = dict(zip(mesh.axis_names, mesh.devices.shape)).get("docs", 1)
    fp = plan_fingerprint(plan)
    kind = "sharded_batched_stacked" if stacked else "sharded_batched"

    def local(cols, params, num_docs, D, G=0):
        note_trace(kind, fp, (B, int(num_docs.shape[-1]), D, G))
        d_local = D // doc_shards
        doc_pos = (jax.lax.axis_index("docs") * d_local
                   + jnp.arange(d_local, dtype=jnp.int32))[None, :]
        # batch axis INNERMOST: vmap the shared per-shard compute over
        # the leading query axis, then pay ONE set of collectives on the
        # stacked partials (the combine/pack is rank-agnostic). The
        # trailing index arg keeps vmap fed when a filterless plan's
        # params pytree is empty
        one = _shard_one(plan, doc_pos, G)
        idx = jnp.arange(B, dtype=jnp.int32)
        in_axes = (0 if stacked else None, 0, 0 if stacked else None, 0)
        outs = jax.vmap(lambda c, p, nd, _i: one(c, p, nd),
                        in_axes=in_axes)(cols, params, num_docs, idx)
        return _shard_combine_pack(plan, outs, G)

    def fn(cols, plist, num_docs, D, G=0):
        ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
        if stacked:
            cs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *cols)
            ns = jnp.stack(num_docs)
            col_spec = P(None, "segments", "docs")
            nd_spec = P(None, "segments")
        else:
            cs, ns = cols, num_docs
            col_spec = P("segments", "docs")
            nd_spec = P("segments")
        in_specs = (
            {k: col_spec for k in cs},
            {k: P(None, "segments", *([None] * (v.ndim - 2)))
             for k, v in ps.items()},
            nd_spec,
        )
        ndim_out = 4 if (plan.num_groups or G) else 3
        sm = shard_map(
            functools.partial(local, D=D, G=G), mesh=mesh,
            in_specs=in_specs,
            out_specs=P(None, "segments", *([None] * (ndim_out - 2))),
        )
        return sm(cs, ps, ns)

    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_batched_sharded_kernel(plan: DevicePlan, mesh, B: int,
                                    stacked: bool = False):
    return make_batched_sharded_kernel(plan, mesh, B, stacked)
