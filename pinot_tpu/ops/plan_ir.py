"""Device plan IR: the hashable structure a compiled kernel is keyed by.

Reference parity: the role of pinot-core's per-segment Plan tree
(plan/maker/InstancePlanMakerImplV2.java:270 chooses the operator chain per
query shape) — but here the "plan" is a pure-data IR handed to
ops/kernels.build_kernel, and compiled-function caching is keyed by it
(SURVEY.md §7 hard-parts: cache compiled kernels keyed by plan shape).

Filter IR nodes (nested tuples, hashable):
    ('and', n1, n2, ...) / ('or', ...) / ('not', n)
    ('leaf', i)        -- i-th entry of DevicePlan.leaves

Leaf kinds (resolved per-segment into parameter arrays, see ops/engine.py):
    'range' : lo[S], hi[S] int32     -- lo <= dictId <= hi  (equals folds here)
    'neq'   : idx[S] int32           -- dictId != idx (idx=-1 matches all)
    'lut'   : table[S, C] bool       -- table[s, dictId] (in/not-in/like/regex)
    'vrange': lo[S], hi[S] float     -- lo <= value <= hi (raw numeric columns)
    'vrange64': lohi/lolo/hihi/hilo[S] int32 -- exact closed-interval compare
              on big-int columns staged as (hi, lo) i32 split planes
              (hi = v >> 24, lo = v & 0xFFFFFF); works with x64 OFF where
              f32 staging would alias values above 2^24 (epoch millis)
    'clp'   : LIKE/regex over a CLP log column, evaluated against the
              column's logtype-id + variable-slot pseudo-columns
              (ops/clp_device.py compiles the pattern to per-segment
              candidate-logtype LUTs + encoded/dict variable conditions;
              leaf.meta = (mode, Kd, Ke) picks the staged slot layout)

Value IR (aggregation inputs / in-kernel transforms):
    ('col', name)       -- column values (dict gather or raw staged block)
    ('ids', name)       -- raw dictIds of a column (group keys)
    ('lit', v)
    ('add'|'sub'|'mul'|'div', a, b)
    ('neg', a)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class DeviceLeaf:
    kind: str         # 'range' | 'neq' | 'lut' | 'vrange' | 'vrange64' | 'clp'
    column: str
    #: kind-specific static shape info folded into the plan signature
    #: ('clp': (mode, Kd, Ke) — see ops/clp_device.py)
    meta: Tuple = ()


@dataclass(frozen=True)
class DevicePlan:
    """Hashable kernel-structure signature."""
    filter_ir: Optional[tuple]            # nested tuple tree or None
    leaves: Tuple[DeviceLeaf, ...]
    value_irs: Tuple[Optional[tuple], ...]  # one per agg slot input (None = count(*))
    #: (op, value_ir index or None, agg-filter index or None) — the third
    #: element selects an entry of agg_filter_irs to AND into the main mask
    #: for this slot (ref FilteredAggregationOperator)
    agg_ops: Tuple[Tuple[str, Optional[int], Optional[int]], ...]
    #: per-aggregation FILTER (WHERE ...) trees (same leaf space as filter_ir)
    agg_filter_irs: Tuple[tuple, ...] = ()
    group_cols: Tuple[str, ...] = ()
    group_strides: Tuple[int, ...] = ()   # mixed-radix strides over padded cards
    num_groups: int = 0                   # padded combined-key space (0 = no group-by)
    #: True: the dense mixed-radix key space exceeded MAX_DEVICE_GROUPS, so
    #: keys are staged as a per-segment COMPACTED key block ('gkey') — host
    #: factorizes the observed combined keys once per (segment, group cols)
    #: and caches the codes + decode table (ref
    #: DictionaryBasedGroupKeyGenerator's sparse map modes). The group
    #: count is then data-dependent and rides the kernel's static G arg.
    group_compact: bool = False
    #: columns staged as dictIds with a dictionary value table
    dict_cols: Tuple[str, ...] = ()
    #: columns staged as raw numeric value blocks
    raw_cols: Tuple[str, ...] = ()
    #: big-int columns staged as (hi, lo) i32 split planes, filter-only
    raw64_cols: Tuple[str, ...] = ()
    #: CLP log columns staged as (name, Kd, Ke) pseudo-column families:
    #: logtype-id block + Kd dict-var-slot id blocks + Ke encoded-var
    #: (hi, lo) i32 split slot blocks (ops/clp_device.py), filter-only
    clp_cols: Tuple[Tuple[str, int, int], ...] = ()
    #: 'agg' (default) | 'topn' — topn plans compute per-segment top-K doc
    #: indices by value_irs[0] (or first-K matching when it is None) for
    #: selection / selection-order-by offload
    mode: str = "agg"
    topn_k: int = 0
    topn_asc: bool = True
    #: True: the batch carries at least one upsert/dedup segment with a
    #: live validDocIds bitmap — the engine stages a bool [S, D] mask
    #: block ('vmask', version-stamped by the bitmap mutation counter)
    #: and kernels AND it into the padding-validity mask, so superseded
    #: rows are invisible to every slot exactly as the host executor's
    #: `mask &= valid.to_mask()` makes them (SURVEY §2.3)
    valid_mask: bool = False
    #: device time-bucket leg (ops/timeseries_device.py): (ts_col,
    #: count_pad) — floor((t - start) / step) fused into the group key
    #: as its LOWEST digit (count_pad is the pow2 bucket of the window's
    #: bucket count, so it multiplies into num_groups ahead of the tag
    #: radices). start/step/count ride params ('tb:*' i32 cells), NOT
    #: the plan, so a dashboard's sliding refresh window re-stages four
    #: scalar rows instead of retracing the kernel.
    tbucket: Tuple = ()
