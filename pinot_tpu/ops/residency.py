"""HBM segment residency: a per-(segment, column) device-resident tier.

The engine's device tier used to cache only whole stacked blocks keyed by
the exact segment-batch tuple — a different pruned subset, or one newly
sealed segment joining the batch, missed the device tier entirely and
re-shipped EVERY column over the ~100ms host<->TPU link. This module
holds the unit that actually survives batch recomposition: one padded
device row per (segment object, column kind), assembled into kernel-ready
[S, D] blocks ON DEVICE (ops/kernels.compiled_row_assembler), so a new
batch composition uploads only the rows it has never seen.

Policy (the tier is HBM — it must never grow past its budget, and one
cold table scan must not flush the hot working set):

  * recency — entries are LRU-ordered; hits refresh.
  * frequency-based admission (TinyLFU-style) — every access, hit or
    miss, bumps a per-(segment name, kind, column) counter in a bounded
    sample window (counters halve when the window fills, so stale
    popularity decays). When the tier is full, a candidate is admitted
    only if its frequency exceeds the LRU victim's — a cold scan's
    once-touched rows lose to the dashboard working set and are simply
    not retained (the query still ran; retention is what's refused).
  * warmup seeding — `seeding()` marks accesses made by the segment
    warmup replay (cache/warmup.py): seeded admissions bypass the
    frequency duel and carry a seed boost, because the FingerprintLog
    replaying them IS the evidence of plan traffic.
  * eviction drops the reference only — in-flight kernels hold evicted
    rows as inputs and JAX refcounting frees the HBM when the last
    consumer finishes (same discipline as the block cache).

The module also owns the host->device **transfer odometer**: every byte
the engine ships through `_put`/row uploads is counted process-wide,
exposed like `kernels.trace_count()` so tests and the bench can assert a
repeated-query steady state uploads NOTHING.
"""
from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# transfer odometer (process-wide, like the kernels.py compile odometer):
# counts bytes shipped host->device through the engine's upload paths.
# Steady-state traffic over resident columns must keep this flat — a
# growing count means the hot path is paying the link again.
# ---------------------------------------------------------------------------
_transfer_lock = threading.Lock()
_transfer_bytes = 0
_transfer_count = 0
_column_bytes = 0


def note_transfer(nbytes: int, column: bool = False) -> None:
    """column=True marks COLUMN payloads (resident rows / stacked
    blocks) as opposed to per-query predicate params — the steady-state
    guard asserts column bytes specifically, because params are tiny and
    plan-keyed while columns are the link-saturating payload."""
    global _transfer_bytes, _transfer_count, _column_bytes
    with _transfer_lock:
        _transfer_bytes += int(nbytes)
        _transfer_count += 1
        if column:
            _column_bytes += int(nbytes)


def transfer_bytes() -> int:
    with _transfer_lock:
        return _transfer_bytes


def transfer_count() -> int:
    with _transfer_lock:
        return _transfer_count


def column_transfer_bytes() -> int:
    with _transfer_lock:
        return _column_bytes


class ResidencyManager:
    """Budgeted per-(segment, column) device-row tier with frequency-based
    admission on top of recency LRU.

    Keys carry (id(segment), segment name) and entries pin the segment
    object, verified by identity on every hit — a same-name/new-object
    segment (the PR-5 replace swap, an ingest re-add) can never serve a
    stale row: id() is not recycled while the entry pins the old object,
    and the new object misses. Frequency counters key on the NAME (they
    survive a version swap: the replacement inherits its plan traffic).
    """

    #: admission credit granted to warmup-seeded rows on top of the
    #: per-access bump — one replayed plan outweighs a few cold touches
    SEED_BOOST = 3

    def __init__(self, budget_bytes: int, admission: bool = True,
                 sample_window: int = 4096, metrics=None,
                 labels: Optional[Dict[str, str]] = None,
                 devices=None):
        self.budget_bytes = max(0, int(budget_bytes))
        self.enabled = self.budget_bytes > 0
        self.admission = bool(admission)
        self.sample_window = max(64, int(sample_window))
        #: mesh devices (multi-chip engines): a resident row commits
        #: whole to ONE chip, so the pooled budget splits evenly into
        #: per-chip shares and eviction/pressure watch the most-loaded
        #: chip — one hot chip OOMs alone long before the pool looks full
        self.devices = list(devices) if devices else []
        n = len(self.devices)
        self.device_budget_bytes = \
            self.budget_bytes // n if n > 1 else self.budget_bytes
        self._metrics = metrics
        self._labels = labels
        self._lock = threading.RLock()
        #: key -> (segment, device row, nbytes, device label); LRU order
        self._entries: "OrderedDict[tuple, Tuple[Any, Any, int, str]]" = \
            OrderedDict()
        self._bytes = 0
        #: device label -> resident bytes (labeled admissions only)
        self._dev_bytes: Dict[str, int] = {}
        #: (segment name, kind, col) -> access count (TinyLFU sketch —
        #: a plain dict is exact and bounded by the halving pass)
        self._freq: Dict[tuple, int] = {}
        self._obs = 0
        self._seeding = threading.local()
        # plain tallies (cheap asserts in tests; the metrics registry
        # carries the same numbers for ops)
        self.hits = 0
        self.misses = 0
        self.admitted = 0
        self.rejected = 0
        self.evicted = 0

    # -- keys -----------------------------------------------------------
    @staticmethod
    def _key(seg, kind: str, col: str, dtype_str: str) -> tuple:
        return (id(seg), seg.name, kind, col, dtype_str)

    @staticmethod
    def _fkey(seg, kind: str, col: str) -> tuple:
        return (seg.name, kind, col)

    # -- seeding (warmup replay) ---------------------------------------
    @contextlib.contextmanager
    def seeding(self):
        """Accesses inside this context are warmup-seeded: admissions
        bypass the frequency duel and carry SEED_BOOST extra credit."""
        depth = getattr(self._seeding, "depth", 0)
        self._seeding.depth = depth + 1
        try:
            yield
        finally:
            self._seeding.depth = depth

    @property
    def seeding_active(self) -> bool:
        return getattr(self._seeding, "depth", 0) > 0

    # -- metering -------------------------------------------------------
    def _meter(self, name: str, value: float = 1) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(name, value, labels=self._labels)

    def _touch(self, fkey: tuple, n: int = 1) -> None:
        self._freq[fkey] = self._freq.get(fkey, 0) + n
        self._obs += n
        if self._obs >= self.sample_window:
            # aging: halve everything so popularity is RECENT popularity
            # (and the dict stays bounded — zeroed keys drop out)
            self._freq = {k: v // 2 for k, v in self._freq.items()
                          if v // 2 > 0}
            self._obs //= 2

    # -- access ---------------------------------------------------------
    def get(self, seg, kind: str, col: str, dtype_str: str):
        """The resident device row for (seg, kind, col), or None on miss.
        Every call counts toward the column's admission frequency."""
        if not self.enabled:
            return None
        key = self._key(seg, kind, col, dtype_str)
        with self._lock:
            boost = self.SEED_BOOST if self.seeding_active else 0
            self._touch(self._fkey(seg, kind, col), 1 + boost)
            entry = self._entries.get(key)
            if entry is not None and entry[0] is seg:
                self._entries.move_to_end(key)
                self.hits += 1
                self._meter("hbm_resident_hit")
                return entry[1]
            self.misses += 1
            self._meter("hbm_resident_miss")
            return None

    def admit(self, seg, kind: str, col: str, dtype_str: str, dev_row,
              nbytes: int, device: Optional[str] = None) -> bool:
        """Offer an uploaded row for retention. Returns True if resident.
        Rejection never fails the query — the caller keeps its transient
        reference; the tier just declines to retain the bytes. `device`
        names the chip holding the row (multi-chip meshes): the row then
        charges THAT chip's share of the budget, so a skewed chip evicts
        (or declines) on its own while the others stay warm."""
        if not self.enabled or nbytes > self.budget_bytes:
            return False
        dlabel = device or ""
        if dlabel and nbytes > self.device_budget_bytes:
            return False
        key = self._key(seg, kind, col, dtype_str)
        fkey = self._fkey(seg, kind, col)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[2]
                if old[3]:
                    self._dev_bytes[old[3]] -= old[2]
            seeded = self.seeding_active
            cand = self._freq.get(fkey, 0)

            # the candidate's own chip first: on a mesh the per-chip
            # share is the binding constraint (a row never spans chips)
            while dlabel and self._dev_bytes.get(dlabel, 0) + nbytes \
                    > self.device_budget_bytes:
                vkey = next((k for k, e in self._entries.items()
                             if e[3] == dlabel), None)
                if vkey is None:
                    break
                if not self._evict_one_locked(vkey, cand, seeded):
                    return False
            while self._bytes + nbytes > self.budget_bytes and self._entries:
                if not self._evict_one_locked(next(iter(self._entries)),
                                              cand, seeded):
                    return False
            self._entries[key] = (seg, dev_row, int(nbytes), dlabel)
            self._bytes += int(nbytes)
            if dlabel:
                self._dev_bytes[dlabel] = \
                    self._dev_bytes.get(dlabel, 0) + int(nbytes)
            self.admitted += 1
            return True

    def _evict_one_locked(self, vkey, cand: int, seeded: bool) -> bool:
        """TinyLFU duel for one eviction victim (caller holds the lock).
        Returns False when the victim is at least as hot as the admission
        candidate — decline retention; this is what stops a cold scan
        flushing the working set."""
        vfreq = self._freq.get((vkey[1], vkey[2], vkey[3]), 0)
        if self.admission and not seeded and cand <= vfreq:
            self.rejected += 1
            self._meter("hbm_admission_rejected")
            return False
        _vseg, _vdev, vnb, vlab = self._entries.pop(vkey)
        self._bytes -= vnb
        if vlab:
            self._dev_bytes[vlab] -= vnb
        self.evicted += 1
        self._meter("hbm_evicted")
        return True

    # -- invalidation ---------------------------------------------------
    def invalidate_segment(self, name: str, keep=None) -> int:
        """Drop resident rows for a replaced/removed segment NAME,
        sparing entries pinned to `keep` (the just-warmed live object).
        Identity keying already guarantees a new object misses; this
        reclaims the old version's HBM promptly. Frequency counters are
        kept — the replacement inherits its column traffic."""
        with self._lock:
            stale = [k for k, e in self._entries.items()
                     if k[1] == name and (keep is None or e[0] is not keep)]
            for k in stale:
                _seg, _dev, nb, lab = self._entries.pop(k)
                self._bytes -= nb
                if lab:
                    self._dev_bytes[lab] -= nb
                self.evicted += 1
                self._meter("hbm_evicted")
            return len(stale)

    def invalidate_superseded_kind(self, seg, kind_prefix: str,
                                   keep_kind: str, col: str) -> int:
        """Drop this segment's resident rows whose kind starts with
        `kind_prefix` but is not `keep_kind` — the version-stamped vmask
        rows: every bitmap mutation admits a fresh 'vmask:<stamp>' row,
        and without this purge the unreachable old-stamp rows would
        squat in the HBM budget until LRU pressure evicts live columns
        (the assembled-block cache gets the same purge engine-side)."""
        with self._lock:
            stale = [k for k, e in self._entries.items()
                     if e[0] is seg and k[3] == col
                     and k[2].startswith(kind_prefix) and k[2] != keep_kind]
            for k in stale:
                _seg, _dev, nb, lab = self._entries.pop(k)
                self._bytes -= nb
                if lab:
                    self._dev_bytes[lab] -= nb
                self.evicted += 1
                self._meter("hbm_evicted")
            return len(stale)

    def drop_all(self) -> None:
        """Bench/test hook: release every resident row (references only —
        in-flight kernels still hold theirs)."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._dev_bytes.clear()

    # -- introspection --------------------------------------------------
    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def bytes_by_device(self) -> Dict[str, int]:
        """Resident bytes per chip label. Only labeled admissions count —
        single-device engines never label, so this is empty there."""
        with self._lock:
            return dict(self._dev_bytes)

    def max_device_bytes(self) -> int:
        """The most-loaded chip's resident bytes (pooled bytes when no
        admission was ever labeled — one device IS the max chip)."""
        with self._lock:
            if self._dev_bytes:
                return max(self._dev_bytes.values())
            return self._bytes

    def pressure(self) -> float:
        """Budget fraction the admission plane gates on: the most-loaded
        chip's fill of its per-chip share on a mesh (one hot chip OOMs
        alone — the pooled number hides that), the pooled fill
        otherwise. 0.0 when unbudgeted."""
        with self._lock:
            if not self.enabled:
                return 0.0
            if self._dev_bytes and self.device_budget_bytes:
                return max(self._dev_bytes.values()) \
                    / self.device_budget_bytes
            return self._bytes / self.budget_bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def resident_for(self, name: str) -> int:
        """Resident row count for a segment name (tests)."""
        with self._lock:
            return sum(1 for k in self._entries if k[1] == name)

    def resident_bytes_by_segment(self) -> Dict[str, int]:
        """Resident bytes keyed by segment NAME — the instance-sweep
        residency payload's raw material (the server maps names to
        tables; brokers then prefer replicas already holding a table's
        columns in HBM)."""
        with self._lock:
            out: Dict[str, int] = {}
            for k, e in self._entries.items():
                out[k[1]] = out.get(k[1], 0) + e[2]
            return out

    def frequency(self, name: str, kind: str, col: str) -> int:
        with self._lock:
            return self._freq.get((name, kind, col), 0)
