"""HBM segment residency: a per-(segment, column) device-resident tier.

The engine's device tier used to cache only whole stacked blocks keyed by
the exact segment-batch tuple — a different pruned subset, or one newly
sealed segment joining the batch, missed the device tier entirely and
re-shipped EVERY column over the ~100ms host<->TPU link. This module
holds the unit that actually survives batch recomposition: one padded
device row per (segment object, column kind), assembled into kernel-ready
[S, D] blocks ON DEVICE (ops/kernels.compiled_row_assembler), so a new
batch composition uploads only the rows it has never seen.

Policy (the tier is HBM — it must never grow past its budget, and one
cold table scan must not flush the hot working set):

  * recency — entries are LRU-ordered; hits refresh.
  * frequency-based admission (TinyLFU-style) — every access, hit or
    miss, bumps a per-(segment name, kind, column) counter in a bounded
    sample window (counters halve when the window fills, so stale
    popularity decays). When the tier is full, a candidate is admitted
    only if its frequency exceeds the LRU victim's — a cold scan's
    once-touched rows lose to the dashboard working set and are simply
    not retained (the query still ran; retention is what's refused).
  * warmup seeding — `seeding()` marks accesses made by the segment
    warmup replay (cache/warmup.py): seeded admissions bypass the
    frequency duel and carry a seed boost, because the FingerprintLog
    replaying them IS the evidence of plan traffic.
  * eviction drops the reference only — in-flight kernels hold evicted
    rows as inputs and JAX refcounting frees the HBM when the last
    consumer finishes (same discipline as the block cache).

The module also owns the host->device **transfer odometer**: every byte
the engine ships through `_put`/row uploads is counted process-wide,
exposed like `kernels.trace_count()` so tests and the bench can assert a
repeated-query steady state uploads NOTHING.
"""
from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# transfer odometer (process-wide, like the kernels.py compile odometer):
# counts bytes shipped host->device through the engine's upload paths.
# Steady-state traffic over resident columns must keep this flat — a
# growing count means the hot path is paying the link again.
# ---------------------------------------------------------------------------
_transfer_lock = threading.Lock()
_transfer_bytes = 0
_transfer_count = 0
_column_bytes = 0


def note_transfer(nbytes: int, column: bool = False) -> None:
    """column=True marks COLUMN payloads (resident rows / stacked
    blocks) as opposed to per-query predicate params — the steady-state
    guard asserts column bytes specifically, because params are tiny and
    plan-keyed while columns are the link-saturating payload."""
    global _transfer_bytes, _transfer_count, _column_bytes
    with _transfer_lock:
        _transfer_bytes += int(nbytes)
        _transfer_count += 1
        if column:
            _column_bytes += int(nbytes)


def transfer_bytes() -> int:
    with _transfer_lock:
        return _transfer_bytes


def transfer_count() -> int:
    with _transfer_lock:
        return _transfer_count


def column_transfer_bytes() -> int:
    with _transfer_lock:
        return _column_bytes


class ResidencyManager:
    """Budgeted per-(segment, column) device-row tier with frequency-based
    admission on top of recency LRU.

    Keys carry (id(segment), segment name) and entries pin the segment
    object, verified by identity on every hit — a same-name/new-object
    segment (the PR-5 replace swap, an ingest re-add) can never serve a
    stale row: id() is not recycled while the entry pins the old object,
    and the new object misses. Frequency counters key on the NAME (they
    survive a version swap: the replacement inherits its plan traffic).
    """

    #: admission credit granted to warmup-seeded rows on top of the
    #: per-access bump — one replayed plan outweighs a few cold touches
    SEED_BOOST = 3

    def __init__(self, budget_bytes: int, admission: bool = True,
                 sample_window: int = 4096, metrics=None,
                 labels: Optional[Dict[str, str]] = None):
        self.budget_bytes = max(0, int(budget_bytes))
        self.enabled = self.budget_bytes > 0
        self.admission = bool(admission)
        self.sample_window = max(64, int(sample_window))
        self._metrics = metrics
        self._labels = labels
        self._lock = threading.RLock()
        #: key -> (segment, device row, nbytes); LRU order
        self._entries: "OrderedDict[tuple, Tuple[Any, Any, int]]" = \
            OrderedDict()
        self._bytes = 0
        #: (segment name, kind, col) -> access count (TinyLFU sketch —
        #: a plain dict is exact and bounded by the halving pass)
        self._freq: Dict[tuple, int] = {}
        self._obs = 0
        self._seeding = threading.local()
        # plain tallies (cheap asserts in tests; the metrics registry
        # carries the same numbers for ops)
        self.hits = 0
        self.misses = 0
        self.admitted = 0
        self.rejected = 0
        self.evicted = 0

    # -- keys -----------------------------------------------------------
    @staticmethod
    def _key(seg, kind: str, col: str, dtype_str: str) -> tuple:
        return (id(seg), seg.name, kind, col, dtype_str)

    @staticmethod
    def _fkey(seg, kind: str, col: str) -> tuple:
        return (seg.name, kind, col)

    # -- seeding (warmup replay) ---------------------------------------
    @contextlib.contextmanager
    def seeding(self):
        """Accesses inside this context are warmup-seeded: admissions
        bypass the frequency duel and carry SEED_BOOST extra credit."""
        depth = getattr(self._seeding, "depth", 0)
        self._seeding.depth = depth + 1
        try:
            yield
        finally:
            self._seeding.depth = depth

    @property
    def seeding_active(self) -> bool:
        return getattr(self._seeding, "depth", 0) > 0

    # -- metering -------------------------------------------------------
    def _meter(self, name: str, value: float = 1) -> None:
        if self._metrics is not None:
            self._metrics.add_meter(name, value, labels=self._labels)

    def _touch(self, fkey: tuple, n: int = 1) -> None:
        self._freq[fkey] = self._freq.get(fkey, 0) + n
        self._obs += n
        if self._obs >= self.sample_window:
            # aging: halve everything so popularity is RECENT popularity
            # (and the dict stays bounded — zeroed keys drop out)
            self._freq = {k: v // 2 for k, v in self._freq.items()
                          if v // 2 > 0}
            self._obs //= 2

    # -- access ---------------------------------------------------------
    def get(self, seg, kind: str, col: str, dtype_str: str):
        """The resident device row for (seg, kind, col), or None on miss.
        Every call counts toward the column's admission frequency."""
        if not self.enabled:
            return None
        key = self._key(seg, kind, col, dtype_str)
        with self._lock:
            boost = self.SEED_BOOST if self.seeding_active else 0
            self._touch(self._fkey(seg, kind, col), 1 + boost)
            entry = self._entries.get(key)
            if entry is not None and entry[0] is seg:
                self._entries.move_to_end(key)
                self.hits += 1
                self._meter("hbm_resident_hit")
                return entry[1]
            self.misses += 1
            self._meter("hbm_resident_miss")
            return None

    def admit(self, seg, kind: str, col: str, dtype_str: str, dev_row,
              nbytes: int) -> bool:
        """Offer an uploaded row for retention. Returns True if resident.
        Rejection never fails the query — the caller keeps its transient
        reference; the tier just declines to retain the bytes."""
        if not self.enabled or nbytes > self.budget_bytes:
            return False
        key = self._key(seg, kind, col, dtype_str)
        fkey = self._fkey(seg, kind, col)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[2]
            seeded = self.seeding_active
            cand = self._freq.get(fkey, 0)
            while self._bytes + nbytes > self.budget_bytes and self._entries:
                vkey = next(iter(self._entries))
                vfreq = self._freq.get((vkey[1], vkey[2], vkey[3]), 0)
                if self.admission and not seeded and cand <= vfreq:
                    # the victim is at least as hot: decline retention —
                    # this is what stops a cold scan flushing the
                    # working set
                    self.rejected += 1
                    self._meter("hbm_admission_rejected")
                    return False
                _vseg, _vdev, vnb = self._entries.pop(vkey)
                self._bytes -= vnb
                self.evicted += 1
                self._meter("hbm_evicted")
            self._entries[key] = (seg, dev_row, int(nbytes))
            self._bytes += int(nbytes)
            self.admitted += 1
            return True

    # -- invalidation ---------------------------------------------------
    def invalidate_segment(self, name: str, keep=None) -> int:
        """Drop resident rows for a replaced/removed segment NAME,
        sparing entries pinned to `keep` (the just-warmed live object).
        Identity keying already guarantees a new object misses; this
        reclaims the old version's HBM promptly. Frequency counters are
        kept — the replacement inherits its column traffic."""
        with self._lock:
            stale = [k for k, e in self._entries.items()
                     if k[1] == name and (keep is None or e[0] is not keep)]
            for k in stale:
                _seg, _dev, nb = self._entries.pop(k)
                self._bytes -= nb
                self.evicted += 1
                self._meter("hbm_evicted")
            return len(stale)

    def invalidate_superseded_kind(self, seg, kind_prefix: str,
                                   keep_kind: str, col: str) -> int:
        """Drop this segment's resident rows whose kind starts with
        `kind_prefix` but is not `keep_kind` — the version-stamped vmask
        rows: every bitmap mutation admits a fresh 'vmask:<stamp>' row,
        and without this purge the unreachable old-stamp rows would
        squat in the HBM budget until LRU pressure evicts live columns
        (the assembled-block cache gets the same purge engine-side)."""
        with self._lock:
            stale = [k for k, e in self._entries.items()
                     if e[0] is seg and k[3] == col
                     and k[2].startswith(kind_prefix) and k[2] != keep_kind]
            for k in stale:
                _seg, _dev, nb = self._entries.pop(k)
                self._bytes -= nb
                self.evicted += 1
                self._meter("hbm_evicted")
            return len(stale)

    def drop_all(self) -> None:
        """Bench/test hook: release every resident row (references only —
        in-flight kernels still hold theirs)."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # -- introspection --------------------------------------------------
    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def resident_for(self, name: str) -> int:
        """Resident row count for a segment name (tests)."""
        with self._lock:
            return sum(1 for k in self._entries if k[1] == name)

    def resident_bytes_by_segment(self) -> Dict[str, int]:
        """Resident bytes keyed by segment NAME — the instance-sweep
        residency payload's raw material (the server maps names to
        tables; brokers then prefer replicas already holding a table's
        columns in HBM)."""
        with self._lock:
            out: Dict[str, int] = {}
            for k, e in self._entries.items():
                out[k[1]] = out.get(k[1], 0) + e[2]
            return out

    def frequency(self, name: str, kind: str, col: str) -> int:
        with self._lock:
            return self._freq.get((name, kind, col), 0)
