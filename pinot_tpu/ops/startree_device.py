"""Device-side star-tree pre-aggregation (ref StarTreeFilterOperator +
StarTreeAggregationExecutor / StarTreeGroupByExecutor, run on TPU).

The host keeps what it is good at — the fit check and the recursive
tree traversal (pointer chasing over the int32 node array) — and the
device does what IT is good at: the residual aggregation over the
matched pre-agg records. Traversal yields record indices into the
pre-agg table (the DFS layout makes every node a contiguous [start,
end) slice); those become a boolean selection mask shipped as kernel
PARAMS, while the pre-agg metric/dim-code columns are staged once as
`(segment, "__startree__<ti>/<pair>")` pseudo-columns through the
engine's host-row / residency / assembled-block tiers and reused across
queries. Two star-tree queries with the same StarTreePlan therefore
differ only in params — they coalesce into ONE jit(vmap) launch through
the ops/dispatch micro-batcher, exactly like scan kernels.

Exactness: integral sum/count pairs ride exact unsigned int planes
(two 24-bit digits, each through kernels._isum_u_slot; grouped via
per-plane i32 scatter-adds), so int sums and counts are bit-identical
to the host paths for any value < 2^48. Float pairs and min/max use the
engine's value dtype (f32 unless x64), the same precision posture as
the scan path. Plan admission (`plan_startree`) proves the bounds from
the tree's actual metric columns and falls back by reason otherwise.
"""
from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.ops import kernels
from pinot_tpu.query.expressions import Identifier
from pinot_tpu.query.results import (AggregationResult, ExecutionStats,
                                     GroupByResult)
from pinot_tpu.query.startree_exec import _agg_pairs_needed, _filter_id_sets
from pinot_tpu.segment.startree import parse_pair

#: unsigned planes per 24-bit digit of an exact-sum slot: 4 * 7 bits
#: covers the digit, per-plane i32 sums stay exact (127 * 2^24 < 2^31)
USUM_PLANES = 4
#: slot width: (hi, lo) digits x USUM_PLANES planes x (hi, lo) f32 halves
USUM_WIDTH = 2 * 2 * USUM_PLANES
#: largest integral value an exact slot can carry (two 24-bit digits)
USUM_MAX = float(1 << 48)
#: f32 represents integers exactly up to 2^24 — the min/max admission bound
_F32_EXACT_INT = float(1 << 24)
#: mixed-radix group-key space cap (mirrors engine.MAX_DEVICE_GROUPS)
_MAX_GROUPS = 1 << 20


class StarTreePlan(NamedTuple):
    """Frozen device plan for one star-tree aggregation shape. Carries
    STRUCTURE only (slot forms, group radix) — never filter literals or
    segment identity — so fingerprint-equal queries with different
    predicate constants share one compiled kernel and one launch."""
    slots: Tuple[Tuple[str, str], ...]        # (op, "func__col") per pair
    group_dims: Tuple[str, ...] = ()
    group_cards: Tuple[int, ...] = ()
    group_strides: Tuple[int, ...] = ()
    num_groups: int = 0


class STFit(NamedTuple):
    """One segment's fitted tree + traversal result."""
    ti: int          # tree index within the segment's reader
    tree: object     # segment.startree.StarTreeV2
    recs: np.ndarray  # selected pre-agg record indices (int64)


def slot_width(op: str) -> int:
    return USUM_WIDTH if op == "usum" else 1


# ---------------------------------------------------------------------------
# Kernels (traced; purity-checked as a kernel module)
# ---------------------------------------------------------------------------

def _grouped_usum(vi, keys, m, num_groups):
    """Exact per-group sum of one 24-bit digit column: per-plane i32
    scatter-adds, each plane returned as f32-exact (hi, lo) halves —
    the grouped counterpart of kernels._isum_u_slot."""
    dt = kernels._value_dtype()
    vi = jnp.where(m, vi, 0)
    safe_keys = jnp.where(m, keys, 0)
    parts = []
    for k in range(USUM_PLANES):
        p = (vi >> jnp.int32(kernels.ISUM_U_BITS * k)) & jnp.int32(127)
        s = kernels._vmap_scatter(
            jnp.zeros((vi.shape[0], num_groups), dtype=jnp.int32),
            safe_keys, p, "add")
        parts.append((s >> jnp.int32(12)).astype(dt))
        parts.append((s & jnp.int32(4095)).astype(dt))
    return parts


def make_startree_kernel(plan: StarTreePlan, kind: str = "startree",
                         extra: tuple = ()):
    """[S, D] pre-agg residual aggregation. cols: "stid:<dim>" group
    codes, "stval:<pair>" float metrics, "sthi:/stlo:<pair>" exact-sum
    digit rows. params: "sel" [S, D] bool selection mask (the traversal
    result — the only per-query input). Flat output [S, 1 + sum(w)]
    with the selected-record count first; grouped [S, G, 1 + sum(w)]
    with the per-group record count at index 0."""
    fp = kernels.plan_fingerprint(plan)

    def kernel(cols, params, num_docs, D, G=0):
        kernels.note_trace(kind, fp, (*extra, int(num_docs.shape[-1]), D, G))
        valid = jnp.arange(D, dtype=jnp.int32)[None, :] < num_docs[:, None]
        sel = params["sel"]
        m = sel & valid
        dt = kernels._value_dtype()
        if plan.group_dims:
            ng = plan.num_groups
            keys = jnp.zeros(valid.shape, dtype=jnp.int32)
            for dim, stride in zip(plan.group_dims, plan.group_strides):
                keys = keys + cols["stid:" + dim] * jnp.int32(stride)
            outs = [kernels._scatter_sum(m.astype(dt),
                                         jnp.where(m, keys, 0), ng)]
            for op, name in plan.slots:
                if op == "usum":
                    outs.extend(_grouped_usum(cols["sthi:" + name], keys,
                                              m, ng))
                    outs.extend(_grouped_usum(cols["stlo:" + name], keys,
                                              m, ng))
                else:
                    outs.append(kernels._grouped_reduce(
                        op, cols["stval:" + name], keys, sel, valid, ng))
            return jnp.stack(outs, axis=-1)
        parts = [jnp.sum(m, axis=1).astype(dt)[:, None]]
        for op, name in plan.slots:
            if op == "usum":
                parts.append(kernels._isum_u_slot(
                    f"isum:u{USUM_PLANES}", cols["sthi:" + name], m))
                parts.append(kernels._isum_u_slot(
                    f"isum:u{USUM_PLANES}", cols["stlo:" + name], m))
            else:
                parts.append(kernels._masked_reduce(
                    op, cols["stval:" + name], sel, valid)[:, None])
        return jnp.concatenate(parts, axis=1)

    return kernel


@functools.lru_cache(maxsize=256)
def compiled_startree_kernel(plan: StarTreePlan):
    return jax.jit(make_startree_kernel(plan), static_argnames=("D", "G"))


def make_batched_startree_kernel(plan: StarTreePlan, B: int,
                                 stacked: bool = False):
    """Coalesced star-tree launch (mirrors kernels.make_batched_kernel):
    broadcast variant shares one staged block across members (same
    segments, different selection masks — the common dashboard case);
    stacked variant stacks per-member blocks for cross-table members."""
    kind = "startree_batched_stacked" if stacked else "startree_batched"
    base = make_startree_kernel(plan, kind=kind, extra=(B,))
    if stacked:
        def fn(clist, plist, ndlist, D, G=0):
            cs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *clist)
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            ns = jnp.stack(ndlist)
            return jax.vmap(lambda c, p, nd: base(c, p, nd, D=D, G=G))(
                cs, ps, ns)
    else:
        def fn(cols, plist, num_docs, D, G=0):
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            idx = jnp.arange(len(plist), dtype=jnp.int32)
            return jax.vmap(lambda p, _i: base(cols, p, num_docs, D=D, G=G))(
                ps, idx)
    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_batched_startree_kernel(plan: StarTreePlan, B: int,
                                     stacked: bool = False):
    return make_batched_startree_kernel(plan, B, stacked)


# ---------------------------------------------------------------------------
# Host-side planning (fit check + traversal + slot admission)
# ---------------------------------------------------------------------------

def plan_startree(segments, ctx):
    """Fit + plan the device star-tree path for one segment batch.

    Returns (plan, needed, fits, None) when every segment has a fitting
    tree and every pair admits a device slot; (None, None, None, reason)
    otherwise — reason is the `startree_fallback` meter's reason= label
    (disabled | aggregation | groupBy | noTree | fit | filter |
    precision | groups) and the caller falls through to the scan path."""
    if ctx.options.get("useStarTree", "true").lower() == "false":
        return None, None, None, "disabled"
    if ctx.distinct or not ctx.aggregations:
        return None, None, None, "aggregation"
    needed = _agg_pairs_needed(ctx)
    if needed is None:
        return None, None, None, "aggregation"
    group_cols: List[str] = []
    for g in ctx.group_by:
        if not isinstance(g, Identifier):
            return None, None, None, "groupBy"
        group_cols.append(g.name)
    pairs_needed = {p for pairs in needed for p in pairs}

    fits: List[STFit] = []
    filter_missed = False
    for seg in segments:
        reader = getattr(seg, "star_tree", None)
        if reader is None or not reader.trees:
            return None, None, None, "noTree"
        fit = None
        for ti, tree in enumerate(reader.trees):
            tree_pairs = {parse_pair(p) for p in tree.meta.pairs}
            if not pairs_needed <= tree_pairs:
                continue
            if not all(c in tree.meta.dims for c in group_cols):
                continue
            id_sets = _filter_id_sets(seg, ctx.filter, tree.meta.dims)
            if id_sets is None:
                filter_missed = True
                continue
            fit = STFit(ti, tree, tree.traverse(id_sets, set(group_cols)))
            break
        if fit is None:
            return None, None, None, "filter" if filter_missed else "fit"
        fits.append(fit)

    # slot admission per pair: exact int planes when every fitted tree's
    # bounds prove the values fit, f32 for float pairs; int pairs whose
    # bounds overflow a slot fall back (the scan path is exact there)
    slots: List[Tuple[str, str]] = []
    for func, col in sorted(pairs_needed):
        lo, hi, integral = 0.0, 0.0, True
        for f in fits:
            b = f.tree.pair_bounds((func, col))
            lo, hi = min(lo, b[0]), max(hi, b[1])
            integral = integral and b[2]
        name = f"{func}__{col}"
        if func in ("sum", "count"):
            if integral and 0.0 <= lo and hi < USUM_MAX:
                slots.append(("usum", name))
            elif integral:
                return None, None, None, "precision"
            else:
                slots.append(("sum", name))
        else:  # min / max: f32 is exact for ints within +-2^24, and
            # within float tolerance for genuinely-float metrics
            if integral and not (-_F32_EXACT_INT <= lo
                                 and hi <= _F32_EXACT_INT):
                return None, None, None, "precision"
            slots.append((func, name))

    cards: List[int] = []
    strides: List[int] = []
    num_groups = 0
    if group_cols:
        cards = [max(int(seg.data_source(c).metadata.cardinality)
                     for seg in segments) for c in group_cols]
        num_groups = 1
        for c in cards:
            num_groups *= c
        if num_groups > _MAX_GROUPS:
            return None, None, None, "groups"
        strides = [int(np.prod(cards[i + 1:], dtype=np.int64))
                   for i in range(len(cards))]

    plan = StarTreePlan(slots=tuple(slots), group_dims=tuple(group_cols),
                        group_cards=tuple(cards),
                        group_strides=tuple(strides), num_groups=num_groups)
    return plan, needed, fits, None


def staged_columns(plan: StarTreePlan, value_dtype):
    """[(kernel col key, fetch form, np dtype)] the engine stages as
    pseudo-column blocks; `fetch_row` materializes one segment's row."""
    out = []
    for op, name in plan.slots:
        if op == "usum":
            out.append(("sthi:" + name, ("hi", name), np.int32))
            out.append(("stlo:" + name, ("lo", name), np.int32))
        else:
            out.append(("stval:" + name, ("val", name), value_dtype))
    for d in plan.group_dims:
        out.append(("stid:" + d, ("id", d), np.int32))
    return out


def fetch_row(tree, form, value_dtype) -> np.ndarray:
    """One tree's raw pre-agg row for a staged-column form."""
    kind, name = form
    if kind == "id":
        return np.ascontiguousarray(tree.dim_codes[name], dtype=np.int32)
    v = tree.metrics[tuple(name.split("__", 1))]
    if kind == "val":
        return v.astype(value_dtype)
    vi = v.astype(np.int64)
    if kind == "hi":
        return (vi >> 24).astype(np.int32)
    return (vi & 0xFFFFFF).astype(np.int32)


def selection_mask(fits: List[STFit], S: int, D: int) -> np.ndarray:
    """[S, D] bool params block from per-segment traversal results."""
    sel = np.zeros((S, D), dtype=bool)
    for i, f in enumerate(fits):
        sel[i, f.recs] = True
    return sel


# ---------------------------------------------------------------------------
# Host-side assembly (mirrors query/startree_exec._whole/_grouped)
# ---------------------------------------------------------------------------

def _slot_layout(plan: StarTreePlan) -> Dict[str, Tuple[int, str]]:
    offs: Dict[str, Tuple[int, str]] = {}
    off = 1  # index 0 is the matched/record-count column
    for op, name in plan.slots:
        offs[name] = (off, op)
        off += slot_width(op)
    return offs


def _usum_value(planes) -> int:
    """Reconstruct the exact integer sum from a usum slot's 16 plane
    halves (hi digit planes then lo digit planes) in python ints."""
    def digit(p):
        total = 0
        for k in range(USUM_PLANES):
            s = int(round(float(p[2 * k]))) * 4096 \
                + int(round(float(p[2 * k + 1])))
            total += s << (kernels.ISUM_U_BITS * k)
        return total
    half = 2 * USUM_PLANES
    return (digit(planes[:half]) << 24) + digit(planes[half:])


def assemble(segments, ctx, plan: StarTreePlan, needed, fits, packed):
    """Per-segment results from the packed kernel output — value-exact
    mirror of the host star-tree executor (types included: count int,
    sum/min/max float, avg (float, int) intermediates)."""
    packed = np.asarray(packed)
    layout = _slot_layout(plan)
    results = []
    for s, seg in enumerate(segments):
        if plan.group_dims:
            results.append(_assemble_group(seg, ctx, plan, needed, layout,
                                           np.asarray(packed[s],
                                                      dtype=np.float64)))
        else:
            results.append(_assemble_flat(seg, ctx, plan, needed, layout,
                                          np.asarray(packed[s],
                                                     dtype=np.float64)))
    return results


def _agg_value(fn_name: str, pairs, get):
    """One aggregation's intermediate from slot values (host parity:
    startree_exec._whole / _grouped element types)."""
    if fn_name == "count":
        return int(get(("count", "*")))
    if fn_name == "avg":
        return (float(get(pairs[0])), int(get(("count", "*"))))
    return float(get(pairs[0]))  # sum / min / max


def _slot_get(layout, row, pair):
    off, op = layout[f"{pair[0]}__{pair[1]}"]
    if op == "usum":
        return _usum_value(row[off:off + USUM_WIDTH])
    return float(row[off])


def _assemble_flat(seg, ctx, plan, needed, layout, row):
    matched = int(round(float(row[0])))
    stats = ExecutionStats(
        num_docs_scanned=matched, num_segments_processed=1,
        num_segments_matched=1 if matched else 0, total_docs=seg.num_docs)
    inters = [_agg_value(fn.name, needed[i],
                         lambda pair: _slot_get(layout, row, pair))
              for i, fn in enumerate(ctx.aggregations)]
    return AggregationResult(inters, stats)


def _assemble_group(seg, ctx, plan, needed, layout, arr):
    cnt = arr[:, 0]
    present = np.nonzero(cnt > 0.5)[0]
    matched = int(round(float(cnt.sum())))
    stats = ExecutionStats(
        num_docs_scanned=matched, num_segments_processed=1,
        num_segments_matched=1 if matched else 0, total_docs=seg.num_docs)
    dicts = [seg.data_source(c).dictionary for c in plan.group_dims]
    cards = [int(seg.data_source(c).metadata.cardinality)
             for c in plan.group_dims]
    groups: Dict[tuple, list] = {}
    for g in present:
        rem = int(g)
        ids = []
        for stride in plan.group_strides:
            ids.append(rem // stride)
            rem = rem % stride
        if any(i >= c for i, c in zip(ids, cards)):
            continue  # radix-padding key outside this segment's dict
        key = tuple(_py(d.get_value(ids[j])) for j, d in enumerate(dicts))
        row = arr[g]
        groups[key] = [_agg_value(fn.name, needed[i],
                                  lambda pair: _slot_get(layout, row, pair))
                       for i, fn in enumerate(ctx.aggregations)]
    return GroupByResult(groups, stats)


def _py(v):
    return v.item() if isinstance(v, np.generic) else v
