"""Device-side time-bucket group-by (ref the `pinot-timeseries` SPI's
TimeBuckets leaf push-down; here the bucketing runs INSIDE the group-by
kernel instead of as a host expression column).

The time-series leaf SQL groups by `floor((t - start) / step)` — an
expression group-by the device scan leg can't admit (group keys must be
dictionary ids), so every dashboard panel used to fall back to the host
executor. This module recognizes that exact shape host-side and fuses
the bucket id into the scatter key: the timestamp stages through the
existing (hi, lo) i32 raw64 planes (exact below 2^55), the kernel
computes `b = (t - start) // step` in i32 from those planes, and `b`
becomes the LOWEST digit of the composite group key — the engine's
successive-division strides then decode it for free.

start / step / count are PARAMS (per-segment i32 cells), not plan
fields: a dashboard's sliding window changes `start` every refresh, and
only `count_pad` — the pow2 bucket of the window's bucket count — is
baked into the plan, so steady-state refreshes re-stage four scalar
param rows and never retrace.

No `kernels` import here: kernels.py imports this module (one-way, the
same direction as its clp_device import).
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from pinot_tpu.query.expressions import Function, Identifier, Literal

#: timestamps stage as (v >> 24, v & 0xFFFFFF) i32 planes — exact while
#: the hi plane fits i32
MAX_TS = 1 << 55

#: widest admissible window in timestamp units: delta must fit i32 with
#: a 2^24 margin (the hi-plane partial product can overshoot the true
#: delta by up to one lo-plane carry before the correction lands)
MAX_WINDOW = (1 << 31) - (1 << 24)

_SHIFT = 1 << 24


class BucketSpec(NamedTuple):
    """Host-side admission result for one leaf query's bucket leg."""
    col: str
    start: int
    step: int
    count: int      # buckets actually addressed by the window
    count_pad: int  # pow2 bucket -> the plan's static group width


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def _int_lit(e) -> Optional[int]:
    if not isinstance(e, Literal):
        return None
    try:
        v = float(e.value)
    except (TypeError, ValueError):
        return None
    return int(v) if v.is_integer() else None


def extract_bucket(e) -> Optional[Tuple[str, int, int]]:
    """(col, start, step) when `e` is exactly
    floor((Identifier - int) / int) with a positive step — the shape
    the time-series leaf SQL emits; None otherwise."""
    if not (isinstance(e, Function) and e.name == "floor"
            and len(e.args) == 1):
        return None
    div = e.args[0]
    if not (isinstance(div, Function) and div.name == "divide"
            and len(div.args) == 2):
        return None
    sub, step_e = div.args
    if not (isinstance(sub, Function) and sub.name == "minus"
            and len(sub.args) == 2 and isinstance(sub.args[0], Identifier)):
        return None
    start = _int_lit(sub.args[1])
    step = _int_lit(step_e)
    if start is None or step is None or step <= 0:
        return None
    return sub.args[0].name, start, step


def extract_window(flt, col: str) -> Optional[Tuple[int, int]]:
    """(lo, hi_excl) from top-level `col >= lo AND col < hi` conjuncts
    — the window the leaf SQL always carries; None when either bound is
    missing (an unbounded scan can't size the bucket grid)."""
    conjuncts = list(flt.args) if isinstance(flt, Function) \
        and flt.name == "and" else [flt] if flt is not None else []
    lo = hi = None
    for c in conjuncts:
        if not (isinstance(c, Function) and len(c.args) == 2
                and isinstance(c.args[0], Identifier)
                and c.args[0].name == col):
            continue
        v = _int_lit(c.args[1])
        if v is None:
            continue
        if c.name == "greater_than_or_equal":
            lo = v if lo is None else max(lo, v)
        elif c.name == "greater_than":
            lo = v + 1 if lo is None else max(lo, v + 1)
        elif c.name == "less_than":
            hi = v if hi is None else min(hi, v)
        elif c.name == "less_than_or_equal":
            hi = v + 1 if hi is None else min(hi, v + 1)
    if lo is None or hi is None or hi <= lo:
        return None
    return lo, hi


def plan_bucket(group_expr, flt, segments) -> Optional[BucketSpec]:
    """Admit the first group-by expression as a fused device time
    bucket, or None (the query stays on whatever path it had). Checks:
    the floor shape, an int timestamp column bounded in [0, 2^55) on
    every segment, a filter window starting at/after `start` (so the
    kernel's delta is never negative for surviving rows), and the
    window fitting the exact-i32 envelope."""
    shape = extract_bucket(group_expr)
    if shape is None:
        return None
    col, start, step = shape
    win = extract_window(flt, col)
    if win is None or win[0] < start:
        return None
    for seg in segments:
        m = seg.metadata.columns.get(col)
        if m is None or m.data_type.np_dtype.kind not in "iu" \
                or m.min_value is None or m.max_value is None:
            return None
        if int(m.min_value) < 0 or int(m.max_value) >= MAX_TS:
            return None
    window = win[1] - 1 - start
    if window >= MAX_WINDOW:
        return None
    count = window // step + 1
    return BucketSpec(col, start, step, count, _pow2(count))


def leaf_params(spec: BucketSpec, S: int):
    """The four per-segment i32 param cells the kernel reads: start's
    (hi, lo) planes, step, and the live bucket count. Imported lazily by
    the engine's _stage; numpy-side only."""
    import numpy as np
    return {
        "tb:shi": np.full(S, spec.start >> 24, np.int32),
        "tb:slo": np.full(S, spec.start & 0xFFFFFF, np.int32),
        "tb:step": np.full(S, spec.step, np.int32),
        "tb:count": np.full(S, spec.count, np.int32),
    }


# ---------------------------------------------------------------------------
# Traced bucket math (called from kernels._compute_slots)
# ---------------------------------------------------------------------------

def bucket_ids(vhi, vlo, shi, slo, step, count, count_pad: int):
    """(bucket ids clipped to [0, count_pad), in-window gate) from the
    staged (hi, lo) timestamp planes. delta reconstructs exactly in i32
    for every row the window filter keeps; out-of-window rows may wrap,
    but the gate (and the query's own t-range conjuncts) zero their
    contribution before the scatter."""
    delta = (vhi - shi[:, None]) * jnp.int32(_SHIFT) + (vlo - slo[:, None])
    b = jnp.floor_divide(delta, step[:, None])
    gate = (delta >= 0) & (b < count[:, None])
    return jnp.clip(b, 0, count_pad - 1).astype(jnp.int32), gate


#: standalone jit entry so tests (and the purity checker's traced-fn
#: sweep) exercise the bucket math without a full kernel launch
compiled_bucket_ids = jax.jit(bucket_ids, static_argnames=("count_pad",))
