"""Device-side vector similarity search (ref
core/operator/filter/VectorSimilarityFilterOperator over the Lucene99
HNSW reader; here the exact/IVF matmul design of
segment/vector_index.py, run on TPU through the kernel factory).

The host keeps what it is good at — index admission, query-vector
parsing, IVF probe selection (an argsort over n_cells centroid scores)
— and the device does what IT is good at: `scores = V @ q` over every
document at once, which is the single best MXU fit in the codebase.
Each segment's [n, d] vector block (and its IVF cell assignments)
stages as `(segment, "__vec__/<col>/<leg>")` pseudo-columns through the
engine's host-row / residency / assembled-block tiers, flattened to one
[S, D * dim_pad] f32 row family so every batch composition shares the
resident rows.

The QUERY VECTOR AND topK live in staged params, never the plan: a
VectorPlan carries structure only (column, pow2 dim/K buckets, IVF
shape, residual-filter IR), so fingerprint-equal concurrent ANN queries
— different query vectors, same shape — coalesce into ONE jit(vmap)
launch through the dispatch ring exactly like scan kernels.

Host-contract parity (query/filter._vector_similarity_mask): the K
winners are chosen over ALL docs (masked only by padding validity and
the IVF probe-cell mask — NEVER by the residual predicate), and the
residual `WHERE ... AND vector_similarity(...)` conjuncts intersect
AFTER selection, so hybrid filters compose K-before-filter exactly as
the host path does. Ties break toward lower doc ids on both paths
(`jax.lax.top_k` device-side, the lexsort in VectorIndex.top_k
host-side), making exact-path doc-id results bit-identical.
"""
from __future__ import annotations

import functools
import json
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.ops import kernels
from pinot_tpu.query.expressions import Function, Identifier, Literal
from pinot_tpu.query.results import ExecutionStats, SelectionResult

#: `vector_fallback{reason=}` vocabulary — why a vector_similarity
#: query left the device path for the host index search:
#:   disabled  — pinot.server.vector.enabled=false
#:   noIndex   — a batch segment has no vector index on the column
#:   metric    — a non-cosine index (L2 staging keeps the host path)
#:   hybrid    — the filter shape doesn't decompose into
#:               vector_similarity AND device-stageable conjuncts
#:               (OR/NOT around the vector fn, an unstageable residual
#:               conjunct, or an ORDER BY the kernel can't honor)
#:   staging   — column staging failed / doc-sharded mesh / block caps
#:   precision — K or dimensionality outside the exact device envelope
FALLBACK_REASONS = ("disabled", "noIndex", "metric", "hybrid",
                    "staging", "precision")

#: IVF probe width — mirrors VectorIndex.top_k's default nprobe
NPROBE = 8

#: per-segment staged vector row cap (f32 bytes): above this one
#: segment's [D, dim_pad] block would dominate HBM — host path instead
MAX_VEC_ROW_BYTES = 512 << 20


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


class VectorPlan(NamedTuple):
    """Frozen device plan for one ANN query SHAPE. Query constants (the
    vector, K, the probe-cell mask, residual predicate literals) live in
    params; the plan carries only structure, so fingerprint-equal
    concurrent queries share one compiled kernel and one launch. The
    residual-filter fields mirror DevicePlan's so kernels._eval_filter
    and the engine's _stage run unchanged against this plan."""
    col: str
    dim_pad: int          # pow2 bucket of the vector dimensionality
    k_pad: int            # pow2 bucket of topK (actual K in params)
    ivf: bool = False
    cells_pad: int = 0    # pow2 bucket of the coarse-cell count
    # -- DevicePlan-compatible residual-filter structure ---------------
    filter_ir: Optional[tuple] = None
    leaves: tuple = ()
    value_irs: tuple = ()
    agg_ops: tuple = ()
    group_compact: bool = False
    tbucket: tuple = ()
    dict_cols: Tuple[str, ...] = ()
    raw_cols: Tuple[str, ...] = ()
    raw64_cols: Tuple[str, ...] = ()
    clp_cols: tuple = ()
    valid_mask: bool = False


# ---------------------------------------------------------------------------
# Kernels (traced; purity-checked as a kernel module)
# ---------------------------------------------------------------------------

def make_vector_kernel(plan: VectorPlan, kind: str = "vector",
                       extra: tuple = ()):
    """[S, D] batched similarity top-K. cols: "vec:<col>" f32
    [S, D * dim_pad] flattened vector blocks (+ "vcell:<col>" i32 cell
    assignments when IVF), plus whatever the residual filter staged.
    params: "vq:q" [S, dim_pad] normalized query, "vq:k" [S] i32 topK,
    "vq:cells" [S, cells_pad] bool probe mask (IVF only), plus residual
    leaf params. Output f32 [S, 1 + 2*kk]: col 0 = surviving-row count,
    then kk doc ids (-1 = empty; exact in f32 below 2^24 docs), then kk
    scores aligned with the ids."""
    fp = kernels.plan_fingerprint(plan)

    def kernel(cols, params, num_docs, D):
        kernels.note_trace(kind, fp, (*extra, int(num_docs.shape[-1]), D))
        valid = jnp.arange(D, dtype=jnp.int32)[None, :] < num_docs[:, None]
        V = cols["vec:" + plan.col].reshape(-1, D, plan.dim_pad)
        # scores = V @ q: ONE batched matvec over every doc of every
        # segment — the MXU path (padding docs/dims are zero rows, so
        # they contribute nothing and are masked out below anyway)
        scores = jnp.einsum("sde,se->sd", V, params["vq:q"],
                            preferred_element_type=jnp.float32)
        # candidate mask: padding validity + IVF probe cells. The
        # residual predicate is deliberately NOT here — K picks over all
        # docs first (host-contract K-before-filter parity).
        cand = valid
        if plan.ivf:
            cell = jnp.clip(cols["vcell:" + plan.col], 0,
                            plan.cells_pad - 1)
            cand = cand & jnp.take_along_axis(params["vq:cells"], cell,
                                              axis=1)
        score = jnp.where(cand, scores, -jnp.inf)
        kk = min(plan.k_pad, D)
        top_vals, top_idx = jax.lax.top_k(score, kk)
        # residual WHERE conjuncts (and the upsert validity mask)
        # intersect AFTER selection — rows the filter drops vanish, but
        # never promote losers into the K
        resid = valid
        if plan.valid_mask:
            resid = resid & cols["vmask"]
        if plan.filter_ir is not None:
            resid = resid & kernels._eval_filter(plan.filter_ir, plan,
                                                 cols, params)
        keep = jnp.take_along_axis(resid & cand, top_idx, axis=1)
        keep = keep & (jnp.arange(kk, dtype=jnp.int32)[None, :]
                       < params["vq:k"][:, None])
        keep = keep & (top_vals > -jnp.inf)
        idx_out = jnp.where(keep, top_idx, -1).astype(jnp.float32)
        svals = jnp.where(keep, top_vals, -jnp.inf).astype(jnp.float32)
        matched = jnp.sum(keep, axis=1).astype(jnp.float32)
        return jnp.concatenate([matched[:, None], idx_out, svals], axis=1)

    return kernel


@functools.lru_cache(maxsize=256)
def compiled_vector_kernel(plan: VectorPlan):
    return jax.jit(make_vector_kernel(plan), static_argnames=("D",))


def make_batched_vector_kernel(plan: VectorPlan, B: int,
                               stacked: bool = False):
    """Coalesced ANN launch (mirrors kernels.make_batched_topn_kernel):
    broadcast members share one staged vector block and differ only in
    params (the concurrent-dashboard / ANN-fleet case — B queries, one
    pass over one copy of the vectors); stacked members stack per-table
    blocks from the residency tier."""
    kind = "vector_batched_stacked" if stacked else "vector_batched"
    base = make_vector_kernel(plan, kind=kind, extra=(B,))
    if stacked:
        def fn(clist, plist, ndlist, D, G=0):
            cs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *clist)
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            ns = jnp.stack(ndlist)
            return jax.vmap(lambda c, p, nd: base(c, p, nd, D=D))(
                cs, ps, ns)
    else:
        def fn(cols, plist, num_docs, D, G=0):
            ps = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *plist)
            idx = jnp.arange(len(plist), dtype=jnp.int32)
            return jax.vmap(lambda p, _i: base(cols, p, num_docs, D=D))(
                ps, idx)
    return jax.jit(fn, static_argnames=("D", "G"))


@functools.lru_cache(maxsize=256)
def compiled_batched_vector_kernel(plan: VectorPlan, B: int,
                                   stacked: bool = False):
    return make_batched_vector_kernel(plan, B, stacked)


# ---------------------------------------------------------------------------
# Host-side planning (filter decomposition + admission)
# ---------------------------------------------------------------------------

def contains_vector(e) -> bool:
    """True when a vector_similarity call appears anywhere in a filter
    tree — the engine's routing test for the vector leg."""
    if not isinstance(e, Function):
        return False
    if e.name == "vector_similarity":
        return True
    return any(contains_vector(a) for a in e.args)


def split_filter(e):
    """(vector fn, residual expr or None, None) when the filter is the
    bare vector_similarity call or a top-level AND with EXACTLY ONE
    vector conjunct; (None, None, reason) otherwise. OR/NOT around the
    vector fn changes its semantics from "intersect with the K nearest"
    to something no top-K kernel computes — those stay host-side
    (reason 'hybrid')."""
    if not isinstance(e, Function):
        return None, None, "hybrid"
    if e.name == "vector_similarity":
        return e, None, None
    if e.name != "and":
        return None, None, "hybrid"
    vec = [a for a in e.args if isinstance(a, Function)
           and a.name == "vector_similarity"]
    rest = [a for a in e.args if not (isinstance(a, Function)
                                      and a.name == "vector_similarity")]
    if len(vec) != 1 or any(contains_vector(a) for a in rest):
        return None, None, "hybrid"
    if not rest:
        return vec[0], None, None
    residual = rest[0] if len(rest) == 1 else Function("and", tuple(rest))
    return vec[0], residual, None


def parse_args(fn: Function):
    """(column, query vector f32, K) — the host mask's exact argument
    contract (query/filter._vector_similarity_mask), including the
    default K=10."""
    if not fn.args or not isinstance(fn.args[0], Identifier):
        raise ValueError("vector_similarity needs a column")
    if len(fn.args) < 2 or not isinstance(fn.args[1], Literal):
        raise ValueError("vector_similarity needs a query vector")
    k = int(fn.args[2].value) if len(fn.args) > 2 \
        and isinstance(fn.args[2], Literal) else 10
    q = np.asarray(json.loads(str(fn.args[1].value)), np.float32).ravel()
    return fn.args[0].name, q, k


def _index_of(seg, col: str):
    try:
        ds = seg.data_source(col)
    except (KeyError, ValueError):
        return None
    return getattr(ds, "vector_index", None)


def admit(segments, col: str, qvec: np.ndarray, k: int, max_k: int):
    """((dim_pad, ivf, cells_pad), None) when every segment's index
    admits the device path; (None, reason) otherwise."""
    if k <= 0 or k > max_k:
        return None, "precision"
    dim = 0
    ivf = False
    max_cells = 0
    for seg in segments:
        index = _index_of(seg, col)
        if index is None:
            return None, "noIndex"
        if index.metric != "cosine":
            return None, "metric"
        d = int(index.vectors.shape[1]) if index.vectors.ndim == 2 else 0
        if d == 0 or (dim and d != dim):
            return None, "precision"
        dim = d
        if index.centroids is not None:
            ivf = True
            max_cells = max(max_cells, len(index.centroids))
    if dim != len(qvec):
        return None, "precision"
    dim_pad = _pow2(dim)
    if dim_pad * 4 > MAX_VEC_ROW_BYTES:
        return None, "staging"
    return (dim_pad, ivf, _pow2(max_cells) if ivf else 0), None


# ---------------------------------------------------------------------------
# Staged-row fetchers + query params
# ---------------------------------------------------------------------------

def vector_row(seg, col: str, dim_pad: int, pad_docs: int) -> np.ndarray:
    """One segment's flattened f32 vector row: [pad_docs, dim_pad]
    zero-padded then raveled, so the row is a prefix of any wider
    assembled block that shares dim_pad (inner-dim padding is uniform
    across the batch — the flat layout composes with per-row pow2 doc
    buckets)."""
    index = _index_of(seg, col)
    out = np.zeros((pad_docs, dim_pad), np.float32)
    v = index.vectors
    out[:v.shape[0], :v.shape[1]] = v
    return out.reshape(-1)


def cell_row(seg, col: str, pad_docs: int) -> np.ndarray:
    """One segment's i32 IVF cell-assignment row (zeros for exact-only
    segments — their probe mask stages all-True, so cell 0 admits)."""
    index = _index_of(seg, col)
    out = np.zeros(pad_docs, np.int32)
    a = index.assignments
    if a is not None:
        out[:len(a)] = a
    return out


def query_params(segments, plan: VectorPlan, qvec: np.ndarray, k: int,
                 S: int, nprobe: int = NPROBE) -> Dict[str, np.ndarray]:
    """Per-query staged params: the cosine-normalized zero-padded query
    vector, the actual K, and (IVF) the probe-cell mask — computed with
    VectorIndex.probe_cells so probe selection (including the
    empty-candidate fall-back-to-all rule) is host-parity by
    construction."""
    n = float(np.linalg.norm(qvec))
    qn = (qvec / max(n, 1e-30)).astype(np.float32)
    q = np.zeros(plan.dim_pad, np.float32)
    q[:len(qn)] = qn
    out = {"vq:q": np.tile(q, (S, 1)),
           "vq:k": np.full(S, k, np.int32)}
    if plan.ivf:
        cells = np.zeros((S, plan.cells_pad), dtype=bool)
        for s, seg in enumerate(segments):
            index = _index_of(seg, col=plan.col)
            if index is None or index.centroids is None:
                cells[s, :] = True
                continue
            probe = index.probe_cells(qn, nprobe)
            if probe is None:
                cells[s, :] = True
            else:
                cells[s, probe] = True
        out["vq:cells"] = cells
    return out


# ---------------------------------------------------------------------------
# Host-side assembly + broker-side merge
# ---------------------------------------------------------------------------

def unpack(packed_row: np.ndarray):
    """(doc ids int64 score-desc, scores f32) of one segment's packed
    kernel row — the raw K winners before doc-order materialization."""
    kk = (len(packed_row) - 1) // 2
    ids = np.asarray(packed_row[1:1 + kk], np.float64)
    scores = np.asarray(packed_row[1 + kk:1 + 2 * kk], np.float32)
    good = ids >= 0
    return ids[good].astype(np.int64), scores[good]


def assemble(segments, ctx, plan: VectorPlan, packed: np.ndarray,
             S_real: int) -> List[SelectionResult]:
    """packed [S, 1 + 2*kk] -> SelectionResults: surviving winners
    materialize in doc-id order truncated to LIMIT+OFFSET, exactly as
    the host SelectionOnlyOperator walks the K-hot filter mask."""
    from pinot_tpu.query.executor_cpu import _project_rows, expand_star
    from pinot_tpu.query.filter import SegmentColumnProvider
    packed = np.asarray(packed)
    fetch = ctx.limit + ctx.offset
    filter_cols = len(set(ctx.filter_columns()))
    results = []
    for s, seg in enumerate(segments[:S_real]):
        ids, _scores = unpack(packed[s])
        ids = ids[ids < seg.num_docs]
        matched = int(round(float(packed[s, 0])))
        idx = np.sort(ids)[:fetch]
        provider = SegmentColumnProvider(seg)
        rows = _project_rows(seg, ctx.select, provider, idx)
        stats = ExecutionStats(
            num_docs_scanned=matched,
            num_entries_scanned_in_filter=seg.num_docs * filter_cols,
            num_entries_scanned_post_filter=len(idx) * max(
                len(ctx.select), 1),
            num_segments_processed=1,
            num_segments_matched=1 if matched else 0,
            total_docs=seg.num_docs)
        results.append(SelectionResult(
            rows, columns=expand_star(seg, ctx), stats=stats))
    return results


def merge_top_k(packed: np.ndarray, S_real: int, k: int):
    """Broker-side cross-segment top-K merge over the packed launch
    output: the global K best (segment, doc, score) triples by score
    descending, ties toward (lower segment, lower doc) — deterministic
    regardless of segment arrival order."""
    entries = []
    packed = np.asarray(packed)
    for s in range(min(S_real, packed.shape[0])):
        ids, scores = unpack(packed[s])
        for d, sc in zip(ids, scores):
            entries.append((-float(sc), s, int(d)))
    entries.sort()
    return [(s, d, -neg) for neg, s, d in entries[:k]]
