"""Device-mesh parallelism: sharded segment batches + collective combines.

The TPU-native re-expression of the reference's parallelism inventory
(SURVEY.md §2.6): intra-server per-segment fan-out becomes a `segments`
mesh axis (DP analog); within-segment doc-block iteration becomes a `docs`
mesh axis (SP analog) with psum combines over ICI; scatter-gather across
servers stays host-side (broker), and multi-stage shuffles map to
collective all-to-alls (phase 2+).
"""
from pinot_tpu.parallel.mesh import make_mesh, segment_sharding
from pinot_tpu.parallel.distributed_query import distributed_query_step

__all__ = ["make_mesh", "segment_sharding", "distributed_query_step"]
