"""Fully-sharded query step with explicit collectives.

This is the multi-chip analog of the single-device fused kernel in
ops/kernels.py: column blocks [S, D] are sharded over BOTH mesh axes
(segments x docs), each device computes its local masked partials, then
  * psum over `docs`     — combines doc-shard partials into per-segment
    results (the ICI collective replacing the reference's in-thread
    block loop, SURVEY.md §2.6 "Multi-stage shuffle / ICI" row)
  * psum over `segments` — combines per-segment partials into the final
    aggregate (replacing combine/BaseCombineOperator's merge +
    BrokerReduceService for the single-table case)
via jax.experimental.shard_map, so the collectives are explicit and
compile to ICI all-reduces rather than relying on GSPMD inference.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # moved out of experimental in newer jax
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def distributed_query_step(mesh: Mesh):
    """Build the jit'd sharded query step for a fixed (range-filter + SUM +
    COUNT + per-group SUM) shape — the SSB Q1.x training-step analog.

    Inputs (global shapes):
      ids    [S, D] int32  filter column dictIds, sharded (segments, docs)
      vals   [S, D] f32    measure values,        sharded (segments, docs)
      gids   [S, D] int32  group column dictIds,  sharded (segments, docs)
      lo, hi [S]    int32  per-segment dictId bounds, sharded (segments,)
      ndocs  [S]    int32  actual docs per segment,   sharded (segments,)
      num_groups     int   static group-key space

    Returns (total_sum [], total_count [], group_sums [num_groups]) —
    all fully replicated after the collectives.
    """

    def step(ids, vals, gids, lo, hi, ndocs, doc_pos, num_groups):
        # local block: [S_loc, D_loc]; doc_pos [1, D_loc] carries each
        # column's GLOBAL doc index (shard-local arange would restart at 0)
        valid = doc_pos < ndocs[:, None]
        mask = (ids >= lo[:, None]) & (ids <= hi[:, None]) & valid
        contrib = jnp.where(mask, vals, 0.0)
        # per-segment partials on this doc shard
        part_sum = jnp.sum(contrib, axis=1)
        part_cnt = jnp.sum(mask, axis=1).astype(jnp.float32)
        # group partials via scatter-add on the local shard
        safe_keys = jnp.where(mask, gids, 0)
        part_groups = jax.vmap(
            lambda k, c: jnp.zeros((num_groups,), jnp.float32).at[k].add(c)
        )(safe_keys, contrib.astype(jnp.float32))
        # combine doc shards -> true per-segment results (ICI all-reduce)
        seg_sum = jax.lax.psum(part_sum, "docs")
        seg_cnt = jax.lax.psum(part_cnt, "docs")
        seg_groups = jax.lax.psum(part_groups, "docs")
        # combine segments -> final aggregate (second ICI all-reduce)
        total_sum = jax.lax.psum(jnp.sum(seg_sum), "segments")
        total_cnt = jax.lax.psum(jnp.sum(seg_cnt), "segments")
        group_sums = jax.lax.psum(jnp.sum(seg_groups, axis=0), "segments")
        return total_sum, total_cnt, group_sums

    def make(num_groups: int, D_shard: int = 0):  # D_shard kept for signature stability
        sm = shard_map(
            partial(step, num_groups=num_groups),
            mesh=mesh,
            in_specs=(P("segments", "docs"), P("segments", "docs"),
                      P("segments", "docs"), P("segments"), P("segments"),
                      P("segments"), P(None, "docs")),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(sm)

    return make
