"""Mesh construction helpers.

A query mesh has two axes:
  `segments` — data parallelism over stacked segments (the analog of the
               reference's CombinePlanNode thread fan-out and of broker
               scatter-gather, SURVEY.md §2.6 rows 1-2)
  `docs`     — sequence parallelism within a segment's doc dimension (the
               long-context axis; partial aggregates combine with psum
               over ICI rather than host merges)
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(devices: Optional[Sequence] = None,
              doc_axis: int = 1) -> Mesh:
    """Mesh over (segments, docs). doc_axis devices are dedicated to the
    intra-segment doc dimension; the rest to the segment axis."""
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if doc_axis < 1 or n % doc_axis != 0:
        raise ValueError(f"doc_axis {doc_axis} must divide device count {n}")
    arr = np.array(devices).reshape(n // doc_axis, doc_axis)
    return Mesh(arr, ("segments", "docs"))


def segment_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """[S, ...] arrays sharded over the segments axis only."""
    return NamedSharding(mesh, P("segments", *([None] * (ndim - 1))))


def block_sharding(mesh: Mesh) -> NamedSharding:
    """[S, D] blocks sharded over both axes."""
    return NamedSharding(mesh, P("segments", "docs"))
