"""Query engine: SQL front-end, planning, execution, reduce.

Reference parity: pinot-common sql parser front-end
(org.apache.pinot.sql.parsers.CalciteSqlParser), pinot-core
core/plan (per-segment physical planning), core/operator (operator tree),
core/query/aggregation, core/query/reduce (broker-side merge).

The TPU execution backend lives in pinot_tpu.ops; this package owns the
host-side logic: parsing, query context, per-segment plan selection, the
CPU reference executor (correctness oracle + fallback for shapes the
device path doesn't cover), and the broker reduce.
"""
from pinot_tpu.query.expressions import Expression, ExpressionType, Literal, Identifier, Function
from pinot_tpu.query.parser import parse_sql
from pinot_tpu.query.context import QueryContext

__all__ = [
    "Expression", "ExpressionType", "Literal", "Identifier", "Function",
    "parse_sql", "QueryContext",
]
