"""Aggregation functions: registry + base contract.

Reference parity: pinot-core
query/aggregation/function/AggregationFunction.java:42 — the contract is
aggregate(block) -> intermediate, aggregateGroupBySV, merge(a, b),
extractFinalResult; AggregationFunctionFactory resolves names.

Each function here exposes BOTH a numpy host path (the correctness oracle /
fallback) and, where possible, a device descriptor the TPU engine composes
into its fused kernel (ops/kernels.py): SUM/COUNT/MIN/MAX are device-native
masked reductions; AVG = SUM+COUNT pair; the sketch family (HLL, TDigest,
distinct sets) stays host-side, as SURVEY.md §7.6 plans.
"""
from pinot_tpu.query.aggregation.base import (
    AggregationFunction, DeviceAggSpec, get_aggregation, is_aggregation,
    REGISTRY)
from pinot_tpu.query.aggregation import functions as _functions  # registers
from pinot_tpu.query.aggregation import functions_stats as _stats  # registers

__all__ = [
    "AggregationFunction", "DeviceAggSpec", "get_aggregation",
    "is_aggregation", "REGISTRY",
]
