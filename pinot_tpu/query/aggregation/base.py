"""Aggregation function base contract + registry."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Type

import numpy as np


@dataclass(frozen=True)
class DeviceAggSpec:
    """How the TPU kernel computes this aggregation's intermediate.

    op: one of 'sum' | 'min' | 'max' | 'count' | 'sumsq' | 'sum3' | 'sum4'
    — the masked reduction the fused device kernel emits. Functions whose
    intermediate is a tuple of these (AVG = sum+count; moments =
    sum+sumsq[+sum3+sum4]+count) list several slots. Functions with no
    spec run host-side.
    """
    ops: tuple  # e.g. ('sum',), ('sum', 'count')


class AggregationFunction:
    """One aggregation instance bound to its argument expressions."""

    #: canonical lower-case name(s) to register under
    names: Sequence[str] = ()
    #: device kernel composition, or None for host-only
    device_spec: Optional[DeviceAggSpec] = None
    #: True: `values` arrives stacked [n_args, n] (covariance, with-time)
    multi_arg: bool = False
    #: True: `values` arrives FLAT (all MV entries) with the mask/keys
    #: pre-expanded per entry by the executor (the *MV family)
    mv_input: bool = False

    def __init__(self, args: tuple):
        self.args = args  # tuple[Expression]

    # -- host (numpy) path --------------------------------------------------
    def aggregate(self, values: Optional[np.ndarray], mask: np.ndarray) -> Any:
        """Whole-block aggregate -> intermediate result.

        values: materialized argument column (None for COUNT(*));
        mask: boolean filter mask over docs.
        """
        raise NotImplementedError

    def aggregate_grouped(self, values: Optional[np.ndarray],
                          keys: np.ndarray, num_groups: int,
                          mask: np.ndarray) -> list:
        """Group-by aggregate: returns list of intermediates per group key.

        keys: int group-key per doc (only where mask); num_groups: key space.
        Default implementation loops groups via sorting; subclasses override
        with vectorized bincount-style paths.
        """
        out = []
        for g in range(num_groups):
            gmask = mask & (keys == g)
            out.append(self.aggregate(values, gmask))
        return out

    # -- merge/extract (ref merge / extractFinalResult) ---------------------
    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def identity(self) -> Any:
        """Intermediate for an empty input (merge identity)."""
        raise NotImplementedError

    def extract_final(self, intermediate: Any) -> Any:
        return intermediate

    # -- device path --------------------------------------------------------
    def from_device_slots(self, slots: Dict[str, Any]) -> Any:
        """Build the intermediate from this function's device reduction
        outputs; slots maps op-name -> scalar/array for this function's
        DeviceAggSpec.ops."""
        raise NotImplementedError

    # -- metadata -----------------------------------------------------------
    @property
    def result_name(self) -> str:
        a = ",".join(str(x) for x in self.args)
        return f"{self.names[0]}({a})"

    @property
    def final_dtype(self) -> str:
        return "DOUBLE"


def scalar(v):
    """Unwrap a numpy scalar to its Python value."""
    return v.item() if isinstance(v, np.generic) else v


REGISTRY: Dict[str, Type[AggregationFunction]] = {}


def register(cls: Type[AggregationFunction]) -> Type[AggregationFunction]:
    for name in cls.names:
        REGISTRY[name.lower()] = cls
    return cls


def is_aggregation(name: str) -> bool:
    if name.lower() in REGISTRY:
        return True
    from pinot_tpu.query.aggregation.functions import resolve_percentile_suffix
    return resolve_percentile_suffix(name, ()) is not None


def get_aggregation(name: str, args: tuple) -> AggregationFunction:
    cls = REGISTRY.get(name.lower())
    if cls is not None:
        return cls(args)
    from pinot_tpu.query.aggregation.functions import resolve_percentile_suffix
    inst = resolve_percentile_suffix(name, args)
    if inst is None:
        raise ValueError(f"unknown aggregation function: {name}")
    return inst
