"""Concrete aggregation functions.

Reference parity: pinot-core query/aggregation/function/ — the families
implemented so far (SUM/MIN/MAX/COUNT/AVG/MINMAXRANGE, DISTINCTCOUNT exact
+ HLL, PERCENTILE exact/est/TDigest, MODE, SUMPRECISION, and the
value-array helpers). Sketches live in sketches.py.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.query.aggregation.base import (
    AggregationFunction, DeviceAggSpec, register)
from pinot_tpu.query.aggregation.sketches import HyperLogLog, TDigest


def _masked(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    if mask is None:
        return values
    return values[mask]


def _grouped_bincount(keys, num_groups, mask, weights=None):
    k = keys[mask]
    w = None if weights is None else weights[mask]
    return np.bincount(k, weights=w, minlength=num_groups)


@register
class CountAggregation(AggregationFunction):
    names = ("count",)
    device_spec = DeviceAggSpec(("count",))

    def aggregate(self, values, mask):
        return int(np.count_nonzero(mask))

    def aggregate_grouped(self, values, keys, num_groups, mask):
        return _grouped_bincount(keys, num_groups, mask).astype(np.int64).tolist()

    def merge(self, a, b):
        return a + b

    def identity(self):
        return 0

    def from_device_slots(self, slots):
        # device counts arrive in the value dtype (single packed output)
        return int(round(float(slots["count"])))

    @property
    def result_name(self):
        return "count(*)" if not self.args or str(self.args[0]) == "*" \
            else super().result_name

    @property
    def final_dtype(self):
        return "LONG"


@register
class SumAggregation(AggregationFunction):
    names = ("sum",)
    device_spec = DeviceAggSpec(("sum",))

    def aggregate(self, values, mask):
        return float(np.sum(_masked(values, mask), dtype=np.float64))

    def aggregate_grouped(self, values, keys, num_groups, mask):
        return _grouped_bincount(keys, num_groups, mask,
                                 values.astype(np.float64)).tolist()

    def merge(self, a, b):
        return a + b

    def identity(self):
        return 0.0

    def from_device_slots(self, slots):
        return float(slots["sum"])


@register
class MinAggregation(AggregationFunction):
    names = ("min",)
    device_spec = DeviceAggSpec(("min",))

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        return float(np.min(v)) if len(v) else float("inf")

    def aggregate_grouped(self, values, keys, num_groups, mask):
        out = np.full(num_groups, np.inf)
        k, v = keys[mask], values[mask].astype(np.float64)
        np.minimum.at(out, k, v)
        return out.tolist()

    def merge(self, a, b):
        return min(a, b)

    def identity(self):
        return float("inf")

    def from_device_slots(self, slots):
        return float(slots["min"])


@register
class MaxAggregation(AggregationFunction):
    names = ("max",)
    device_spec = DeviceAggSpec(("max",))

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        return float(np.max(v)) if len(v) else float("-inf")

    def aggregate_grouped(self, values, keys, num_groups, mask):
        out = np.full(num_groups, -np.inf)
        k, v = keys[mask], values[mask].astype(np.float64)
        np.maximum.at(out, k, v)
        return out.tolist()

    def merge(self, a, b):
        return max(a, b)

    def identity(self):
        return float("-inf")

    def from_device_slots(self, slots):
        return float(slots["max"])


@register
class AvgAggregation(AggregationFunction):
    """Intermediate is (sum, count) (ref AvgAggregationFunction AvgPair)."""
    names = ("avg",)
    device_spec = DeviceAggSpec(("sum", "count"))

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        return (float(np.sum(v, dtype=np.float64)), len(v))

    def aggregate_grouped(self, values, keys, num_groups, mask):
        s = _grouped_bincount(keys, num_groups, mask, values.astype(np.float64))
        c = _grouped_bincount(keys, num_groups, mask)
        return list(zip(s.tolist(), c.astype(np.int64).tolist()))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def identity(self):
        return (0.0, 0)

    def extract_final(self, intermediate):
        s, c = intermediate
        return s / c if c else float("-inf")  # ref returns NEGATIVE_INFINITY

    def from_device_slots(self, slots):
        return (float(slots["sum"]), int(round(float(slots["count"]))))


@register
class MinMaxRangeAggregation(AggregationFunction):
    """Intermediate is (min, max) (ref MinMaxRangeAggregationFunction)."""
    names = ("minmaxrange",)
    device_spec = DeviceAggSpec(("min", "max"))

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        if not len(v):
            return (float("inf"), float("-inf"))
        return (float(np.min(v)), float(np.max(v)))

    def merge(self, a, b):
        return (min(a[0], b[0]), max(a[1], b[1]))

    def identity(self):
        return (float("inf"), float("-inf"))

    def extract_final(self, intermediate):
        return intermediate[1] - intermediate[0]

    def from_device_slots(self, slots):
        return (float(slots["min"]), float(slots["max"]))


@register
class SumPrecisionAggregation(AggregationFunction):
    """Exact big-decimal sum (ref SumPrecisionAggregationFunction)."""
    names = ("sumprecision",)

    def aggregate(self, values, mask):
        from decimal import Decimal
        v = _masked(values, mask)
        total = Decimal(0)
        for x in v.tolist():
            total += Decimal(str(x))
        return total

    def merge(self, a, b):
        return a + b

    def identity(self):
        from decimal import Decimal
        return Decimal(0)

    def extract_final(self, intermediate):
        return str(intermediate)

    @property
    def final_dtype(self):
        return "BIG_DECIMAL"


@register
class DistinctCountAggregation(AggregationFunction):
    """Exact distinct count; intermediate is a value set
    (ref DistinctCountAggregationFunction)."""
    names = ("distinctcount", "distinctcountbitmap", "segmentpartitioneddistinctcount")

    def aggregate(self, values, mask):
        return set(np.unique(_masked(values, mask)).tolist())

    def aggregate_grouped(self, values, keys, num_groups, mask):
        out = [set() for _ in range(num_groups)]
        k, v = keys[mask], values[mask]
        order = np.argsort(k, kind="stable")
        k, v = k[order], v[order]
        bounds = np.searchsorted(k, np.arange(num_groups + 1))
        for g in range(num_groups):
            seg = v[bounds[g]:bounds[g + 1]]
            if len(seg):
                out[g] = set(np.unique(seg).tolist())
        return out

    def merge(self, a, b):
        return a | b

    def identity(self):
        return set()

    def extract_final(self, intermediate):
        return len(intermediate)

    @property
    def final_dtype(self):
        return "INT"


@register
class DistinctCountHLLAggregation(AggregationFunction):
    """Approximate distinct count via HyperLogLog
    (ref DistinctCountHLLAggregationFunction, log2m default 12)."""
    names = ("distinctcounthll", "distinctcounthllplus", "distinctcountull",
             "distinctcountcpcsketch")

    def _log2m(self) -> int:
        from pinot_tpu.query.expressions import Literal
        if len(self.args) > 1 and isinstance(self.args[1], Literal):
            return int(self.args[1].value)
        return 12

    def aggregate(self, values, mask):
        hll = HyperLogLog(self._log2m())
        hll.add_array(_masked(values, mask))
        return hll

    def merge(self, a, b):
        return a.merge(b)

    def identity(self):
        return HyperLogLog(self._log2m())

    def extract_final(self, intermediate):
        return intermediate.cardinality()

    @property
    def device_spec(self):
        """Device path: registers computed by a hash->bucket->max-scatter
        kernel over the column's i32 split planes (ops/kernels.py 'hll'
        op); bit-identical to the host sketch, so partials merge exactly.
        Plain-column args only (the kernel hashes staged planes)."""
        from pinot_tpu.query.expressions import Identifier
        if self.args and isinstance(self.args[0], Identifier) \
                and self.args[0].name != "*":
            return DeviceAggSpec(ops=(f"hll:{self._log2m()}",))
        return None

    def from_device_slots(self, slots):
        return HyperLogLog.from_registers(
            slots[f"hll:{self._log2m()}"], self._log2m())

    @property
    def final_dtype(self):
        return "LONG"


class _ValueCollectingAggregation(AggregationFunction):
    """Base for functions whose intermediate is the collected value array."""

    def aggregate(self, values, mask):
        return _masked(values, mask).astype(np.float64)

    def aggregate_grouped(self, values, keys, num_groups, mask):
        k, v = keys[mask], values[mask].astype(np.float64)
        order = np.argsort(k, kind="stable")
        k, v = k[order], v[order]
        bounds = np.searchsorted(k, np.arange(num_groups + 1))
        return [v[bounds[g]:bounds[g + 1]] for g in range(num_groups)]

    def merge(self, a, b):
        return np.concatenate([a, b])

    def identity(self):
        return np.empty(0, dtype=np.float64)


@register
class PercentileAggregation(_ValueCollectingAggregation):
    """Exact percentile (ref PercentileAggregationFunction).

    percentile(col, p) or legacy percentileNN(col) via name suffix.
    """
    names = ("percentile", "percentileest", "percentilerawest")

    def __init__(self, args, percent: Optional[float] = None):
        super().__init__(args)
        from pinot_tpu.query.expressions import Literal
        if percent is not None:
            self._pct = percent
        elif len(args) > 1 and isinstance(args[1], Literal):
            self._pct = float(args[1].value)
        else:
            self._pct = 50.0

    def extract_final(self, intermediate):
        if not len(intermediate):
            return float("-inf")
        # ref PercentileAggregationFunction: index = floor(len * p / 100) on
        # the sorted array, clamped to the last element
        v = np.sort(intermediate)
        idx = min(int(len(v) * self._pct / 100.0), len(v) - 1)
        return float(v[idx])


@register
class PercentileTDigestAggregation(AggregationFunction):
    """Approximate percentile via t-digest
    (ref PercentileTDigestAggregationFunction, compression 100)."""
    names = ("percentiletdigest", "percentilerawtdigest")

    def __init__(self, args, percent: Optional[float] = None):
        super().__init__(args)
        from pinot_tpu.query.expressions import Literal
        self._pct = percent if percent is not None else (
            float(args[1].value) if len(args) > 1 and isinstance(args[1], Literal)
            else 50.0)
        self._compression = (
            float(args[2].value) if len(args) > 2 and isinstance(args[2], Literal)
            else 100.0)

    #: device histogram resolution (quantile error <= one bucket width of
    #: the column's [min, max] range on top of the digest's own error)
    DEVICE_BUCKETS = 8192

    def aggregate(self, values, mask):
        td = TDigest(self._compression)
        td.add_array(_masked(values, mask))
        return td

    def merge(self, a, b):
        return a.merge(b)

    def identity(self):
        return TDigest(self._compression)

    def extract_final(self, intermediate):
        return intermediate.quantile(self._pct / 100.0)

    @property
    def device_spec(self):
        """Device path: fixed-bucket histogram partials (scatter-add over
        value buckets, bounds from segment metadata min/max) converted to
        centroid weights host-side. Plain-column args only (bucket bounds
        come from that column's metadata)."""
        from pinot_tpu.query.expressions import Identifier
        if self.args and isinstance(self.args[0], Identifier) \
                and self.args[0].name != "*":
            return DeviceAggSpec(ops=(f"hist:{self.DEVICE_BUCKETS}",))
        return None

    def from_device_slots(self, slots):
        return TDigest.from_histogram(
            slots["hist_lo"], slots["hist_width"],
            slots[f"hist:{self.DEVICE_BUCKETS}"], self._compression)


@register
class ModeAggregation(AggregationFunction):
    """Most frequent value; intermediate is value->count dict
    (ref ModeAggregationFunction, default MIN tie-break)."""
    names = ("mode",)

    def aggregate(self, values, mask):
        v, c = np.unique(_masked(values, mask), return_counts=True)
        return dict(zip(v.tolist(), c.tolist()))

    def merge(self, a, b):
        for k, v in b.items():
            a[k] = a.get(k, 0) + v
        return a

    def identity(self):
        return {}

    def extract_final(self, intermediate):
        if not intermediate:
            return float("-inf")
        best = max(intermediate.items(), key=lambda kv: (kv[1], -_as_float(kv[0])))
        return float(best[0])


def _as_float(x) -> float:
    try:
        return float(x)
    except (TypeError, ValueError):
        return 0.0


@register
class CountMVAggregation(AggregationFunction):
    """COUNT over multi-value column entries (ref CountMVAggregationFunction);
    values here is the per-doc entry-count array."""
    names = ("countmv",)

    def aggregate(self, values, mask):
        return int(np.sum(_masked(values, mask)))

    def merge(self, a, b):
        return a + b

    def identity(self):
        return 0

    @property
    def final_dtype(self):
        return "LONG"


# Legacy percentileNN / percentileTDigestNN names (ref
# AggregationFunctionFactory parses the numeric suffix).
def resolve_percentile_suffix(name: str, args: tuple):
    """percentile95(col) style names -> configured instance, or None."""
    import re
    m = re.fullmatch(r"(percentile(?:est|kll|tdigest|rawest|rawtdigest)?)(\d{1,3})",
                     name.lower())
    if m is None:
        return None
    base, pct = m.group(1), float(m.group(2))
    if "tdigest" in base:
        return PercentileTDigestAggregation(args, percent=pct)
    if "kll" in base:
        from pinot_tpu.query.aggregation.functions_stats import (
            PercentileKLLAggregation)
        return PercentileKLLAggregation(args, percent=pct)
    return PercentileAggregation(args, percent=pct)
