"""Statistical / sketch / multi-value aggregation functions.

Reference parity: pinot-core query/aggregation/function/ —
VarianceAggregationFunction + StdDev variants (via Welford-style merge;
here raw-moment tuples), SkewnessAggregationFunction /
KurtosisAggregationFunction (FourthMoment.java), CovarianceAggregationFunction,
FirstWithTimeAggregationFunction / LastWithTime,
HistogramAggregationFunction, DistinctSum/DistinctAvg, BoolAnd/BoolOr,
DistinctCountThetaSketchAggregationFunction, PercentileKLL, and the MV
family (SumMV/MinMV/MaxMV/AvgMV/MinMaxRangeMV/DistinctCountMV —
ref *MVAggregationFunction classes).

Device offload: variance/stddev ride (sum, sumsq, count) slots;
skew/kurtosis add (sum3, sum4); the rest are host-side (sketches and
multi-arg functions per SURVEY §7.6).
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from pinot_tpu.query.aggregation.base import (
    AggregationFunction, DeviceAggSpec, register, scalar as _scalar)
from pinot_tpu.query.aggregation.functions import _masked
from pinot_tpu.query.aggregation.sketches import KLLSketch, ThetaSketch


# ---------------------------------------------------------------------------
# moments: variance / stddev / skew / kurtosis
# ---------------------------------------------------------------------------

class _MomentsAggregation(AggregationFunction):
    """Intermediate = (count, sum, sumsq[, sum3, sum4]) raw moments —
    trivially mergeable and exactly what the device kernel emits."""
    order = 2

    def aggregate(self, values, mask):
        v = _masked(values, mask).astype(np.float64)
        out = [float(len(v)), float(v.sum()), float((v * v).sum())]
        if self.order >= 4:
            out.append(float((v ** 3).sum()))
            out.append(float((v ** 4).sum()))
        return tuple(out)

    def aggregate_grouped(self, values, keys, num_groups, mask):
        k = keys[mask]
        v = values[mask].astype(np.float64)
        cnt = np.bincount(k, minlength=num_groups)
        s1 = np.bincount(k, weights=v, minlength=num_groups)
        s2 = np.bincount(k, weights=v * v, minlength=num_groups)
        cols = [cnt.astype(np.float64), s1, s2]
        if self.order >= 4:
            cols.append(np.bincount(k, weights=v ** 3, minlength=num_groups))
            cols.append(np.bincount(k, weights=v ** 4, minlength=num_groups))
        return [tuple(float(c[g]) for c in cols) for g in range(num_groups)]

    def merge(self, a, b):
        return tuple(x + y for x, y in zip(a, b))

    def identity(self):
        return (0.0,) * (3 if self.order < 4 else 5)

    def from_device_slots(self, slots):
        out = [slots["count"], slots["sum"], slots["sumsq"]]
        if self.order >= 4:
            out.append(slots["sum3"])
            out.append(slots["sum4"])
        return tuple(float(x) for x in out)


def _central_moments(inter):
    n, s1, s2 = inter[0], inter[1], inter[2]
    if n == 0:
        return 0.0, 0.0, 0.0, None, None
    mean = s1 / n
    m2 = s2 / n - mean * mean
    if len(inter) < 5:
        return n, mean, m2, None, None
    s3, s4 = inter[3], inter[4]
    m3 = s3 / n - 3 * mean * s2 / n + 2 * mean ** 3
    m4 = s4 / n - 4 * mean * s3 / n + 6 * mean * mean * s2 / n - 3 * mean ** 4
    return n, mean, m2, m3, m4


@register
class VariancePopAggregation(_MomentsAggregation):
    names = ("variance", "var_pop", "varpop")
    device_spec = DeviceAggSpec(("sum", "sumsq", "count"))

    def extract_final(self, inter):
        n, _mean, m2, _, _ = _central_moments(inter)
        return max(m2, 0.0) if n else 0.0


@register
class VarianceSampAggregation(_MomentsAggregation):
    names = ("var_samp", "varsamp", "variancesamp")
    device_spec = DeviceAggSpec(("sum", "sumsq", "count"))

    def extract_final(self, inter):
        n, _mean, m2, _, _ = _central_moments(inter)
        if n < 2:
            return 0.0
        return max(m2 * n / (n - 1), 0.0)


@register
class StdDevPopAggregation(VariancePopAggregation):
    names = ("stddev", "stddev_pop", "stddevpop")

    def extract_final(self, inter):
        return float(np.sqrt(super().extract_final(inter)))


@register
class StdDevSampAggregation(VarianceSampAggregation):
    names = ("stddev_samp", "stddevsamp")

    def extract_final(self, inter):
        return float(np.sqrt(super().extract_final(inter)))


@register
class SkewnessAggregation(_MomentsAggregation):
    """ref SkewnessAggregationFunction (FourthMoment based)."""
    names = ("skewness",)
    order = 4
    device_spec = DeviceAggSpec(("sum", "sumsq", "sum3", "sum4", "count"))

    def extract_final(self, inter):
        n, _mean, m2, m3, _ = _central_moments(inter)
        if not n or m2 <= 0:
            return 0.0
        return float(m3 / m2 ** 1.5)


@register
class KurtosisAggregation(_MomentsAggregation):
    """Excess kurtosis (ref KurtosisAggregationFunction)."""
    names = ("kurtosis",)
    order = 4
    device_spec = DeviceAggSpec(("sum", "sumsq", "sum3", "sum4", "count"))

    def extract_final(self, inter):
        n, _mean, m2, _m3, m4 = _central_moments(inter)
        if not n or m2 <= 0:
            return 0.0
        return float(m4 / (m2 * m2) - 3.0)


# ---------------------------------------------------------------------------
# covariance (two-argument)
# ---------------------------------------------------------------------------

class _CovarianceBase(AggregationFunction):
    """values arrives stacked [2, n] (multi_arg contract).
    Intermediate = (count, sum_x, sum_y, sum_xy)."""
    multi_arg = True

    def aggregate(self, values, mask):
        x = values[0][mask].astype(np.float64)
        y = values[1][mask].astype(np.float64)
        return (float(len(x)), float(x.sum()), float(y.sum()),
                float((x * y).sum()))

    def aggregate_grouped(self, values, keys, num_groups, mask):
        k = keys[mask]
        x = values[0][mask].astype(np.float64)
        y = values[1][mask].astype(np.float64)
        cnt = np.bincount(k, minlength=num_groups).astype(np.float64)
        sx = np.bincount(k, weights=x, minlength=num_groups)
        sy = np.bincount(k, weights=y, minlength=num_groups)
        sxy = np.bincount(k, weights=x * y, minlength=num_groups)
        return [(float(cnt[g]), float(sx[g]), float(sy[g]), float(sxy[g]))
                for g in range(num_groups)]

    def merge(self, a, b):
        return tuple(p + q for p, q in zip(a, b))

    def identity(self):
        return (0.0, 0.0, 0.0, 0.0)


@register
class CovarPopAggregation(_CovarianceBase):
    names = ("covar_pop", "covarpop")

    def extract_final(self, inter):
        n, sx, sy, sxy = inter
        if n == 0:
            return 0.0
        return float(sxy / n - (sx / n) * (sy / n))


@register
class CovarSampAggregation(_CovarianceBase):
    names = ("covar_samp", "covarsamp")

    def extract_final(self, inter):
        n, sx, sy, sxy = inter
        if n < 2:
            return 0.0
        return float((sxy - sx * sy / n) / (n - 1))


# ---------------------------------------------------------------------------
# FIRST/LAST with time (two-argument)
# ---------------------------------------------------------------------------

class _WithTimeBase(AggregationFunction):
    """firstwithtime(col, timeCol[, 'dataType']) — intermediate is
    (time, value) of the extreme-time row (ref FirstWithTimeAggregationFunction)."""
    multi_arg = True
    #: number of leading args that are data columns (3rd is a type literal)
    n_data_args = 2
    pick_first = True

    def aggregate(self, values, mask):
        v = values[0][mask]
        t = values[1][mask].astype(np.float64)
        if len(t) == 0:
            return None
        idx = int(np.argmin(t) if self.pick_first else np.argmax(t))
        return (float(t[idx]), _scalar(v[idx]))

    def aggregate_grouped(self, values, keys, num_groups, mask):
        k = keys[mask]
        v = values[0][mask]
        t = values[1][mask].astype(np.float64)
        out = [None] * num_groups
        order = np.argsort(k, kind="stable")
        k, v, t = k[order], v[order], t[order]
        bounds = np.searchsorted(k, np.arange(num_groups + 1))
        for g in range(num_groups):
            ts = t[bounds[g]:bounds[g + 1]]
            if len(ts):
                i = int(np.argmin(ts) if self.pick_first else np.argmax(ts))
                out[g] = (float(ts[i]), _scalar(v[bounds[g] + i]))
        return out

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if self.pick_first:
            return a if a[0] <= b[0] else b
        return a if a[0] >= b[0] else b

    def identity(self):
        return None

    def extract_final(self, inter):
        return inter[1] if inter is not None else None


@register
class FirstWithTimeAggregation(_WithTimeBase):
    names = ("firstwithtime",)
    pick_first = True


@register
class LastWithTimeAggregation(_WithTimeBase):
    names = ("lastwithtime",)
    pick_first = False


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------

@register
class HistogramAggregation(AggregationFunction):
    """histogram(col, lower, upper, numBins) — final result is the
    per-bucket count list (ref HistogramAggregationFunction equal-length
    mode)."""
    names = ("histogram",)

    def __init__(self, args):
        super().__init__(args)
        from pinot_tpu.query.expressions import Literal
        lits = [a.value for a in args[1:] if isinstance(a, Literal)]
        if len(lits) != 3:
            raise ValueError(
                "histogram(col, lower, upper, numBins) expected")
        self.lower, self.upper = float(lits[0]), float(lits[1])
        self.bins = int(lits[2])
        self.edges = np.linspace(self.lower, self.upper, self.bins + 1)

    def aggregate(self, values, mask):
        v = _masked(values, mask).astype(np.float64)
        counts, _ = np.histogram(v, bins=self.edges)
        return counts.astype(np.float64)

    def aggregate_grouped(self, values, keys, num_groups, mask):
        k = keys[mask]
        v = values[mask].astype(np.float64)
        out = []
        order = np.argsort(k, kind="stable")
        k, v = k[order], v[order]
        bounds = np.searchsorted(k, np.arange(num_groups + 1))
        for g in range(num_groups):
            counts, _ = np.histogram(v[bounds[g]:bounds[g + 1]],
                                     bins=self.edges)
            out.append(counts.astype(np.float64))
        return out

    def merge(self, a, b):
        return a + b

    def identity(self):
        return np.zeros(self.bins, dtype=np.float64)

    def extract_final(self, inter):
        return [float(x) for x in inter]

    @property
    def final_dtype(self):
        return "DOUBLE_ARRAY"


# ---------------------------------------------------------------------------
# boolean / distinct-value folds
# ---------------------------------------------------------------------------

@register
class BoolAndAggregation(AggregationFunction):
    names = ("bool_and", "booland")
    device_spec = DeviceAggSpec(("min", "count"))

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        return bool(np.all(v.astype(bool))) if len(v) else True

    def merge(self, a, b):
        return a and b

    def identity(self):
        return True

    def from_device_slots(self, slots):
        return bool(slots["count"] == 0 or slots["min"] >= 0.5)

    @property
    def final_dtype(self):
        return "BOOLEAN"


@register
class BoolOrAggregation(AggregationFunction):
    names = ("bool_or", "boolor")
    device_spec = DeviceAggSpec(("max", "count"))

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        return bool(np.any(v.astype(bool))) if len(v) else False

    def merge(self, a, b):
        return a or b

    def identity(self):
        return False

    def from_device_slots(self, slots):
        return bool(slots["count"] > 0 and slots["max"] >= 0.5)

    @property
    def final_dtype(self):
        return "BOOLEAN"


class _DistinctFoldBase(AggregationFunction):
    """Set intermediate with a numeric fold at extraction."""

    def aggregate(self, values, mask):
        return set(np.unique(_masked(values, mask)).tolist())

    def merge(self, a, b):
        return a | b

    def identity(self):
        return set()


@register
class DistinctSumAggregation(_DistinctFoldBase):
    names = ("distinctsum",)

    def extract_final(self, inter):
        return float(sum(inter)) if inter else 0.0


@register
class DistinctAvgAggregation(_DistinctFoldBase):
    names = ("distinctavg",)

    def extract_final(self, inter):
        return float(sum(inter) / len(inter)) if inter else 0.0


# ---------------------------------------------------------------------------
# sketches
# ---------------------------------------------------------------------------

@register
class DistinctCountThetaAggregation(AggregationFunction):
    """ref DistinctCountThetaSketchAggregationFunction (nominal entries
    default 4096)."""
    names = ("distinctcountthetasketch", "distinctcountrawthetasketch")

    def _k(self) -> int:
        from pinot_tpu.query.expressions import Literal
        if len(self.args) > 1 and isinstance(self.args[1], Literal):
            try:
                return int(self.args[1].value)
            except (TypeError, ValueError):
                return 4096
        return 4096

    def aggregate(self, values, mask):
        sk = ThetaSketch(self._k())
        sk.add_array(_masked(values, mask))
        return sk

    def merge(self, a, b):
        return a.merge(b)

    def identity(self):
        return ThetaSketch(self._k())

    def extract_final(self, inter):
        return inter.estimate()

    @property
    def final_dtype(self):
        return "LONG"


@register
class PercentileKLLAggregation(AggregationFunction):
    """ref PercentileKLLAggregationFunction (K default 200)."""
    names = ("percentilekll", "percentilerawkll")

    def __init__(self, args, percent: Optional[float] = None):
        super().__init__(args)
        from pinot_tpu.query.expressions import Literal
        self._pct = percent if percent is not None else (
            float(args[1].value) if len(args) > 1
            and isinstance(args[1], Literal) else 50.0)
        self._k = (int(args[2].value) if len(args) > 2
                   and isinstance(args[2], Literal) else 200)

    def aggregate(self, values, mask):
        sk = KLLSketch(self._k)
        sk.add_array(_masked(values, mask))
        return sk

    def merge(self, a, b):
        return a.merge(b)

    def identity(self):
        return KLLSketch(self._k)

    def extract_final(self, inter):
        return inter.quantile(self._pct / 100.0)


# ---------------------------------------------------------------------------
# multi-value (MV) family — values arrive FLAT with pre-expanded mask/keys
# ---------------------------------------------------------------------------

class _MVMixin:
    mv_input = True


@register
class SumMVAggregation(_MVMixin, AggregationFunction):
    names = ("summv",)

    def aggregate(self, values, mask):
        return float(_masked(values, mask).astype(np.float64).sum())

    def merge(self, a, b):
        return a + b

    def identity(self):
        return 0.0


@register
class MinMVAggregation(_MVMixin, AggregationFunction):
    names = ("minmv",)

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        return float(v.min()) if len(v) else float("inf")

    def merge(self, a, b):
        return min(a, b)

    def identity(self):
        return float("inf")


@register
class MaxMVAggregation(_MVMixin, AggregationFunction):
    names = ("maxmv",)

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        return float(v.max()) if len(v) else float("-inf")

    def merge(self, a, b):
        return max(a, b)

    def identity(self):
        return float("-inf")


@register
class AvgMVAggregation(_MVMixin, AggregationFunction):
    names = ("avgmv",)

    def aggregate(self, values, mask):
        v = _masked(values, mask).astype(np.float64)
        return (float(v.sum()), int(len(v)))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def identity(self):
        return (0.0, 0)

    def extract_final(self, inter):
        s, n = inter
        return s / n if n else 0.0


@register
class MinMaxRangeMVAggregation(_MVMixin, AggregationFunction):
    names = ("minmaxrangemv",)

    def aggregate(self, values, mask):
        v = _masked(values, mask)
        if not len(v):
            return (float("inf"), float("-inf"))
        return (float(v.min()), float(v.max()))

    def merge(self, a, b):
        return (min(a[0], b[0]), max(a[1], b[1]))

    def identity(self):
        return (float("inf"), float("-inf"))

    def extract_final(self, inter):
        lo, hi = inter
        return hi - lo if hi >= lo else 0.0


@register
class DistinctCountMVAggregation(_MVMixin, AggregationFunction):
    names = ("distinctcountmv",)

    def aggregate(self, values, mask):
        return set(np.unique(_masked(values, mask)).tolist())

    def merge(self, a, b):
        return a | b

    def identity(self):
        return set()

    def extract_final(self, inter):
        return len(inter)

    @property
    def final_dtype(self):
        return "INT"



@register
class DistinctCountTupleAggregation(DistinctCountThetaAggregation):
    """Tuple-sketch distinct count rides the same KMV machinery (ref
    DistinctCountTupleSketchAggregationFunction — the tuple sketch is a
    theta sketch with per-key summaries; distinct counting only needs the
    key set)."""
    names = ("distinctcounttuplesketch", "distinctcountrawintegersumtuplesketch")


# ---------------------------------------------------------------------------
# funnel + collection aggregations
# ---------------------------------------------------------------------------

@register
class FunnelCountAggregation(AggregationFunction):
    """funnelcount(correlate_col, step1_cond, step2_cond, ...) — per-step
    counts of correlation ids that satisfied ALL steps up to k
    (ref FunnelCountAggregationFunction's set-intersection strategy; the
    ordered/window variants are the reference's non-default modes).

    Intermediate: list of per-step id SETS (prefix-intersection deferred
    to extract so merges stay unions)."""
    names = ("funnelcount", "funnel_count")
    multi_arg = True

    def aggregate(self, values, mask):
        corr = values[0]
        steps = values[1:]
        out = []
        for s in steps:
            m = mask & (np.asarray(s).astype(bool))
            out.append({_scalar(v) for v in corr[m]})
        return out

    def aggregate_grouped(self, values, keys, num_groups, mask):
        out = [self.identity() for _ in range(num_groups)]
        corr = values[0]
        for si, s in enumerate(values[1:]):
            m = mask & (np.asarray(s).astype(bool))
            k = keys[m]
            c = corr[m]
            for g, v in zip(k, c):
                while len(out[g]) <= si:
                    out[g].append(set())
                out[g][si].add(_scalar(v))
        return out

    def merge(self, a, b):
        n = max(len(a), len(b))
        out = []
        for i in range(n):
            sa = a[i] if i < len(a) else set()
            sb = b[i] if i < len(b) else set()
            out.append(sa | sb)
        return out

    def identity(self):
        return [set() for _ in self.args[1:]]

    def extract_final(self, inter):
        counts = []
        reached = None
        for s in inter:
            reached = set(s) if reached is None else (reached & s)
            counts.append(len(reached))
        return counts

    @property
    def final_dtype(self):
        return "LONG_ARRAY"


@register
class FunnelCompleteCountAggregation(FunnelCountAggregation):
    """Count of ids completing EVERY step (ref
    FunnelCompleteCountAggregationFunction)."""
    names = ("funnelcompletecount",)

    def extract_final(self, inter):
        counts = super().extract_final(inter)
        return counts[-1] if counts else 0

    @property
    def final_dtype(self):
        return "LONG"


@register
class ArrayAggAggregation(AggregationFunction):
    """arrayagg(col[, 'dataType'][, distinct]) — collect values (ref
    ArrayAggFunction family)."""
    names = ("arrayagg", "array_agg", "listagg")

    def _distinct(self) -> bool:
        from pinot_tpu.query.expressions import Literal
        return any(isinstance(a, Literal) and str(a.value).lower() == "true"
                   for a in self.args[1:])

    def aggregate(self, values, mask):
        return [_scalar(v) for v in values[mask]]

    def aggregate_grouped(self, values, keys, num_groups, mask):
        k = keys[mask]
        v = values[mask]
        order = np.argsort(k, kind="stable")
        k, v = k[order], v[order]
        bounds = np.searchsorted(k, np.arange(num_groups + 1))
        return [[_scalar(x) for x in v[bounds[g]:bounds[g + 1]]]
                for g in range(num_groups)]

    def merge(self, a, b):
        return a + b

    def identity(self):
        return []

    def extract_final(self, inter):
        if self._distinct():
            seen = []
            have = set()
            for v in inter:
                if v not in have:
                    have.add(v)
                    seen.append(v)
            return seen
        return inter

    @property
    def final_dtype(self):
        return "ARRAY"
