"""Host-side cardinality/quantile sketches.

Reference parity: the reference uses library sketches
(com.clearspring HyperLogLog, com.tdunning TDigest, Apache DataSketches) —
pinot-core query/aggregation/function/DistinctCountHLLAggregationFunction,
PercentileTDigestAggregationFunction. These are clean-room numpy
implementations of the standard algorithms (Flajolet et al. HLL with the
usual bias corrections; Dunning's t-digest with size-capped centroid
merging). They stay host-side per SURVEY.md §7.6.
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np


class HyperLogLog:
    """Classic HLL with 2^log2m registers and linear-counting small-range
    correction.

    The hash is a 32-bit pair (bucket from h1, rank from clz(h2)+1) over
    the value's (hi = v >> 24, lo = v & 0xFFFFFF) i32 split planes — the
    SAME planes the device engine stages for big-int columns, so the TPU
    kernel (ops/kernels.py hll op) produces bit-identical registers and
    device/host sketches merge exactly.
    """

    def __init__(self, log2m: int = 12):
        self.log2m = log2m
        self.m = 1 << log2m
        self.registers = np.zeros(self.m, dtype=np.uint8)

    @classmethod
    def from_registers(cls, registers: np.ndarray,
                       log2m: int = 12) -> "HyperLogLog":
        out = cls(log2m)
        np.maximum(out.registers, registers.astype(np.uint8),
                   out=out.registers)
        return out

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        hi, lo = _split_planes(values)
        h1, h2 = hash32_pair(hi, lo)
        idx = (h1 & np.uint32(self.m - 1)).astype(np.int64)
        # rank = leading zeros of h2 + 1 (h2 == 0 -> 33); frexp is exact:
        # h2 = frac * 2^e with frac in [0.5, 1) -> clz = 32 - e
        _frac, e = np.frexp(h2.astype(np.float64))
        rank = np.where(h2 != 0, 33 - e, 33).astype(np.uint8)
        np.maximum.at(self.registers, idx, rank)

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        assert self.log2m == other.log2m
        out = HyperLogLog(self.log2m)
        out.registers = np.maximum(self.registers, other.registers)
        return out

    def cardinality(self) -> int:
        m = float(self.m)
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / float(np.sum(2.0 ** -self.registers.astype(np.float64)))
        if est <= 2.5 * m:
            zeros = int(np.count_nonzero(self.registers == 0))
            if zeros:
                est = m * np.log(m / zeros)
        elif est > (1 << 32) / 30.0:
            # large-range correction for the 32-bit rank hash (hash-value
            # saturation near 2^32 distinct values)
            est = -(2.0 ** 32) * np.log(1.0 - est / 2.0 ** 32)
        return int(round(est))


def _split_planes(values: np.ndarray):
    """Value array -> (hi, lo) uint32 planes matching the device engine's
    big-int staging (ops/engine.py _stage raw64: hi = v >> 24 as i32,
    lo = v & 0xFFFFFF)."""
    if values.dtype.kind in "iu":
        v = values.astype(np.int64)
        hi64 = v >> 24
        wrapped = hi64.astype(np.int32)
        # fold bits the i32 wrap loses (nonzero only for |v| >= 2^55) so
        # huge longs differing in the top byte don't collide; the fold is
        # identity for device-admissible ranges, keeping hash parity
        excess = ((hi64 - wrapped.astype(np.int64)) >> 32).astype(np.int32)
        hi = (wrapped ^ excess).astype(np.uint32)
        lo = (v & 0xFFFFFF).astype(np.int32).astype(np.uint32)
        return hi, lo
    if values.dtype.kind == "f":
        x = values.astype(np.float64).view(np.uint64)
    else:
        x = np.array([hash(v) & 0xFFFFFFFFFFFFFFFF for v in values.tolist()],
                     dtype=np.uint64)
    # fold the top bits (sign + high exponent) into hi so +x/-x and
    # exponent-distant values don't collide
    hi = (((x >> np.uint64(24)) ^ (x >> np.uint64(56)))
          & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    lo = (x & np.uint64(0xFFFFFF)).astype(np.uint32)
    return hi, lo


def _fmix32(h: np.ndarray) -> np.ndarray:
    """murmur3 32-bit finalizer (wrapping uint32 arithmetic)."""
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    return h


def hash32_pair(hi: np.ndarray, lo: np.ndarray):
    """Two decorrelated 32-bit avalanche hashes over (hi, lo) planes.
    Mirrored exactly by the device kernel (ops/kernels.py) in uint32 —
    keep both implementations in lockstep."""
    with np.errstate(over="ignore"):
        h1 = _fmix32(_fmix32(lo ^ np.uint32(0x9E3779B9)) ^ hi)
        h2 = _fmix32(_fmix32(hi ^ np.uint32(0x85EBCA77)) ^ lo)
    return h1, h2


def _hash64(values: np.ndarray) -> np.ndarray:
    """64-bit avalanche hash (splitmix64 finalizer) over arbitrary values."""
    if values.dtype.kind in "iu":
        x = values.astype(np.uint64)
    elif values.dtype.kind == "f":
        x = values.astype(np.float64).view(np.uint64)
    else:
        x = np.array([hash(v) & 0xFFFFFFFFFFFFFFFF for v in values.tolist()],
                     dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


class TDigest:
    """Size-capped merging t-digest (Dunning & Ertl).

    Centroids are kept sorted; when the buffer exceeds a threshold the
    digest re-clusters under the scale-function size bound
    k1(q) = compression/ (2*pi) * asin(2q-1).
    """

    def __init__(self, compression: float = 100.0):
        self.compression = compression
        self.means = np.empty(0, dtype=np.float64)
        self.weights = np.empty(0, dtype=np.float64)
        self._buf_means: list = []
        self._buf_weights: list = []
        self.total = 0.0

    #: buffered points before a re-cluster (compress is vectorized, so a
    #: large buffer amortizes the sort)
    BUFFER = 1 << 16

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        self._buf_means.append(np.asarray(values, dtype=np.float64).ravel())
        self.total += float(len(values))
        if sum(len(b) for b in self._buf_means) > self.BUFFER:
            self._compress()

    @classmethod
    def from_histogram(cls, lo: float, width: float, counts: np.ndarray,
                       compression: float = 100.0) -> "TDigest":
        """Digest from fixed-bucket histogram partials (the device sketch
        path): each non-empty bucket becomes a centroid at its center with
        weight = count. Quantile error is bounded by one bucket width on
        top of the digest's own error."""
        out = cls(compression)
        counts = np.asarray(counts, dtype=np.float64)
        nz = np.nonzero(counts > 0)[0]
        out.means = lo + (nz.astype(np.float64) + 0.5) * width
        out.weights = counts[nz]
        out.total = float(counts.sum())
        out._compress(force=True)
        return out

    def merge(self, other: "TDigest") -> "TDigest":
        out = TDigest(self.compression)
        self._compress()
        other._compress()
        out.means = np.concatenate([self.means, other.means])
        out.weights = np.concatenate([self.weights, other.weights])
        out.total = self.total + other.total
        out._compress(force=True)
        return out

    def _k(self, q: np.ndarray) -> np.ndarray:
        q = np.clip(q, 1e-12, 1 - 1e-12)
        return self.compression * (np.arcsin(2 * q - 1) / np.pi + 0.5)

    def _compress(self, force: bool = False) -> None:
        """Vectorized merging pass: sort all points, assign each to the
        integer cluster floor(k(q_mid)) of its cumulative-weight midpoint
        quantile, and merge clusters with reduceat — the standard
        scale-function construction, O(n log n) with no Python loop."""
        if not self._buf_means and not force:
            return
        parts = [self.means] + self._buf_means
        wparts = [self.weights] + [np.ones(len(b)) for b in self._buf_means]
        means = np.concatenate(parts)
        weights = np.concatenate(wparts)
        self._buf_means, self._buf_weights = [], []
        if len(means) == 0:
            return
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        total = weights.sum()
        q_mid = (np.cumsum(weights) - weights / 2.0) / total
        cluster = np.floor(self._k(q_mid)).astype(np.int64)
        _uniq, idx = np.unique(cluster, return_index=True)
        wsum = np.add.reduceat(weights, idx)
        msum = np.add.reduceat(means * weights, idx)
        self.means = msum / wsum
        self.weights = wsum

    def quantile(self, q: float) -> float:
        self._compress(force=True)
        if len(self.means) == 0:
            return float("-inf")
        if len(self.means) == 1:
            return float(self.means[0])
        cum = np.cumsum(self.weights) - self.weights / 2.0
        target = q * self.total
        return float(np.interp(target, cum, self.means))


class ThetaSketch:
    """KMV-style theta sketch for distinct counting with set operations
    (ref DistinctCountThetaSketchAggregationFunction over Apache
    DataSketches; clean-room K-minimum-values design: keep the k smallest
    64-bit hashes; theta = k-th smallest / 2^64, estimate = (k-1)/theta)."""

    def __init__(self, k: int = 4096):
        self.k = k
        self.hashes = np.empty(0, dtype=np.uint64)  # sorted, unique
        self.theta = np.uint64(0xFFFFFFFFFFFFFFFF)

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        h = np.unique(_hash64(values))
        self._absorb(h)

    def _absorb(self, h: np.ndarray) -> None:
        h = h[h < self.theta]
        merged = np.unique(np.concatenate([self.hashes, h]))
        if len(merged) > self.k:
            merged = merged[: self.k]
            self.theta = merged[-1]
            merged = merged[:-1]
        self.hashes = merged

    def merge(self, other: "ThetaSketch") -> "ThetaSketch":
        out = ThetaSketch(min(self.k, other.k))
        out.theta = min(self.theta, other.theta)
        both = np.unique(np.concatenate([self.hashes, other.hashes]))
        both = both[both < out.theta]
        if len(both) > out.k:
            both = both[: out.k]
            out.theta = both[-1]
            both = both[:-1]
        out.hashes = both
        return out

    def estimate(self) -> int:
        full = np.uint64(0xFFFFFFFFFFFFFFFF)
        if self.theta == full:
            return int(len(self.hashes))
        frac = float(self.theta) / float(full)
        return int(round(len(self.hashes) / frac))


class KLLSketch:
    """KLL quantile sketch (Karnin-Lang-Liberty) — clean-room: compactor
    levels with capacity decaying by ~(2/3)^h; a full level sorts, keeps a
    random parity's every-other item, and promotes it with doubled weight
    (ref PercentileKLLAggregationFunction over DataSketches KllDoublesSketch).
    """

    def __init__(self, k: int = 200, _seed: int = 0):
        self.k = k
        self.levels: list = [np.empty(0, dtype=np.float64)]
        self.n = 0
        # seeded: query results must be reproducible (and host/device parity
        # harnesses run the same query twice)
        self._rng = np.random.default_rng(_seed)

    def _capacity(self, height: int, num_levels: int) -> int:
        depth = num_levels - height - 1
        return max(int(np.ceil(self.k * (2.0 / 3.0) ** depth)), 8)

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        self.n += int(len(values))
        self.levels[0] = np.concatenate(
            [self.levels[0], values.astype(np.float64)])
        self._compress()

    def _compress(self) -> None:
        h = 0
        while h < len(self.levels):
            if len(self.levels[h]) > self._capacity(h, len(self.levels)):
                buf = np.sort(self.levels[h])
                offset = int(self._rng.integers(0, 2))
                promoted = buf[offset::2]
                self.levels[h] = np.empty(0, dtype=np.float64)
                if h + 1 == len(self.levels):
                    self.levels.append(np.empty(0, dtype=np.float64))
                self.levels[h + 1] = np.concatenate(
                    [self.levels[h + 1], promoted])
            h += 1

    def merge(self, other: "KLLSketch") -> "KLLSketch":
        out = KLLSketch(min(self.k, other.k))
        out.n = self.n + other.n
        nl = max(len(self.levels), len(other.levels))
        out.levels = []
        for h in range(nl):
            parts = []
            if h < len(self.levels):
                parts.append(self.levels[h])
            if h < len(other.levels):
                parts.append(other.levels[h])
            out.levels.append(np.concatenate(parts) if parts
                              else np.empty(0, dtype=np.float64))
        out._compress()
        return out

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return float("-inf")
        vals, weights = [], []
        for h, lvl in enumerate(self.levels):
            if len(lvl):
                vals.append(lvl)
                weights.append(np.full(len(lvl), 2 ** h, dtype=np.float64))
        v = np.concatenate(vals)
        w = np.concatenate(weights)
        order = np.argsort(v, kind="stable")
        v, w = v[order], w[order]
        cum = np.cumsum(w)
        target = q * cum[-1]
        idx = int(np.searchsorted(cum, target, side="left"))
        return float(v[min(idx, len(v) - 1)])
