"""Host-side cardinality/quantile sketches.

Reference parity: the reference uses library sketches
(com.clearspring HyperLogLog, com.tdunning TDigest, Apache DataSketches) —
pinot-core query/aggregation/function/DistinctCountHLLAggregationFunction,
PercentileTDigestAggregationFunction. These are clean-room numpy
implementations of the standard algorithms (Flajolet et al. HLL with the
usual bias corrections; Dunning's t-digest with size-capped centroid
merging). They stay host-side per SURVEY.md §7.6.
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np


class HyperLogLog:
    """Classic HLL with 2^log2m registers and linear-counting small-range
    correction."""

    def __init__(self, log2m: int = 12):
        self.log2m = log2m
        self.m = 1 << log2m
        self.registers = np.zeros(self.m, dtype=np.uint8)

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        hashes = _hash64(values)
        idx = (hashes >> np.uint64(64 - self.log2m)).astype(np.int64)
        rest = hashes << np.uint64(self.log2m)
        # rank = leading zeros of the remaining bits + 1, capped
        nbits = 64 - self.log2m
        rank = np.full(len(hashes), nbits + 1, dtype=np.uint8)
        found = np.zeros(len(hashes), dtype=bool)
        for b in range(nbits):
            bit = (rest >> np.uint64(63 - b)) & np.uint64(1)
            newly = (~found) & (bit == 1)
            rank[newly] = b + 1
            found |= newly
        np.maximum.at(self.registers, idx, rank)

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        assert self.log2m == other.log2m
        out = HyperLogLog(self.log2m)
        out.registers = np.maximum(self.registers, other.registers)
        return out

    def cardinality(self) -> int:
        m = float(self.m)
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / float(np.sum(2.0 ** -self.registers.astype(np.float64)))
        if est <= 2.5 * m:
            zeros = int(np.count_nonzero(self.registers == 0))
            if zeros:
                est = m * np.log(m / zeros)
        return int(round(est))


def _hash64(values: np.ndarray) -> np.ndarray:
    """64-bit avalanche hash (splitmix64 finalizer) over arbitrary values."""
    if values.dtype.kind in "iu":
        x = values.astype(np.uint64)
    elif values.dtype.kind == "f":
        x = values.astype(np.float64).view(np.uint64)
    else:
        x = np.array([hash(v) & 0xFFFFFFFFFFFFFFFF for v in values.tolist()],
                     dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


class TDigest:
    """Size-capped merging t-digest (Dunning & Ertl).

    Centroids are kept sorted; when the buffer exceeds a threshold the
    digest re-clusters under the scale-function size bound
    k1(q) = compression/ (2*pi) * asin(2q-1).
    """

    def __init__(self, compression: float = 100.0):
        self.compression = compression
        self.means = np.empty(0, dtype=np.float64)
        self.weights = np.empty(0, dtype=np.float64)
        self._buf_means: list = []
        self._buf_weights: list = []
        self.total = 0.0

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        self._buf_means.extend(values.astype(np.float64).tolist())
        self._buf_weights.extend([1.0] * len(values))
        self.total += float(len(values))
        if len(self._buf_means) > 10 * self.compression:
            self._compress()

    def merge(self, other: "TDigest") -> "TDigest":
        out = TDigest(self.compression)
        self._compress()
        other._compress()
        out.means = np.concatenate([self.means, other.means])
        out.weights = np.concatenate([self.weights, other.weights])
        out.total = self.total + other.total
        out._compress(force=True)
        return out

    def _k(self, q: np.ndarray) -> np.ndarray:
        q = np.clip(q, 1e-12, 1 - 1e-12)
        return self.compression * (np.arcsin(2 * q - 1) / np.pi + 0.5)

    def _compress(self, force: bool = False) -> None:
        if not self._buf_means and not force:
            return
        means = np.concatenate([self.means, np.array(self._buf_means)])
        weights = np.concatenate([self.weights, np.array(self._buf_weights)])
        self._buf_means, self._buf_weights = [], []
        if len(means) == 0:
            return
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        total = weights.sum()
        out_means, out_weights = [], []
        cur_m, cur_w = means[0], weights[0]
        w_so_far = 0.0
        for i in range(1, len(means)):
            q0 = w_so_far / total
            q1 = (w_so_far + cur_w + weights[i]) / total
            if self._k(np.array([q1]))[0] - self._k(np.array([q0]))[0] <= 1.0:
                cur_m = (cur_m * cur_w + means[i] * weights[i]) / (cur_w + weights[i])
                cur_w += weights[i]
            else:
                out_means.append(cur_m)
                out_weights.append(cur_w)
                w_so_far += cur_w
                cur_m, cur_w = means[i], weights[i]
        out_means.append(cur_m)
        out_weights.append(cur_w)
        self.means = np.array(out_means)
        self.weights = np.array(out_weights)

    def quantile(self, q: float) -> float:
        self._compress(force=True)
        if len(self.means) == 0:
            return float("-inf")
        if len(self.means) == 1:
            return float(self.means[0])
        cum = np.cumsum(self.weights) - self.weights / 2.0
        target = q * self.total
        return float(np.interp(target, cum, self.means))


class ThetaSketch:
    """KMV-style theta sketch for distinct counting with set operations
    (ref DistinctCountThetaSketchAggregationFunction over Apache
    DataSketches; clean-room K-minimum-values design: keep the k smallest
    64-bit hashes; theta = k-th smallest / 2^64, estimate = (k-1)/theta)."""

    def __init__(self, k: int = 4096):
        self.k = k
        self.hashes = np.empty(0, dtype=np.uint64)  # sorted, unique
        self.theta = np.uint64(0xFFFFFFFFFFFFFFFF)

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        h = np.unique(_hash64(values))
        self._absorb(h)

    def _absorb(self, h: np.ndarray) -> None:
        h = h[h < self.theta]
        merged = np.unique(np.concatenate([self.hashes, h]))
        if len(merged) > self.k:
            merged = merged[: self.k]
            self.theta = merged[-1]
            merged = merged[:-1]
        self.hashes = merged

    def merge(self, other: "ThetaSketch") -> "ThetaSketch":
        out = ThetaSketch(min(self.k, other.k))
        out.theta = min(self.theta, other.theta)
        both = np.unique(np.concatenate([self.hashes, other.hashes]))
        both = both[both < out.theta]
        if len(both) > out.k:
            both = both[: out.k]
            out.theta = both[-1]
            both = both[:-1]
        out.hashes = both
        return out

    def estimate(self) -> int:
        full = np.uint64(0xFFFFFFFFFFFFFFFF)
        if self.theta == full:
            return int(len(self.hashes))
        frac = float(self.theta) / float(full)
        return int(round(len(self.hashes) / frac))


class KLLSketch:
    """KLL quantile sketch (Karnin-Lang-Liberty) — clean-room: compactor
    levels with capacity decaying by ~(2/3)^h; a full level sorts, keeps a
    random parity's every-other item, and promotes it with doubled weight
    (ref PercentileKLLAggregationFunction over DataSketches KllDoublesSketch).
    """

    def __init__(self, k: int = 200, _seed: int = 0):
        self.k = k
        self.levels: list = [np.empty(0, dtype=np.float64)]
        self.n = 0
        # seeded: query results must be reproducible (and host/device parity
        # harnesses run the same query twice)
        self._rng = np.random.default_rng(_seed)

    def _capacity(self, height: int, num_levels: int) -> int:
        depth = num_levels - height - 1
        return max(int(np.ceil(self.k * (2.0 / 3.0) ** depth)), 8)

    def add_array(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        self.n += int(len(values))
        self.levels[0] = np.concatenate(
            [self.levels[0], values.astype(np.float64)])
        self._compress()

    def _compress(self) -> None:
        h = 0
        while h < len(self.levels):
            if len(self.levels[h]) > self._capacity(h, len(self.levels)):
                buf = np.sort(self.levels[h])
                offset = int(self._rng.integers(0, 2))
                promoted = buf[offset::2]
                self.levels[h] = np.empty(0, dtype=np.float64)
                if h + 1 == len(self.levels):
                    self.levels.append(np.empty(0, dtype=np.float64))
                self.levels[h + 1] = np.concatenate(
                    [self.levels[h + 1], promoted])
            h += 1

    def merge(self, other: "KLLSketch") -> "KLLSketch":
        out = KLLSketch(min(self.k, other.k))
        out.n = self.n + other.n
        nl = max(len(self.levels), len(other.levels))
        out.levels = []
        for h in range(nl):
            parts = []
            if h < len(self.levels):
                parts.append(self.levels[h])
            if h < len(other.levels):
                parts.append(other.levels[h])
            out.levels.append(np.concatenate(parts) if parts
                              else np.empty(0, dtype=np.float64))
        out._compress()
        return out

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return float("-inf")
        vals, weights = [], []
        for h, lvl in enumerate(self.levels):
            if len(lvl):
                vals.append(lvl)
                weights.append(np.full(len(lvl), 2 ** h, dtype=np.float64))
        v = np.concatenate(vals)
        w = np.concatenate(weights)
        order = np.argsort(v, kind="stable")
        v, w = v[order], w[order]
        cum = np.cumsum(w)
        target = q * cum[-1]
        idx = int(np.searchsorted(cum, target, side="left"))
        return float(v[min(idx, len(v) - 1)])
