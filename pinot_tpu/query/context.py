"""QueryContext: executable view of a parsed query.

Reference parity: org.apache.pinot.common.request.context.QueryContext
(pinot-common) — built from PinotQuery, it pre-extracts the aggregation
list, group-by expressions, filter/having trees, order-by and options, and
classifies the query shape the way InstancePlanMakerImplV2.makeSegmentPlanNode
(pinot-core plan/maker/InstancePlanMakerImplV2.java:270) switches on:
aggregation / group-by / selection / distinct.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from pinot_tpu.query.aggregation import AggregationFunction, get_aggregation, is_aggregation
from pinot_tpu.query.expressions import (
    Expression, Function, Identifier, Literal, extract_aggregations)
from pinot_tpu.query.parser import PinotQuery, parse_sql


@dataclass
class QueryContext:
    table: str
    select: List[Expression]                 # post-alias-strip select exprs
    aliases: List[Optional[str]]             # per select expr
    distinct: bool
    filter: Optional[Expression]
    group_by: List[Expression]
    having: Optional[Expression]
    order_by: List[Tuple[Expression, bool]]
    limit: int
    offset: int
    options: Dict[str, str]
    explain: bool = False

    # derived
    aggregations: List[Function] = field(default_factory=list)       # agg fn nodes
    #: binding keys as they appear in select/having/order-by — equals
    #: aggregations[i] except for FILTER aggs where it's the filter_agg node
    agg_keys: List[Function] = field(default_factory=list)
    agg_functions: List[AggregationFunction] = field(default_factory=list)
    # per-aggregation FILTER (WHERE ...) condition, or None
    # (ref FilteredAggregationOperator)
    agg_filters: List[Optional[Expression]] = field(default_factory=list)
    _agg_index: Dict[Function, int] = field(default_factory=dict)

    @classmethod
    def from_query(cls, q: PinotQuery) -> "QueryContext":
        select, aliases = [], []
        for e in q.select_list:
            if isinstance(e, Function) and e.name == "as":
                select.append(e.args[0])
                aliases.append(e.args[1].value)  # type: ignore[union-attr]
            else:
                select.append(e)
                aliases.append(None)
        ctx = cls(table=q.table, select=select, aliases=aliases,
                  distinct=q.distinct, filter=q.filter, group_by=list(q.group_by),
                  having=q.having, order_by=list(q.order_by), limit=q.limit,
                  offset=q.offset, options=dict(q.options), explain=q.explain)
        ctx._extract_aggregations()
        return ctx

    @classmethod
    def from_sql(cls, sql: str) -> "QueryContext":
        return cls.from_query(parse_sql(sql))

    # ------------------------------------------------------------------
    def _extract_aggregations(self) -> None:
        seen: Dict[Function, int] = {}
        out: List[Function] = []          # outer nodes (binding keys)
        inner: List[Function] = []        # the agg function node itself
        filters: List[Optional[Expression]] = []

        def walk(e: Expression) -> None:
            if not isinstance(e, Function):
                return
            if e.name == "filter_agg":
                if e not in seen:
                    seen[e] = len(out)
                    out.append(e)
                    inner.append(e.args[0])  # type: ignore[arg-type]
                    filters.append(e.args[1])
                return  # don't descend: inner agg is owned by this node
            if is_aggregation(e.name):
                if e not in seen:
                    seen[e] = len(out)
                    out.append(e)
                    inner.append(e)
                    filters.append(None)
                return
            for a in e.args:
                walk(a)

        sources = list(self.select) + [e for e, _ in self.order_by]
        if self.having is not None:
            sources.append(self.having)
        for e in sources:
            walk(e)
        self.aggregations = inner
        self.agg_keys = out
        self.agg_filters = filters
        self._agg_index = seen
        self.agg_functions = [
            get_aggregation(f.name, f.args) for f in inner]

    def agg_index(self, node: Function) -> int:
        return self._agg_index[node]

    # -- query-shape classification (ref makeSegmentPlanNode:270) -----------
    @property
    def is_aggregation_query(self) -> bool:
        return bool(self.aggregations) and not self.group_by

    @property
    def is_group_by_query(self) -> bool:
        return bool(self.aggregations) and bool(self.group_by)

    @property
    def is_distinct_query(self) -> bool:
        return self.distinct

    @property
    def is_selection_query(self) -> bool:
        return not self.aggregations and not self.distinct

    #: options that steer caching/observability, not the result — two
    #: queries differing only here MUST share a fingerprint
    _FINGERPRINT_OPT_DENYLIST = frozenset(
        {"skipcache", "usecache", "trace", "timeoutms"})

    def fingerprint(self) -> str:
        """Canonical digest of everything that determines the RESULT:
        table, select list (post-alias-strip) + aliases, distinct flag,
        filter / group-by / having / order-by trees, limit/offset, and
        result-affecting options. Shared by both cache tiers: the broker
        keys whole responses on it, the server keys per-segment partials
        on it (the time-boundary extra filter is ANDed into `filter`
        before server-side execution, so it participates naturally).

        Expression nodes are frozen dataclasses with deterministic
        `__str__`, which makes str() a stable serialization — no salted
        `hash()` anywhere, so the digest is reproducible across
        processes."""
        memo = self.__dict__.get("_fp_memo")
        if memo is not None:
            return memo
        opts = sorted(
            (k.lower(), str(v)) for k, v in self.options.items()
            if k.lower() not in self._FINGERPRINT_OPT_DENYLIST)
        parts = [
            "tbl:" + self.table,
            "sel:" + "|".join(str(e) for e in self.select),
            "als:" + "|".join(a or "" for a in self.aliases),
            "dst:" + str(self.distinct),
            "flt:" + (str(self.filter) if self.filter is not None else ""),
            "gby:" + "|".join(str(e) for e in self.group_by),
            "hav:" + (str(self.having) if self.having is not None else ""),
            "oby:" + "|".join(f"{e}/{'asc' if asc else 'desc'}"
                              for e, asc in self.order_by),
            "lim:" + str(self.limit),
            "off:" + str(self.offset),
            "exp:" + str(self.explain),
            "opt:" + "|".join(f"{k}={v}" for k, v in opts),
        ]
        # memoized: the server hot path fingerprints once for the warmup
        # plan log and once for tier-2 cache keys; recomputing the full
        # canonical serialization + sha256 per call is pure waste. The
        # ONE post-parse mutation site (merge_extra_filter) invalidates.
        fp = hashlib.sha256("\n".join(parts).encode()).hexdigest()
        self._fp_memo = fp
        return fp

    def filter_columns(self) -> List[str]:
        return self.filter.columns() if self.filter is not None else []

    def result_column_names(self) -> List[str]:
        out = []
        for e, alias in zip(self.select, self.aliases):
            out.append(alias if alias is not None else _column_name(e))
        return out


def _column_name(e: Expression) -> str:
    if isinstance(e, Identifier):
        return e.name
    if isinstance(e, Function) and is_aggregation(e.name):
        return get_aggregation(e.name, e.args).result_name
    return str(e)


def merge_extra_filter(ctx: QueryContext,
                       extra_filter: Optional[str]) -> None:
    """AND an expression string (the hybrid time-boundary predicate) into
    ctx.filter, in place. This is the ONE canonical merge: tier-2 cache
    keys hash the MERGED tree via ctx.fingerprint(), so the warmup replay
    (cache/warmup.py) must merge bit-for-bit identically to the server
    execute path (server/query_server.py) — both call here."""
    if not extra_filter:
        return
    from pinot_tpu.ingest.transforms import parse_expression
    from pinot_tpu.query.expressions import func
    extra = parse_expression(extra_filter)
    ctx.filter = (extra if ctx.filter is None
                  else func("and", ctx.filter, extra))
    ctx.__dict__.pop("_fp_memo", None)  # filter changed: digest is stale
