"""Server-side query executor: prune, fan out over segments, combine.

Reference parity: pinot-core
query/executor/ServerQueryExecutorV1Impl.java:94,159 (segment acquisition +
pruning + plan + execute) and operator/combine/BaseCombineOperator.java:54
(fan N segment plans over worker threads, merge results). The TPU twist:
instead of one thread per segment, dict-encoded scan shapes are STACKED
into [num_segments, padded_docs] device blocks and executed as ONE jit'd
kernel over the mesh's `segments` axis (ops/engine.py); shapes the device
engine doesn't cover fall back per-segment to the numpy reference path.
"""
from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Sequence

from pinot_tpu.query import executor_cpu
from pinot_tpu.ops import dispatch as dispatch_mod
from pinot_tpu.cache.core import cache_bypassed
from pinot_tpu.cache.segment_cache import is_cacheable_shape
from pinot_tpu.utils import tracing
from pinot_tpu.utils.failpoints import fire
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.pruner import prune_segments
from pinot_tpu.query.reduce import BrokerResponse, reduce_results
from pinot_tpu.query.results import ExecutionStats
from pinot_tpu.segment.loader import ImmutableSegment


class QueryExecutor:
    """Executes queries over a set of loaded segments (one 'server')."""

    def __init__(self, segments: Sequence[ImmutableSegment],
                 use_tpu: bool = True, max_threads: int = 8, engine=None,
                 segment_cache=None, cancel_check=None):
        """engine: a shared TpuOperatorExecutor. Long-lived callers (the
        server) MUST pass one — the engine owns the HBM block cache, and a
        per-request engine would re-upload every column on every query.
        segment_cache: a shared SegmentResultCache (cache/segment_cache.py)
        — same lifetime rule as the engine; None disables tier-2 caching.
        cancel_check: zero-arg callable polled between segments (the
        ResourceAccountant.check_cancelled discipline, ref
        Tracing.ThreadAccountantOps.sample in DocIdSetOperator:70) —
        raises to stop the loop when the query is cancelled or past its
        deadline. Segment granularity is the unit of work here; finer
        checks would sit inside jit'd kernels where Python can't poll."""
        self.segments = list(segments)
        self.max_threads = max_threads
        self._tpu_engine = engine
        self._use_tpu = use_tpu
        self._segment_cache = segment_cache
        self._cancel_check = cancel_check

    @property
    def tpu_engine(self):
        if self._tpu_engine is None and self._use_tpu:
            from pinot_tpu.ops.engine import TpuOperatorExecutor
            self._tpu_engine = TpuOperatorExecutor()
        return self._tpu_engine

    # ------------------------------------------------------------------
    def execute_context(self, ctx: QueryContext):
        """Per-segment results for a query context (server-side half).
        Returns (results, prune_stats)."""
        selected = prune_segments(self.segments, ctx)
        selected_set = set(id(s) for s in selected)
        prune_stats = ExecutionStats()
        for seg in self.segments:
            if id(seg) not in selected_set:
                prune_stats.num_segments_pruned += 1
                prune_stats.total_docs += seg.num_docs
        results: List[Any] = []

        # tier-2 segment result cache: immutable segments with a cached
        # partial for this plan fingerprint skip execution entirely;
        # consuming/upsert segments never hit (is_cacheable_segment), so
        # the mutable tail of a hybrid table always re-executes
        cache = self._segment_cache
        plan_fp: Optional[str] = None
        to_run = selected
        cache_hits = 0
        if cache is not None and cache.enabled and is_cacheable_shape(ctx) \
                and not cache_bypassed(ctx.options):
            plan_fp = ctx.fingerprint()
            with tracing.Scope("SegmentResultCache") as sc:
                to_run = []
                for s in selected:
                    hit = cache.get(s, plan_fp)
                    if hit is not None:
                        results.append(hit)
                        cache_hits += 1
                    else:
                        to_run.append(s)
                sc.set(cacheHit=cache_hits > 0, cacheHits=cache_hits,
                       cacheMisses=len(to_run))
            # mirror on the enclosing request node so trace consumers see
            # cacheHit without walking children
            tracing.annotate(cacheHit=cache_hits > 0)

        # consuming (mutable) segments always run host-side: their columns
        # are unsorted-dict/append buffers, not stageable blocks. Upsert
        # segments with live validDocIds DO ride the device path: the
        # engine stages the bitmap as a version-stamped mask block and
        # ANDs it in-kernel (plan.valid_mask), so upsert/dedup tables
        # share the same jit(vmap) coalesced launches as append-only ones
        device_candidates = [
            s for s in to_run if isinstance(s, ImmutableSegment)]
        dc = set(id(s) for s in device_candidates)
        host_only = [s for s in to_run if id(s) not in dc]
        remaining = device_candidates
        device_fut = None
        device_results_now = None
        if self._use_tpu and device_candidates:
            if self._cancel_check is not None:
                self._cancel_check()
            engine = self.tpu_engine
            if engine is not None and engine.supports(ctx):
                if host_only:
                    # staging + launch ride the engine's dispatch
                    # pipeline; the future resolves off-thread, so this
                    # server thread executes its host-path segments IN
                    # PARALLEL with the device round trip instead of
                    # after it
                    device_fut = engine.execute_async(
                        device_candidates, ctx,
                        cancel_check=self._cancel_check)
                else:
                    # nothing to overlap within this query: skip the
                    # async hop (lone-query p50 stays at the floor);
                    # cross-query overlap still happens in the ring
                    device_results_now, remaining = engine.execute(
                        device_candidates, ctx,
                        cancel_check=self._cancel_check)
                if device_fut is not None:
                    remaining = []

        # captured on the REQUEST thread: run_one executes on pool
        # workers where the accounting thread-local doesn't flow (the
        # span-handle discipline) — cache puts there still charge the
        # query's miss bytes
        from pinot_tpu.utils import accounting
        slip = accounting.current_slip()

        def run_one(s):
            # cooperative cancel poll per segment: a deadline-expired
            # or broker-cancelled query stops HERE instead of
            # finishing work nobody will read (the failpoint site
            # lets chaos tests make each segment arbitrarily slow)
            if self._cancel_check is not None:
                self._cancel_check()
            fire("server.execute.segment",
                 segment=getattr(s, "name", None))
            with accounting.charging(slip):
                r = executor_cpu.execute_segment(s, ctx)
                if plan_fp is not None:
                    cache.put(s, plan_fp, r)  # no-op for mutable segments
            return r

        def run_host(seg_list):
            if not seg_list:
                return []
            if len(seg_list) == 1:
                return [run_one(seg_list[0])]
            with ThreadPoolExecutor(
                    max_workers=min(len(seg_list), self.max_threads)) as pool:
                return list(pool.map(run_one, seg_list))

        # host-only segments overlap the in-flight device future
        host_results = run_host(host_only)
        if device_fut is not None:
            # bounded by the query's deadline/cancel checker when one is
            # attached; callers without one (no query id, MSE leaf path,
            # warmup replay) fall back to wait_result's default hard cap
            # so a stranded engine future can never park this thread
            device_results_now, remaining = dispatch_mod.wait_result(
                device_fut, self._cancel_check)
        if device_results_now is not None:
            results.extend(device_results_now)
            # engine results are positional per candidate when nothing
            # fell back; only then is the segment<->result mapping
            # known for cache population
            if plan_fp is not None and not remaining \
                    and len(device_results_now) == len(device_candidates):
                for s, r in zip(device_candidates, device_results_now):
                    cache.put(s, plan_fp, r)
        results.extend(host_results)
        # device fallbacks (shapes/columns the engine rejected) run last
        results.extend(run_host(list(remaining)))
        return results, prune_stats

    def execute(self, sql: str) -> BrokerResponse:
        """Full single-process path: parse -> execute -> reduce
        (the BaseQueriesTest.getBrokerResponse analog)."""
        start = time.time()
        ctx = QueryContext.from_sql(sql)
        trace_on = ctx.options.get("trace", "false").lower() == "true"
        req_trace = tracing.RequestTrace() if trace_on else None
        if req_trace is not None:
            with req_trace:
                results, prune_stats = self.execute_context(ctx)
                with tracing.Scope("BrokerReduce"):
                    resp = reduce_results(ctx, results)
        else:
            results, prune_stats = self.execute_context(ctx)
            resp = reduce_results(ctx, results)
        resp.stats.merge(prune_stats)
        if req_trace is not None:
            resp.trace = req_trace.to_dict()
        resp.num_servers_queried = resp.num_servers_responded = 1
        resp.time_used_ms = (time.time() - start) * 1000.0
        return resp
