"""CPU reference executor: one segment through plan-shaped execution.

Reference parity: the per-segment operator chains of pinot-core —
AggregationOperator (operator/query/AggregationOperator.java:64),
GroupByOperator (:101) with DictionaryBasedGroupKeyGenerator,
Selection/Distinct operators — collapsed into whole-column numpy execution
(no 10k-doc block loop: the block iteration exists in the reference to
bound memory; columns here are already materialized arrays).

This path is the correctness oracle the TPU engine is tested against
(tests/queries/, the BaseQueriesTest.java:74 analog) and the fallback for
query shapes the device engine doesn't cover yet.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.query import transform
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expression, Function, Identifier, Literal
from pinot_tpu.query.filter import SegmentColumnProvider, evaluate_filter
from pinot_tpu.query.results import (
    AggregationResult, DistinctResult, ExecutionStats, GroupByResult,
    SelectionResult)
from pinot_tpu.segment.loader import ImmutableSegment

# ref plan/maker/InstancePlanMakerImplV2.java DEFAULT_NUM_GROUPS_LIMIT
DEFAULT_NUM_GROUPS_LIMIT = 100_000


def execute_segment(seg: ImmutableSegment, ctx: QueryContext):
    """Run one segment, returning the shape-appropriate SegmentResult."""
    from pinot_tpu.utils import tracing
    snap = getattr(seg, "snapshot", None)
    if snap is not None:
        # consuming segment: pin ONE doc count for the whole query —
        # per-column snapshots drift while the consumer appends, and a
        # filter mask built at count N must never index a column read
        # at count N+k
        seg = snap()
    if tracing.active():
        with tracing.Scope("SegmentExecutor", segment=seg.name) as scope:
            result = _execute_segment(seg, ctx)
            scope.set(numDocsScanned=result.stats.num_docs_scanned)
            return result
    return _execute_segment(seg, ctx)


def _execute_segment(seg: ImmutableSegment, ctx: QueryContext):
    # star-tree fast path (ref AggregationOperator._useStarTree): answer
    # from pre-aggregated records when a tree fits the query shape.
    # Mask-aware gating: pre-agg records bake in superseded rows, so an
    # upsert validDocIds bitmap disqualifies the tree ONLY while it has
    # cleared bits — an all-set bitmap is a no-op mask and the tree's
    # totals are exact (ADVICE r1 hardened into a predicate, not a
    # blanket exclusion).
    _vd = getattr(seg, "valid_doc_ids", None)
    if ctx.aggregations and getattr(seg, "metadata", None) is not None \
            and getattr(seg.metadata, "star_tree", None) \
            and (_vd is None or _vd.is_full()):
        from pinot_tpu.query.startree_exec import execute_star_tree
        result = execute_star_tree(seg, ctx)
        if result is not None:
            return result
    provider = SegmentColumnProvider(seg)
    mask = evaluate_filter(seg, ctx.filter, provider)
    # upsert: only the latest row per primary key is visible
    # (ref: queries AND validDocIds into their filter, SURVEY.md §2.3)
    valid = getattr(seg, "valid_doc_ids", None)
    if valid is not None:
        vmask = valid.to_mask()
        if len(vmask) < seg.num_docs:  # growing mutable segment
            vmask = np.concatenate(
                [vmask, np.zeros(seg.num_docs - len(vmask), bool)])
        mask &= vmask[:seg.num_docs]
    stats = ExecutionStats(
        num_docs_scanned=int(np.count_nonzero(mask)),
        num_entries_scanned_in_filter=(
            seg.num_docs * len(set(ctx.filter_columns())) if ctx.filter is not None else 0),
        num_segments_processed=1,
        num_segments_matched=1 if mask.any() else 0,
        total_docs=seg.num_docs)

    if ctx.is_group_by_query:
        return _group_by(seg, ctx, provider, mask, stats)
    if ctx.is_aggregation_query:
        return _aggregate(seg, ctx, provider, mask, stats)
    if ctx.is_distinct_query:
        return _distinct(seg, ctx, provider, mask, stats)
    return _select(seg, ctx, provider, mask, stats)


# ---------------------------------------------------------------------------

def _agg_input(seg: ImmutableSegment, fn_node: Function, provider,
               fn=None) -> Optional[np.ndarray]:
    """Materialize the aggregation argument column (None for COUNT(*)).
    multi_arg functions get all non-literal args stacked [k, n]."""
    if not fn_node.args:
        return None
    arg = fn_node.args[0]
    if isinstance(arg, Identifier) and arg.name == "*":
        return None
    if fn_node.name == "countmv":
        ds = seg.data_source(arg.name)  # type: ignore[union-attr]
        return np.diff(ds.mv_offsets()).astype(np.int64)
    if fn is not None and fn.multi_arg:
        # LIST of per-arg columns, not np.stack: stacking would unify
        # dtypes (an i64 time column next to a f64 value column silently
        # casts to f64, aliasing timestamps above 2^53)
        cols = []
        for a in fn_node.args:
            if isinstance(a, Literal):
                continue  # config literals (type name, percent, ...)
            col = np.asarray(transform.evaluate(a, provider))
            if col.ndim == 0:
                col = np.broadcast_to(col, (seg.num_docs,))
            cols.append(col)
        return cols
    out = np.asarray(transform.evaluate(arg, provider))
    if out.ndim == 0:
        out = np.broadcast_to(out, (seg.num_docs,))
    return out


def _mv_flat_input(seg: ImmutableSegment, fn_node: Function):
    """(flat values, per-doc entry counts) for the *MV aggregations."""
    arg = fn_node.args[0]
    ds = seg.data_source(arg.name)  # type: ignore[union-attr]
    return ds.values(), np.diff(ds.mv_offsets())


def _agg_mask(seg, ctx: QueryContext, provider, mask, i):
    """Combined doc mask for the i-th aggregation: query filter AND the
    aggregation's own FILTER (WHERE ...) clause, if any
    (ref FilteredAggregationOperator)."""
    cond = ctx.agg_filters[i]
    if cond is None:
        return mask
    return mask & evaluate_filter(seg, cond, provider)


def _aggregate(seg, ctx: QueryContext, provider, mask, stats) -> AggregationResult:
    inters = []
    for i, (node, fn) in enumerate(zip(ctx.aggregations, ctx.agg_functions)):
        fmask = _agg_mask(seg, ctx, provider, mask, i)
        if fn.mv_input:
            flat, counts = _mv_flat_input(seg, node)
            inters.append(fn.aggregate(flat, np.repeat(fmask, counts)))
            stats.num_entries_scanned_post_filter += int(counts[fmask].sum())
            continue
        values = _agg_input(seg, node, provider, fn)
        inters.append(fn.aggregate(values, fmask))
        if values is not None:
            stats.num_entries_scanned_post_filter += stats.num_docs_scanned
    return AggregationResult(inters, stats)


def _group_key_arrays(seg, ctx: QueryContext, provider, mask):
    """Factorize each group-by expression into (codes, uniques) over the
    masked docs (ref DictionaryBasedGroupKeyGenerator — dictIds combine into
    flat group keys; expression group-bys factorize their value arrays)."""
    codes_list, uniques_list = [], []
    for e in ctx.group_by:
        vals = np.asarray(transform.evaluate(e, provider))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (seg.num_docs,))
        masked = vals[mask]
        uniques, codes = np.unique(masked, return_inverse=True)
        codes_list.append(codes)
        uniques_list.append(uniques)
    return codes_list, uniques_list


def _group_by(seg, ctx: QueryContext, provider, mask, stats) -> GroupByResult:
    num_groups_limit = int(ctx.options.get("numGroupsLimit", DEFAULT_NUM_GROUPS_LIMIT))
    if not mask.any():
        return GroupByResult({}, stats)
    codes_list, uniques_list = _group_key_arrays(seg, ctx, provider, mask)
    cards = [len(u) for u in uniques_list]
    # combined key = mixed-radix over per-column codes
    combined = codes_list[0].astype(np.int64)
    for c, card in zip(codes_list[1:], cards[1:]):
        combined = combined * card + c
    present, combined_codes = np.unique(combined, return_inverse=True)
    limit_reached = len(present) > num_groups_limit
    if limit_reached:
        present = present[:num_groups_limit]
    num_groups = len(present)

    # decode present combined keys back to value tuples
    key_cols = []
    rem = present.copy()
    for card, uniques in zip(reversed(cards), reversed(uniques_list)):
        key_cols.append(uniques[(rem % card).astype(np.int64)])
        rem //= card
    key_cols.reverse()
    keys = [tuple(_scalar(col[g]) for col in key_cols) for g in range(num_groups)]

    sub_mask = np.ones(len(combined_codes), dtype=bool) if not limit_reached \
        else (combined_codes < num_groups)
    doc_idx = np.nonzero(mask)[0]
    full_keys = np.full(seg.num_docs, 0, dtype=np.int64)
    full_keys[doc_idx] = combined_codes
    gmask = mask.copy()
    gmask[doc_idx[~sub_mask]] = False

    per_fn: List[list] = []
    for i, (node, fn) in enumerate(zip(ctx.aggregations, ctx.agg_functions)):
        fmask = _agg_mask(seg, ctx, provider, gmask, i)
        if fn.mv_input:
            flat, counts = _mv_flat_input(seg, node)
            per_fn.append(fn.aggregate_grouped(
                flat, np.repeat(full_keys, counts), num_groups,
                np.repeat(fmask, counts)))
            stats.num_entries_scanned_post_filter += int(counts[fmask].sum())
            continue
        values = _agg_input(seg, node, provider, fn)
        per_fn.append(fn.aggregate_grouped(values, full_keys, num_groups, fmask))
        if values is not None:
            stats.num_entries_scanned_post_filter += stats.num_docs_scanned
    groups = {keys[g]: [per_fn[f][g] for f in range(len(per_fn))]
              for g in range(num_groups)}
    return GroupByResult(groups, stats, num_groups_limit_reached=limit_reached)


def _project_rows(seg, exprs: List[Expression], provider, doc_idx: np.ndarray):
    cols = []
    for e in exprs:
        if isinstance(e, Identifier) and e.name == "*":
            for name in seg.column_names:
                cols.append(np.asarray(provider.column(name))[doc_idx])
            continue
        vals = np.asarray(transform.evaluate(e, provider))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (seg.num_docs,))
        cols.append(vals[doc_idx])
    return [tuple(_scalar(c[i]) for c in cols) for i in range(len(doc_idx))]


def expand_star(seg: ImmutableSegment, ctx: QueryContext) -> List[str]:
    names = []
    result_names = ctx.result_column_names()
    for i, e in enumerate(ctx.select):
        if isinstance(e, Identifier) and e.name == "*":
            names.extend(seg.column_names)
        else:
            names.append(ctx.aliases[i] or result_names[i])
    return names


def _select(seg, ctx: QueryContext, provider, mask, stats) -> SelectionResult:
    doc_idx = np.nonzero(mask)[0]
    fetch = ctx.limit + ctx.offset
    if not ctx.order_by:
        doc_idx = doc_idx[:fetch]  # ref SelectionOnlyOperator early-exit
        rows = _project_rows(seg, ctx.select, provider, doc_idx)
        stats.num_entries_scanned_post_filter = len(doc_idx) * max(len(ctx.select), 1)
        return SelectionResult(rows, columns=expand_star(seg, ctx), stats=stats)
    # order-by: evaluate sort keys, partial-sort, keep top fetch rows
    # (ref SelectionOrderByOperator)
    sort_cols = []
    for e, asc in ctx.order_by:
        vals = np.asarray(transform.evaluate(e, provider))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (seg.num_docs,))
        sort_cols.append((vals[doc_idx], asc))
    order = _lexsort(sort_cols)
    doc_idx = doc_idx[order][:fetch]
    rows = _project_rows(seg, ctx.select, provider, doc_idx)
    order_values = _project_rows(seg, [e for e, _ in ctx.order_by], provider, doc_idx)
    stats.num_entries_scanned_post_filter = len(doc_idx) * max(len(ctx.select), 1)
    return SelectionResult(rows, order_values=order_values,
                           columns=expand_star(seg, ctx), stats=stats)


def _lexsort(sort_cols) -> np.ndarray:
    """Stable multi-key argsort honoring per-key asc/desc."""
    keys = []
    for vals, asc in reversed(sort_cols):
        if not asc:
            if vals.dtype.kind in "iuf":
                vals = -vals.astype(np.float64)
            else:
                # desc on strings: rank-invert via factorize
                uniques, codes = np.unique(vals, return_inverse=True)
                vals = -codes
        keys.append(vals)
    return np.lexsort(keys)


def _distinct(seg, ctx: QueryContext, provider, mask, stats) -> DistinctResult:
    doc_idx = np.nonzero(mask)[0]
    rows = _project_rows(seg, ctx.select, provider, doc_idx)
    return DistinctResult(set(rows), stats=stats)


def _scalar(v):
    if isinstance(v, np.generic):
        return v.item()
    return v
