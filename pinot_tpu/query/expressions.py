"""Expression AST for the SQL front-end.

Reference parity: pinot-common's Thrift `Expression`
(LITERAL/IDENTIFIER/FUNCTION) used by PinotQuery, and
`ExpressionContext`/`FilterContext` in
pinot-core/src/main/java/org/apache/pinot/common/request/context/.

Operators are normalized to lower-case function names the way
CalciteSqlParser does (`=` -> "equals", `+` -> "plus", ...), so the rest of
the engine only ever sees three node kinds.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Iterator, List, Optional, Sequence, Tuple


class ExpressionType(enum.Enum):
    LITERAL = "LITERAL"
    IDENTIFIER = "IDENTIFIER"
    FUNCTION = "FUNCTION"


# Filter function names (ref FilterKind enum in
# pinot-common/.../sql/FilterKind.java).
FILTER_KINDS = {
    "and", "or", "not",
    "equals", "not_equals", "greater_than", "greater_than_or_equal",
    "less_than", "less_than_or_equal", "between", "range",
    "in", "not_in", "like", "regexp_like", "text_match", "json_match",
    "is_null", "is_not_null", "vector_similarity",
}

COMPARISON_KINDS = {
    "equals", "not_equals", "greater_than", "greater_than_or_equal",
    "less_than", "less_than_or_equal",
}


@dataclass(frozen=True)
class Expression:
    """Base expression node."""

    def walk(self) -> Iterator["Expression"]:
        yield self

    @property
    def is_literal(self) -> bool:
        return isinstance(self, Literal)

    @property
    def is_identifier(self) -> bool:
        return isinstance(self, Identifier)

    @property
    def is_function(self) -> bool:
        return isinstance(self, Function)

    def columns(self) -> List[str]:
        """All identifier names referenced under this expression."""
        out: List[str] = []
        for node in self.walk():
            if isinstance(node, Identifier):
                out.append(node.name)
        return out


@dataclass(frozen=True)
class Literal(Expression):
    value: Any  # int | float | str | bool | None | list (for IN value arrays)

    def __str__(self) -> str:
        if isinstance(self.value, str):
            return "'" + self.value.replace("'", "''") + "'"
        if self.value is None:
            return "NULL"
        return str(self.value)


@dataclass(frozen=True)
class Identifier(Expression):
    name: str

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Function(Expression):
    name: str  # normalized lower-case ("sum", "equals", "plus", ...)
    args: Tuple[Expression, ...] = ()

    def walk(self) -> Iterator[Expression]:
        yield self
        for a in self.args:
            yield from a.walk()

    def __str__(self) -> str:
        return f"{self.name}({', '.join(str(a) for a in self.args)})"


def func(name: str, *args: Expression) -> Function:
    return Function(name.lower(), tuple(args))


def lit(value: Any) -> Literal:
    return Literal(value)


def ident(name: str) -> Identifier:
    return Identifier(name)


def is_agg_function(name: str) -> bool:
    from pinot_tpu.query.aggregation import is_aggregation
    return is_aggregation(name)


def extract_aggregations(expr: Expression) -> List[Function]:
    """All aggregation-function nodes under expr (pre-order)."""
    out: List[Function] = []
    for node in expr.walk():
        if isinstance(node, Function) and is_agg_function(node.name):
            out.append(node)
    return out
