"""Filter evaluation: predicate resolution + index-aware mask production.

Reference parity: pinot-core operator/filter/ — predicates pre-resolve
against each segment's sorted dictionary into dictId ranges/sets
(filter/predicate/PredicateEvaluator.java:26), then the cheapest operator
is picked per column (plan/FilterPlanNode.java:67): sorted index -> doc
ranges, inverted index -> bitmap union, otherwise a dictId scan. Output is
a dense boolean doc mask — the TPU-native stand-in for BlockDocIdSet
(dense masks instead of doc-id streams, per SURVEY.md §7 hard-parts note).

The same ResolvedPredicate objects parameterize the device kernels: a
'range' predicate becomes per-segment (lo, hi) scalars broadcast into the
jit'd compare, a 'set' predicate becomes a per-segment dictId lookup table.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from pinot_tpu.query import transform
from pinot_tpu.query.expressions import (
    COMPARISON_KINDS, Expression, Function, Identifier, Literal)
from pinot_tpu.segment.loader import DataSource, ImmutableSegment


@dataclass
class ResolvedPredicate:
    """A leaf predicate resolved to dictIds for one segment.

    kind: 'range' (lo<=id<=hi), 'set' (id in ids), 'notset', 'all', 'none',
    'isnull', 'notnull'.
    """
    column: str
    kind: str
    lo: int = 0
    hi: int = -1
    ids: Optional[np.ndarray] = None

    @property
    def is_range(self) -> bool:
        return self.kind == "range"


def like_to_regex(pattern: str) -> str:
    """SQL LIKE -> anchored regex (ref RegexpPatternConverterUtils)."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def resolve_predicate(seg: ImmutableSegment, fn: Function) -> Optional[ResolvedPredicate]:
    """Resolve a leaf filter function against a segment's dictionary.

    Returns None when the predicate isn't a plain dict-column predicate
    (expression lhs, raw column, or unsupported op) — caller falls back to
    value-space evaluation.
    """
    if not fn.args or not isinstance(fn.args[0], Identifier):
        return None
    if fn.name not in ("is_null", "is_not_null") and not all(
            isinstance(a, Literal) for a in fn.args[1:]):
        return None  # non-literal rhs (e.g. col = col) -> value-space fallback
    col = fn.args[0].name
    if not seg.has_column(col):
        return None
    ds = seg.data_source(col)
    if not ds.metadata.has_dictionary:
        return None
    d = ds.dictionary
    card = d.cardinality
    name = fn.name

    def _lit(i: int):
        a = fn.args[i]
        return a.value if isinstance(a, Literal) else None

    if name == "equals":
        idx = d.index_of(_coerce(d, _lit(1)))
        if idx < 0:
            return ResolvedPredicate(col, "none")
        return ResolvedPredicate(col, "range", idx, idx)
    if name == "not_equals":
        idx = d.index_of(_coerce(d, _lit(1)))
        if idx < 0:
            return ResolvedPredicate(col, "all")
        return ResolvedPredicate(col, "notset", ids=np.array([idx], dtype=np.int32))
    if name in ("greater_than", "greater_than_or_equal",
                "less_than", "less_than_or_equal", "between", "range"):
        lo, hi = 0, card - 1
        if name == "between":
            lo = d.insertion_index(_coerce(d, _lit(1)), side="left")
            hi = d.insertion_index(_coerce(d, _lit(2)), side="right") - 1
        elif name.startswith("greater"):
            side = "left" if name.endswith("equal") else "right"
            lo = d.insertion_index(_coerce(d, _lit(1)), side=side)
        else:
            side = "right" if name.endswith("equal") else "left"
            hi = d.insertion_index(_coerce(d, _lit(1)), side=side) - 1
        if lo > hi:
            return ResolvedPredicate(col, "none")
        return ResolvedPredicate(col, "range", lo, hi)
    if name in ("in", "not_in"):
        vals = [a.value for a in fn.args[1:] if isinstance(a, Literal)]
        ids = np.array(sorted({i for v in vals
                               if (i := d.index_of(_coerce(d, v))) >= 0}),
                       dtype=np.int32)
        if name == "in":
            if len(ids) == 0:
                return ResolvedPredicate(col, "none")
            return ResolvedPredicate(col, "set", ids=ids)
        if len(ids) == 0:
            return ResolvedPredicate(col, "all")
        return ResolvedPredicate(col, "notset", ids=ids)
    if name in ("like", "regexp_like"):
        pattern = _lit(1)
        if pattern is None:
            return None
        # FST-index path (ref LuceneFSTIndexReader): anchored literal
        # prefixes become O(log n) dictId ranges instead of a full
        # dictionary regex scan; results cache per (dictionary, pattern)
        regex = like_to_regex(pattern) if name == "like" else pattern
        ids = d.fst_index.matching_dict_ids(regex)
        if len(ids) == 0:
            return ResolvedPredicate(col, "none")
        # contiguous match ranges collapse to a range predicate
        if len(ids) == ids[-1] - ids[0] + 1:
            return ResolvedPredicate(col, "range", int(ids[0]), int(ids[-1]))
        return ResolvedPredicate(col, "set", ids=ids)
    if name == "is_null":
        return ResolvedPredicate(col, "isnull")
    if name == "is_not_null":
        return ResolvedPredicate(col, "notnull")
    return None


def _coerce(d, value):
    """Coerce a literal into the dictionary's value domain."""
    if value is None:
        return value
    vals = d.values
    if vals.dtype.kind in "iuf" and isinstance(value, str):
        return float(value)
    if vals.dtype.kind in "iu" and isinstance(value, float) and value.is_integer():
        return int(value)
    if vals.dtype.kind in "UOS" and not isinstance(value, (str, bytes)):
        return str(value)
    return value


# ---------------------------------------------------------------------------
# Mask production (index-aware)
# ---------------------------------------------------------------------------

def predicate_mask(seg: ImmutableSegment, pred: ResolvedPredicate) -> np.ndarray:
    """Boolean doc mask for a resolved predicate, via the cheapest index
    (ref FilterPlanNode.java:67 operator selection)."""
    n = seg.num_docs
    if pred.kind == "all":
        return np.ones(n, dtype=bool)
    if pred.kind == "none":
        return np.zeros(n, dtype=bool)
    ds = seg.data_source(pred.column)
    if pred.kind == "isnull":
        nv = ds.null_value_vector
        return nv.to_mask() if nv is not None else np.zeros(n, dtype=bool)
    if pred.kind == "notnull":
        nv = ds.null_value_vector
        return ~nv.to_mask() if nv is not None else np.ones(n, dtype=bool)

    # sorted column: predicate range -> contiguous doc range
    si = ds.sorted_index
    if si is not None and pred.is_range:
        start, end = si.range_for_ids(pred.lo, pred.hi)
        mask = np.zeros(n, dtype=bool)
        mask[start:end] = True
        return mask
    # inverted index: union of per-dictId doc lists (worth it for small sets)
    inv = ds.inverted_index
    if inv is not None and pred.kind == "set" and len(pred.ids) <= 16:
        mask = np.zeros(n, dtype=bool)
        mask[inv.doc_ids_for_many(pred.ids)] = True
        return mask
    if inv is not None and pred.is_range and pred.hi - pred.lo < 16:
        mask = np.zeros(n, dtype=bool)
        ids = np.arange(pred.lo, pred.hi + 1, dtype=np.int32)
        mask[inv.doc_ids_for_many(ids)] = True
        return mask
    # scan path over dictIds (ref ScanBasedFilterOperator — int compares)
    dict_ids = ds.dict_ids() if ds.metadata.single_value else None
    if dict_ids is None:  # MV column: any-entry-matches semantics
        offsets, flat = ds.mv_offsets(), ds.dict_ids()
        if len(flat) == 0:
            return np.zeros(n, dtype=bool)
        entry_mask = _ids_mask(flat, pred)
        doc_of_entry = np.repeat(np.arange(n), np.diff(offsets))
        mask = np.zeros(n, dtype=bool)
        mask[doc_of_entry[entry_mask]] = True
        return mask
    return _ids_mask(dict_ids, pred)


def _ids_mask(dict_ids: np.ndarray, pred: ResolvedPredicate) -> np.ndarray:
    if pred.kind == "range":
        return (dict_ids >= pred.lo) & (dict_ids <= pred.hi)
    member = np.isin(dict_ids, pred.ids)
    return member if pred.kind == "set" else ~member


def evaluate_filter(seg: ImmutableSegment, expr: Optional[Expression],
                    provider=None) -> np.ndarray:
    """Full filter tree -> boolean doc mask."""
    n = seg.num_docs
    if expr is None:
        return np.ones(n, dtype=bool)
    if isinstance(expr, Function):
        if expr.name == "and":
            mask = evaluate_filter(seg, expr.args[0], provider)
            for a in expr.args[1:]:
                if not mask.any():
                    break
                mask &= evaluate_filter(seg, a, provider)
            return mask
        if expr.name == "or":
            mask = evaluate_filter(seg, expr.args[0], provider)
            for a in expr.args[1:]:
                if mask.all():
                    break
                mask |= evaluate_filter(seg, a, provider)
            return mask
        if expr.name == "not":
            return ~evaluate_filter(seg, expr.args[0], provider)
        if expr.name == "json_match":
            return _json_match_mask(seg, expr)
        if expr.name == "text_match":
            return _text_match_mask(seg, expr)
        if expr.name == "vector_similarity":
            return _vector_similarity_mask(seg, expr)
        if expr.name in ("st_within_distance", "geo_within_distance"):
            return _geo_distance_mask(seg, expr)
        pred = resolve_predicate(seg, expr)
        if pred is not None:
            return predicate_mask(seg, pred)
        return _value_space_mask(seg, expr, provider)
    if isinstance(expr, Literal):
        return np.full(n, bool(expr.value), dtype=bool)
    raise ValueError(f"invalid filter expression: {expr}")


def _value_space_mask(seg: ImmutableSegment, fn: Function, provider) -> np.ndarray:
    """Generic fallback: evaluate the predicate over materialized values
    (ref ExpressionFilterOperator)."""
    if provider is None:
        provider = SegmentColumnProvider(seg)
    name = fn.name
    if name in COMPARISON_KINDS:
        out = transform.evaluate(fn, provider)
        # copy: broadcast views are read-only and AND/OR combines in place
        return np.broadcast_to(
            np.asarray(out, dtype=bool), (seg.num_docs,)).copy()
    lhs = np.asarray(transform.evaluate(fn.args[0], provider))
    if name == "between":
        lo = transform.evaluate(fn.args[1], provider)
        hi = transform.evaluate(fn.args[2], provider)
        return (lhs >= lo) & (lhs <= hi)
    if name in ("in", "not_in"):
        vals = [a.value for a in fn.args[1:] if isinstance(a, Literal)]
        if lhs.dtype.kind in "iuf":
            vals = [float(v) for v in vals]
        else:
            vals = [str(v) for v in vals]
        member = np.isin(lhs, np.array(vals))
        return member if name == "in" else ~member
    if name in ("like", "regexp_like"):
        pattern = fn.args[1].value  # type: ignore[union-attr]
        rx = re.compile(like_to_regex(pattern) if name == "like" else pattern)
        return np.array([bool(rx.search(str(v))) for v in lhs.tolist()])
    if name == "is_null":
        return np.isnan(lhs) if lhs.dtype.kind == "f" else np.zeros(seg.num_docs, bool)
    if name == "is_not_null":
        return ~np.isnan(lhs) if lhs.dtype.kind == "f" else np.ones(seg.num_docs, bool)
    raise ValueError(f"unsupported filter function: {name}")


def parse_filter_string(s: str) -> Expression:
    """Parse a standalone predicate string (json_match's filter argument
    — SQL predicate syntax over double-quoted json paths)."""
    from pinot_tpu.query.parser import SqlParseError, _Parser, tokenize
    p = _Parser(tokenize(s))
    e = p.expr()
    t = p.peek()
    if t.kind != "end":
        raise SqlParseError(f"trailing input in filter at {t.pos}: {t.text!r}")
    return e


def _vector_similarity_mask(seg: ImmutableSegment, fn: Function) -> np.ndarray:
    """vector_similarity(col, 'json query vector', topK) — the K nearest
    docs by the index's metric (ref VectorSimilarityFilterOperator over
    the HNSW reader; here exact/IVF matmul search,
    segment/vector_index.py)."""
    import json as _json
    col = fn.args[0]
    assert isinstance(col, Identifier), "vector_similarity needs a column"
    q = fn.args[1]
    assert isinstance(q, Literal), "vector_similarity needs a query vector"
    k = int(fn.args[2].value) if len(fn.args) > 2 \
        and isinstance(fn.args[2], Literal) else 10
    ds = seg.data_source(col.name)
    index = getattr(ds, "vector_index", None)
    if index is None:
        raise ValueError(f"no vector index on column {col.name!r}")
    ids = index.top_k(np.asarray(_json.loads(str(q.value)), np.float32), k)
    mask = np.zeros(seg.num_docs, dtype=bool)
    mask[ids] = True
    return mask


def _geo_distance_mask(seg: ImmutableSegment, fn: Function) -> np.ndarray:
    """st_within_distance(col, lat, lng, meters) — grid-cell candidates +
    exact haversine (ref H3IndexFilterOperator / ST_DISTANCE < r
    rewrite); falls back to a full haversine scan without an index."""
    col = fn.args[0]
    assert isinstance(col, Identifier), "st_within_distance needs a column"
    lat = float(fn.args[1].value)   # type: ignore[union-attr]
    lng = float(fn.args[2].value)   # type: ignore[union-attr]
    meters = float(fn.args[3].value)  # type: ignore[union-attr]
    ds = seg.data_source(col.name)
    index = getattr(ds, "geo_index", None)
    mask = np.zeros(seg.num_docs, dtype=bool)
    if index is not None:
        mask[index.within_distance(lat, lng, meters)] = True
        return mask
    from pinot_tpu.segment.geo_index import haversine_m, parse_point
    pts = [parse_point(v) for v in ds.values()]
    d = haversine_m(np.asarray([p[0] for p in pts]),
                    np.asarray([p[1] for p in pts]), lat, lng)
    return d <= meters  # NaN distances compare False: bad rows never match


def _json_match_mask(seg: ImmutableSegment, fn: Function) -> np.ndarray:
    """json_match(col, 'predicate over "$.paths"') — index-backed when the
    column carries a JSON index (ref JsonMatchFilterOperator +
    ImmutableJsonIndexReader.getMatchingDocIds); otherwise a transient
    index over the column's values answers exactly (ExpressionFilter-style
    fallback)."""
    col = fn.args[0]
    assert isinstance(col, Identifier), "json_match needs a column"
    pred = parse_filter_string(str(fn.args[1].value))  # type: ignore
    ds = seg.data_source(col.name)
    idx = getattr(ds, "json_index", None)  # mutable sources lack the attr
    if idx is None:
        from pinot_tpu.segment.json_index import JsonIndex
        idx = JsonIndex.build(ds.values(), seg.num_docs)
        if hasattr(ds, "_json"):
            ds._json = idx  # memoize the transient index on the source
    mask = np.zeros(seg.num_docs, dtype=bool)
    docs = idx.matching_docs(pred)
    mask[docs[docs < seg.num_docs]] = True
    return mask


def _text_match_mask(seg: ImmutableSegment, fn: Function) -> np.ndarray:
    """text_match(col, 'lucene-style query') — ref TextMatchFilterOperator
    over the text index; transient index fallback without one."""
    col = fn.args[0]
    assert isinstance(col, Identifier), "text_match needs a column"
    query = str(fn.args[1].value)  # type: ignore[union-attr]
    ds = seg.data_source(col.name)
    idx = getattr(ds, "text_index", None)  # mutable sources lack the attr
    if idx is None:
        from pinot_tpu.segment.text_index import TextIndex
        idx = TextIndex.build(ds.values(), seg.num_docs)
        if hasattr(ds, "_text"):
            ds._text = idx  # memoize the transient index on the source
    raw = ds.values() if '"' in query else None  # phrases verify adjacency
    mask = np.zeros(seg.num_docs, dtype=bool)
    docs = idx.matching_docs(query, raw_values=raw)
    mask[docs[docs < seg.num_docs]] = True
    return mask


class SegmentColumnProvider:
    """ColumnProvider over one segment's materialized values."""

    def __init__(self, seg: ImmutableSegment):
        self._seg = seg

    def column(self, name: str) -> np.ndarray:
        return self._seg.data_source(name).values()

    def data_source(self, name: str):
        """Index-aware access for transforms (map_value's dense keys)."""
        try:
            return self._seg.data_source(name)
        except (KeyError, ValueError):
            return None

    def mv_lists(self, name: str):
        """Multi-value column as per-doc lists (for MV-aware transforms)."""
        ds = self._seg.data_source(name)
        offsets = ds.mv_offsets()
        if ds.metadata.has_dictionary:
            flat = ds.dictionary.get_values(ds.dict_ids())
        else:
            flat = ds.values()
        return [flat[offsets[i]:offsets[i + 1]]
                for i in range(len(offsets) - 1)]

    @property
    def num_docs(self) -> int:
        return self._seg.num_docs
