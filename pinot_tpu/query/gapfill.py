"""Gapfill: fill missing time buckets in group-by results.

Reference parity: pinot-core query/reduce/ gapfill processors
(GapfillProcessor.java + BaseGapfillProcessor — the GAPFILL table
function fills absent time buckets per key combination with
FILL_DEFAULT_VALUE / FILL_PREVIOUS_VALUE).

Activation here is option-driven (per-query SET options, the same
mechanism the reference uses for engine selection):

    SET gapfillTimeCol = ts_bucket;   -- a GROUP BY column in the select
    SET gapfillStart = 0;             -- first bucket (inclusive)
    SET gapfillEnd = 100;             -- end (exclusive)
    SET gapfillStep = 10;             -- bucket width
    SET gapfillMode = PREVIOUS;       -- PREVIOUS | ZERO | NULL

Missing buckets are inserted per combination of the remaining group-by
columns; aggregate columns fill with the previous bucket's value (or
0/NULL per mode), matching FILL_PREVIOUS_VALUE semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def maybe_gapfill(ctx, table):
    """Apply gapfill when the query options ask for it; returns the
    (possibly new) ResultTable."""
    opts = ctx.options
    col = opts.get("gapfillTimeCol")
    if not col or table is None:
        return table
    try:
        start = int(opts["gapfillStart"])
        end = int(opts["gapfillEnd"])
        step = int(opts["gapfillStep"])
    except (KeyError, ValueError):
        return table
    if step <= 0 or col not in table.columns:
        return table
    #: guard against grid bombs (SET gapfillStep=1 over a huge range):
    #: more buckets than this skips the fill rather than OOMing the broker
    if (end - start) // step > 100_000:
        return table
    mode = opts.get("gapfillMode", "PREVIOUS").upper()
    tcol = table.columns.index(col)
    # key columns = the other GROUP BY output columns; if a GROUP BY
    # column is NOT selected, distinct groups would collapse onto one
    # (key, time) slot and silently drop rows — bail instead
    group_names = {str(g) for g in ctx.group_by}
    selected = set(table.columns)
    if not group_names <= (selected | {col}):
        return table
    key_idx = [i for i, c in enumerate(table.columns)
               if c != col and (c in group_names or str(c) in group_names)]
    fill_idx = [i for i in range(len(table.columns))
                if i != tcol and i not in key_idx]

    by_key: Dict[Tuple, Dict[int, tuple]] = {}
    for row in table.rows:
        key = tuple(row[i] for i in key_idx)
        by_key.setdefault(key, {})[int(row[tcol])] = row

    out: List[tuple] = []
    grid = set(range(start, end, step))  # built once, shared across keys
    for key, buckets in by_key.items():
        prev: Optional[tuple] = None
        # emit ALL real buckets (even off-grid / out of [start, end)) plus
        # the missing grid buckets — gapfill inserts, never drops data
        times = sorted(set(buckets) | grid)
        for t in times:
            row = buckets.get(t)
            if row is None:
                filled = [None] * len(table.columns)
                filled[tcol] = t
                for pos_k, i in enumerate(key_idx):
                    filled[i] = key[pos_k]
                for i in fill_idx:
                    if mode == "PREVIOUS" and prev is not None:
                        filled[i] = prev[i]
                    elif mode == "ZERO":
                        filled[i] = 0
                    else:
                        filled[i] = None
                row = tuple(filled)
            out.append(row)
            prev = row
    from pinot_tpu.query.reduce import ResultTable
    return ResultTable(table.columns, table.column_types, out)
