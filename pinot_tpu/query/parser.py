"""SQL parser: Pinot query subset -> PinotQuery AST.

Reference parity: org.apache.pinot.sql.parsers.CalciteSqlParser
(pinot-common) — the reference leans on Calcite's babel parser; here a
hand-rolled lexer + recursive-descent/precedence-climbing parser covers the
single-stage dialect: SELECT [DISTINCT] exprs FROM table [WHERE ...]
[GROUP BY ...] [HAVING ...] [ORDER BY ...] [LIMIT n [OFFSET m]]
[OPTION(k=v,...)], plus leading `SET k=v;` statements for query options.

Operators normalize to function names as CalciteSqlParser does
(`=` -> equals, `BETWEEN` -> between, `+` -> plus ...), producing the
Expression AST in expressions.py.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.query.expressions import (
    Expression, Function, Identifier, Literal, func, ident, lit)

# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*"|`(?:[^`]|``)*`)
  | (?P<name>[A-Za-z_][A-Za-z0-9_$.]*)
  | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\+|-|\*|/|%|;)
""", re.VERBOSE)


@dataclass
class Token:
    kind: str  # number | string | qident | name | op | end
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlParseError(f"unexpected character {sql[pos]!r} at {pos}")
        kind = m.lastgroup
        if kind not in ("ws", "comment"):
            tokens.append(Token(kind, m.group(), pos))
        pos = m.end()
    tokens.append(Token("end", "", pos))
    return tokens


class SqlParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# AST container
# ---------------------------------------------------------------------------

@dataclass
class PinotQuery:
    """Parsed query (ref Thrift PinotQuery, pinot-common query.thrift)."""
    table: str = ""
    select_list: List[Expression] = field(default_factory=list)
    distinct: bool = False
    filter: Optional[Expression] = None
    group_by: List[Expression] = field(default_factory=list)
    having: Optional[Expression] = None
    order_by: List[Tuple[Expression, bool]] = field(default_factory=list)  # (expr, asc)
    limit: int = 10  # Pinot default limit
    offset: int = 0
    options: Dict[str, str] = field(default_factory=dict)
    explain: bool = False


_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "ORDER", "BY", "HAVING", "LIMIT",
    "OFFSET", "OPTION", "AS", "AND", "OR", "NOT", "IN", "BETWEEN", "LIKE",
    "IS", "NULL", "TRUE", "FALSE", "ASC", "DESC", "DISTINCT", "SET",
    "EXPLAIN", "PLAN", "FOR",
}

# Binary operator -> canonical function name (ref CalciteSqlParser op mapping)
_CMP_FUNCS = {
    "=": "equals", "!=": "not_equals", "<>": "not_equals",
    "<": "less_than", ">": "greater_than",
    "<=": "less_than_or_equal", ">=": "greater_than_or_equal",
}
_ADD_FUNCS = {"+": "plus", "-": "minus"}
_MUL_FUNCS = {"*": "times", "/": "divide", "%": "mod"}


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    # -- token helpers ------------------------------------------------------
    def peek(self, offset: int = 0) -> Token:
        return self.toks[min(self.i + offset, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "end":
            self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[Token]:
        t = self.peek()
        if t.kind == "name" and t.upper in kws:
            return self.next()
        return None

    def expect_kw(self, kw: str) -> Token:
        t = self.accept_kw(kw)
        if t is None:
            raise SqlParseError(f"expected {kw} at {self.peek().pos}, got {self.peek().text!r}")
        return t

    def accept_op(self, *ops: str) -> Optional[Token]:
        t = self.peek()
        if t.kind == "op" and t.text in ops:
            return self.next()
        return None

    def expect_op(self, op: str) -> Token:
        t = self.accept_op(op)
        if t is None:
            raise SqlParseError(f"expected {op!r} at {self.peek().pos}, got {self.peek().text!r}")
        return t

    # -- statement ----------------------------------------------------------
    def parse(self) -> PinotQuery:
        q = PinotQuery()
        # leading SET k = v; statements (query options)
        while self.accept_kw("SET"):
            key = self._name_text(self.next())
            self.expect_op("=")
            q.options[key] = self._literal_text(self.next())
            self.accept_op(";")
        if self.accept_kw("EXPLAIN"):
            self.expect_kw("PLAN")
            self.expect_kw("FOR")
            q.explain = True
        self.expect_kw("SELECT")
        if self.accept_kw("DISTINCT"):
            q.distinct = True
        q.select_list = self._select_list()
        self.expect_kw("FROM")
        q.table = self._table_name()
        if self.accept_kw("WHERE"):
            q.filter = self.expr()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            q.group_by = self._expr_list()
        if self.accept_kw("HAVING"):
            q.having = self.expr()
        self._tail_clauses(q)
        self.accept_op(";")
        t = self.peek()
        if t.kind != "end":
            raise SqlParseError(f"trailing input at {t.pos}: {t.text!r}")
        return q

    def _tail_clauses(self, q) -> None:
        """ORDER BY / LIMIT[,off|OFFSET] / OPTION(...) — shared between
        the single-stage statement tail and MSE compound-query tails."""
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            q.order_by = self._order_list()
        if self.accept_kw("LIMIT"):
            a = int(self._literal_text(self.next()))
            if self.accept_op(","):
                q.offset, q.limit = a, int(self._literal_text(self.next()))
            else:
                q.limit = a
                if self.accept_kw("OFFSET"):
                    q.offset = int(self._literal_text(self.next()))
        if self.accept_kw("OPTION"):
            self.expect_op("(")
            while True:
                key = self._name_text(self.next())
                self.expect_op("=")
                q.options[key] = self._literal_text(self.next())
                if not self.accept_op(","):
                    break
            self.expect_op(")")

    def _name_text(self, t: Token) -> str:
        if t.kind == "qident":
            return t.text[1:-1].replace('""', '"').replace("``", "`")
        if t.kind in ("name", "string"):
            return t.text.strip("'")
        raise SqlParseError(f"expected name at {t.pos}, got {t.text!r}")

    def _literal_text(self, t: Token) -> str:
        if t.kind == "string":
            return t.text[1:-1].replace("''", "'")
        if t.kind in ("number", "name"):
            return t.text
        raise SqlParseError(f"expected literal at {t.pos}, got {t.text!r}")

    def _table_name(self) -> str:
        t = self.next()
        return self._name_text(t)

    def _select_list(self) -> List[Expression]:
        out = []
        while True:
            if self.accept_op("*"):
                out.append(ident("*"))
            else:
                e = self.expr()
                if self.accept_kw("AS"):
                    alias = self._name_text(self.next())
                    e = func("as", e, lit(alias))
                out.append(e)
            if not self.accept_op(","):
                return out

    def _expr_list(self) -> List[Expression]:
        out = [self.expr()]
        while self.accept_op(","):
            out.append(self.expr())
        return out

    def _order_list(self) -> List[Tuple[Expression, bool]]:
        out = []
        while True:
            e = self.expr()
            asc = True
            if self.accept_kw("DESC"):
                asc = False
            else:
                self.accept_kw("ASC")
            # NULLS FIRST/LAST accepted and ignored (default ordering)
            if self.accept_kw("NULLS"):
                self.next()
            out.append((e, asc))
            if not self.accept_op(","):
                return out

    # -- expression precedence climbing -------------------------------------
    # OR < AND < NOT < comparison/IN/BETWEEN/LIKE/IS < add < mul < unary < atom
    def expr(self) -> Expression:
        return self._or()

    def _or(self) -> Expression:
        left = self._and()
        args = [left]
        while self.accept_kw("OR"):
            args.append(self._and())
        return func("or", *args) if len(args) > 1 else left

    def _and(self) -> Expression:
        left = self._not()
        args = [left]
        while self.accept_kw("AND"):
            args.append(self._not())
        return func("and", *args) if len(args) > 1 else left

    def _not(self) -> Expression:
        if self.accept_kw("NOT"):
            return func("not", self._not())
        return self._comparison()

    def _comparison(self) -> Expression:
        left = self._additive()
        t = self.peek()
        if t.kind == "op" and t.text in _CMP_FUNCS:
            self.next()
            return func(_CMP_FUNCS[t.text], left, self._additive())
        negate = False
        if t.kind == "name" and t.upper == "NOT" \
                and self.peek(1).upper in ("IN", "BETWEEN", "LIKE"):
            self.next()
            negate = True
            t = self.peek()
        if self.accept_kw("BETWEEN"):
            lo = self._additive()
            self.expect_kw("AND")
            hi = self._additive()
            e: Expression = func("between", left, lo, hi)
            return func("not", e) if negate else e
        if self.accept_kw("IN"):
            self.expect_op("(")
            vals = self._expr_list()
            self.expect_op(")")
            e = func("not_in" if negate else "in", left, *vals)
            return e
        if self.accept_kw("LIKE"):
            e = func("like", left, self._additive())
            return func("not", e) if negate else e
        if self.accept_kw("IS"):
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                return func("is_not_null", left)
            self.expect_kw("NULL")
            return func("is_null", left)
        return left

    def _additive(self) -> Expression:
        left = self._multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in _ADD_FUNCS:
                self.next()
                left = func(_ADD_FUNCS[t.text], left, self._multiplicative())
            else:
                return left

    def _multiplicative(self) -> Expression:
        left = self._unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in _MUL_FUNCS:
                self.next()
                left = func(_MUL_FUNCS[t.text], left, self._unary())
            else:
                return left

    def _unary(self) -> Expression:
        if self.accept_op("-"):
            inner = self._unary()
            if isinstance(inner, Literal) and isinstance(inner.value, (int, float)):
                return lit(-inner.value)
            return func("minus", lit(0), inner)
        self.accept_op("+")
        return self._atom()

    def _atom(self) -> Expression:
        t = self.peek()
        if t.kind == "number":
            self.next()
            txt = t.text
            if re.fullmatch(r"\d+", txt):
                return lit(int(txt))
            return lit(float(txt))
        if t.kind == "string":
            self.next()
            return lit(t.text[1:-1].replace("''", "'"))
        if t.kind == "qident":
            self.next()
            return ident(self._name_text(t))
        if self.accept_op("("):
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "name":
            up = t.upper
            if up == "NULL":
                self.next()
                return lit(None)
            if up == "TRUE":
                self.next()
                return lit(True)
            if up == "FALSE":
                self.next()
                return lit(False)
            if up == "CASE":
                return self._case()
            self.next()
            if self.peek().kind == "op" and self.peek().text == "(":
                return self._call(t.text)
            return ident(t.text)
        raise SqlParseError(f"unexpected token {t.text!r} at {t.pos}")

    def _case(self) -> Expression:
        """CASE WHEN c1 THEN v1 ... [ELSE e] END -> case(c1,v1,...,e)."""
        self.expect_kw("CASE")
        args: List[Expression] = []
        while self.accept_kw("WHEN"):
            args.append(self.expr())
            self.expect_kw("THEN")
            args.append(self.expr())
        if self.accept_kw("ELSE"):
            args.append(self.expr())
        else:
            args.append(lit(None))
        self.expect_kw("END")
        return func("case", *args)

    def _call(self, name: str) -> Expression:
        self.expect_op("(")
        lname = name.lower()
        if self.accept_op(")"):
            e: Expression = func(lname)
        elif lname == "count" and self.accept_op("*"):
            self.expect_op(")")
            e = func("count", ident("*"))
        else:
            distinct = bool(self.accept_kw("DISTINCT"))
            args = self._expr_list()
            self.expect_op(")")
            if distinct:
                e = func("distinctcount", *args) if lname == "count" \
                    else func(lname, func("distinct", *args))
            else:
                e = func(lname, *args)
        # FILTER (WHERE cond) suffix for filtered aggregation
        if self.accept_kw("FILTER"):
            self.expect_op("(")
            self.expect_kw("WHERE")
            cond = self.expr()
            self.expect_op(")")
            e = func("filter_agg", e, cond)
        return e


def parse_sql(sql: str) -> PinotQuery:
    """Parse a SQL string into a PinotQuery AST."""
    return _Parser(tokenize(sql)).parse()
