"""Segment pruning before execution.

Reference parity: pinot-core query/pruner/ — ColumnValueSegmentPruner
(min/max + bloom-filter checks on EQ/range predicates,
ColumnValueSegmentPruner.java), SelectionQuerySegmentPruner (limit-0 /
already-satisfied selections). Partition and time pruning happen
broker-side in routing (broker/routing.py), as in the reference.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from pinot_tpu.models import DataType
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expression, Function, Identifier, Literal
from pinot_tpu.segment.loader import ImmutableSegment


def prune_segments(segments: List[ImmutableSegment],
                   ctx: QueryContext) -> List[ImmutableSegment]:
    if ctx.filter is None:
        return list(segments)
    return [s for s in segments if not _can_prune(s, ctx.filter)]


def _can_prune(seg: ImmutableSegment, expr: Expression) -> bool:
    """True when the filter provably matches nothing in this segment."""
    if not isinstance(expr, Function):
        return False
    name = expr.name
    if name == "and":
        return any(_can_prune(seg, a) for a in expr.args)
    if name == "or":
        return all(_can_prune(seg, a) for a in expr.args)
    if name not in ("equals", "between", "greater_than", "greater_than_or_equal",
                    "less_than", "less_than_or_equal", "in"):
        return False
    if not expr.args or not isinstance(expr.args[0], Identifier):
        return False
    col = expr.args[0].name
    meta = seg.metadata.columns.get(col)
    if meta is None or meta.min_value is None or meta.max_value is None:
        return False
    lo, hi = meta.min_value, meta.max_value

    def lit(i: int):
        a = expr.args[i]
        return a.value if isinstance(a, Literal) else None

    try:
        if name == "equals":
            v = lit(1)
            if v is None:
                return False
            if _cmp_lt(v, lo) or _cmp_lt(hi, v):
                return True
            bloom = seg.data_source(col).bloom_filter
            if bloom is not None:
                # probe with the value coerced into the column's STORED
                # domain (what BloomFilter.build hashed); a raw literal of
                # a different type hashes differently and would wrongly
                # prune (ADVICE r1: `WHERE intcol = 5.0` pruned everything)
                ok, pv = _bloom_probe_value(meta, v)
                if ok and not bloom.might_contain(pv):
                    return True
            return False
        if name == "in":
            vals = [a.value for a in expr.args[1:] if isinstance(a, Literal)]
            return all(_cmp_lt(v, lo) or _cmp_lt(hi, v) for v in vals) if vals else False
        if name == "between":
            a, b = lit(1), lit(2)
            return a is not None and b is not None and (_cmp_lt(hi, a) or _cmp_lt(b, lo))
        if name == "greater_than":
            v = lit(1)
            return v is not None and not _cmp_lt(v, hi)
        if name == "greater_than_or_equal":
            v = lit(1)
            return v is not None and _cmp_lt(hi, v)
        if name == "less_than":
            v = lit(1)
            return v is not None and not _cmp_lt(lo, v)
        if name == "less_than_or_equal":
            v = lit(1)
            return v is not None and _cmp_lt(v, lo)
    except TypeError:
        return False
    return False


def _bloom_probe_value(meta, v) -> Tuple[bool, Optional[object]]:
    """Coerce a literal into the stored value domain the bloom filter was
    built over. Returns (ok, value); ok=False means 'cannot probe' and the
    caller must skip the bloom check rather than prune."""
    st = meta.data_type.stored_type
    try:
        if st in (DataType.INT, DataType.LONG):
            if isinstance(v, str):
                # int() first: float() loses precision above 2^53 and would
                # probe the wrong long value for e.g. '9007199254740993'
                try:
                    v = int(v)
                except ValueError:
                    v = float(v)
            if isinstance(v, float):
                if not v.is_integer():
                    return False, None
                v = int(v)
            return True, int(v)
        if st in (DataType.FLOAT, DataType.DOUBLE):
            f = float(v)
            if st is DataType.FLOAT:
                # stored values are f32; the filter hashed the f64-widened
                # f32 value, so round-trip through f32 before probing
                f = float(np.float32(f))
            return True, f
        if st is DataType.STRING:
            return True, v if isinstance(v, str) else str(v)
        if st is DataType.BYTES and isinstance(v, bytes):
            return True, v
    except (TypeError, ValueError):
        pass
    return False, None


def _cmp_lt(a, b) -> bool:
    if isinstance(a, str) != isinstance(b, str):
        a, b = float(a), float(b)
    return a < b
