"""Segment pruning before execution.

Reference parity: pinot-core query/pruner/ — ColumnValueSegmentPruner
(min/max + bloom-filter checks on EQ/range predicates,
ColumnValueSegmentPruner.java), SelectionQuerySegmentPruner (limit-0 /
already-satisfied selections). Partition and time pruning happen
broker-side in routing (broker/routing.py), as in the reference.
"""
from __future__ import annotations

from typing import List, Optional

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expression, Function, Identifier, Literal
from pinot_tpu.segment.loader import ImmutableSegment


def prune_segments(segments: List[ImmutableSegment],
                   ctx: QueryContext) -> List[ImmutableSegment]:
    if ctx.filter is None:
        return list(segments)
    return [s for s in segments if not _can_prune(s, ctx.filter)]


def _can_prune(seg: ImmutableSegment, expr: Expression) -> bool:
    """True when the filter provably matches nothing in this segment."""
    if not isinstance(expr, Function):
        return False
    name = expr.name
    if name == "and":
        return any(_can_prune(seg, a) for a in expr.args)
    if name == "or":
        return all(_can_prune(seg, a) for a in expr.args)
    if name not in ("equals", "between", "greater_than", "greater_than_or_equal",
                    "less_than", "less_than_or_equal", "in"):
        return False
    if not expr.args or not isinstance(expr.args[0], Identifier):
        return False
    col = expr.args[0].name
    meta = seg.metadata.columns.get(col)
    if meta is None or meta.min_value is None or meta.max_value is None:
        return False
    lo, hi = meta.min_value, meta.max_value

    def lit(i: int):
        a = expr.args[i]
        return a.value if isinstance(a, Literal) else None

    try:
        if name == "equals":
            v = lit(1)
            if v is None:
                return False
            if _cmp_lt(v, lo) or _cmp_lt(hi, v):
                return True
            bloom = seg.data_source(col).bloom_filter
            if bloom is not None and not bloom.might_contain(v):
                return True
            return False
        if name == "in":
            vals = [a.value for a in expr.args[1:] if isinstance(a, Literal)]
            return all(_cmp_lt(v, lo) or _cmp_lt(hi, v) for v in vals) if vals else False
        if name == "between":
            a, b = lit(1), lit(2)
            return a is not None and b is not None and (_cmp_lt(hi, a) or _cmp_lt(b, lo))
        if name == "greater_than":
            v = lit(1)
            return v is not None and not _cmp_lt(v, hi)
        if name == "greater_than_or_equal":
            v = lit(1)
            return v is not None and _cmp_lt(hi, v)
        if name == "less_than":
            v = lit(1)
            return v is not None and not _cmp_lt(lo, v)
        if name == "less_than_or_equal":
            v = lit(1)
            return v is not None and _cmp_lt(v, lo)
    except TypeError:
        return False
    return False


def _cmp_lt(a, b) -> bool:
    if isinstance(a, str) != isinstance(b, str):
        a, b = float(a), float(b)
    return a < b
