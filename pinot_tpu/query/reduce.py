"""Broker-side reduce: merge per-segment/per-server results into the final
result table.

Reference parity: pinot-core query/reduce/BrokerReduceService.java:61 and
the per-shape reducers (AggregationDataTableReducer,
GroupByDataTableReducer with IndexedTable merge + HavingFilterHandler +
PostAggregationHandler, SelectionDataTableReducer, DistinctDataTableReducer).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    Expression, Function, Identifier, Literal, extract_aggregations)
from pinot_tpu.query.results import (
    AggregationResult, DistinctResult, ExecutionStats, GroupByResult,
    SelectionResult)


@dataclass
class ResultTable:
    columns: List[str]
    column_types: List[str]
    rows: List[Tuple]

    def to_dict(self) -> dict:
        return {"dataSchema": {"columnNames": self.columns,
                               "columnDataTypes": self.column_types},
                "rows": [list(r) for r in self.rows]}


@dataclass
class BrokerResponse:
    """Ref BrokerResponseNative (pinot-common response/broker/)."""
    result_table: Optional[ResultTable] = None
    exceptions: List[dict] = field(default_factory=list)
    stats: ExecutionStats = field(default_factory=ExecutionStats)
    time_used_ms: float = 0.0
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    num_groups_limit_reached: bool = False
    trace: Optional[dict] = None  # operator trace tree when trace=true
    #: True when this response was served from the broker result cache
    #: (tier 1); never True on a freshly executed response
    cache_hit: bool = False
    #: True when the answer is known-incomplete (a server timed out, died
    #: mid-query, or segments had no surviving replica) — the exceptions
    #: list carries the why (ref BrokerResponseNative partialResult)
    partial_result: bool = False
    #: True when the answer was served from the result cache PAST its
    #: TTL under brownout (health/brownout.py rung 2): correct as of
    #: when it was cached, knowingly stale now — clients choose whether
    #: stale beats failed
    stale_result: bool = False

    def to_dict(self) -> dict:
        d = {
            "resultTable": self.result_table.to_dict() if self.result_table else None,
            "exceptions": self.exceptions,
            "numServersQueried": self.num_servers_queried,
            "numServersResponded": self.num_servers_responded,
            "numDocsScanned": self.stats.num_docs_scanned,
            "numEntriesScannedInFilter": self.stats.num_entries_scanned_in_filter,
            "numEntriesScannedPostFilter": self.stats.num_entries_scanned_post_filter,
            "numSegmentsProcessed": self.stats.num_segments_processed,
            "numSegmentsMatched": self.stats.num_segments_matched,
            "numSegmentsPrunedByServer": self.stats.num_segments_pruned,
            "totalDocs": self.stats.total_docs,
            "numGroupsLimitReached": self.num_groups_limit_reached,
            "timeUsedMs": self.time_used_ms,
            "cacheHit": self.cache_hit,
            "partialResult": self.partial_result,
            "staleResult": self.stale_result,
        }
        if self.trace is not None:
            d["traceInfo"] = self.trace
        return d

    @property
    def rows(self) -> List[Tuple]:
        return self.result_table.rows if self.result_table else []


# ---------------------------------------------------------------------------
# Post-aggregation expression evaluation (scalar space)
# ---------------------------------------------------------------------------

_SCALAR_FUNCS = {
    "plus": lambda a, b: a + b,
    "minus": lambda a, b: a - b,
    "times": lambda a, b: a * b,
    "divide": lambda a, b: a / b if b else float("inf") if a > 0 else float("-inf") if a < 0 else float("nan"),
    "mod": lambda a, b: a % b,
    "abs": abs,
    "sqrt": math.sqrt,
    "ln": math.log, "log": math.log, "log10": math.log10, "log2": math.log2,
    "exp": math.exp,
    "ceil": math.ceil, "floor": math.floor,
    "equals": lambda a, b: a == b,
    "not_equals": lambda a, b: a != b,
    "greater_than": lambda a, b: a > b,
    "greater_than_or_equal": lambda a, b: a >= b,
    "less_than": lambda a, b: a < b,
    "less_than_or_equal": lambda a, b: a <= b,
    "and": lambda *xs: all(xs),
    "or": lambda *xs: any(xs),
    "not": lambda a: not a,
}


def eval_scalar(expr: Expression, bindings: Dict[Expression, Any]) -> Any:
    """Evaluate an expression over scalar bindings (ref
    PostAggregationHandler / HavingFilterHandler)."""
    if expr in bindings:
        return bindings[expr]
    if isinstance(expr, Literal):
        return expr.value
    if isinstance(expr, Function):
        if expr.name == "between":
            v = eval_scalar(expr.args[0], bindings)
            return (eval_scalar(expr.args[1], bindings) <= v
                    <= eval_scalar(expr.args[2], bindings))
        if expr.name == "in":
            v = eval_scalar(expr.args[0], bindings)
            return any(v == eval_scalar(a, bindings) for a in expr.args[1:])
        fn = _SCALAR_FUNCS.get(expr.name)
        if fn is None:
            raise ValueError(f"unsupported post-aggregation function: {expr.name}")
        return fn(*(eval_scalar(a, bindings) for a in expr.args))
    raise ValueError(f"unbound expression in post-aggregation: {expr}")


# ---------------------------------------------------------------------------
# Reducers
# ---------------------------------------------------------------------------

def reduce_results(ctx: QueryContext, results: Sequence[Any]) -> BrokerResponse:
    """Merge SegmentResults (from any mix of servers/paths) into the final
    BrokerResponse (ref BrokerReduceService.reduceOnDataTable)."""
    resp = BrokerResponse()
    results = [r for r in results if r is not None]
    for r in results:
        resp.stats.merge(r.stats)
    if ctx.is_group_by_query:
        resp.result_table = _reduce_group_by(ctx, results, resp)
    elif ctx.is_aggregation_query:
        resp.result_table = _reduce_aggregation(ctx, results)
    elif ctx.is_distinct_query:
        resp.result_table = _reduce_distinct(ctx, results)
    else:
        resp.result_table = _reduce_selection(ctx, results)
    return resp


def _final_type(v: Any, declared: str) -> str:
    return declared


def _reduce_aggregation(ctx: QueryContext, results: List[AggregationResult]) -> ResultTable:
    merged = [fn.identity() for fn in ctx.agg_functions]
    for r in results:
        for i, fn in enumerate(ctx.agg_functions):
            merged[i] = fn.merge(merged[i], r.intermediates[i])
    finals = [fn.extract_final(m) for fn, m in zip(ctx.agg_functions, merged)]
    bindings: Dict[Expression, Any] = {
        node: v for node, v in zip(ctx.agg_keys, finals)}
    row = tuple(eval_scalar(e, bindings) for e in ctx.select)
    names = ctx.result_column_names()
    types = [_result_type(e, ctx) for e in ctx.select]
    return ResultTable(names, types, [row])


def _reduce_group_by(ctx: QueryContext, results: List[GroupByResult],
                     resp: BrokerResponse) -> ResultTable:
    # IndexedTable-style merge (ref GroupByDataTableReducer)
    merged: Dict[Tuple, List[Any]] = {}
    for r in results:
        resp.num_groups_limit_reached |= r.num_groups_limit_reached
        for key, inters in r.groups.items():
            cur = merged.get(key)
            if cur is None:
                merged[key] = list(inters)
            else:
                for i, fn in enumerate(ctx.agg_functions):
                    cur[i] = fn.merge(cur[i], inters[i])

    rows = []
    for key, inters in merged.items():
        finals = [fn.extract_final(m) for fn, m in zip(ctx.agg_functions, inters)]
        bindings: Dict[Expression, Any] = dict(zip(ctx.group_by, key))
        bindings.update(zip(ctx.agg_keys, finals))
        if ctx.having is not None and not eval_scalar(ctx.having, bindings):
            continue
        # the output row evaluates against CLEAN bindings first (an alias
        # may shadow a column its own expression reads); aliases then bind
        # to the COMPUTED values for ORDER BY — after the HAVING gate, so
        # guarded expressions never evaluate for excluded groups
        out_row = tuple(eval_scalar(e, bindings) for e in ctx.select)
        for val, alias in zip(out_row, ctx.aliases):
            if alias is not None:
                bindings[Identifier(alias)] = val
        sort_key = tuple(eval_scalar(e, bindings) for e, _ in ctx.order_by)
        rows.append((sort_key, out_row))

    names = ctx.result_column_names()
    types = [_result_type(e, ctx) for e in ctx.select]
    if "gapfillTimeCol" in ctx.options:
        # fill BEFORE sort/limit so ordering + limit apply to the filled
        # series (ref GapfillProcessor running inside the reducer)
        from pinot_tpu.query.gapfill import maybe_gapfill
        pre = ResultTable(names, types, [r for _, r in rows])
        filled = maybe_gapfill(ctx, pre)
        if filled is not pre:  # options were valid and fill applied
            try:
                return ResultTable(
                    names, types,
                    _sort_limit_filled(ctx, names, filled.rows))
            except (ValueError, KeyError):
                # ORDER BY references something not reconstructible from
                # the output row (e.g. an unselected column): fall back
                # to the unfilled path rather than failing the query
                pass
    if ctx.order_by:
        rows = _sorted_by_keys(rows, [asc for _, asc in ctx.order_by])
    out = [r for _, r in rows][ctx.offset:ctx.offset + ctx.limit]
    return ResultTable(names, types, out)


def _sort_limit_filled(ctx: QueryContext, names, filled_rows):
    """ORDER BY + OFFSET/LIMIT over gap-filled rows: sort keys re-derive
    from the output columns (select expressions + aliases)."""
    if not ctx.order_by:
        return list(filled_rows)[ctx.offset:ctx.offset + ctx.limit]
    keyed = []
    for row in filled_rows:
        bindings = {Identifier(n): v for n, v in zip(names, row)}
        for e, v in zip(ctx.select, row):
            bindings[e] = v
        keyed.append((tuple(eval_scalar(e, bindings)
                            for e, _ in ctx.order_by), row))
    keyed = _sorted_by_keys(keyed, [asc for _, asc in ctx.order_by])
    return [r for _, r in keyed][ctx.offset:ctx.offset + ctx.limit]


def _sorted_by_keys(rows, ascs: List[bool]):
    """Sort (sort_key, row) pairs honoring per-key direction."""
    import functools

    def cmp(a, b):
        for i, asc in enumerate(ascs):
            ka, kb = a[0][i], b[0][i]
            if ka == kb:
                continue
            lt = _lt(ka, kb)
            return (-1 if lt else 1) if asc else (1 if lt else -1)
        return 0

    return sorted(rows, key=functools.cmp_to_key(cmp))


def _lt(a, b) -> bool:
    try:
        return a < b
    except TypeError:
        return str(a) < str(b)


def _reduce_selection(ctx: QueryContext, results: List[SelectionResult]) -> ResultTable:
    names = list(ctx.result_column_names())
    for r in results:
        if getattr(r, "columns", None):
            names = list(r.columns)
            break
    if not ctx.order_by:
        rows: List[Tuple] = []
        for r in results:
            rows.extend(r.rows)
        rows = rows[ctx.offset:ctx.offset + ctx.limit]
        return ResultTable(names, ["UNKNOWN"] * len(names), rows)
    paired = []
    for r in results:
        ov = r.order_values if r.order_values is not None else r.rows
        paired.extend(zip(ov, r.rows))
    paired = _sorted_by_keys(paired, [asc for _, asc in ctx.order_by])
    rows = [row for _, row in paired][ctx.offset:ctx.offset + ctx.limit]
    return ResultTable(names, ["UNKNOWN"] * len(names), rows)


def _reduce_distinct(ctx: QueryContext, results: List[DistinctResult]) -> ResultTable:
    seen = set()
    for r in results:
        seen |= r.rows
    rows = list(seen)
    if ctx.order_by:
        # order-by exprs must be in the select list for distinct
        idx = {e: i for i, e in enumerate(ctx.select)}
        paired = [(tuple(row[idx[e]] for e, _ in ctx.order_by), row) for row in rows]
        paired = _sorted_by_keys(paired, [asc for _, asc in ctx.order_by])
        rows = [row for _, row in paired]
    rows = rows[ctx.offset:ctx.offset + ctx.limit]
    names = list(ctx.result_column_names())
    return ResultTable(names, ["UNKNOWN"] * len(names), rows)


def _result_type(e: Expression, ctx: QueryContext) -> str:
    from pinot_tpu.query.aggregation import get_aggregation, is_aggregation
    if isinstance(e, Function) and is_aggregation(e.name):
        return get_aggregation(e.name, e.args).final_dtype
    if isinstance(e, Function):
        return "DOUBLE"
    return "UNKNOWN"
