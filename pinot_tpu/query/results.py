"""Per-segment / per-server intermediate result containers.

Reference parity: pinot-core operator result blocks
(AggregationResultsBlock, GroupByResultsBlock, SelectionResultsBlock,
DistinctResultsBlock) and the serialized DataTable (pinot-common
datatable/DataTableImplV4.java:82) they travel as. Here they are plain
Python containers; the wire serde lives in server/datatable.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class ExecutionStats:
    """Ref core/operator/ExecutionStatistics.java + DataTable metadata."""
    num_docs_scanned: int = 0
    num_entries_scanned_in_filter: int = 0
    num_entries_scanned_post_filter: int = 0
    num_segments_processed: int = 0
    num_segments_matched: int = 0
    total_docs: int = 0
    num_segments_pruned: int = 0

    def merge(self, o: "ExecutionStats") -> None:
        self.num_docs_scanned += o.num_docs_scanned
        self.num_entries_scanned_in_filter += o.num_entries_scanned_in_filter
        self.num_entries_scanned_post_filter += o.num_entries_scanned_post_filter
        self.num_segments_processed += o.num_segments_processed
        self.num_segments_matched += o.num_segments_matched
        self.total_docs += o.total_docs
        self.num_segments_pruned += o.num_segments_pruned


@dataclass
class AggregationResult:
    """One intermediate per aggregation function."""
    intermediates: List[Any]
    stats: ExecutionStats = field(default_factory=ExecutionStats)


@dataclass
class GroupByResult:
    """group-key tuple (raw values) -> list of intermediates."""
    groups: Dict[Tuple, List[Any]]
    stats: ExecutionStats = field(default_factory=ExecutionStats)
    num_groups_limit_reached: bool = False


@dataclass
class SelectionResult:
    """Projected rows; order_values present when pre-sorted server-side."""
    rows: List[Tuple]
    order_values: Optional[List[Tuple]] = None
    columns: Optional[List[str]] = None  # star-expanded column names
    stats: ExecutionStats = field(default_factory=ExecutionStats)


@dataclass
class DistinctResult:
    rows: set
    stats: ExecutionStats = field(default_factory=ExecutionStats)


SegmentResult = Any  # union of the above
