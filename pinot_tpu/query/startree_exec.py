"""Star-tree query execution: answer aggregations from pre-agg records.

Reference parity: pinot-core core/startree/ — StarTreeUtils (fit check:
aggregations must map to the tree's function-column pairs, filter must be
an AND of predicates on split-order dims), StarTreeFilterOperator.java:90
(traversal), StarTreeAggregationExecutor / StarTreeGroupByExecutor
(aggregate the pre-agg metric columns over matched records). Used by
executor_cpu.execute_segment when a segment has a fitting tree and the
query doesn't disable it (option useStarTree=false).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expression, Function, Identifier
from pinot_tpu.query.filter import resolve_predicate
from pinot_tpu.query.results import AggregationResult, ExecutionStats, GroupByResult
from pinot_tpu.segment.startree import DimFilter


def _agg_pairs_needed(ctx: QueryContext) -> Optional[List[List[Tuple[str, str]]]]:
    """Per aggregation: list of (func, col) pre-agg pairs it needs, or None
    when some aggregation can't be served from a star-tree."""
    out = []
    for node, filt in zip(ctx.aggregations, ctx.agg_filters):
        if filt is not None:
            return None  # FILTER aggs bypass the tree (ref StarTreeUtils)
        name = node.name
        if name == "count":
            out.append([("count", "*")])
            continue
        if not node.args or not isinstance(node.args[0], Identifier):
            return None
        col = node.args[0].name
        if name in ("sum", "min", "max"):
            out.append([(name, col)])
        elif name == "avg":
            out.append([("sum", col), ("count", "*")])
        else:
            return None
    return out


def _filter_id_sets(seg, expr: Optional[Expression], dims: List[str]
                    ) -> Optional[Dict[str, Optional[DimFilter]]]:
    """AND-only filter tree -> per-dim matching DimFilters, or None when
    the filter doesn't fit (non-AND composition, non-tree dim, unsupported
    predicate). Range predicates stay as [lo, hi] intervals end to end —
    never materialized into dictId arrays — so arbitrarily wide ranges
    fit the tree path."""
    sets: Dict[str, Optional[DimFilter]] = {d: None for d in dims}
    if expr is None:
        return sets

    def add(pred_col: str, f: DimFilter) -> bool:
        cur = sets.get(pred_col)
        sets[pred_col] = f if cur is None else cur.intersect(f)
        return True

    def walk(e: Expression) -> bool:
        if not isinstance(e, Function):
            return False
        if e.name == "and":
            return all(walk(a) for a in e.args)
        if not e.args or not isinstance(e.args[0], Identifier):
            return False
        col = e.args[0].name
        if col not in sets:
            return False  # predicate on a non-tree dim
        p = resolve_predicate(seg, e)
        if p is None:
            return False
        if p.kind == "all":
            return True
        if p.kind == "none":
            return add(col, DimFilter.from_ids(np.empty(0, dtype=np.int32)))
        if p.kind == "range":
            return add(col, DimFilter.from_range(p.lo, p.hi))
        if p.kind == "set":
            return add(col, DimFilter.from_ids(p.ids))
        return False  # notset / null kinds -> scan path

    if not walk(expr):
        return None
    return sets


def execute_star_tree(seg, ctx: QueryContext):
    """Returns AggregationResult/GroupByResult, or None when no tree fits."""
    if ctx.options.get("useStarTree", "true").lower() == "false":
        return None
    reader = getattr(seg, "star_tree", None)
    if reader is None or not reader.trees:
        return None
    if not ctx.aggregations or ctx.distinct:
        return None
    needed = _agg_pairs_needed(ctx)
    if needed is None:
        return None
    group_cols: List[str] = []
    for g in ctx.group_by:
        if not isinstance(g, Identifier):
            return None
        group_cols.append(g.name)

    for tree in reader.trees:
        dims = tree.meta.dims
        tree_pairs = set()
        for p in tree.meta.pairs:
            func, col = p.split("__", 1)
            tree_pairs.add((func.lower(), col))
        if not all(pair in tree_pairs for pairs in needed for pair in pairs):
            continue
        if not all(c in dims for c in group_cols):
            continue
        id_sets = _filter_id_sets(seg, ctx.filter, dims)
        if id_sets is None:
            continue
        return _execute_on_tree(seg, tree, ctx, needed, group_cols, id_sets)
    return None


def _execute_on_tree(seg, tree, ctx: QueryContext, needed, group_cols,
                     id_sets):
    recs = tree.traverse(id_sets, set(group_cols))
    stats = ExecutionStats(
        num_docs_scanned=len(recs),   # pre-agg records scanned
        num_segments_processed=1,
        num_segments_matched=1 if len(recs) else 0,
        total_docs=seg.num_docs)

    def pair_values(pair):
        return tree.metrics[pair][recs]

    if not group_cols:
        inters = [_whole(fn_node.name, needed[i], pair_values)
                  for i, fn_node in enumerate(ctx.aggregations)]
        return AggregationResult(inters, stats)

    # group-by: decode group keys from record dim codes via dictionaries
    dicts = [seg.data_source(c).dictionary for c in group_cols]
    codes = [tree.dim_codes[c][recs] for c in group_cols]
    stacked = np.stack(codes, axis=1) if codes else np.empty((len(recs), 0))
    uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
    n_groups = len(uniq)
    groups: Dict[tuple, list] = {}
    per_fn = []
    for i, fn_node in enumerate(ctx.aggregations):
        per_fn.append(_grouped(fn_node.name, needed[i], pair_values, inverse,
                               n_groups))
    for g in range(n_groups):
        key = tuple(_py(d.get_value(int(uniq[g, j])))
                    for j, d in enumerate(dicts))
        groups[key] = [per_fn[i][g] for i in range(len(per_fn))]
    return GroupByResult(groups, stats)


def _whole(name: str, pairs, pair_values):
    if name == "count":
        return int(pair_values(("count", "*")).sum())
    if name == "sum":
        return float(pair_values(pairs[0]).sum())
    if name == "min":
        v = pair_values(pairs[0])
        return float(v.min()) if len(v) else float("inf")
    if name == "max":
        v = pair_values(pairs[0])
        return float(v.max()) if len(v) else float("-inf")
    if name == "avg":
        return (float(pair_values(pairs[0]).sum()),
                int(pair_values(("count", "*")).sum()))
    raise AssertionError(name)


def _grouped(name: str, pairs, pair_values, inverse, n_groups):
    def bsum(pair):
        return np.bincount(inverse, weights=pair_values(pair),
                           minlength=n_groups)
    if name == "count":
        return bsum(("count", "*")).astype(np.int64).tolist()
    if name == "sum":
        return bsum(pairs[0]).tolist()
    if name == "avg":
        s = bsum(pairs[0])
        c = bsum(("count", "*")).astype(np.int64)
        return list(zip(s.tolist(), c.tolist()))
    v = pair_values(pairs[0])
    if name == "min":
        out = np.full(n_groups, np.inf)
        np.minimum.at(out, inverse, v)
        return out.tolist()
    out = np.full(n_groups, -np.inf)
    np.maximum.at(out, inverse, v)
    return out.tolist()


def _py(v):
    return v.item() if isinstance(v, np.generic) else v
