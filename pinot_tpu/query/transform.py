"""Transform-function evaluation over column blocks.

Reference parity: pinot-core
operator/transform/function/TransformFunction.java:35 (block-at-a-time
evaluation; 72 function classes) + the scalar function registry in
pinot-common function/. Here an expression tree evaluates directly over
whole-column numpy arrays supplied by a ColumnProvider; the device engine
mirrors the same arithmetic in jnp for the shapes it offloads.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, Protocol

import numpy as np

from pinot_tpu.query.expressions import Expression, Function, Identifier, Literal


class ColumnProvider(Protocol):
    def column(self, name: str) -> np.ndarray: ...
    @property
    def num_docs(self) -> int: ...


_BINARY_NUMERIC: Dict[str, Callable] = {
    "plus": np.add,
    "minus": np.subtract,
    "times": np.multiply,
    "divide": lambda a, b: np.divide(np.asarray(a, dtype=np.float64), b),
    "mod": np.mod,
    "pow": np.power,
    "power": np.power,
}

_UNARY_NUMERIC: Dict[str, Callable] = {
    "abs": np.abs,
    "ceil": np.ceil,
    "floor": np.floor,
    "exp": np.exp,
    "ln": np.log,
    "log": np.log,
    "log2": np.log2,
    "log10": np.log10,
    "sqrt": np.sqrt,
    "sign": np.sign,
    "negate": np.negative,
    "sin": np.sin, "cos": np.cos, "tan": np.tan,
    "asin": np.arcsin, "acos": np.arccos, "atan": np.arctan,
    "sinh": np.sinh, "cosh": np.cosh, "tanh": np.tanh,
    "degrees": np.degrees, "radians": np.radians,
}

_COMPARISONS: Dict[str, Callable] = {
    "equals": lambda a, b: _eq(a, b),
    "not_equals": lambda a, b: ~_eq(a, b),
    "greater_than": lambda a, b: np.greater(a, b),
    "greater_than_or_equal": lambda a, b: np.greater_equal(a, b),
    "less_than": lambda a, b: np.less(a, b),
    "less_than_or_equal": lambda a, b: np.less_equal(a, b),
}


def _eq(a, b):
    a_s = np.asarray(a).dtype.kind in "UOS"
    b_s = np.asarray(b).dtype.kind in "UOS"
    if a_s != b_s:  # numeric vs string comparison via string form
        a = np.asarray(a).astype(str) if not a_s else a
        b = np.asarray(b).astype(str) if not b_s else b
    return np.equal(a, b)


def evaluate(expr: Expression, provider: ColumnProvider) -> Any:
    """Evaluate expr to a numpy array (or scalar for literal-only trees)."""
    if isinstance(expr, Literal):
        return expr.value
    if isinstance(expr, Identifier):
        return provider.column(expr.name)
    assert isinstance(expr, Function)
    name = expr.name
    if name in _BINARY_NUMERIC:
        a = evaluate(expr.args[0], provider)
        b = evaluate(expr.args[1], provider)
        return _BINARY_NUMERIC[name](a, b)
    if name in _UNARY_NUMERIC:
        return _UNARY_NUMERIC[name](_as_numeric(evaluate(expr.args[0], provider)))
    if name in _COMPARISONS:
        return _COMPARISONS[name](evaluate(expr.args[0], provider),
                                  evaluate(expr.args[1], provider))
    if name in _PREDICATES:
        return _PREDICATES[name](expr, provider)
    handler = _SPECIAL.get(name)
    if handler is not None:
        return handler(expr, provider)
    raise ValueError(f"unsupported transform function: {name}")


# -- predicate evaluation over plain providers (MSE intermediate blocks;
#    segment scans use the index-aware path in query/filter.py instead) ----

def _bool(x, p: ColumnProvider) -> np.ndarray:
    arr = np.asarray(x)
    if arr.ndim == 0:
        arr = np.full(p.num_docs, bool(arr))
    return arr.astype(bool, copy=False)


def _pred_and(e: Function, p: ColumnProvider):
    out = _bool(evaluate(e.args[0], p), p)
    for a in e.args[1:]:
        out = out & _bool(evaluate(a, p), p)
    return out


def _pred_or(e: Function, p: ColumnProvider):
    out = _bool(evaluate(e.args[0], p), p)
    for a in e.args[1:]:
        out = out | _bool(evaluate(a, p), p)
    return out


def _pred_between(e: Function, p: ColumnProvider):
    v = evaluate(e.args[0], p)
    lo = evaluate(e.args[1], p)
    hi = evaluate(e.args[2], p)
    return np.greater_equal(v, lo) & np.less_equal(v, hi)


def _pred_in(e: Function, p: ColumnProvider):
    v = np.asarray(evaluate(e.args[0], p))
    vals = [a.value for a in e.args[1:]]  # type: ignore[union-attr]
    if v.dtype.kind in "UOS":
        vals = [str(x) for x in vals]
        v = v.astype(str)
    else:
        # numeric column: coerce string literals into the value domain
        # (parity with the leaf path, query/filter.py _value_space_mask)
        vals = [float(x) if isinstance(x, str) else x for x in vals]
    return np.isin(v, np.asarray(vals))


def _pred_like(e: Function, p: ColumnProvider):
    import re as _re
    from pinot_tpu.query.filter import like_to_regex
    v = np.asarray(evaluate(e.args[0], p))
    pattern = e.args[1].value  # type: ignore[union-attr]
    rx = _re.compile(like_to_regex(pattern) if e.name == "like" else pattern)
    return np.array([rx.search(str(x)) is not None for x in v], bool)


def _pred_is_null(e: Function, p: ColumnProvider):
    v = np.asarray(evaluate(e.args[0], p))
    if v.dtype.kind == "f":
        return np.isnan(v)
    if v.dtype.kind == "O":
        return np.array([x is None for x in v], bool)
    return np.zeros(len(v), bool)


_PREDICATES: Dict[str, Callable] = {
    "and": _pred_and,
    "or": _pred_or,
    "not": lambda e, p: ~_bool(evaluate(e.args[0], p), p),
    "between": _pred_between,
    "in": _pred_in,
    "not_in": lambda e, p: ~_pred_in(e, p),
    "like": _pred_like,
    "regexp_like": _pred_like,
    "is_null": _pred_is_null,
    "is_not_null": lambda e, p: ~_pred_is_null(e, p),
}


def _as_numeric(x):
    arr = np.asarray(x)
    if arr.dtype.kind in "UOS":
        return arr.astype(np.float64)
    return x


def _broadcast(x, n: int) -> np.ndarray:
    arr = np.asarray(x)
    if arr.ndim == 0:
        return np.broadcast_to(arr, (n,))
    return arr


# -- special forms ----------------------------------------------------------

def _case(expr: Function, p: ColumnProvider):
    n = p.num_docs
    *pairs, default = expr.args
    result = _broadcast(evaluate(default, p), n).copy() \
        if default is not None else np.full(n, np.nan)
    assigned = np.zeros(n, dtype=bool)
    for i in range(0, len(pairs), 2):
        cond = _broadcast(evaluate(pairs[i], p), n).astype(bool)
        val = _broadcast(evaluate(pairs[i + 1], p), n)
        take = cond & ~assigned
        if result.dtype != val.dtype and (result.dtype.kind in "UOS"
                                          or val.dtype.kind in "UOS"):
            result = result.astype(object)
            val = val.astype(object)
        result = np.where(take, val, result)
        assigned |= cond
    return result


def _concat(expr: Function, p: ColumnProvider):
    parts = [np.asarray(evaluate(a, p)).astype(str) for a in expr.args]
    n = max((len(x) for x in parts if x.ndim), default=1)
    parts = [_broadcast(x, n) for x in parts]
    out = parts[0]
    for part in parts[1:]:
        out = np.char.add(out, part)
    return out


def _substr(expr: Function, p: ColumnProvider):
    s = np.asarray(evaluate(expr.args[0], p)).astype(str)
    start = int(evaluate(expr.args[1], p))
    if len(expr.args) > 2:
        end = int(evaluate(expr.args[2], p))
        return np.array([x[start:end] for x in s])
    return np.array([x[start:] for x in s])


def _cast(expr: Function, p: ColumnProvider):
    v = evaluate(expr.args[0], p)
    target = expr.args[1]
    tname = (target.value if isinstance(target, Literal) else target.name).upper()
    arr = np.asarray(v)
    if tname in ("INT", "INTEGER"):
        return arr.astype(np.float64).astype(np.int32) if arr.dtype.kind in "UOS" \
            else arr.astype(np.int32)
    if tname == "LONG":
        return arr.astype(np.float64).astype(np.int64) if arr.dtype.kind in "UOS" \
            else arr.astype(np.int64)
    if tname == "FLOAT":
        return arr.astype(np.float32)
    if tname == "DOUBLE":
        return arr.astype(np.float64)
    if tname in ("STRING", "VARCHAR"):
        return arr.astype(str)
    if tname == "BOOLEAN":
        return arr.astype(bool)
    raise ValueError(f"unsupported cast target {tname}")


def _clpdecode(expr: Function, p: ColumnProvider):
    """clpDecode(logtypeCol, dictVarsCol, encodedVarsCol) -> message strings
    (ref CLPDecodeTransformFunction, used with clp-log ingestion where the
    enricher split a field into three columns)."""
    from pinot_tpu.segment.clp import decode_message
    lt = np.asarray(evaluate(expr.args[0], p)).astype(str)
    dv = p.mv_lists(expr.args[1].name)  # type: ignore[union-attr]
    ev = p.mv_lists(expr.args[2].name)  # type: ignore[union-attr]
    return np.array([decode_message(lt[i], dv[i], [int(x) for x in ev[i]])
                     for i in range(len(lt))], dtype=object)


_SPECIAL: Dict[str, Callable] = {
    "clpdecode": _clpdecode,
    "case": _case,
    "concat": _concat,
    "substr": _substr,
    "substring": _substr,
    "cast": _cast,
    "lower": lambda e, p: np.char.lower(np.asarray(evaluate(e.args[0], p)).astype(str)),
    "upper": lambda e, p: np.char.upper(np.asarray(evaluate(e.args[0], p)).astype(str)),
    "trim": lambda e, p: np.char.strip(np.asarray(evaluate(e.args[0], p)).astype(str)),
    "length": lambda e, p: np.char.str_len(np.asarray(evaluate(e.args[0], p)).astype(str)),
    "strlen": lambda e, p: np.char.str_len(np.asarray(evaluate(e.args[0], p)).astype(str)),
    "reverse": lambda e, p: np.array(
        [x[::-1] for x in np.asarray(evaluate(e.args[0], p)).astype(str)]),
    "coalesce": lambda e, p: _coalesce(e, p),
    "json_extract_scalar": lambda e, p: _json_extract_scalar(e, p),
    "map_value": lambda e, p: _map_value(e, p),
    "st_distance": lambda e, p: _st_distance(e, p),
    "json_extract_key": lambda e, p: _json_extract_key(e, p),
    "json_format": lambda e, p: np.array(
        [_json_format_one(v) for v in np.asarray(evaluate(e.args[0], p))],
        dtype=object),
}


def _map_value(expr: Function, p: ColumnProvider):
    """map_value(col, 'key'[, default]) — index-backed dense sub-column
    when the segment carries a map index (ref segment/index/map/ dense
    keys), JSON parse per row otherwise."""
    col = expr.args[0]
    key = str(expr.args[1].value)  # type: ignore[union-attr]
    default = expr.args[2].value if len(expr.args) > 2 else None  # type: ignore[union-attr]
    index = None
    ds_getter = getattr(p, "data_source", None)
    if isinstance(col, Identifier) and ds_getter is not None:
        ds = ds_getter(col.name)
        index = getattr(ds, "map_index", None) if ds is not None else None
    if index is not None:
        sub = index.value_column(key)
        if sub is None:
            return np.full(index.num_docs, default, object)
        out = sub.copy()
        if default is not None:
            out[out == None] = default  # noqa: E711
        return out
    vals = np.asarray(evaluate(col, p))
    out = np.full(len(vals), default, object)
    for i, v in enumerate(vals):
        try:
            m = json.loads(str(v))
            if isinstance(m, dict) and key in m:
                out[i] = m[key]
        except ValueError:
            pass
    return out


def _st_distance(expr: Function, p: ColumnProvider):
    """st_distance(col, 'lat,lng') — haversine meters to a fixed point
    (ref StDistanceFunction; points are 'lat,lng' strings here).
    Malformed/null points yield NaN (same contract as the geo index)."""
    from pinot_tpu.segment.geo_index import haversine_m, parse_point
    vals = np.asarray(evaluate(expr.args[0], p))
    rlat, rlng = parse_point(expr.args[1].value)  # type: ignore[union-attr]
    pts = [parse_point(v) for v in vals]
    return haversine_m(np.array([a for a, _ in pts]),
                       np.array([b for _, b in pts]), rlat, rlng)


def _json_format_one(v) -> str:
    if v is None:
        return ""
    try:
        return json.dumps(json.loads(str(v)))
    except ValueError:
        return str(v)


def _json_extract_scalar(expr: Function, p: ColumnProvider):
    """json_extract_scalar(col, '$.path', resultType[, default]) — ref
    pinot-common function/scalar JsonFunctions + the
    JsonExtractScalarTransformFunction block evaluator."""
    from pinot_tpu.segment.json_index import extract_path
    col = np.asarray(evaluate(expr.args[0], p))
    path = str(expr.args[1].value)  # type: ignore[union-attr]
    rtype = str(expr.args[2].value).upper() if len(expr.args) > 2 else "STRING"
    default = expr.args[3].value if len(expr.args) > 3 else None  # type: ignore

    def conv(v):
        if v is None:
            return default
        if rtype in ("INT", "LONG"):
            try:
                return int(v)
            except (TypeError, ValueError):
                return default
        if rtype in ("FLOAT", "DOUBLE"):
            try:
                return float(v)
            except (TypeError, ValueError):
                return default
        if isinstance(v, (dict, list)):
            return json.dumps(v)
        return str(v)

    out = np.empty(len(col), dtype=object)
    for i, raw in enumerate(col):
        try:
            doc = json.loads(raw) if isinstance(raw, (str, bytes)) else raw
        except (ValueError, TypeError):
            doc = None
        out[i] = conv(extract_path(doc, path))
    if rtype in ("INT", "LONG"):
        if all(v is not None for v in out):
            return out.astype(np.int64)
        # missing paths with no default fall back to NaN floats (like the
        # DOUBLE branch) — but only while every present value survives the
        # f64 round trip; big int64s (snowflake ids) would silently alias
        if any(v is not None and abs(int(v)) > (1 << 53) for v in out):
            raise ValueError(
                f"json_extract_scalar {rtype} over {path!r}: some documents "
                "lack the path and values exceed float precision — pass an "
                "explicit default argument")
        return np.array([np.nan if v is None else float(v) for v in out],
                        dtype=np.float64)
    if rtype in ("FLOAT", "DOUBLE"):
        return np.array([np.nan if v is None else v for v in out],
                        dtype=np.float64)
    return out


def _json_extract_key(expr: Function, p: ColumnProvider):
    """json_extract_key(col, '$.path') -> sorted keys of the object."""
    from pinot_tpu.segment.json_index import extract_path
    col = np.asarray(evaluate(expr.args[0], p))
    path = str(expr.args[1].value)  # type: ignore[union-attr]
    out = np.empty(len(col), dtype=object)
    for i, raw in enumerate(col):
        try:
            doc = json.loads(raw) if isinstance(raw, (str, bytes)) else raw
        except (ValueError, TypeError):
            doc = None
        v = extract_path(doc, path)
        out[i] = sorted(v.keys()) if isinstance(v, dict) else []
    return out


def _missing_mask(arr: np.ndarray) -> np.ndarray:
    """Per-element missing test: NaN for float arrays, None/NaN elements
    for object arrays (ingestion records carry None, not NaN)."""
    if arr.dtype.kind == "f":
        return np.isnan(arr)
    if arr.dtype.kind == "O":
        return np.fromiter(
            (x is None or (isinstance(x, float) and np.isnan(x))
             for x in arr), dtype=bool, count=len(arr))
    return np.zeros(len(arr), dtype=bool)


def _coalesce(expr: Function, p: ColumnProvider):
    n = p.num_docs
    result = None
    for a in expr.args:
        try:
            v = _broadcast(evaluate(a, p), n)
        except TypeError:
            continue  # null-propagating sub-expression: the arg is NULL
        if result is None:
            result = v.copy()
        else:
            result = np.where(missing, v, result)
        missing = _missing_mask(result)
        if not missing.any():
            break
    if result is None:  # every argument was NULL
        result = np.full(n, None, dtype=object)
    return result
