"""Columnar segment format: the storage engine.

Reference parity: pinot-segment-spi (contracts: IndexSegment, DataSource:41,
ForwardIndexReader:38, Dictionary:37, PinotDataBuffer:60) and
pinot-segment-local (readers/creators).

Design (TPU-first): every index is a contiguous, 64-byte-aligned slice of one
packed per-segment file (analog of the v3 `columns.psf` + `index_map` layout,
ref segment/store/SingleFileIndexDirectory.java:69). Dict-encoded columns are
fixed-bit packed little-endian words so the hot path — bulk unpack to int32
dictIds — is a single vectorized pass (numpy host-side, Pallas device-side),
then block-copied to TPU HBM.
"""
from pinot_tpu.segment.bitmap import Bitmap
from pinot_tpu.segment.creator import SegmentCreator, build_segment
from pinot_tpu.segment.loader import ImmutableSegment, load_segment

__all__ = ["Bitmap", "SegmentCreator", "build_segment", "ImmutableSegment", "load_segment"]
