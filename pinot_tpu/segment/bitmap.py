"""Doc-id bitmaps — the filter-result currency.

Reference parity: RoaringBitmap usage across the reference (inverted indexes,
null-value vectors, upsert validDocIds; e.g. BitmapInvertedIndexReader,
filter/BitmapBasedFilterOperator.java:32). TPU-first substitution: a dense
bitset over the segment's doc-id space. Segments are bounded (millions of
docs), so dense is small (1M docs = 125KB), composes with numpy bitwise ops
host-side, and converts losslessly to the dense 0/1 mask tensors the device
kernels consume — no run-length decode on the hot path.
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

_POPCOUNT8 = np.array([bin(i).count("1") for i in range(256)], dtype=np.int64)


class Bitmap:
    """Fixed-size dense bitset over [0, num_docs).

    ``version`` is a mutation counter bumped AFTER every in-place change
    (set/clear/resize): upsert validDocIds mutate in place without the
    owning segment object changing, so any cache staging this bitmap's
    contents (the device-resident mask tier, ops/engine.py) keys on the
    version — a mutation addresses a fresh key and the stale staged copy
    becomes unreachable. Bump-after-mutate means a racing reader that
    snapshots (version, mask) can only ever pair an OLD stamp with
    equal-or-newer contents — never serve contents older than its stamp.
    """

    __slots__ = ("num_docs", "_bytes", "version", "_full_memo")

    def __init__(self, num_docs: int, buf: Optional[np.ndarray] = None):
        self.num_docs = num_docs
        self.version = 0
        self._full_memo: Optional[tuple] = None
        nbytes = (num_docs + 7) // 8
        if buf is None:
            self._bytes = np.zeros(nbytes, dtype=np.uint8)
        else:
            b = np.frombuffer(buf, dtype=np.uint8, count=nbytes) \
                if isinstance(buf, (bytes, bytearray, memoryview)) else np.asarray(buf, dtype=np.uint8)
            self._bytes = b.copy() if b.base is not None else b

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_indices(cls, num_docs: int, indices: Iterable[int]) -> "Bitmap":
        idx = np.asarray(list(indices) if not isinstance(indices, np.ndarray) else indices,
                         dtype=np.int64)
        bm = cls(num_docs)
        if len(idx):
            bits = np.zeros(((num_docs + 7) // 8) * 8, dtype=np.uint8)
            bits[idx] = 1
            bm._bytes = np.packbits(bits)
        return bm

    @classmethod
    def from_mask(cls, mask: np.ndarray) -> "Bitmap":
        mask = np.asarray(mask, dtype=bool)
        bm = cls(len(mask))
        bm._bytes = np.packbits(mask)
        return bm

    @classmethod
    def all_set(cls, num_docs: int) -> "Bitmap":
        bm = cls(num_docs)
        bm._bytes[:] = 0xFF
        bm._trim()
        return bm

    @classmethod
    def from_range(cls, num_docs: int, start: int, end: int) -> "Bitmap":
        """Set docs in [start, end)."""
        mask = np.zeros(num_docs, dtype=bool)
        mask[start:end] = True
        return cls.from_mask(mask)

    def _trim(self):
        """Zero out padding bits beyond num_docs.

        packbits is MSB-first: doc i is bit (7 - i%8) of byte i//8, so the
        valid bits of the final byte are its top (8 - extra) bits.
        """
        extra = (8 - self.num_docs % 8) % 8
        if extra:
            self._bytes[-1] &= np.uint8(0xFF & (0xFF << extra))

    # -- ops ----------------------------------------------------------------
    def __and__(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap(self.num_docs)
        out._bytes = self._bytes & other._bytes
        return out

    def __or__(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap(self.num_docs)
        out._bytes = self._bytes | other._bytes
        return out

    def __xor__(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap(self.num_docs)
        out._bytes = self._bytes ^ other._bytes
        return out

    def invert(self) -> "Bitmap":
        out = Bitmap(self.num_docs)
        out._bytes = ~self._bytes
        out._trim()
        return out

    def andnot(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap(self.num_docs)
        out._bytes = self._bytes & ~other._bytes
        return out

    # -- accessors ----------------------------------------------------------
    def cardinality(self) -> int:
        return int(_POPCOUNT8[self._bytes].sum())

    def is_empty(self) -> bool:
        return not self._bytes.any()

    def is_full(self) -> bool:
        """True when every doc in [0, num_docs) is set — a no-op mask.
        Memoized per mutation version: the star-tree gate asks this per
        aggregation query, and an O(num_docs/8) popcount per query would
        put bitmap scans back on the hot path."""
        memo = self._full_memo
        if memo is not None and memo[0] == self.version:
            return memo[1]
        full = self.cardinality() == self.num_docs
        self._full_memo = (self.version, full)
        return full

    def contains(self, doc_id: int) -> bool:
        return bool((self._bytes[doc_id >> 3] >> (7 - (doc_id & 7))) & 1)

    def clear(self, doc_id: int) -> None:
        self._bytes[doc_id >> 3] &= np.uint8(0xFF ^ (0x80 >> (doc_id & 7)))
        self.version += 1

    def resize(self, num_docs: int) -> None:
        """Grow in place (mutable/realtime usage; bits init to 0)."""
        nbytes = (num_docs + 7) // 8
        if nbytes > len(self._bytes):
            self._bytes = np.concatenate(
                [self._bytes, np.zeros(nbytes - len(self._bytes), np.uint8)])
        self.num_docs = num_docs
        self.version += 1

    def set(self, doc_id: int) -> None:
        self._bytes[doc_id >> 3] |= np.uint8(1 << (7 - (doc_id & 7)))
        self.version += 1

    def to_mask(self) -> np.ndarray:
        """Dense bool mask of length num_docs (device-kernel input)."""
        return np.unpackbits(self._bytes, count=self.num_docs).astype(bool)

    def to_indices(self) -> np.ndarray:
        """Sorted int32 doc ids (BlockDocIdIterator analog)."""
        return np.flatnonzero(self.to_mask()).astype(np.int32)

    # -- serde --------------------------------------------------------------
    def to_bytes(self) -> bytes:
        return self._bytes.tobytes()

    @classmethod
    def from_bytes(cls, num_docs: int, data: bytes) -> "Bitmap":
        return cls(num_docs, data)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Bitmap) and self.num_docs == other.num_docs
                and np.array_equal(self._bytes, other._bytes))

    def __repr__(self) -> str:
        return f"Bitmap(num_docs={self.num_docs}, cardinality={self.cardinality()})"
