"""Fixed-bit packing of non-negative int arrays (dictionary ids).

Reference parity: pinot-segment-local io/util/FixedBitIntReaderWriterV2.java:41-124
(aligned bulk unpack of 32-value chunks) and PinotDataBitSetV2. The byte format
here is our own: a dense MSB-first bitstream, padded to whole bytes — chosen so
both numpy (unpackbits) and a future Pallas shift/mask kernel can decode it
without per-value branching.

A C++ fast path (pinot_tpu/native) is used when available; numpy vectorized
otherwise. Both produce identical buffers.
"""
from __future__ import annotations

import numpy as np


def num_bits(cardinality: int) -> int:
    """Minimum bits to represent dictionary ids [0, cardinality)."""
    if cardinality <= 1:
        return 1
    return int(cardinality - 1).bit_length()


def pack(values: np.ndarray, bits: int) -> bytes:
    """Pack int array (values < 2**bits, >= 0) into an MSB-first bitstream."""
    values = np.ascontiguousarray(values, dtype=np.uint32)
    if bits < 1 or bits > 32:
        raise ValueError(f"bits must be in [1,32], got {bits}")
    n = len(values)
    if n == 0:
        return b""
    # (n, bits) matrix of bits, MSB first, then packbits.
    shifts = np.arange(bits - 1, -1, -1, dtype=np.uint32)
    bitmat = ((values[:, None] >> shifts[None, :]) & 1).astype(np.uint8)
    return np.packbits(bitmat.reshape(-1)).tobytes()


def unpack(buf: np.ndarray, n: int, bits: int) -> np.ndarray:
    """Unpack n values of `bits` width from an MSB-first bitstream.

    buf: uint8 array (may be a memmap slice). Returns int32 array of length n.
    """
    if n == 0:
        return np.empty(0, dtype=np.int32)
    buf = np.frombuffer(buf, dtype=np.uint8, count=(n * bits + 7) // 8) \
        if isinstance(buf, (bytes, bytearray, memoryview)) else np.asarray(buf, dtype=np.uint8)
    total_bits = n * bits
    bitarr = np.unpackbits(buf, count=total_bits).reshape(n, bits)
    weights = (1 << np.arange(bits - 1, -1, -1, dtype=np.int64))
    out = bitarr.astype(np.int64) @ weights
    return out.astype(np.int32)


def packed_size(n: int, bits: int) -> int:
    return (n * bits + 7) // 8


def pack_to_words(values: np.ndarray, bits: int) -> np.ndarray:
    """Pack into little-endian uint32 words, 32 values per `bits` words group.

    Device-friendly layout used for HBM upload when in-kernel unpacking is
    enabled: value i lives at bit offset (i*bits) in a flat little-endian
    word stream, so a Pallas kernel computes word = off>>5, shift = off&31 and
    reads at most two words per value.
    """
    values = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(values)
    total_bits = n * bits
    nwords = (total_bits + 31) // 32
    out = np.zeros(nwords + 1, dtype=np.uint64)  # +1 slack for spill
    offs = np.arange(n, dtype=np.uint64) * np.uint64(bits)
    word_idx = (offs >> np.uint64(5)).astype(np.int64)
    shift = (offs & np.uint64(31)).astype(np.uint64)
    lo = (values << shift) & np.uint64(0xFFFFFFFF)
    hi = values >> (np.uint64(32) - shift)
    # values with shift==0 have hi = v >> 32 == 0 for bits<=32; safe.
    np.add.at(out, word_idx, lo)   # disjoint bits -> add == or
    np.add.at(out, word_idx + 1, hi)
    return out[:nwords].astype(np.uint32)


def unpack_from_words(words: np.ndarray, n: int, bits: int) -> np.ndarray:
    """Inverse of pack_to_words (host-side check of the device layout)."""
    words = np.asarray(words, dtype=np.uint32)
    w64 = np.concatenate([words.astype(np.uint64), np.zeros(1, dtype=np.uint64)])
    offs = np.arange(n, dtype=np.uint64) * np.uint64(bits)
    word_idx = (offs >> np.uint64(5)).astype(np.int64)
    shift = (offs & np.uint64(31)).astype(np.uint64)
    both = w64[word_idx] | (w64[word_idx + 1] << np.uint64(32))
    mask = np.uint64((1 << bits) - 1)
    return ((both >> shift) & mask).astype(np.int32)
