"""CLP-style log compression: logtype templates + variable columns.

Reference parity: the y-scope extension — CLPForwardIndexCreatorV1/V2 and
CLPForwardIndexReaderV1/V2 (pinot-segment-local
segment/index/readers/forward/, SURVEY.md §2.2 row 4), which split each
log message via com.yscope.clp:clp-ffi (JNI -> C++) into:
  logtype      — the message template with variables replaced by
                 placeholder bytes (highly repetitive -> dictionary)
  dictVars     — variable tokens that only round-trip as strings
  encodedVars  — integral/float variables packed into int64

This is a clean-room codec with our own placeholders and byte format (the
reference's exact CLP encoding lives in the external clp-ffi library, not
in-tree). Round-trip is exact: tokens only become encoded/dict variables
when re-rendering reproduces the original text.

Placeholders (chosen outside printable ASCII):
  \\x11 int variable (rendered str(int))
  \\x12 dictionary variable (string token)
  \\x13 float variable (IEEE bits in int64, rendered repr-roundtrip)
"""
from __future__ import annotations

import re
import struct
from typing import Any, Iterable, List, Optional, Sequence, Tuple

import numpy as np

INT_PH = "\x11"
DICT_PH = "\x12"
FLOAT_PH = "\x13"

# token = run of non-delimiter chars; delimiters stay in the logtype
_TOKEN_RE = re.compile(r"[^\s=:,\[\]\(\)\"']+")
_HAS_DIGIT = re.compile(r"\d")


def encode_token(tok: str) -> Tuple[str, Any]:
    """Classify one token exactly as :func:`encode_message` would:
    ``("static", tok)`` | ``("int", int64_value)`` | ``("float",
    ieee_bits_as_int64)`` | ``("dict", tok)``. The single source of
    truth for variable extraction — the device pushdown planner
    (ops/clp_device.py) mirrors the codec through this function."""
    if not _HAS_DIGIT.search(tok):
        return "static", tok
    # exact-roundtrip int
    try:
        v = int(tok)
        if str(v) == tok and -(2**63) <= v < 2**63:
            return "int", v
    except ValueError:
        pass
    # exact-roundtrip float
    try:
        f = float(tok)
        if repr(f) == tok:
            return "float", struct.unpack("<q", struct.pack("<d", f))[0]
    except ValueError:
        pass
    return "dict", tok


def encode_message(msg: str) -> Tuple[str, List[str], List[int]]:
    """message -> (logtype, dict_vars, encoded_vars)."""
    dict_vars: List[str] = []
    encoded: List[int] = []

    def repl(m: re.Match) -> str:
        kind, val = encode_token(m.group())
        if kind == "static":
            return val
        if kind == "dict":
            dict_vars.append(val)
            return DICT_PH
        encoded.append(val)
        return INT_PH if kind == "int" else FLOAT_PH

    logtype = _TOKEN_RE.sub(repl, msg)
    return logtype, dict_vars, encoded


def decode_message(logtype: str, dict_vars: Sequence[str],
                   encoded_vars: Sequence[int]) -> str:
    out: List[str] = []
    di = ei = 0
    for ch in logtype:
        if ch == INT_PH:
            out.append(str(encoded_vars[ei]))
            ei += 1
        elif ch == FLOAT_PH:
            out.append(repr(struct.unpack(
                "<d", struct.pack("<q", encoded_vars[ei]))[0]))
            ei += 1
        elif ch == DICT_PH:
            out.append(dict_vars[di])
            di += 1
        else:
            out.append(ch)
    return "".join(out)


# ---------------------------------------------------------------------------
# Forward index (one packed buffer per CLP column)
# ---------------------------------------------------------------------------
# layout: u32 section count-free header:
#   u32 num_docs, u32 num_logtypes, u32 lt_blob_len
#   logtype dictionary: i32 offsets[num_logtypes+1] + utf8 blob
#   i32 logtype_id per doc
#   dictvars: u32 num_unique, u32 uniq_len, str_section(uniques),
#             i32 var_offsets[num_docs+1], i32 var_ids[num_vars]
#             (vars are themselves dictionary-encoded — repeated tokens
#              like hostnames/task-ids collapse, ref CLP var dictionary)
#   encodedvars: i32 enc_offsets[num_docs+1], i64 flat[num_enc]

_U32 = struct.Struct("<I")


def write_clp_column(messages: Sequence[Any]) -> bytes:
    n = len(messages)
    logtypes: List[str] = []
    lt_index = {}
    lt_ids = np.empty(n, dtype=np.int32)
    all_dict_vars: List[str] = []
    dv_counts = np.empty(n, dtype=np.int32)
    all_enc: List[int] = []
    enc_counts = np.empty(n, dtype=np.int32)
    for i, m in enumerate(messages):
        lt, dv, ev = encode_message("" if m is None else str(m))
        idx = lt_index.get(lt)
        if idx is None:
            idx = len(logtypes)
            lt_index[lt] = idx
            logtypes.append(lt)
        lt_ids[i] = idx
        all_dict_vars.extend(dv)
        dv_counts[i] = len(dv)
        all_enc.extend(ev)
        enc_counts[i] = len(ev)

    def str_section(strings: List[str]) -> bytes:
        offsets = np.zeros(len(strings) + 1, dtype=np.int32)
        blobs = [s.encode() for s in strings]
        np.cumsum([len(b) for b in blobs], out=offsets[1:len(strings) + 1])
        return offsets.tobytes() + b"".join(blobs)

    lt_section = str_section(logtypes)
    uniq_vars = list(dict.fromkeys(all_dict_vars))
    var_index = {v: i for i, v in enumerate(uniq_vars)}
    var_ids = np.array([var_index[v] for v in all_dict_vars], dtype=np.int32)
    uniq_section = str_section(uniq_vars)
    parts = [
        _U32.pack(n), _U32.pack(len(logtypes)), _U32.pack(len(lt_section)),
        lt_section,
        lt_ids.tobytes(),
        _U32.pack(len(uniq_vars)), _U32.pack(len(uniq_section)), uniq_section,
        _prefix(dv_counts).tobytes(), var_ids.tobytes(),
        _prefix(enc_counts).tobytes(),
        np.asarray(all_enc, dtype=np.int64).tobytes(),
    ]
    return b"".join(parts)


def _prefix(counts: np.ndarray) -> np.ndarray:
    out = np.zeros(len(counts) + 1, dtype=np.int32)
    np.cumsum(counts, out=out[1:])
    return out


def pack_compressed(buf: bytes, compression: str = "LZ4") -> bytes:
    """Envelope: u32 codec_id, u32 raw_len, compressed payload (the chunk
    compression the reference applies on top of CLP sections)."""
    from pinot_tpu.segment import codec
    cid, comp = codec.compress(buf, codec.codec_id(compression))
    return _U32.pack(cid) + _U32.pack(len(buf)) + comp


def unpack_compressed(buf) -> bytes:
    from pinot_tpu.segment import codec
    buf = bytes(buf)
    cid = _U32.unpack_from(buf, 0)[0]
    raw_len = _U32.unpack_from(buf, 4)[0]
    return codec.decompress(buf[8:], cid, raw_len)


class CLPForwardIndexReader:
    """Ref CLPForwardIndexReaderV2 — decodes on demand; the logtype ids and
    dictionary are directly accessible for template-level predicates."""

    def __init__(self, buf: bytes):
        buf = bytes(buf)
        self.num_docs = _U32.unpack_from(buf, 0)[0]
        num_lt = _U32.unpack_from(buf, 4)[0]
        lt_len = _U32.unpack_from(buf, 8)[0]
        pos = 12
        self.logtypes, _ = self._read_strs(buf, pos, num_lt)
        pos += lt_len
        self.logtype_ids = np.frombuffer(buf, np.int32, self.num_docs, pos)
        pos += 4 * self.num_docs
        num_uniq = _U32.unpack_from(buf, pos)[0]
        uniq_len = _U32.unpack_from(buf, pos + 4)[0]
        pos += 8
        self.var_dictionary, _ = self._read_strs(buf, pos, num_uniq)
        pos += uniq_len
        self.dv_offsets = np.frombuffer(buf, np.int32, self.num_docs + 1, pos)
        pos += 4 * (self.num_docs + 1)
        num_dv = int(self.dv_offsets[-1])
        self.var_ids = np.frombuffer(buf, np.int32, num_dv, pos)
        pos += 4 * num_dv
        self.enc_offsets = np.frombuffer(buf, np.int32, self.num_docs + 1, pos)
        pos += 4 * (self.num_docs + 1)
        num_enc = int(self.enc_offsets[-1])
        self.encoded_vars = np.frombuffer(buf, np.int64, num_enc, pos)

    @staticmethod
    def _read_strs(buf: bytes, pos: int, count: int):
        """Returns (strings, total section length in bytes)."""
        offsets = np.frombuffer(buf, np.int32, count + 1, pos)
        blob_start = pos + 4 * (count + 1)
        out = []
        for i in range(count):
            out.append(buf[blob_start + offsets[i]:
                           blob_start + offsets[i + 1]].decode())
        return out, 4 * (count + 1) + int(offsets[-1])

    def get(self, doc_id: int) -> str:
        """Random access: one doc from the prefix-offset indexes — never a
        full-column decode (ref CLPForwardIndexReaderV2.getString)."""
        lt = self.logtypes[self.logtype_ids[doc_id]]
        dv = [self.var_dictionary[i] for i in
              self.var_ids[self.dv_offsets[doc_id]:self.dv_offsets[doc_id + 1]]]
        ev = self.encoded_vars[self.enc_offsets[doc_id]:self.enc_offsets[doc_id + 1]]
        return decode_message(lt, dv, ev.tolist())

    def decode_all(self) -> np.ndarray:
        """Whole-column decode into ONE object array allocation; the
        int arrays convert to python lists once up front instead of a
        numpy scalar boxing per element per doc."""
        n = self.num_docs
        out = np.empty(n, dtype=object)
        lts = self.logtypes
        vd = self.var_dictionary
        lt_ids = self.logtype_ids.tolist()
        var_ids = self.var_ids.tolist()
        dvo = self.dv_offsets.tolist()
        eco = self.enc_offsets.tolist()
        enc = self.encoded_vars.tolist()
        for d in range(n):
            dv = [vd[i] for i in var_ids[dvo[d]:dvo[d + 1]]]
            out[d] = decode_message(lts[lt_ids[d]], dv, enc[eco[d]:eco[d + 1]])
        return out

    @property
    def max_dict_vars(self) -> int:
        """Widest per-doc dictionary-variable count (device slot sizing)."""
        if getattr(self, "_max_dv", None) is None:
            self._max_dv = int(np.diff(self.dv_offsets).max()) \
                if self.num_docs else 0
        return self._max_dv

    @property
    def max_encoded_vars(self) -> int:
        """Widest per-doc encoded-variable count (device slot sizing)."""
        if getattr(self, "_max_ev", None) is None:
            self._max_ev = int(np.diff(self.enc_offsets).max()) \
                if self.num_docs else 0
        return self._max_ev

    @property
    def var_index(self) -> dict:
        """token -> var-dictionary id (planner-side group pruning)."""
        if getattr(self, "_var_index", None) is None:
            self._var_index = {v: i for i, v in
                               enumerate(self.var_dictionary)}
        return self._var_index


def clp_enricher(fields: Sequence[str]):
    """Ingestion enricher (ref recordtransformer/enricher/clp/
    CLPEncodingEnricher): splits each configured string field into
    <field>_logtype / <field>_dictionaryVars / <field>_encodedVars columns
    for tables that store the three CLP parts as separate columns."""
    def enrich(record: dict) -> None:
        for f in fields:
            v = record.get(f)
            if v is None:
                continue
            lt, dv, ev = encode_message(str(v))
            record[f + "_logtype"] = lt
            record[f + "_dictionaryVars"] = dv
            record[f + "_encodedVars"] = ev
    return enrich


# register as an index plugin (the IndexPlugin/ServiceLoader seam —
# segment build and load resolve 'clp_forward' through the registry)
def _register() -> None:
    import sys

    from pinot_tpu.utils import plugins
    plugins.register("index", "clp_forward", sys.modules[__name__])


_register()
