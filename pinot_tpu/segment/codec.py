"""Chunk compression codecs for raw (no-dictionary) forward indexes.

Reference parity: pinot-segment-spi compression/ChunkCompressionType.java:21
(PASS_THROUGH/SNAPPY/ZSTANDARD/LZ4/GZIP). Here: PASS_THROUGH, GZIP via zlib,
ZSTANDARD via the `zstandard` wheel when present, and LZ4 (block format) via
the native C++ library (pinot_tpu/native) when built. Codecs unavailable in
the environment fall back to GZIP at *write* time (the chunk header records
the codec actually used, so readers never guess).
"""
from __future__ import annotations

import zlib

_ZSTD = None
try:  # optional wheel
    import zstandard as _ZSTD  # type: ignore
except ImportError:
    _ZSTD = None

PASS_THROUGH = 0
GZIP = 1
ZSTANDARD = 2
LZ4 = 3

_NAMES = {"PASS_THROUGH": PASS_THROUGH, "GZIP": GZIP, "ZSTANDARD": ZSTANDARD, "LZ4": LZ4}
_IDS = {v: k for k, v in _NAMES.items()}


def codec_id(name: str) -> int:
    return _NAMES[name.upper()]


def codec_name(cid: int) -> str:
    return _IDS[cid]


def _native_lz4():
    from pinot_tpu.native import lib  # lazy; may be None
    return lib


def resolve(cid: int) -> int:
    """Resolve the codec actually usable in this environment."""
    if cid == ZSTANDARD and _ZSTD is None:
        return GZIP
    if cid == LZ4 and _native_lz4() is None:
        return GZIP
    return cid


def compress(data: bytes, cid: int) -> tuple[int, bytes]:
    """Returns (actual_codec_id, compressed)."""
    cid = resolve(cid)
    if cid == PASS_THROUGH:
        return cid, data
    if cid == GZIP:
        return cid, zlib.compress(data, level=1)
    if cid == ZSTANDARD:
        return cid, _ZSTD.ZstdCompressor(level=3).compress(data)
    if cid == LZ4:
        return cid, _native_lz4().lz4_compress(data)
    raise ValueError(f"unknown codec {cid}")


def decompress(data: bytes, cid: int, raw_size: int) -> bytes:
    if cid == PASS_THROUGH:
        return bytes(data)
    if cid == GZIP:
        return zlib.decompress(bytes(data))
    if cid == ZSTANDARD:
        if _ZSTD is None:
            raise RuntimeError("segment written with ZSTANDARD but wheel missing")
        return _ZSTD.ZstdDecompressor().decompress(bytes(data), max_output_size=raw_size)
    if cid == LZ4:
        lib = _native_lz4()
        if lib is None:
            raise RuntimeError("segment written with LZ4 but native lib missing")
        return lib.lz4_decompress(bytes(data), raw_size)
    raise ValueError(f"unknown codec {cid}")
