"""Segment creation: columnar rows -> immutable packed segment.

Reference parity: pinot-segment-local
segment/creator/impl/SegmentIndexCreationDriverImpl.java:93,231 — stats pass
(cardinality/min/max/sortedness), dictionary creation, per-column index
writing, v3 packing, metadata. Single-pass here because input is already
columnar in memory (the ingestion layer materializes columns).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema, TableConfig)
from pinot_tpu.segment import bitpack, fwd, index_types as it
from pinot_tpu.segment.bitmap import Bitmap
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.indexes import BloomFilter, InvertedIndex, RangeIndex, SortedIndex
from pinot_tpu.segment.meta import ColumnMetadata, SegmentMetadata
from pinot_tpu.segment.store import index_key, write_segment

ColumnData = Union[np.ndarray, Sequence]


class SegmentCreator:
    def __init__(self, table_config: TableConfig, schema: Schema):
        self.table_config = table_config
        self.schema = schema

    def build(self, columns: Dict[str, ColumnData], out_dir: str,
              segment_name: str, partition_id: Optional[int] = None) -> str:
        """columns: name -> values (SV: flat array/list, may contain None;
        MV: list of lists). Returns out_dir."""
        idx_cfg = self.table_config.indexing
        num_docs = _num_docs(columns, self.schema)
        for cname, cdata in columns.items():
            if cdata is not None and len(cdata) != num_docs:
                raise ValueError(
                    f"column {cname!r} has {len(cdata)} values, expected {num_docs}")
        buffers: Dict[str, bytes] = {}
        col_meta: Dict[str, ColumnMetadata] = {}

        for spec in self.schema.fields:
            if spec.virtual:
                continue
            data = columns.get(spec.name)
            if spec.single_value:
                meta = self._build_sv(spec, data, num_docs, idx_cfg, buffers)
            else:
                meta = self._build_mv(spec, data, num_docs, idx_cfg, buffers)
            if partition_id is not None and spec.name in self.table_config.partition_config:
                pc = self.table_config.partition_config[spec.name]
                meta.partition_function = pc.get("functionName", "Modulo")
                meta.num_partitions = pc.get("numPartitions", 1)
                meta.partitions = [partition_id]
            col_meta[spec.name] = meta

        time_col = self.table_config.retention.time_column
        start_t = end_t = None
        if time_col and time_col in col_meta:
            start_t = col_meta[time_col].min_value
            end_t = col_meta[time_col].max_value

        metadata = SegmentMetadata(
            segment_name=segment_name,
            table_name=self.table_config.table_name_with_type,
            num_docs=num_docs, columns=col_meta, time_column=time_col,
            start_time=start_t, end_time=end_t,
            creation_time_ms=int(time.time() * 1000),
        )

        # Star-tree build happens before packing (ref
        # SegmentIndexCreationDriverImpl.java:396 buildStarTreeV2IfNecessary).
        if idx_cfg.star_tree_configs:
            try:
                from pinot_tpu.segment.startree import build_star_trees
            except ImportError as e:
                raise NotImplementedError(
                    "star-tree index build is not available in this build") from e
            build_star_trees(self.table_config, self.schema, columns, metadata, buffers)

        write_segment(out_dir, metadata, buffers)
        return out_dir

    # ------------------------------------------------------------------
    def _build_sv(self, spec: FieldSpec, data: Optional[ColumnData], num_docs: int,
                  idx_cfg, buffers: Dict[str, bytes]) -> ColumnMetadata:
        name = spec.name
        values, null_bm = _normalize_sv(spec, data, num_docs)
        meta = ColumnMetadata(name=name, data_type=spec.data_type,
                              field_type=spec.field_type, single_value=True,
                              total_entries=num_docs, has_nulls=not null_bm.is_empty())
        if not null_bm.is_empty():
            buffers[index_key(name, it.NULLVECTOR)] = null_bm.to_bytes()
            meta.indexes.append(it.NULLVECTOR)

        # CLP log columns: template/variable split instead of plain fwd
        # (ref CLPForwardIndexCreatorV2; SURVEY.md §2.2 y-scope addition)
        if name in idx_cfg.clp_columns:
            # resolved through the plugin registry — the CLP codec is a
            # shipped plugin, not a hardwired import (ref IndexPlugin)
            from pinot_tpu.utils import plugins
            clp = plugins.get_or_load("index", "clp_forward")
            if spec.data_type.stored_type is not DataType.STRING:
                raise ValueError(f"CLP column {name!r} must be STRING-typed")
            meta.has_dictionary = False
            meta.cardinality = len(set(values.tolist()))
            buffers[index_key(name, it.CLP)] = clp.pack_compressed(
                clp.write_clp_column(values), idx_cfg.compression)
            meta.indexes.append(it.CLP)
            return meta
        use_dict = name not in idx_cfg.no_dictionary_columns
        if use_dict:
            dictionary, dict_ids = Dictionary.build(spec.data_type, values)
            card = dictionary.cardinality
            bits = bitpack.num_bits(card)
            meta.has_dictionary = True
            meta.cardinality = card
            meta.bits_per_element = bits
            meta.min_value = dictionary.min_value
            meta.max_value = dictionary.max_value
            meta.is_sorted = bool(num_docs <= 1 or np.all(dict_ids[1:] >= dict_ids[:-1]))
            buffers[index_key(name, it.DICTIONARY)] = dictionary.to_bytes()
            buffers[index_key(name, it.FORWARD)] = fwd.write_sv_dict(dict_ids, bits)
            meta.indexes += [it.DICTIONARY, it.FORWARD]

            if meta.is_sorted:
                buffers[index_key(name, it.SORTED)] = \
                    SortedIndex.build(dict_ids, card).to_bytes()
                meta.indexes.append(it.SORTED)
            if name in idx_cfg.inverted_index_columns and not meta.is_sorted:
                buffers[index_key(name, it.INVERTED)] = \
                    InvertedIndex.build(dict_ids, card, num_docs).to_bytes()
                meta.indexes.append(it.INVERTED)
            if name in idx_cfg.range_index_columns and not meta.is_sorted:
                buffers[index_key(name, it.RANGE)] = \
                    RangeIndex.build(dict_ids, card, num_docs).to_bytes()
                meta.indexes.append(it.RANGE)
            if name in idx_cfg.bloom_filter_columns:
                buffers[index_key(name, it.BLOOM)] = \
                    BloomFilter.build(list(dictionary.values)).to_bytes()
                meta.indexes.append(it.BLOOM)
            self._build_json_text(name, values, num_docs, idx_cfg,
                                  buffers, meta)
        else:
            meta.has_dictionary = False
            st = spec.data_type.stored_type
            if st.is_fixed_width:
                arr = np.asarray(values, dtype=spec.data_type.np_dtype)
                meta.min_value = arr.min().item() if num_docs else None
                meta.max_value = arr.max().item() if num_docs else None
                buffers[index_key(name, it.FORWARD)] = \
                    fwd.write_raw_fixed(arr, idx_cfg.compression)
            else:
                is_bytes = st is DataType.BYTES
                if num_docs:
                    meta.min_value = min(values)
                    meta.max_value = max(values)
                buffers[index_key(name, it.FORWARD)] = \
                    fwd.write_raw_var(list(values), idx_cfg.compression, is_bytes)
            meta.indexes.append(it.FORWARD)
            if name in idx_cfg.bloom_filter_columns:
                buffers[index_key(name, it.BLOOM)] = \
                    BloomFilter.build(list(dict.fromkeys(values))).to_bytes()
                meta.indexes.append(it.BLOOM)
            self._build_json_text(name, values, num_docs, idx_cfg,
                                  buffers, meta)
        return meta

    def _build_json_text(self, name, values, num_docs, idx_cfg,
                         buffers, meta) -> None:
        """JSON / text indexes on STRING columns (ref
        creator/impl/json/, creator/impl/text/)."""
        if name in idx_cfg.json_index_columns:
            from pinot_tpu.segment.json_index import JsonIndex
            buffers[index_key(name, it.JSON)] = \
                JsonIndex.build(values, num_docs).to_bytes()
            meta.indexes.append(it.JSON)
        if name in idx_cfg.text_index_columns:
            from pinot_tpu.segment.text_index import TextIndex
            buffers[index_key(name, it.TEXT)] = \
                TextIndex.build(values, num_docs).to_bytes()
            meta.indexes.append(it.TEXT)
        if name in idx_cfg.vector_index_columns:
            # vectors arrive as JSON-array strings (or lists); the index
            # holds the dense [n, d] block (ref HnswVectorIndexCreator)
            import json as _json

            from pinot_tpu.segment.vector_index import VectorIndex
            vecs = [(_json.loads(v) if isinstance(v, (str, bytes)) else v)
                    for v in values]
            buffers[index_key(name, it.VECTOR)] = \
                VectorIndex.build(np.asarray(vecs, np.float32)).to_bytes()
            meta.indexes.append(it.VECTOR)
        if name in idx_cfg.geo_index_columns:
            # points arrive as 'lat,lng' strings (ref geospatial creator);
            # malformed points parse to NaN and index into no cell
            from pinot_tpu.segment.geo_index import GeoIndex, parse_point
            pts = [parse_point(v) for v in values]
            buffers[index_key(name, it.GEO)] = \
                GeoIndex.build([p[0] for p in pts],
                               [p[1] for p in pts]).to_bytes()
            meta.indexes.append(it.GEO)
        if name in idx_cfg.map_index_columns:
            from pinot_tpu.segment.map_index import MapIndex
            buffers[index_key(name, it.MAP)] = \
                MapIndex.build(values, num_docs).to_bytes()
            meta.indexes.append(it.MAP)

    # ------------------------------------------------------------------
    def _build_mv(self, spec: FieldSpec, data: Optional[ColumnData], num_docs: int,
                  idx_cfg, buffers: Dict[str, bytes]) -> ColumnMetadata:
        name = spec.name
        rows: List[list] = []
        default = spec.default_null_value
        src = data if data is not None else [None] * num_docs
        null_docs = []
        for i, row in enumerate(src):
            if row is None or (isinstance(row, (list, tuple, np.ndarray)) and len(row) == 0):
                rows.append([default])
                null_docs.append(i)
            elif isinstance(row, (list, tuple, np.ndarray)):
                rows.append([spec.data_type.convert(v) for v in row])
            else:
                rows.append([spec.data_type.convert(row)])
        flat = np.array([v for r in rows for v in r],
                        dtype=spec.data_type.np_dtype if spec.data_type.np_dtype != np.dtype(object) else object)
        dictionary, flat_ids = Dictionary.build(spec.data_type, flat)
        card = dictionary.cardinality
        bits = bitpack.num_bits(card)
        ids_per_doc = []
        pos = 0
        for r in rows:
            ids_per_doc.append(flat_ids[pos:pos + len(r)])
            pos += len(r)
        meta = ColumnMetadata(
            name=name, data_type=spec.data_type, field_type=spec.field_type,
            single_value=False, has_dictionary=True, cardinality=card,
            bits_per_element=bits, min_value=dictionary.min_value,
            max_value=dictionary.max_value, is_sorted=False,
            total_entries=len(flat),
            max_num_multi_values=max((len(r) for r in rows), default=0),
            has_nulls=bool(null_docs),
        )
        buffers[index_key(name, it.DICTIONARY)] = dictionary.to_bytes()
        buffers[index_key(name, it.FORWARD)] = fwd.write_mv_dict(ids_per_doc, bits)
        meta.indexes += [it.DICTIONARY, it.FORWARD]
        if null_docs:
            buffers[index_key(name, it.NULLVECTOR)] = \
                Bitmap.from_indices(num_docs, null_docs).to_bytes()
            meta.indexes.append(it.NULLVECTOR)
        if name in idx_cfg.inverted_index_columns:
            offsets = np.zeros(num_docs + 1, dtype=np.int32)
            np.cumsum([len(r) for r in rows], out=offsets[1:])
            buffers[index_key(name, it.INVERTED)] = \
                InvertedIndex.build_mv(offsets, flat_ids, card, num_docs).to_bytes()
            meta.indexes.append(it.INVERTED)
        return meta


def _num_docs(columns: Dict[str, ColumnData], schema: Schema) -> int:
    for name in schema.column_names:
        if name in columns and columns[name] is not None:
            return len(columns[name])
    raise ValueError("no columns provided")


def _normalize_sv(spec: FieldSpec, data: Optional[ColumnData], num_docs: int):
    """Replace nulls with the default null value; return (values, null bitmap).

    Ref: record transformer null handling + NullValueVectorCreator.
    """
    default = spec.default_null_value
    if data is None:
        return (np.full(num_docs, default, dtype=spec.data_type.np_dtype),
                Bitmap.all_set(num_docs))
    npdt = spec.data_type.np_dtype
    if isinstance(data, np.ndarray) and data.dtype != np.dtype(object):
        arr = np.ascontiguousarray(data, dtype=npdt)
        if np.issubdtype(arr.dtype, np.floating):
            nan_mask = np.isnan(arr)
            if nan_mask.any():
                arr = arr.copy()
                arr[nan_mask] = default
                return arr, Bitmap.from_mask(nan_mask)
        return arr, Bitmap(num_docs)
    null_idx = []
    out = []
    for i, v in enumerate(data):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            out.append(default)
            null_idx.append(i)
        else:
            out.append(spec.data_type.convert(v))
    arr = np.array(out, dtype=npdt)
    return arr, Bitmap.from_indices(num_docs, null_idx)


def build_segment(table_config: TableConfig, schema: Schema,
                  columns: Dict[str, ColumnData], out_dir: str,
                  segment_name: str, **kw) -> str:
    return SegmentCreator(table_config, schema).build(columns, out_dir, segment_name, **kw)
