"""Sorted per-column dictionaries: value <-> dictId.

Reference parity: pinot-segment-spi index/reader/Dictionary.java:37 and
pinot-segment-local readers ({Int,Long,Float,Double,String,Bytes}Dictionary,
creator SegmentDictionaryCreator). As in the reference, dictionaries are
value-sorted, so range predicates resolve to contiguous dictId ranges
(searchsorted) and min/max are dictIds 0 and N-1 — which is what lets device
filter kernels compare int32 dictIds instead of values.

Serialized form:
  numeric: the sorted value array, raw little-endian.
  string/bytes: int32 offsets array (n+1 entries) followed by the UTF-8 blob.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from pinot_tpu.models.field_spec import DataType


class Dictionary:
    """Immutable sorted dictionary over a column's distinct values."""

    def __init__(self, data_type: DataType, values: np.ndarray):
        self.data_type = data_type
        self._values = values  # sorted; numeric ndarray or object ndarray

    # -- factory ------------------------------------------------------------
    @classmethod
    def build(cls, data_type: DataType, column: np.ndarray) -> Tuple["Dictionary", np.ndarray]:
        """Build from raw column values; returns (dictionary, dictIds)."""
        uniques, inverse = np.unique(column, return_inverse=True)
        return cls(data_type, uniques), inverse.astype(np.int32)

    # -- Dictionary contract (ref Dictionary.java:37) -----------------------
    def __len__(self) -> int:
        return len(self._values)

    @property
    def cardinality(self) -> int:
        return len(self._values)

    def get_value(self, dict_id: int) -> Any:
        v = self._values[dict_id]
        if isinstance(v, np.generic):
            return v.item()
        return v

    def get_values(self, dict_ids: np.ndarray) -> np.ndarray:
        return self._values[dict_ids]

    @property
    def values(self) -> np.ndarray:
        return self._values

    @property
    def fst_index(self):
        """Lazy FST-style regex/prefix index over the sorted terms (ref
        LuceneFSTIndexReader; see segment/fst_index.py)."""
        fst = getattr(self, "_fst", None)
        if fst is None:
            from pinot_tpu.segment.fst_index import FstIndex
            fst = self._fst = FstIndex(self._values)
        return fst

    def index_of(self, value: Any) -> int:
        """DictId of value, or -1 (ref Dictionary.indexOf null handling).

        Exact-match semantics: a non-integral float never matches an int
        dictionary, out-of-dtype-range values never match.
        """
        i = self.insertion_index(value, side="left")
        if i < len(self._values) and self._values[i] == value:
            return i
        return -1

    def insertion_index(self, value: Any, side: str = "left") -> int:
        """searchsorted position — used to resolve range predicates.

        The value is NOT coerced to the dictionary dtype: numpy's comparison
        promotion handles mixed int/float and out-of-range bounds correctly
        (e.g. `x > 3.5` on an int column resolves at position of 4).
        """
        return int(np.searchsorted(self._values, value, side=side))

    @property
    def min_value(self) -> Any:
        return self.get_value(0)

    @property
    def max_value(self) -> Any:
        return self.get_value(len(self._values) - 1)

    # -- numeric view for device upload -------------------------------------
    def values_as_f64(self) -> Optional[np.ndarray]:
        """Dictionary values as float64 (None for non-numeric) — used to map
        dictId aggregation results back to value space on device."""
        if self._values.dtype == np.dtype(object):
            return None
        return self._values.astype(np.float64)

    # -- serde --------------------------------------------------------------
    def to_bytes(self) -> bytes:
        if self._values.dtype == np.dtype(object):
            encoded = [v.encode("utf-8") if isinstance(v, str) else bytes(v)
                       for v in self._values]
            offsets = np.zeros(len(encoded) + 1, dtype=np.int32)
            np.cumsum([len(b) for b in encoded], out=offsets[1:])
            return offsets.tobytes() + b"".join(encoded)
        return np.ascontiguousarray(self._values).tobytes()

    @classmethod
    def from_bytes(cls, data_type: DataType, data: np.ndarray, cardinality: int) -> "Dictionary":
        npdt = data_type.np_dtype
        if npdt == np.dtype(object):
            raw = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, memoryview)) \
                else np.asarray(data, dtype=np.uint8)
            offsets = raw[: (cardinality + 1) * 4].view(np.int32)
            blob = raw[(cardinality + 1) * 4:].tobytes()
            is_bytes = data_type.stored_type is DataType.BYTES
            vals = np.empty(cardinality, dtype=object)
            for i in range(cardinality):
                chunk = blob[offsets[i]:offsets[i + 1]]
                vals[i] = chunk if is_bytes else chunk.decode("utf-8")
            return cls(data_type, vals)
        raw = np.frombuffer(data, dtype=npdt, count=cardinality) \
            if isinstance(data, (bytes, memoryview)) else np.asarray(data).view(npdt)[:cardinality]
        return cls(data_type, raw)
